"""Parallel, pipelined host staging for projected random effects.

BENCH_r05 put the per-entity projection pass at ~40 s of the ~42 s cold
staging time for 10M rows / 1M entities — the dominant end-to-end cost of
a cold GAME fit, while the vmapped coordinate fits it feeds finish in
under a second. The structure of the fix is the one Snap ML
(arXiv:1803.06333) and "Large-Scale Stochastic Learning using GPUs"
(arXiv:1702.07005) use: partition the host-side data-preparation work and
OVERLAP it with accelerator compute instead of serializing
stage-everything-then-fit.

Three ideas, all exact (staged bytes identical to the serial path):

1. **Entity-axis sharding.** Every per-bucket staging computation
   (triplet sort + segment pass, active-pair extraction, the Pearson cap,
   the projected feature scatter, the bucket-layout label/weight gathers)
   is per-LANE math — sorted runs never span lanes. So a bucket splits
   into lane slices ("shards") that workers process independently; the
   concatenation of shard outputs is bit-identical to the whole-bucket
   build. The one cross-lane quantity, the bucket's projected width
   ``d_active`` (pow-2 of the max per-lane active count), is a max-reduce
   over shard maxima — phase A (pair extraction) runs per shard, the
   width reduces per bucket, then phase B (column-map fill + feature
   scatter) runs per shard again.

2. **Worker pool.** Shard tasks run on a thread pool by default — the
   dominant kernels (np.sort/argsort over the packed lane-col keys, the
   reduceat segment sums) release the GIL — with a process-pool fallback
   (``StagingConfig.mode="process"``) for workloads where GIL-holding
   fancy-indexing dominates. Either way the merged output is identical:
   scheduling never changes content, only timing.

3. **Bounded pipelined handoff.** Shards are handed to the consumer (the
   coordinate's fit stream — see RandomEffectCoordinate._iter_bucket_data)
   in plan order as they finish, through a depth-bounded producer/consumer
   seam: the first per-entity fits dispatch while later shards are still
   projecting, and at most ``pipeline_depth`` staged-but-unconsumed shard
   blocks exist at once (bounding host memory — the serial path
   materialized every bucket before the first fit).

The staging cache (game/staging_cache.py) is shard-granular: each shard's
arrays are written (atomically) the moment the shard is staged, so a
killed run resumes with partial credit and a corrupted shard invalidates
only itself, not the whole entry.

Threading notes: the scheduler is a daemon thread that never runs inside
the pool; pool tasks never block on futures or semaphores — so there is
no lost-wakeup/deadlock topology. If the consumer never drains the
stream, staging stalls at the depth bound and the daemon scheduler dies
with the process.

Failure contract (docs/ROBUSTNESS.md): every shard task is wrapped in a
degradation ladder — bounded retry with deterministic jittered backoff,
then (for a crashed worker that broke the pool) QUARANTINE of the pool
and serial re-staging inline on the scheduler thread. Content never
depends on which rung produced it (the parity tests' core property), so
recovery is bit-identical. A shard that exceeds
``StagingConfig.straggler_timeout_s`` is re-staged serially instead of
stalling the consumer (the late pool result is discarded); every retry /
straggler emits an event and counts in ``ProjectionStager.fault_stats``.
Faults are injectable at the ``staging.phase_a`` / ``staging.phase_b``
sites (photon_ml_tpu/faults) — the chaos suite drives every rung.
"""

from __future__ import annotations

import concurrent.futures as cf
import dataclasses
import functools
import logging
import os
import queue
import random
import threading
import time
from typing import Optional

import numpy as np

from photon_ml_tpu import faults as flt
from photon_ml_tpu.utils import workers as pools

logger = logging.getLogger("photon_ml_tpu.game")

from photon_ml_tpu.game import buckets as bkt
from photon_ml_tpu.game import projector as prj
from photon_ml_tpu.game import staging_cache
from photon_ml_tpu.utils import events as ev_mod

# Max entity lanes per staged shard AND per vmapped random-effect solve
# dispatch (random_effect.py imports this): the solver's carry/line-search
# temps scale with lanes, and one dispatch over ~600k lanes OOMs a 16 GB
# chip. 64k lanes keeps temps ~100 MB at typical widths while staying
# large enough to saturate the chip — and gives the 10M-row/1M-entity
# bench config ~15 shards, enough granularity for an 8-worker pool.
LANE_CHUNK = 65536


@dataclasses.dataclass(frozen=True)
class StagingConfig:
    """Knobs of the parallel staging pipeline.

    ``workers``: pool size (None → os.cpu_count()). ``mode``: "thread"
    (default; numpy's sort/segment kernels release the GIL) or "process"
    (fallback when GIL-holding gathers dominate; ships arrays by pickle,
    spawn-safe with JAX). ``pipeline_depth``: max staged-but-unconsumed
    shard blocks (None → workers + 2). ``shard_entities``: lanes per
    shard (None → LANE_CHUNK; rounded up to the bucketing's entity pad
    multiple so device sharding survives).

    Resilience knobs (docs/ROBUSTNESS.md): ``max_retries`` bounds the
    per-shard retry ladder (0 = fail on first error);
    ``retry_backoff_s`` is the base of the exponential jittered backoff
    between attempts (jitter is deterministic in (seed, shard, attempt));
    ``straggler_timeout_s`` re-stages a shard serially when its pool task
    exceeds the deadline instead of stalling the consumer (None = wait
    forever, the pre-hardening behavior).
    """

    workers: Optional[int] = None
    mode: str = "thread"
    pipeline_depth: Optional[int] = None
    shard_entities: Optional[int] = None
    max_retries: int = 2
    retry_backoff_s: float = 0.05
    straggler_timeout_s: Optional[float] = None
    retry_jitter_seed: int = 0

    def __post_init__(self):
        if self.mode not in ("thread", "process"):
            raise ValueError(
                f"staging mode must be 'thread' or 'process', "
                f"got {self.mode!r}")
        for name in ("workers", "pipeline_depth", "shard_entities"):
            v = getattr(self, name)
            if v is not None and v < 1:
                raise ValueError(f"staging {name} must be >= 1, got {v}")
        if self.max_retries < 0:
            raise ValueError(
                f"staging max_retries must be >= 0, got {self.max_retries}")
        if self.retry_backoff_s < 0:
            raise ValueError(f"staging retry_backoff_s must be >= 0, "
                             f"got {self.retry_backoff_s}")
        if (self.straggler_timeout_s is not None
                and self.straggler_timeout_s <= 0):
            raise ValueError(f"staging straggler_timeout_s must be > 0, "
                             f"got {self.straggler_timeout_s}")

    def resolved_workers(self) -> int:
        return max(1, self.workers or os.cpu_count() or 1)

    def resolved_depth(self) -> int:
        return self.pipeline_depth or self.resolved_workers() + 2


def resolved_shard_entities(config: StagingConfig, pad: int) -> int:
    size = config.shard_entities or LANE_CHUNK
    return ((size + pad - 1) // pad) * pad


def plan_shards(bucketing, shard_entities: Optional[int] = None,
                pad: Optional[int] = None) -> list[tuple[int, int, int]]:
    """(bucket, lane_lo, lane_hi) shard plan in consumption order.

    Bucket sizes are pad multiples and the shard size is rounded up to a
    pad multiple, so every slice (tails included) keeps the divisibility
    the mesh sharding of staged blocks needs.
    """
    pad = pad or bucketing.entity_pad_multiple
    size = resolved_shard_entities(
        StagingConfig(shard_entities=shard_entities), pad)
    plan = []
    for bi, b in enumerate(bucketing.buckets):
        for lo in range(0, b.num_entities, size):
            plan.append((bi, lo, min(lo + size, b.num_entities)))
    return plan


@dataclasses.dataclass
class ShardTask:
    """Everything one shard's phase A/B tasks need, self-contained so
    process-mode workers get it by pickle (lanes LOCAL to the slice)."""

    index: int
    bucket: int
    lo: int
    hi: int
    entity_rows: np.ndarray  # (E_loc,)
    example_idx: np.ndarray  # (E_loc, cap) int64 global example ids
    counts: np.ndarray
    t_cols: np.ndarray  # int64 triplet columns
    t_vals: np.ndarray
    t_lanes: np.ndarray  # int64 LOCAL lanes
    t_cappos: np.ndarray  # int32 per-triplet slot within the lane cap
    t_y: Optional[np.ndarray] = None  # float64 labels per triplet (ratio)
    yb: Optional[np.ndarray] = None  # (E_loc, cap) float64 labels (ratio)
    y0: float = 0.0


def split_shard_triplets(
    bucketing,
    plan: list[tuple[int, int, int]],
    X,
    coo=None,
    labels: Optional[np.ndarray] = None,
) -> list[ShardTask]:
    """Build every shard's task in ONE global pass over the nonzeros.

    Like projector.all_bucket_triplets but shard-granular: one
    row → (shard, local lane, cap slot) map, one nnz-sized gather, and
    one stable radix argsort of the int16 shard ids groups the triplets
    into contiguous per-shard slices (stable ⇒ original triplet order
    within each shard, the order the whole-bucket build sees).
    """
    n_rows, _ = prj._shard_shape(X)
    if coo is None:
        coo = prj.shard_coo(X)
    rows_nz, cols_nz, vals_nz = coo
    if len(plan) >= 2 ** 15:
        raise ValueError(f"{len(plan)} shards overflow the int16 map; "
                         "raise shard_entities")
    shard_of = np.full(n_rows, -1, np.int16)
    lane_local = np.full(n_rows, -1, np.int32)
    cappos_of = np.zeros(n_rows, np.int32)
    for si, (bi, lo, hi) in enumerate(plan):
        ex = bucketing.buckets[bi].example_idx[lo:hi]
        kept = ex >= 0
        rk = ex[kept]
        shard_of[rk] = si
        lane_local[rk] = np.broadcast_to(
            np.arange(ex.shape[0], dtype=np.int32)[:, None], ex.shape)[kept]
        cappos_of[rk] = np.broadcast_to(
            np.arange(ex.shape[1], dtype=np.int32)[None, :], ex.shape)[kept]
    ts = shard_of[rows_nz]  # the one nnz-sized gather
    order = np.argsort(ts, kind="stable")  # int16 → radix, O(nnz)
    ts_s = ts[order]
    sids = np.arange(len(plan), dtype=ts_s.dtype)
    starts = np.searchsorted(ts_s, sids, side="left")
    ends = np.searchsorted(ts_s, sids, side="right")
    rows_s = rows_nz[order]
    cols_s = cols_nz[order].astype(np.int64)
    vals_s = vals_nz[order]
    lanes_s = lane_local[rows_s].astype(np.int64)
    cappos_s = cappos_of[rows_s]
    y64 = None
    y_s = None
    y0 = 0.0
    if labels is not None:
        y64 = np.asarray(labels, np.float64)
        y_s = y64[rows_s]
        y0 = float(y64[0]) if y64.size else 0.0
    tasks = []
    for si, (bi, lo, hi) in enumerate(plan):
        b = bucketing.buckets[bi]
        sl = slice(int(starts[si]), int(ends[si]))
        yb = None
        if y64 is not None:
            ex = b.example_idx[lo:hi]
            yb = y64[np.maximum(ex, 0)]
            yb[ex < 0] = 0.0
        tasks.append(ShardTask(
            index=si, bucket=bi, lo=lo, hi=hi,
            entity_rows=b.entity_rows[lo:hi],
            example_idx=b.example_idx[lo:hi],
            counts=b.counts[lo:hi],
            t_cols=cols_s[sl], t_vals=vals_s[sl], t_lanes=lanes_s[sl],
            t_cappos=cappos_s[sl],
            t_y=None if y_s is None else y_s[sl], yb=yb, y0=y0))
    return tasks


# ------------------------------------------------------------- pool tasks
#
# Module-level pure functions so the process pool can pickle them. Big
# read-only context (response/weights/norm arrays/dense X) travels once
# per worker through the pool initializer (utils/workers.py — shared with
# the ingestion pipeline) instead of once per task.


def _retry_delay(base: float, attempt: int, seed: int, index: int) -> float:
    """Exponential backoff with DETERMINISTIC jitter: attempt k waits
    ``base * 2^(k-1) * uniform[0.5, 1.5)`` where the uniform draw is
    seeded by (seed, shard, attempt) — chaos tests replay identically."""
    r = random.Random(f"{seed}|{index}|{attempt}").random()
    return base * (2.0 ** (attempt - 1)) * (0.5 + r)


def _phase_a(task: ShardTask, d: int, intercept_index: Optional[int],
             ratio: Optional[float]):
    """Unique active (lane, col) pairs of one shard + the lane-count max
    that feeds the bucket's d_active reduce."""
    flt.fire(flt.sites.STAGING_PHASE_A, index=task.index)
    live = np.flatnonzero(np.asarray(task.entity_rows) >= 0).astype(
        np.int64)
    u_lane, u_col = prj.active_pairs(
        task.entity_rows.shape[0], d, intercept_index, live,
        task.t_cols, task.t_vals, task.t_lanes,
        ratio=ratio, t_y=task.t_y, y0=task.y0, yb=task.yb,
        kept=task.example_idx >= 0)
    counts = prj.active_lane_counts(u_lane, task.entity_rows.shape[0])
    return u_lane, u_col, int(counts.max()) if counts.size else 0


def _phase_b(task: ShardTask, cols: np.ndarray, d_active: int,
             ctx: Optional[dict] = None):
    """One shard's staged tuple, laid out exactly as the serial
    coordinate staging: (Xb, yb, wb, ex, rows[, cols][, f_p][, s_p])."""
    flt.fire(flt.sites.STAGING_PHASE_B, index=task.index)
    if ctx is None:
        ctx = pools.worker_ctx()
    sub = bkt.EntityBucket(entity_rows=task.entity_rows,
                           example_idx=task.example_idx,
                           counts=task.counts)
    proj = prj.BucketProjection(cols=cols, d_active=int(d_active))
    X = ctx.get("dense_X")
    if X is not None:
        Xb = prj.gather_projected_features(sub, proj, X)
    else:
        trips = prj.BucketTriplets(
            rows=np.zeros(0, np.int32), cols=task.t_cols,
            vals=task.t_vals, lanes=task.t_lanes, cappos=task.t_cappos)
        E_loc, cap = task.example_idx.shape
        Xb = prj.scatter_projected(E_loc, cap, ctx["d"], proj, trips)
    (yb,) = bkt.gather_bucket_arrays(sub, ctx["response"])
    wb = bkt.bucket_weights(sub, ctx["weights"])
    ex32 = task.example_idx.astype(np.int32)
    out = [Xb, yb, wb, ex32, task.entity_rows, cols]
    factors, shifts = ctx.get("factors"), ctx.get("shifts")
    if factors is not None or shifts is not None:
        f_p, s_p = prj.project_norm_arrays(proj, factors, shifts)
        if factors is not None:
            out.append(f_p)
        if shifts is not None:
            out.append(s_p)
    return tuple(out)


def _make_pool(mode: str, workers: int, ctx: dict):
    # Shared pool plumbing (utils/workers.py): spawn-context process pools
    # with the ctx/fault-plan initializer, thread pools otherwise.
    return pools.make_pool(mode, workers, ctx,
                           thread_name_prefix="pml-staging")


# ------------------------------------------------------------ the stager


class ProjectionStager:
    """Background staging pipeline for one projected RE coordinate.

    Construction is cheap: the heavy work (triplet extraction, shard
    split, phase A/B tasks) runs on a daemon scheduler thread + worker
    pool. Consumers:

    - ``shards()`` yields staged host tuples in plan order as they
      finish (blocking), releasing the depth bound as it goes — the
      coordinate's fit stream.
    - ``cols_list()`` blocks until every shard's column map exists
      (phase A of all buckets) — the subspace-model table build.
    - ``set_subspace(dict)`` hands the subspace join arrays over for the
      cache entry's completion record.
    """

    def __init__(
        self,
        *,
        bucketing,
        X,
        response: np.ndarray,
        weights: np.ndarray,
        intercept_index: Optional[int],
        features_to_samples_ratio: Optional[float] = None,
        factors: Optional[np.ndarray] = None,
        shifts: Optional[np.ndarray] = None,
        config: Optional[StagingConfig] = None,
        cache_dir: Optional[str] = None,
        cache_key: Optional[str] = None,
        expect_subspace: bool = False,
        label: str = "",
        min_dim: int = 8,
        emitter: Optional[ev_mod.EventEmitter] = None,
    ):
        from photon_ml_tpu.data.game_data import SparseShard

        self.config = config or StagingConfig()
        self._bucketing = bucketing
        self._X = X
        self._is_sparse = isinstance(X, SparseShard)
        self._d = prj._shard_shape(X)[1]
        self._response = np.asarray(response)
        self._weights = np.asarray(weights)
        self._ii = intercept_index
        self._ratio = features_to_samples_ratio
        self._factors = factors
        self._shifts = shifts
        self._min_dim = min_dim
        self._cache_dir = cache_dir if cache_key else None
        self._cache_key = cache_key
        self._label = label
        self._emitter = emitter or ev_mod.default_emitter
        self._arity = 6 + (factors is not None) + (shifts is not None)

        pad = bucketing.entity_pad_multiple
        self.plan = plan_shards(bucketing,
                                self.config.shard_entities, pad)
        self.num_shards = len(self.plan)
        self._futures = [cf.Future() for _ in range(self.num_shards)]
        self._cols: list[Optional[np.ndarray]] = [None] * self.num_shards
        self._cols_ready = threading.Event()
        self._error: Optional[BaseException] = None
        self._sub: Optional[dict] = None
        self._sub_expected = expect_subspace
        self._state_lock = threading.Lock()
        self._done_count = 0
        self._finalized = False
        self._complete = threading.Event()  # scheduler fully retired
        self._t0 = time.monotonic()
        # Degradation-ladder bookkeeping. Writes happen on the scheduler
        # thread (completion callbacks only ENQUEUE failures); tests read
        # after join(), which publishes via self._complete.
        self._quarantined = False
        # Shards claimed by exactly one producer (pool callback, retry,
        # or straggler restage) — the loser of any race discards.
        self._claimed: set[int] = set()
        self._claim_lock = threading.Lock()
        self.fault_stats = {"retries": 0, "serial_restages": 0,
                            "stragglers": 0, "quarantined": False}

        # Probe the shard-granular cache: valid shards skip phases A+B
        # entirely (their column map rides in the cached tuple).
        self._cached: dict[int, tuple] = {}
        if self._cache_dir:
            for i, (bi, lo, hi) in enumerate(self.plan):
                t = staging_cache.load_shard(self._cache_dir,
                                             self._cache_key, i)
                if t is not None and self._valid_shard(t, bi, lo, hi):
                    self._cached[i] = t
        self._emitter.emit(ev_mod.StagingStart(
            label=label, num_shards=self.num_shards,
            workers=self.config.resolved_workers(), mode=self.config.mode,
            cached_shards=len(self._cached)))
        for i, t in self._cached.items():
            self._cols[i] = np.asarray(t[5])
            self._futures[i].set_result(("cache", t))
            self._emitter.emit(ev_mod.StagingShard(
                label=label, index=i, bucket=self.plan[i][0],
                entities=self.plan[i][2] - self.plan[i][1],
                seconds=0.0, source="cache"))
            self._shard_done()
        if len(self._cached) == self.num_shards:
            self._cols_ready.set()
            self._complete.set()
            self._thread = None
        else:
            self._sem = threading.Semaphore(self.config.resolved_depth())
            self._thread = threading.Thread(
                target=self._run, daemon=True,
                name=f"pml-staging-sched[{label}]")
            self._thread.start()

    # -- cache helpers ----------------------------------------------------

    def _valid_shard(self, t, bi, lo, hi) -> bool:
        b = self._bucketing.buckets[bi]
        return (len(t) == self._arity
                and t[0].ndim == 3
                and all(a.shape[0] == hi - lo for a in t)
                and t[0].shape[1] == b.capacity
                and t[5].shape[1] == t[0].shape[2])

    def cached_subspace(self) -> Optional[dict]:
        """The completion-record subspace arrays of a COMPLETE cache
        entry (None when absent/partial/invalid)."""
        if not self._cache_dir:
            return None
        return staging_cache.load_subspace(self._cache_dir, self._cache_key,
                                           expected_shards=self.num_shards)

    def set_subspace(self, sub: dict) -> None:
        """Record the coordinate's subspace join arrays so the cache
        entry can be finalized once every shard is written."""
        with self._state_lock:
            self._sub = dict(sub)
        self._maybe_finalize()

    # -- consumer API -----------------------------------------------------

    def shards(self):
        """Yield staged host tuples in plan order (blocking); the depth
        bound is released as the consumer takes each staged shard."""
        for i in range(self.num_shards):
            src, t = self._futures[i].result()
            try:
                yield t
            finally:
                if src == "staged":
                    self._sem.release()

    def cols_list(self) -> list[np.ndarray]:
        """Per-shard (E_loc, d_active) column maps, blocking until phase
        A (or the cache) has produced all of them."""
        self._cols_ready.wait()
        if self._error is not None:
            raise self._error
        return list(self._cols)  # type: ignore[arg-type]

    # -- scheduler --------------------------------------------------------

    def _run(self):
        try:
            self._stage_missing()
        except BaseException as e:  # propagate to every waiter
            # pml: allow[PML005] single-writer seam: _error is written only
            # here, before _cols_ready.set(); Event.set() publishes it
            # (happens-before) to the cols_list() reader.
            self._error = e
            self._cols_ready.set()
            for f in self._futures:
                if not f.done():
                    f.set_exception(e)
        finally:
            self._complete.set()

    def join(self) -> None:
        """Block until the pipeline has fully retired (every shard
        produced AND its cache write finished) — the deterministic
        sync point for warm-restart tests and benchmarks; consumers
        that only need the data use shards()/cols_list()."""
        self._complete.wait()

    def _stage_missing(self):
        workers = self.config.resolved_workers()
        ctx = {
            "response": self._response,
            "weights": self._weights,
            "factors": self._factors,
            "shifts": self._shifts,
            "d": self._d,
            "dense_X": None if self._is_sparse else np.asarray(self._X),
        }
        plan = flt.current_plan()
        if plan is not None:
            # Injected faults must reach spawn-fresh process workers too.
            ctx["fault_plan"] = plan
        labels = (self._response if self._ratio is not None else None)
        tasks = split_shard_triplets(self._bucketing, self.plan, self._X,
                                     labels=labels)
        missing = [i for i in range(self.num_shards)
                   if i not in self._cached]
        is_process = self.config.mode == "process"
        if is_process:
            pool_a = pool_b = _make_pool("process", workers, ctx)
        else:
            # Two pools so phase-B tasks never queue behind the FIFO tail
            # of phase-A tasks: the first staged shard reaches the
            # consumer while later buckets are still in their sort pass.
            pool_a = _make_pool("thread", workers, ctx)
            pool_b = _make_pool("thread", workers, ctx)
        try:
            a_futs = {i: self._submit(pool_a, _phase_a,
                                      (tasks[i], self._d, self._ii,
                                       self._ratio), i)
                      for i in missing}
            # Per-bucket width reduce + column-map fill (cheap, in this
            # thread), publishing cols for cols_list() BEFORE any
            # depth-bounded phase-B submission can stall on a consumer
            # that hasn't started training yet.
            by_bucket: dict[int, list[int]] = {}
            for i, (bi, lo, hi) in enumerate(self.plan):
                by_bucket.setdefault(bi, []).append(i)
            for bi, shard_ids in by_bucket.items():
                pairs: dict[int, tuple] = {}
                max_active = 0
                cached_width = None
                for i in shard_ids:
                    if i in self._cached:
                        w = int(self._cached[i][5].shape[1])
                        cached_width = max(cached_width or 0, w)
                    else:
                        u_lane, u_col, mx = self._shard_result(
                            i, a_futs.pop(i), pool_a, _phase_a,
                            (tasks[i], self._d, self._ii, self._ratio))
                        pairs[i] = (u_lane, u_col)
                        max_active = max(max_active, mx)
                width = prj.projection_width(
                    np.asarray([max(1, max_active)]), self._d,
                    self._min_dim)
                if cached_width is not None:
                    # A partial cache entry's shards were written with the
                    # full bucket's width (same key ⇒ same data), which
                    # upper-bounds any recomputed-slice width.
                    width = max(width, cached_width)
                for i in shard_ids:
                    if i not in self._cached:
                        u_lane, u_col = pairs.pop(i)
                        lo, hi = self.plan[i][1], self.plan[i][2]
                        # pml: allow[PML005] single-writer seam: _cols slots
                        # are filled only by this scheduler thread before
                        # _cols_ready.set(); the Event publishes them.
                        self._cols[i] = prj.fill_cols(
                            u_lane, u_col, hi - lo, width, self._ii)
            self._cols_ready.set()
            self._run_phase_b(tasks, missing, pool_b, ctx, is_process)
        finally:
            pool_a.shutdown(wait=False)
            if pool_b is not pool_a:
                pool_b.shutdown(wait=False)

    # -- degradation ladder (docs/ROBUSTNESS.md) ---------------------------

    def _submit(self, pool, fn, args, i):
        """Pool submission, or None when the pool is quarantined/broken —
        the caller then runs the task inline (serial fallback)."""
        if self._quarantined:
            return None
        try:
            return pool.submit(fn, *args)
        except RuntimeError as e:  # BrokenExecutor / shut-down pool
            self._note_quarantine(i, e)
            return None

    def _note_quarantine(self, i, exc) -> None:
        if not self._quarantined:
            self._quarantined = True
            self.fault_stats["quarantined"] = True
            logger.warning(
                "staging[%s]: worker pool broken at shard %d (%s: %s) — "
                "quarantining the pool; remaining shards re-stage "
                "serially (bit-identical, slower)",
                self._label, i, type(exc).__name__, exc)

    def _note_retry(self, i, attempt, exc) -> None:
        self.fault_stats["retries"] += 1
        logger.warning(
            "staging[%s]: shard %d attempt %d failed (%s: %s) — retrying",
            self._label, i, attempt, type(exc).__name__, exc)
        self._emitter.emit(ev_mod.StagingRetry(
            label=self._label, index=i, attempt=attempt,
            error=f"{type(exc).__name__}: {exc}"))

    def _shard_result(self, i, fut, pool, fn, args):
        """One shard task's result, walking the ladder: pooled attempts
        with deterministic jittered backoff → quarantine when a crashed
        worker broke the pool → inline serial execution on this thread.
        Raises only when every rung failed (a deterministic task bug,
        not an execution fault)."""
        attempt = 0
        while True:
            try:
                if fut is None:
                    self.fault_stats["serial_restages"] += 1
                    return fn(*args)
                return fut.result()
            except cf.BrokenExecutor as e:
                # A crashed worker takes the whole pool down. That is not
                # this task's fault — no retry budget burned.
                self._note_quarantine(i, e)
                fut = None
            except Exception as e:
                attempt += 1
                if attempt > self.config.max_retries:
                    raise
                self._note_retry(i, attempt, e)
                delay = _retry_delay(self.config.retry_backoff_s, attempt,
                                     self.config.retry_jitter_seed, i)
                if delay > 0:
                    time.sleep(delay)
                fut = self._submit(pool, fn, args, i)

    def _publish_b(self, i, t_submit, res) -> None:
        """Phase-B success path (pool callback thread, retry, or
        straggler restage): the FIRST producer wins the claim, hands the
        shard to the consumer (the fit stream is latency-sensitive), then
        persists it; any later duplicate producer discards silently."""
        with self._claim_lock:
            if i in self._claimed:
                return
            self._claimed.add(i)
        self._futures[i].set_result(("staged", res))
        bi, lo, hi = self.plan[i]
        self._emitter.emit(ev_mod.StagingShard(
            label=self._label, index=i, bucket=bi,
            entities=hi - lo,
            seconds=time.monotonic() - t_submit,
            source="staged"))
        if self._cache_dir:
            try:
                staging_cache.save_shard(
                    self._cache_dir, self._cache_key, i, res)
            except OSError as e:
                # Cache is best-effort, staging is not.
                logger.warning(
                    "staging[%s]: cache write for shard %d failed "
                    "(%s: %s); staging continues", self._label, i,
                    type(e).__name__, e)
        self._shard_done()

    def _run_phase_b(self, tasks, missing, pool_b, ctx, is_process):
        """Depth-bounded phase-B dispatch in plan order. One scheduler
        loop (this thread) owns submissions, backoff retries, quarantine
        fallback, and the straggler deadline; pool completion callbacks
        take the low-latency success handoff directly and only enqueue
        FAILURES back here."""
        if not missing:
            return
        cfg = self.config
        failures: queue.Queue = queue.Queue()
        remaining = set(missing)
        to_submit = list(missing)
        inflight: dict[int, float] = {}  # shard → latest dispatch time
        retry_at: list[tuple[float, int]] = []  # (due time, shard)
        attempts: dict[int, int] = {}

        def _b_args(i):
            args = (tasks[i], self._cols[i], int(self._cols[i].shape[1]))
            return args if is_process else args + (ctx,)

        def _is_claimed(i):
            with self._claim_lock:
                return i in self._claimed

        def _fail(i, e):
            with self._claim_lock:
                if i in self._claimed:
                    return
                self._claimed.add(i)
            logger.error(
                "staging[%s]: shard %d failed after %d attempt(s): "
                "%s: %s", self._label, i, attempts.get(i, 0) + 1,
                type(e).__name__, e)
            if not self._futures[i].done():
                self._futures[i].set_exception(e)

        def _serial(i, t_submit):
            self.fault_stats["serial_restages"] += 1
            try:
                # Inline runs in the DRIVER process, where the process
                # pool's worker-ctx initializer never ran — always pass
                # the ctx explicitly.
                res = _phase_b(tasks[i], self._cols[i],
                               int(self._cols[i].shape[1]), ctx)
            except Exception as e:
                _handle_failure(i, e)
            else:
                self._publish_b(i, t_submit, res)

        def _handle_failure(i, e):
            if not (i in remaining and not _is_claimed(i)):
                return  # another producer already settled this shard
            now = time.monotonic()
            inflight.pop(i, None)
            if isinstance(e, cf.BrokenExecutor):
                self._note_quarantine(i, e)
                _serial(i, now)
                return
            att = attempts.get(i, 0) + 1
            attempts[i] = att
            if att > cfg.max_retries:
                _fail(i, e)
                return
            self._note_retry(i, att, e)
            retry_at.append((now + _retry_delay(
                cfg.retry_backoff_s, att, cfg.retry_jitter_seed, i), i))

        def _dispatch(i):
            now = time.monotonic()
            fut = self._submit(pool_b, _phase_b, _b_args(i), i)
            if fut is None:  # quarantined → serial fallback, right now
                _serial(i, now)
                return
            inflight[i] = now
            fut.add_done_callback(functools.partial(_on_b, i, now))

        def _on_b(i, t_submit, fut):  # pool callback thread
            try:
                res = fut.result()
            except BaseException as e:
                failures.put((i, e))
            else:
                self._publish_b(i, t_submit, res)

        while True:
            with self._claim_lock:
                remaining -= self._claimed
            if not remaining:
                return
            now = time.monotonic()
            # Due retries first: a recovering shard is the consumer's
            # critical path (shards() yields in plan order).
            due = [i for t, i in retry_at if t <= now]
            retry_at[:] = [(t, i) for t, i in retry_at if t > now]
            for i in due:
                if i in remaining and not _is_claimed(i):
                    _dispatch(i)
            while True:
                try:
                    i, e = failures.get_nowait()
                except queue.Empty:
                    break
                _handle_failure(i, e)
            if cfg.straggler_timeout_s is not None:
                for i in sorted(remaining):
                    t0 = inflight.get(i)
                    if (t0 is None or _is_claimed(i)
                            or now - t0 <= cfg.straggler_timeout_s):
                        continue
                    waited = now - t0
                    inflight.pop(i, None)
                    self.fault_stats["stragglers"] += 1
                    logger.warning(
                        "staging[%s]: shard %d exceeded the straggler "
                        "deadline (%.2fs > %.2fs) — re-staging serially; "
                        "the late pool result will be discarded",
                        self._label, i, waited, cfg.straggler_timeout_s)
                    self._emitter.emit(ev_mod.StagingStraggler(
                        label=self._label, index=i,
                        waited_seconds=waited))
                    _serial(i, t0)
            # Depth-bounded submission in plan order; when submission is
            # blocked on the depth bound, keep ticking so retries and
            # straggler checks stay live (a blocking acquire here would
            # freeze the ladder while the consumer catches up).
            if to_submit:
                if self._sem.acquire(timeout=0.05):
                    _dispatch(to_submit.pop(0))
                continue
            timeout = 0.1
            if retry_at:
                timeout = min(timeout,
                              max(0.005, min(t for t, _ in retry_at) - now))
            if cfg.straggler_timeout_s is not None:
                timeout = min(timeout,
                              max(0.005, cfg.straggler_timeout_s / 4))
            try:
                i, e = failures.get(timeout=timeout)
            except queue.Empty:
                continue
            _handle_failure(i, e)

    def _shard_done(self):
        with self._state_lock:
            self._done_count += 1
            last = self._done_count == self.num_shards
        if last:
            self._emitter.emit(ev_mod.StagingFinish(
                label=self._label, num_shards=self.num_shards,
                cached_shards=len(self._cached),
                wall_seconds=time.monotonic() - self._t0))
            self._maybe_finalize()

    def _maybe_finalize(self):
        if not self._cache_dir:
            return
        with self._state_lock:
            ready = (self._done_count == self.num_shards
                     and (not self._sub_expected or self._sub is not None)
                     and not self._finalized)
            if ready:
                self._finalized = True
        if ready:
            try:
                staging_cache.save_meta(self._cache_dir, self._cache_key,
                                        self.num_shards, self._sub)
            except OSError:
                pass


# ------------------------------------------------- projection-only helper


def project_buckets(
    bucketing,
    X,
    intercept_index: Optional[int] = None,
    labels: Optional[np.ndarray] = None,
    features_to_samples_ratio: Optional[float] = None,
    config: Optional[StagingConfig] = None,
    min_dim: int = 8,
) -> list[prj.BucketProjection]:
    """Parallel projection build WITHOUT the feature gathers: one
    BucketProjection per bucket, bit-identical to calling
    ``build_bucket_projection`` per bucket. This is the bench's
    projection-wall measurement (and a convenient standalone API when
    only the column maps are needed)."""
    config = config or StagingConfig()
    plan = plan_shards(bucketing, config.shard_entities)
    tasks = split_shard_triplets(
        bucketing, plan, X,
        labels=labels if features_to_samples_ratio is not None else None)
    d = prj._shard_shape(X)[1]
    workers = config.resolved_workers()
    ratio = features_to_samples_ratio
    if workers == 1 or config.mode == "process":
        # In-line for 1 worker; process mode gains nothing here (the
        # pair arrays would be pickled back at once) — keep it simple.
        a_res = [_phase_a(t, d, intercept_index, ratio) for t in tasks]
    else:
        with cf.ThreadPoolExecutor(max_workers=workers) as pool:
            a_res = list(pool.map(
                lambda t: _phase_a(t, d, intercept_index, ratio), tasks))
    out = []
    for bi, b in enumerate(bucketing.buckets):
        ids = [i for i, p in enumerate(plan) if p[0] == bi]
        max_active = max((a_res[i][2] for i in ids), default=0)
        width = prj.projection_width(
            np.asarray([max(1, max_active)]), d, min_dim)
        cols = np.concatenate([
            prj.fill_cols(a_res[i][0], a_res[i][1],
                          plan[i][2] - plan[i][1], width, intercept_index)
            for i in ids]) if ids else np.full((0, width), -1, np.int32)
        out.append(prj.BucketProjection(cols=cols, d_active=int(width)))
    return out
