"""Persistent, shard-granular staging cache for projected random effects.

Reference parity note: the reference pays its RandomEffectDataset build
(partition + projector construction) inside every Spark job and relies on
RDD caching within the job; re-running the driver re-pays it. Here the
host-side staging products (per-shard projected feature blocks + column
maps + subspace join tables) persist on disk keyed by the DATASET CONTENT
DIGEST (game/descent._dataset_digest) plus every staging parameter, so a
re-fit of the same data in a fresh process skips the projection pass
entirely — at the 10M-row / 1M-entity flagship config that pass is tens of
seconds of sort/segment work per coordinate.

Layout (v2, shard-granular): ``<cache_dir>/<key>/`` holding

- ``s<i>_<j>.npy`` — array j of staged shard i (one shard = one lane
  slice of one bucket, the unit the parallel pipeline produces);
- ``s<i>.ok`` — shard i's commit marker (JSON ``{"arity": k}``), written
  LAST via atomic rename, so a reader never trusts a half-written shard;
- ``sub_<name>.npy`` + ``meta.json`` — the subspace join arrays and the
  entry's completion record, written once every shard exists.

Shards are written **as they are produced** by the staging pipeline
(game/staging.py): a killed run leaves a partial entry whose valid shards
are reused on restart — only the missing/corrupt ones restage. Loads
memory-map the arrays: the host copy is never materialized — bytes stream
straight from the page cache into the device transfer the coordinate
performs anyway.

Anything unreadable (version skew, partial copy, foreign files) is
treated as a per-shard miss — the caller restages and overwrites.
Corruption that keeps a parseable npy header (bit rot, a torn page, an
injected fault) is caught by the per-file CRC32 recorded in the commit
marker: loads verify every array's checksum before trusting the shard
(docs/ROBUSTNESS.md), so a corrupt shard degrades to a restage of
exactly that shard — never silently wrong staged bytes.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
from typing import Optional

import numpy as np

from photon_ml_tpu import faults as flt
# Shared atomic-write + CRC discipline (utils/diskio.py); the historical
# names stay importable from here (checkpoint.py and tests use them).
from photon_ml_tpu.utils.diskio import atomic_write as _atomic_write
from photon_ml_tpu.utils.diskio import file_crc32

logger = logging.getLogger("photon_ml_tpu.game")

# Bump when the staged representation changes shape/meaning. v2: whole-
# bucket tuples became per-shard (lane-slice) tuples with commit markers.
# v3: markers carry per-file CRC32s; loads verify before trusting.
STAGING_VERSION = 3


def staging_key(dataset, norm, **params) -> str:
    """Cache key: dataset content digest + normalization digest + every
    staging parameter (bounds, seed, projection flags, shard size, …)."""
    from photon_ml_tpu.game.descent import (_dataset_digest,
                                            normalization_digest)

    h = hashlib.sha1()
    h.update(f"v{STAGING_VERSION}".encode())
    h.update(_dataset_digest(dataset).encode())
    h.update(normalization_digest(norm).encode())
    for k in sorted(params):
        h.update(f"{k}={params[k]!r};".encode())
    return h.hexdigest()


def save_shard(cache_dir: str, key: str, index: int,
               arrays: tuple[np.ndarray, ...]) -> None:
    """Persist one staged shard; the ``.ok`` marker (carrying each
    array file's CRC32) commits it last."""
    flt.fire(flt.sites.STAGING_CACHE_SAVE_SHARD, index=index)
    path = os.path.join(cache_dir, key)
    os.makedirs(path, exist_ok=True)
    crcs = []
    for j, a in enumerate(arrays):
        fpath = os.path.join(path, f"s{index}_{j}.npy")
        _atomic_write(fpath,
                      lambda f, _a=a: np.save(f, np.asarray(_a),
                                              allow_pickle=False))
        crcs.append(file_crc32(fpath))
        # Injected bit rot lands AFTER the checksum was taken over the
        # good bytes — the torn-page/bit-rot shape CRC must catch.
        flt.corrupt_file(flt.sites.STAGING_CACHE_SHARD_FILE, fpath, index=index)
    marker = json.dumps({"arity": len(arrays), "crc": crcs,
                         "version": STAGING_VERSION}).encode()
    _atomic_write(os.path.join(path, f"s{index}.ok"),
                  lambda f: f.write(marker))


def load_shard(cache_dir: str, key: str, index: int
               ) -> Optional[tuple[np.ndarray, ...]]:
    """One staged shard (memory-mapped, read-only), or None on any miss:
    no marker, version skew, unreadable arrays (truncation included —
    np.load validates the header), or a CRC mismatch against the commit
    marker (silent corruption)."""
    path = os.path.join(cache_dir, key)
    try:
        flt.fire(flt.sites.STAGING_CACHE_LOAD_SHARD, index=index)
        with open(os.path.join(path, f"s{index}.ok")) as f:
            marker = json.load(f)
        if marker.get("version") != STAGING_VERSION:
            return None
        crcs = marker["crc"]
        files = [os.path.join(path, f"s{index}_{j}.npy")
                 for j in range(int(marker["arity"]))]
        for fpath, want in zip(files, crcs):
            got = file_crc32(fpath)
            if got != want:
                logger.warning(
                    "staging cache shard %s is corrupt (crc %08x != "
                    "committed %08x) — treating as a miss and restaging",
                    fpath, got, want)
                return None
        return tuple(np.load(fpath, mmap_mode="r", allow_pickle=False)
                     for fpath in files)
    except Exception:
        logger.debug("staging cache miss for %s shard %d",
                     key, index, exc_info=True)
        return None


def save_meta(cache_dir: str, key: str, num_shards: int,
              subspace: Optional[dict] = None) -> None:
    """Finalize an entry: subspace join arrays + the completion record
    (``meta.json``, written last — its presence means COMPLETE)."""
    path = os.path.join(cache_dir, key)
    os.makedirs(path, exist_ok=True)
    for name, a in (subspace or {}).items():
        _atomic_write(os.path.join(path, f"sub_{name}.npy"),
                      lambda f, _a=a: np.save(f, np.asarray(_a),
                                              allow_pickle=False))
    meta = json.dumps({"version": STAGING_VERSION,
                       "num_shards": int(num_shards),
                       "subspace": sorted(subspace or {})}).encode()
    _atomic_write(os.path.join(path, "meta.json"),
                  lambda f: f.write(meta))


def load_subspace(cache_dir: str, key: str,
                  expected_shards: Optional[int] = None
                  ) -> Optional[dict]:
    """The subspace arrays of a COMPLETE entry (None when the entry is
    absent, partial, version-skewed, or shaped for a different plan)."""
    path = os.path.join(cache_dir, key)
    try:
        with open(os.path.join(path, "meta.json")) as f:
            meta = json.load(f)
        if meta.get("version") != STAGING_VERSION:
            return None
        if (expected_shards is not None
                and meta.get("num_shards") != expected_shards):
            return None
        return {name: np.load(os.path.join(path, f"sub_{name}.npy"),
                              mmap_mode="r", allow_pickle=False)
                for name in meta["subspace"]}
    except Exception:
        logger.debug("staging cache subspace miss for %s", key,
                     exc_info=True)
        return None


def save(cache_dir: str, key: str,
         shard_arrays: list[tuple[np.ndarray, ...]],
         subspace: Optional[dict] = None) -> None:
    """Convenience: persist a complete entry in one call."""
    for i, t in enumerate(shard_arrays):
        save_shard(cache_dir, key, i, t)
    save_meta(cache_dir, key, len(shard_arrays), subspace)


def load(cache_dir: str, key: str
         ) -> Optional[tuple[list[tuple[np.ndarray, ...]],
                             dict[str, np.ndarray]]]:
    """(shard_arrays, subspace) of a COMPLETE entry, or None on any miss
    (a single bad shard fails the whole-entry load; the pipeline's
    per-shard probing is what gives partial credit)."""
    path = os.path.join(cache_dir, key)
    try:
        with open(os.path.join(path, "meta.json")) as f:
            meta = json.load(f)
        if meta.get("version") != STAGING_VERSION:
            return None
        shards = []
        for i in range(int(meta["num_shards"])):
            t = load_shard(cache_dir, key, i)
            if t is None:
                return None
            shards.append(t)
        subspace = {
            name: np.load(os.path.join(path, f"sub_{name}.npy"),
                          mmap_mode="r", allow_pickle=False)
            for name in meta["subspace"]}
        return shards, subspace
    except Exception:
        logger.debug("staging cache whole-entry miss for %s", key,
                     exc_info=True)
        return None
