"""Persistent staging cache for projected random-effect coordinates.

Reference parity note: the reference pays its RandomEffectDataset build
(partition + projector construction) inside every Spark job and relies on
RDD caching within the job; re-running the driver re-pays it. Here the
host-side staging products (per-bucket projected feature blocks + column
maps + subspace join tables) persist on disk keyed by the DATASET CONTENT
DIGEST (game/descent._dataset_digest) plus every staging parameter, so a
re-fit of the same data in a fresh process skips the projection pass
entirely — at the 10M-row / 1M-entity flagship config that pass is tens of
seconds of sort/segment work per coordinate.

Layout: ``<cache_dir>/<key>/`` holding ``meta.json`` (bucket tuple arity)
and one ``.npy`` per staged array. Writers stage into a temp directory and
``os.rename`` it into place (atomic on one filesystem), so readers never
observe a half-written entry. Loads memory-map the arrays: the host copy
is never materialized — bytes stream straight from the page cache into the
device transfer the coordinate performs anyway.

Anything unreadable (version skew, partial copy, foreign files) is treated
as a miss — the caller restages and overwrites.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import tempfile
from typing import Optional

import numpy as np

# Bump when the staged representation changes shape/meaning.
STAGING_VERSION = 1


def staging_key(dataset, norm, **params) -> str:
    """Cache key: dataset content digest + normalization digest + every
    staging parameter (bounds, seed, projection flags, …)."""
    from photon_ml_tpu.game.descent import (_dataset_digest,
                                            normalization_digest)

    h = hashlib.sha1()
    h.update(f"v{STAGING_VERSION}".encode())
    h.update(_dataset_digest(dataset).encode())
    h.update(normalization_digest(norm).encode())
    for k in sorted(params):
        h.update(f"{k}={params[k]!r};".encode())
    return h.hexdigest()


def save(cache_dir: str, key: str,
         bucket_arrays: list[tuple[np.ndarray, ...]],
         subspace: Optional[dict[str, np.ndarray]] = None) -> None:
    """Persist one coordinate's staged host arrays (atomic rename)."""
    os.makedirs(cache_dir, exist_ok=True)
    tmp = tempfile.mkdtemp(dir=cache_dir, prefix=f".{key}.tmp")
    try:
        meta = {"version": STAGING_VERSION,
                "arity": [len(t) for t in bucket_arrays],
                "subspace": sorted(subspace) if subspace else []}
        for i, t in enumerate(bucket_arrays):
            for j, a in enumerate(t):
                np.save(os.path.join(tmp, f"b{i}_{j}.npy"),
                        np.asarray(a), allow_pickle=False)
        for name, a in (subspace or {}).items():
            np.save(os.path.join(tmp, f"sub_{name}.npy"),
                    np.asarray(a), allow_pickle=False)
        with open(os.path.join(tmp, "meta.json"), "w") as f:
            json.dump(meta, f)
        final = os.path.join(cache_dir, key)
        if os.path.isdir(final):
            # Replace, never keep: the caller just restaged because load()
            # missed, so whatever sits here is stale or corrupt (a
            # concurrent GOOD writer produced identical content — swapping
            # it is harmless). Move aside first so readers only ever see a
            # complete entry at ``final``.
            old = tempfile.mkdtemp(dir=cache_dir, prefix=f".{key}.old")
            os.rename(final, os.path.join(old, "entry"))
            shutil.rmtree(old, ignore_errors=True)
        try:
            os.rename(tmp, final)
        except OSError:
            shutil.rmtree(tmp, ignore_errors=True)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise


def load(cache_dir: str, key: str
         ) -> Optional[tuple[list[tuple[np.ndarray, ...]],
                             dict[str, np.ndarray]]]:
    """(bucket_arrays, subspace) for a cached key, or None on any miss.

    Arrays come back memory-mapped (read-only)."""
    path = os.path.join(cache_dir, key)
    meta_path = os.path.join(path, "meta.json")
    try:
        with open(meta_path) as f:
            meta = json.load(f)
        if meta.get("version") != STAGING_VERSION:
            return None
        bucket_arrays = [
            tuple(np.load(os.path.join(path, f"b{i}_{j}.npy"),
                          mmap_mode="r", allow_pickle=False)
                  for j in range(arity))
            for i, arity in enumerate(meta["arity"])]
        subspace = {
            name: np.load(os.path.join(path, f"sub_{name}.npy"),
                          mmap_mode="r", allow_pickle=False)
            for name in meta["subspace"]}
        return bucket_arrays, subspace
    except Exception:
        return None
