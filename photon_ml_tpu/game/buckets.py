"""Entity bucketing: the TPU answer to RandomEffectDataset partitioning.

Reference parity: photon-api ``data/RandomEffectDataset.scala`` (build:
keyBy(REId) → ``RandomEffectDatasetPartitioner`` greedy bin-packing →
active/passive split with ``numActiveDataPointsLowerBound`` /
``numActiveDataPointsUpperBound``) and ``data/LocalDataset.scala``.

TPU-first design (SURVEY.md §2.5 P2): instead of an RDD of ragged per-entity
``LocalDataset``s solved sequentially per executor, entities are grouped
into a small number of BUCKETS by sample count (power-of-two capacities).
Each bucket is a dense padded block:

    features (E_b, cap_b, d)   labels/weights/offsets (E_b, cap_b)

so one ``vmap``-ped optimizer solves every entity in the bucket
simultaneously, and the entity axis shards over the mesh. Padding rows have
weight 0 (inert by the aggregator contract). The permutation indices into
the flat example order are kept so per-iteration offsets can be gathered
(and scores scattered) without re-bucketing.

Active/passive semantics (reference):
- entities with fewer than ``lower_bound`` examples get NO model (their
  examples are passive: scored with zero random-effect contribution);
- entities keep at most ``upper_bound`` examples for training (the rest of
  their examples are passive but still scored with the trained model).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np


@dataclasses.dataclass
class EntityBucket:
    """One padded bucket of entities with similar sample counts."""

    entity_rows: np.ndarray  # (E_b,) int32: rows into the entity table; -1 pad
    example_idx: np.ndarray  # (E_b, cap) int64: flat example indices; -1 pad
    counts: np.ndarray  # (E_b,) int32 true (capped) sample counts

    @property
    def num_entities(self) -> int:
        return int(self.entity_rows.shape[0])

    @property
    def capacity(self) -> int:
        return int(self.example_idx.shape[1])


@dataclasses.dataclass
class EntityBucketing:
    """Bucketed grouping of a dataset's examples by entity."""

    buckets: list[EntityBucket]
    num_entities: int
    trained_entities: np.ndarray  # bool (num_entities,): has a model
    # Entities dropped by the lower bound (passive-only).
    num_passive_only_entities: int
    num_passive_examples: int
    # Every bucket's entity count is a multiple of this (consumers chunking
    # the entity axis must keep slice lengths multiples of it to preserve
    # mesh-divisibility of sharded staging).
    entity_pad_multiple: int = 8


def _next_pow2(x: int) -> int:
    return 1 << max(0, int(np.ceil(np.log2(max(1, x)))))


def build_bucketing(
    entity_ids: np.ndarray,
    num_entities: int,
    lower_bound: int = 1,
    upper_bound: Optional[int] = None,
    entity_pad_multiple: int = 8,
    min_capacity: int = 8,
    rng: Optional[np.random.Generator] = None,
    counts_all: Optional[np.ndarray] = None,
) -> EntityBucketing:
    """Group example rows by entity into padded power-of-two buckets.

    ``upper_bound`` caps examples per entity (reference
    numActiveDataPointsUpperBound: keeps a random subset); ``lower_bound``
    drops entities with too few examples from training entirely.
    ``counts_all`` optionally supplies the per-entity bincount of
    ``entity_ids`` precomputed elsewhere (the ingestion layer folds it
    while decoding — GameDataset.entity_counts), skipping one pass over
    the id column here; it MUST equal ``np.bincount(entity_ids)`` up to
    trailing zeros, and the result is identical either way.
    """
    entity_ids = np.asarray(entity_ids)
    n = entity_ids.shape[0]
    # Entity ids are rows into the entity table (non-negative, bounded) —
    # the int32 sort key below would silently mis-sort ids >= 2**31 and
    # bincount would raise on negatives, so turn violations into a loud
    # error here.
    if n and (int(entity_ids.min()) < 0
              or int(entity_ids.max()) >= num_entities):
        raise ValueError(
            f"entity ids must lie in [0, {num_entities}); got range "
            f"[{int(entity_ids.min())}, {int(entity_ids.max())}]")
    # Segments come from one bincount pass instead of np.unique's second
    # sort; int32 keys sort measurably faster than int64 at 10⁷ rows (the
    # narrowing is guarded: past int32 range keep the original dtype).
    sort_keys = (entity_ids.astype(np.int32, copy=False)
                 if num_entities <= 2**31 else entity_ids)
    order = np.argsort(sort_keys, kind="stable")
    if counts_all is None:
        counts_all = np.bincount(entity_ids)
    else:
        counts_all = np.asarray(counts_all)
        if int(counts_all.sum()) != n:
            raise ValueError(
                f"precomputed counts_all sums to {int(counts_all.sum())} "
                f"but the id column has {n} rows")
    uniq = np.flatnonzero(counts_all)
    counts = counts_all[uniq]
    starts = (np.cumsum(counts) - counts).astype(np.int64)

    trained = np.zeros(num_entities, bool)
    capped = counts if upper_bound is None else np.minimum(counts, upper_bound)
    keep = counts >= max(1, lower_bound)
    num_passive_only = int((~keep).sum())
    passive_examples = int(counts[~keep].sum())
    if upper_bound is not None:
        passive_examples += int((counts - capped)[keep].sum())

    # Bucket key: power-of-two capacity of the capped count. log2 of an
    # exact power of two is exact in float64, so ceil never overshoots.
    caps = np.maximum(
        min_capacity,
        1 << np.ceil(np.log2(np.maximum(capped, 1))).astype(np.int64))
    buckets: list[EntityBucket] = []
    for cap in np.unique(caps[keep]):
        sel = np.where(keep & (caps == cap))[0]
        e_b = len(sel)
        pad_e = ((e_b + entity_pad_multiple - 1) // entity_pad_multiple
                 ) * entity_pad_multiple
        ex = np.full((pad_e, int(cap)), -1, np.int64)
        rows = np.full((pad_e,), -1, np.int32)
        cnts = np.zeros((pad_e,), np.int32)
        # One padded gather for the whole class (no per-entity loop; at
        # 10⁶ entities the loop dominated staging): lane j of entity i
        # reads order[starts[i] + j] when j < its capped count.
        c_sel = capped[sel].astype(np.int64)
        lane = np.arange(int(cap), dtype=np.int64)[None, :]
        valid = lane < c_sel[:, None]
        src = np.minimum(starts[sel][:, None] + lane, n - 1)
        ex[:e_b] = np.where(valid, order[src], -1)
        if rng is not None:
            # Random capping draws per-entity subsets; only entities whose
            # count exceeds the cap need it (same rng call sequence as the
            # historical per-entity loop: ascending entity order).
            for i in np.flatnonzero(c_sel < counts[sel]):
                u = sel[i]
                pick = rng.choice(counts[u], size=int(c_sel[i]),
                                  replace=False)
                ex[i, :c_sel[i]] = order[starts[u] + pick]
        rows[:e_b] = uniq[sel]
        cnts[:e_b] = c_sel
        trained[uniq[sel]] = True
        buckets.append(EntityBucket(entity_rows=rows, example_idx=ex,
                                    counts=cnts))

    return EntityBucketing(
        buckets=buckets,
        num_entities=num_entities,
        trained_entities=trained,
        num_passive_only_entities=num_passive_only,
        num_passive_examples=passive_examples,
        entity_pad_multiple=entity_pad_multiple,
    )


def gather_bucket_arrays(
    bucket: EntityBucket,
    *arrays: np.ndarray,
) -> tuple[np.ndarray, ...]:
    """Gather per-example arrays into the bucket's (E_b, cap, ...) layout.

    Padded slots gather row 0 but are masked by the zero weight produced by
    ``bucket_weights`` — callers must use that weight array.
    """
    idx = np.maximum(bucket.example_idx, 0)
    return tuple(a[idx] for a in arrays)


def bucket_weights(bucket: EntityBucket, weights: np.ndarray) -> np.ndarray:
    """Example weights in bucket layout with padding slots zeroed."""
    idx = np.maximum(bucket.example_idx, 0)
    w = weights[idx]
    w[bucket.example_idx < 0] = 0.0
    return w
