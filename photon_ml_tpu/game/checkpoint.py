"""Checkpoint/restart for coordinate descent.

Reference parity: the Spark reference recovers from executor loss via RDD
lineage re-execution; XLA has no lineage, so (SURVEY.md §5, failure/elastic
row) the TPU-native replacement is explicit per-(iteration, coordinate)
checkpointing of the coefficient state + progress counters, with restart
from the newest checkpoint (``--resume`` in ``cli/game_train.py``).

Layout under the checkpoint directory::

    state.json            # progress counters + history + fingerprint
                          # + per-artifact CRC32 map (the commit point)
    state.json.prev       # the PREVIOUS committed state (recovery)
    model/                # models/io.py GameModel directory (newest state)
    <artifact>.prev       # previous generation of every file the newest
                          # commit rewrote (hardlinks: one inode, no copy)
    residuals.npz         # the descent loop's (n,) score total at the
                          # committed step — restoring it (instead of
                          # re-summing per-coordinate scores) makes resume
                          # BIT-EXACT: fresh summation changes the f32
                          # accumulation order, and nonconvex coordinates
                          # (factored alternation) amplify that ~1e-7
                          # offset perturbation into ~1e-3 coefficient
                          # drift. Optional: checkpoints without it (older
                          # layouts) fall back to re-summation.

Crash-consistency model: every file write is atomic (tmp + ``os.replace``)
and ``state.json`` is the COMMIT POINT, written last. A kill mid-save
leaves either the previous state.json (the step is simply retrained on
resume — coefficient files newer than the committed step only change the
warm start of that retraining) or the new one (fully committed). There is
never a moment without a readable checkpoint.

Corruption model (docs/ROBUSTNESS.md): atomicity cannot defend against
bit rot, torn pages, or a partial copy restored from backup — corruption
that keeps files readable but wrong. Every committed artifact's CRC32
rides in ``state.json``; ``load`` verifies before trusting. On a
mismatch (or an unparseable state/model file) the manager FALLS BACK to
the previous committed generation — each save first hardlinks the files
it is about to rewrite to ``<name>.prev``, so generation N-1 survives
commit N at zero copy cost — emits a ``CheckpointRecovered`` event, and
resumes from there (the lost step is simply retrained). Both generations
corrupt → train from scratch with a warning: recovery degrades, it never
resumes silently wrong state.

Each save rewrites only the coordinate(s) that changed — the others'
coefficient files are already current on disk — so per-step checkpoint
cost is one coordinate's coefficients + two small json files, not the
whole model.

A configuration fingerprint (task, update sequence, iterations, locked
set, per-coordinate optimizer/regularization, dataset row count) is stored
alongside and validated on load: a checkpoint written under a different
configuration is discarded (with a warning) instead of silently resuming
the wrong run.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import shutil
import zlib
from typing import Optional

import numpy as np

from photon_ml_tpu import faults as flt
from photon_ml_tpu import obs
from photon_ml_tpu.game.models import CoordinateModel, GameModel
from photon_ml_tpu.game.staging_cache import file_crc32
from photon_ml_tpu.models import io as model_io
from photon_ml_tpu.utils.diskio import atomic_write
from photon_ml_tpu.types import TaskType
from photon_ml_tpu.utils import events as ev_mod

logger = logging.getLogger("photon_ml_tpu.game")

_STATE = "state.json"
_MODEL = "model"
_RESIDUALS = "residuals.npz"
_SWEEP_DIR = "sweep"
_PREV = ".prev"
_STREAM_STATE = "stream_state.npz"
_STREAM_META = "stream_meta.json"
_STREAM_DIR = "stream-step-{step}"


def _preserve_file(path: str) -> None:
    """Keep the committed generation of ``path`` alive as ``path.prev``
    before a rewrite. Hardlink (one inode, no copy); a filesystem
    without hardlinks falls back to a copy."""
    if not os.path.exists(path):
        return
    prev = path + _PREV
    try:
        os.unlink(prev)
    except OSError:
        pass  # absent or unremovable; os.link/copy below decides
    try:
        os.link(path, prev)
    except OSError:
        shutil.copy2(path, prev)


@dataclasses.dataclass
class CheckpointState:
    """Restart state: the newest models + how far the loop got."""

    models: dict[str, CoordinateModel]
    done_steps: int  # completed (iteration, coordinate) updates (linear)
    records: list[dict]  # CoordinateDescentHistory records so far
    complete: bool  # descent finished; models are the final result
    fingerprint: Optional[dict]  # config the checkpoint was written under
    residual_total: Optional["np.ndarray"] = None  # (n,) score total
    # cid -> {array name -> np.ndarray}: the gated descent's dirty-set
    # evidence (game/sweep.py CoordinateSweepState.to_arrays) — restoring
    # it makes a resumed GATED run re-derive the exact dirty sets an
    # uninterrupted run would have used (bit-exact resume).
    sweep_states: Optional[dict] = None
    recovered: bool = False  # True when this state came from the .prev
    #                          generation after a corruption fallback


class CheckpointManager:
    """Save/restore coordinate-descent state under one directory."""

    def __init__(self, directory: str):
        self.directory = directory
        # Until this process has written one FULL snapshot, incremental
        # saves are upgraded to full ones. Guards against a stale model
        # directory left by a discarded (fingerprint-mismatched) or
        # unrelated earlier run contaminating coordinates that this run's
        # `updated` lists haven't touched yet.
        self._full_snapshot_written = False
        # rel artifact path → CRC32 of its committed bytes. Complete by
        # construction: the first save of a process is a full snapshot.
        self._crcs: dict[str, int] = {}

    # -- path helpers --------------------------------------------------------

    def _abs(self, rel: str) -> str:
        return os.path.join(self.directory, rel.replace("/", os.sep))

    def _preserve(self, rel: str) -> None:
        _preserve_file(self._abs(rel))

    def stream_dir(self, step: int) -> str:
        """Directory for one descent step's MID-OPTIMIZATION streaming
        state (StreamingStateStore) — the streamed fixed-effect update
        is the multi-hour unit at flagship scale, so it checkpoints
        inside the step, not just between steps."""
        return os.path.join(self.directory, _STREAM_DIR.format(step=step))

    def _commit_file(self, rel: str) -> None:
        """Record one just-written artifact's CRC. Injected bit rot
        lands AFTER the checksum was taken over the good bytes (the
        corruption shape the CRC must catch later)."""
        path = self._abs(rel)
        self._crcs[rel] = file_crc32(path)
        flt.corrupt_file(flt.sites.CHECKPOINT_ARTIFACT, path)

    # -- write -------------------------------------------------------------

    def save(
        self,
        task: TaskType,
        models: dict[str, CoordinateModel],
        *,
        done_steps: int,
        records: list[dict],
        complete: bool = False,
        fingerprint: Optional[dict] = None,
        updated: Optional[list[str]] = None,
        residual_total: Optional["np.ndarray"] = None,
        sweep_states: Optional[dict] = None,
    ) -> None:
        """Persist state. ``updated`` names the coordinates whose
        coefficients changed since the last save (all, if None or if the
        model directory does not exist yet).

        Multi-host: only process 0 writes (the checkpoint dir is a shared
        filesystem; concurrent writers would corrupt the incremental
        layout). Loads run on every rank so control flow stays identical.
        """
        import jax

        if jax.process_index() != 0:
            return
        with obs.span("checkpoint.save", cat="checkpoint",
                      done_steps=done_steps, complete=complete):
            self._write(task, models, done_steps=done_steps,
                        records=records, complete=complete,
                        fingerprint=fingerprint, updated=updated,
                        residual_total=residual_total,
                        sweep_states=sweep_states)
        mx = obs.metrics()
        if mx is not None:
            mx.counter("photon_checkpoint_writes_total",
                       kind="descent").inc()

    def _write(self, task, models, *, done_steps, records, complete,
               fingerprint, updated, residual_total,
               sweep_states=None) -> None:
        flt.fire(flt.sites.CHECKPOINT_SAVE)
        model_dir = os.path.join(self.directory, _MODEL)
        os.makedirs(model_dir, exist_ok=True)
        write_set = (set(models)
                     if updated is None or not self._full_snapshot_written
                     else set(updated))
        meta = {}
        for cid, m in models.items():
            cmeta = model_io.coordinate_meta(m)
            sub = ("fixed-effect" if cmeta["type"] == "fixed"
                   else "random-effect")
            rel = f"{_MODEL}/{sub}/{cid}/coefficients.npz"
            if cid in write_set:
                self._preserve(rel)
                meta[cid] = model_io.save_coordinate(model_dir, cid, m)
                self._commit_file(rel)
            else:
                meta[cid] = cmeta
                if rel not in self._crcs and os.path.exists(self._abs(rel)):
                    self._crcs[rel] = file_crc32(self._abs(rel))
        meta_rel = f"{_MODEL}/metadata.json"
        self._preserve(meta_rel)
        model_io.write_metadata(model_dir, task, meta)
        self._commit_file(meta_rel)
        # Residuals before the commit point, atomically; stale files are
        # removed rather than left to pair with a state they don't match.
        res_path = os.path.join(self.directory, _RESIDUALS)
        self._preserve(_RESIDUALS)
        if residual_total is not None:
            atomic_write(res_path, lambda f: np.savez(
                f, total=np.asarray(residual_total)))
            self._commit_file(_RESIDUALS)
        else:
            if os.path.exists(res_path):
                os.remove(res_path)
            self._crcs.pop(_RESIDUALS, None)
        # Gated-sweep dirty-set state: one npz per gated coordinate,
        # under the same discipline as residuals.npz (atomic, .prev
        # preserved, CRC'd, written before the commit point). The fire()
        # is the chaos kill seam (docs/ROBUSTNESS.md ``sweep.gate_state``)
        # — bit rot coverage rides the shared checkpoint.artifact hook
        # inside _commit_file.
        stale_sweep = {r for r in self._crcs if r.startswith(
            _SWEEP_DIR + "/")}
        if sweep_states:
            flt.fire(flt.sites.SWEEP_GATE_STATE)
            os.makedirs(os.path.join(self.directory, _SWEEP_DIR),
                        exist_ok=True)
            for cid, arrays in sweep_states.items():
                rel = f"{_SWEEP_DIR}/{cid}.npz"
                self._preserve(rel)
                atomic_write(self._abs(rel),
                             lambda f, a=arrays: np.savez(
                                 f, **{k: np.asarray(v)
                                       for k, v in a.items()}))
                self._commit_file(rel)
                stale_sweep.discard(rel)
        for rel in stale_sweep:
            try:
                os.remove(self._abs(rel))
            except OSError:
                pass
            self._crcs.pop(rel, None)
        # Commit point: state.json last, atomically — carrying the CRC of
        # every artifact this generation consists of.
        self._preserve(_STATE)
        state_body = json.dumps({
            "done_steps": done_steps,
            "records": records,
            "complete": complete,
            "fingerprint": fingerprint,
            "artifacts": self._crcs,
        }, indent=2)
        atomic_write(os.path.join(self.directory, _STATE),
                     lambda f: f.write(state_body.encode()))
        self._full_snapshot_written = True
        logger.info("checkpoint committed: %d step(s) -> %s", done_steps,
                    self.directory)

    # -- read --------------------------------------------------------------

    def _read_state(self, path: str) -> Optional[dict]:
        """Parse one state file; unreadable/unparseable → None (a
        corruption signal for the caller, never an exception)."""
        if not os.path.exists(path):
            return None
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError) as e:
            logger.warning("checkpoint state %s is unreadable (%s: %s)",
                           path, type(e).__name__, e)
            return None

    def _bad_artifacts(self, state: dict) -> list[str]:
        """Artifacts of ``state`` whose on-disk bytes fail their
        committed CRC32 (missing counts as failed). Checkpoints from
        layouts without CRCs verify vacuously."""
        bad = []
        for rel, want in (state.get("artifacts") or {}).items():
            path = self._abs(rel)
            try:
                ok = file_crc32(path) == want
            except OSError:
                ok = False
            if not ok:
                bad.append(rel)
        return bad

    def _recover(self) -> Optional[dict]:
        """Fall back to the previous committed generation: restore every
        ``.prev`` artifact the previous state's CRC map vouches for, then
        re-verify. Returns the recovered state, or None when the previous
        generation is unusable too (→ train from scratch)."""
        prev_state_path = os.path.join(self.directory, _STATE + _PREV)
        prev = self._read_state(prev_state_path)
        if prev is None:
            return None
        for rel, want in (prev.get("artifacts") or {}).items():
            path = self._abs(rel)
            try:
                if os.path.exists(path) and file_crc32(path) == want:
                    continue  # current file already IS the prev content
                prev_file = path + _PREV
                if (os.path.exists(prev_file)
                        and file_crc32(prev_file) == want):
                    os.replace(prev_file, path)
            except OSError as e:
                logger.warning("checkpoint recovery could not restore %s "
                               "(%s: %s)", rel, type(e).__name__, e)
        if self._bad_artifacts(prev):
            return None
        # The previous generation is now THE committed generation.
        try:
            os.replace(prev_state_path,
                       os.path.join(self.directory, _STATE))
        except OSError:
            pass  # another rank won the race; the content is identical
        return prev

    def load(self, expected_fingerprint: Optional[dict] = None
             ) -> Optional[CheckpointState]:
        """Return the committed state, or None if absent or written under a
        different configuration than ``expected_fingerprint``.

        Verifies every artifact's CRC32 first. Corruption (CRC mismatch,
        unparseable state.json, an unloadable model file) triggers ONE
        fallback to the previous committed generation — logged and
        announced with a ``CheckpointRecovered`` event; if that
        generation is unusable too, returns None (train from scratch).
        """
        flt.fire(flt.sites.CHECKPOINT_LOAD)
        state_path = os.path.join(self.directory, _STATE)
        if not os.path.exists(state_path) \
                and not os.path.exists(state_path + _PREV):
            return None
        state = self._read_state(state_path)
        recovered = False
        reason = ""
        if state is not None:
            bad = self._bad_artifacts(state)
            if bad:
                reason = f"artifact CRC mismatch: {sorted(bad)}"
                state = None
        else:
            reason = "state.json unreadable"
        if state is None:
            state = self._recover()
            recovered = state is not None
            if not recovered:
                logger.error(
                    "checkpoint at %s is corrupt (%s) and the previous "
                    "generation is unusable — training from scratch",
                    self.directory, reason or "no committed state")
                return None
        saved_fp = state.get("fingerprint")
        if (expected_fingerprint is not None and saved_fp is not None
                and saved_fp != expected_fingerprint):
            logger.warning(
                "checkpoint at %s was written under a different "
                "configuration — discarding it and training from scratch "
                "(saved=%s expected=%s)",
                self.directory, saved_fp, expected_fingerprint)
            return None
        try:
            game = model_io.load_game_model(
                os.path.join(self.directory, _MODEL))
        except Exception as e:
            # CRC-less layouts (or a corrupt file both generations
            # share): one recovery attempt, then give up cleanly.
            if recovered:
                logger.error("recovered checkpoint at %s still does not "
                             "load (%s: %s) — training from scratch",
                             self.directory, type(e).__name__, e)
                return None
            reason = f"model load failed: {type(e).__name__}: {e}"
            state = self._recover()
            if state is None:
                logger.error(
                    "checkpoint at %s is corrupt (%s) and the previous "
                    "generation is unusable — training from scratch",
                    self.directory, reason)
                return None
            recovered = True
            saved_fp = state.get("fingerprint")
            try:
                game = model_io.load_game_model(
                    os.path.join(self.directory, _MODEL))
            except Exception as e2:
                logger.error("recovered checkpoint at %s still does not "
                             "load (%s: %s) — training from scratch",
                             self.directory, type(e2).__name__, e2)
                return None
        if recovered:
            logger.warning(
                "checkpoint at %s was corrupt (%s); recovered the "
                "previous committed generation (%d step(s)) — the lost "
                "step retrains on resume",
                self.directory, reason, int(state["done_steps"]))
            ev_mod.default_emitter.emit(ev_mod.CheckpointRecovered(
                directory=self.directory,
                done_steps=int(state["done_steps"]),
                reason=reason))
        residual_total = None
        res_path = os.path.join(self.directory, _RESIDUALS)
        if os.path.exists(res_path):
            try:
                with np.load(res_path) as z:
                    residual_total = z["total"]
            except Exception as e:
                # Descent re-sums scores when residuals are unusable —
                # correct, just not bit-exact (descent logs that path).
                logger.warning(
                    "checkpoint residuals at %s are unreadable (%s: %s) "
                    "— falling back to re-summation", res_path,
                    type(e).__name__, e)
        # Gated-sweep state: only artifacts the committed generation
        # vouches for (its CRC map) — a stale file from a discarded run
        # must not seed dirty sets. Unreadable entries degrade to None
        # for that coordinate (descent re-tracks from a forced full
        # sweep — correct, just not bit-exact, and it logs that path).
        sweep_states = None
        for rel in (state.get("artifacts") or {}):
            if not rel.startswith(_SWEEP_DIR + "/"):
                continue
            cid = os.path.basename(rel)[:-len(".npz")]
            try:
                with np.load(self._abs(rel), allow_pickle=False) as z:
                    arrays = {k: z[k] for k in z.files}
            except Exception as e:
                logger.warning(
                    "checkpoint sweep state %s is unreadable (%s: %s) — "
                    "the coordinate re-tracks from a forced full sweep",
                    rel, type(e).__name__, e)
                continue
            sweep_states = sweep_states or {}
            sweep_states[cid] = arrays
        # Seed the CRC ledger so this process's next incremental save
        # carries forward the artifacts it does not rewrite.
        self._crcs = dict(state.get("artifacts") or {})
        return CheckpointState(
            models=dict(game.models),
            done_steps=int(state["done_steps"]),
            records=list(state["records"]),
            complete=bool(state["complete"]),
            fingerprint=saved_fp,
            residual_total=residual_total,
            sweep_states=sweep_states,
            recovered=recovered,
        )


def _is_primary_rank() -> bool:
    """True on the ONE rank that owns shared checkpoint state: rank 0
    of the ``jax.distributed`` world AND rank 0 of any armed fabric
    (fabric/runtime.py). A CPU process group never initializes
    ``jax.distributed`` collectives, so the fabric rank is the gate
    that actually fires there — without it, W hosts would race one
    store directory."""
    import jax

    from photon_ml_tpu.fabric import runtime as fabric_runtime

    return jax.process_index() == 0 and fabric_runtime.rank() == 0


class StreamingStateStore:
    """Mid-L-BFGS state for the streamed fixed-effect coordinate, under
    the repo's checkpoint discipline: atomic writes, a CRC32-carrying
    commit marker written LAST, and two generations via ``.prev``
    hardlinks (docs/STREAMING.md "Checkpoint format").

    Layout under the store directory (one per descent step, from
    ``CheckpointManager.stream_dir``)::

        stream_state.npz       # optim/streaming.snapshot_state arrays
        stream_meta.json       # CRC32 + fingerprint + iteration (COMMIT)
        <both>.prev            # the previous committed generation

    A kill between the npz and meta writes leaves a newer npz with an
    older meta — ``load`` trusts the META (the commit point) and falls
    back to the ``.prev`` npz its CRC vouches for; the torn iteration
    simply re-runs on resume. Corruption of one generation degrades to
    the previous one (CheckpointRecovered event); both gone → None, and
    the coordinate re-optimizes the step from its warm start — recovery
    degrades, it never resumes silently wrong state.
    """

    def __init__(self, directory: str):
        self.directory = directory

    # -- write -------------------------------------------------------------

    def save(self, state: dict, fingerprint: Optional[dict] = None,
             environment: Optional[dict] = None) -> None:
        """Persist one iteration snapshot (rank 0 only — the store lives
        on the shared checkpoint filesystem).

        ``environment`` records where the snapshot was TAKEN (device
        count, mesh shape) — informational, never validated: the
        snapshot arrays are all device-count-free ``(d,)``/``(M, d)``
        driver state (optim/streaming.snapshot_state), and the chunk
        ranges are re-derived from ``shard_chunk_ranges(num_chunks, D′)``
        at construction, so a checkpoint written at D devices resumes at
        D′ ≠ D (docs/STREAMING.md "Elastic resume"). What MUST match
        rides in ``fingerprint``."""
        from photon_ml_tpu.utils.diskio import atomic_write, file_crc32

        if not _is_primary_rank():
            return
        with obs.span("checkpoint.stream_state", cat="checkpoint",
                      iteration=int(state["it"])):
            os.makedirs(self.directory, exist_ok=True)
            flt.fire(flt.sites.STREAM_CHECKPOINT_WRITE)
            path = os.path.join(self.directory, _STREAM_STATE)
            _preserve_file(path)
            arrays = {k: np.asarray(v) for k, v in state.items()}
            atomic_write(path, lambda f: np.savez(f, **arrays))
            # CRC over the GOOD bytes first, injected bit rot after — the
            # corruption shape load() must catch. Distinct corrupt-hook
            # site (the convention of checkpoint.save /
            # checkpoint.artifact): fire() and corrupt_file() each count
            # occurrences, so sharing a name would interleave the two
            # hooks' occurrence spaces.
            crc = file_crc32(path)
            flt.corrupt_file(flt.sites.STREAM_CHECKPOINT_ARTIFACT, path)
            meta_path = os.path.join(self.directory, _STREAM_META)
            _preserve_file(meta_path)
            atomic_write(meta_path, lambda f: f.write(json.dumps({
                "crc": crc,
                "iteration": int(state["it"]),
                "fingerprint": fingerprint,
                "environment": environment,
            }).encode()))
        mx = obs.metrics()
        if mx is not None:
            mx.counter("photon_checkpoint_writes_total",
                       kind="stream").inc()
        logger.debug("stream state committed: iteration %d -> %s",
                     int(state["it"]), self.directory)

    # -- read --------------------------------------------------------------

    def _read_meta(self, path: str) -> Optional[dict]:
        if not os.path.exists(path):
            return None
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError) as e:
            logger.warning("stream meta %s unreadable (%s: %s)", path,
                           type(e).__name__, e)
            return None

    def _load_generation(self, meta: Optional[dict]) -> Optional[dict]:
        """The npz whose CRC the given meta vouches for: the current
        file, or its ``.prev`` (a kill between npz and meta writes)."""
        from photon_ml_tpu.utils.diskio import file_crc32

        if meta is None:
            return None
        path = os.path.join(self.directory, _STREAM_STATE)
        for cand in (path, path + _PREV):
            try:
                if os.path.exists(cand) and \
                        file_crc32(cand) == int(meta["crc"]):
                    with np.load(cand, allow_pickle=False) as z:
                        return {k: z[k] for k in z.files}
            except (OSError, ValueError, KeyError, zlib.error) as e:
                logger.warning("stream state %s unusable (%s: %s)", cand,
                               type(e).__name__, e)
        return None

    def load(self, expected_fingerprint: Optional[dict] = None,
             environment: Optional[dict] = None) -> Optional[dict]:
        """The newest committed snapshot, or None (absent, corrupt in
        both generations, or written under a different fingerprint —
        the step then re-optimizes from its warm start).

        ``environment`` is the LOADER's device environment; when it
        differs from the one recorded at save time the resume is
        ELASTIC — announced loudly (a D→D′ resume changes accumulation
        order, so values drift within the sharded-parity tolerance
        instead of staying byte-equal) but never rejected: that is the
        preemptible-hardware contract (docs/STREAMING.md)."""
        flt.fire(flt.sites.STREAM_CHECKPOINT_LOAD)
        meta_path = os.path.join(self.directory, _STREAM_META)
        meta = self._read_meta(meta_path)
        state = self._load_generation(meta)
        recovered = False
        if state is None:
            prev = self._read_meta(meta_path + _PREV)
            state = self._load_generation(prev)
            if state is None:
                if meta is not None or prev is not None:
                    logger.error(
                        "stream checkpoint at %s is corrupt in both "
                        "generations — the step re-optimizes from its "
                        "warm start", self.directory)
                return None
            meta = prev
            recovered = True
        saved_fp = meta.get("fingerprint")
        if (expected_fingerprint is not None and saved_fp is not None
                and saved_fp != expected_fingerprint):
            logger.warning(
                "stream checkpoint at %s was written under a different "
                "configuration — discarding (saved=%s expected=%s)",
                self.directory, saved_fp, expected_fingerprint)
            return None
        if recovered:
            logger.warning(
                "stream checkpoint at %s was corrupt; recovered the "
                "previous committed generation (iteration %d) — the torn "
                "iteration re-runs", self.directory,
                int(meta["iteration"]))
            ev_mod.default_emitter.emit(ev_mod.CheckpointRecovered(
                directory=self.directory,
                done_steps=int(meta["iteration"]),
                reason="stream state CRC mismatch"))
        saved_env = meta.get("environment")
        if (environment is not None and saved_env is not None
                and saved_env != environment):
            logger.warning(
                "ELASTIC resume at %s: snapshot written under %s, "
                "resuming under %s — chunk ranges re-shard over the new "
                "device count; expect sharded-parity (not byte) "
                "agreement with the writing run", self.directory,
                saved_env, environment)
        return state

    def clear(self) -> None:
        """Remove the store (the step committed; its mid-step state is
        stale and must not leak into a later run's resume)."""
        if not _is_primary_rank():
            return
        shutil.rmtree(self.directory, ignore_errors=True)
