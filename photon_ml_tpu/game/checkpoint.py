"""Checkpoint/restart for coordinate descent.

Reference parity: the Spark reference recovers from executor loss via RDD
lineage re-execution; XLA has no lineage, so (SURVEY.md §5, failure/elastic
row) the TPU-native replacement is explicit per-(iteration, coordinate)
checkpointing of the coefficient state + progress counters, with restart
from the newest checkpoint (``--resume`` in ``cli/game_train.py``).

Layout under the checkpoint directory::

    state.json            # progress counters + history + fingerprint
    model/                # models/io.py GameModel directory (newest state)
    residuals.npz         # the descent loop's (n,) score total at the
                          # committed step — restoring it (instead of
                          # re-summing per-coordinate scores) makes resume
                          # BIT-EXACT: fresh summation changes the f32
                          # accumulation order, and nonconvex coordinates
                          # (factored alternation) amplify that ~1e-7
                          # offset perturbation into ~1e-3 coefficient
                          # drift. Optional: checkpoints without it (older
                          # layouts) fall back to re-summation.

Crash-consistency model: every file write is atomic (tmp + ``os.replace``)
and ``state.json`` is the COMMIT POINT, written last. A kill mid-save
leaves either the previous state.json (the step is simply retrained on
resume — coefficient files newer than the committed step only change the
warm start of that retraining) or the new one (fully committed). There is
never a moment without a readable checkpoint.

Each save rewrites only the coordinate(s) that changed — the others'
coefficient files are already current on disk — so per-step checkpoint
cost is one coordinate's coefficients + two small json files, not the
whole model.

A configuration fingerprint (task, update sequence, iterations, locked
set, per-coordinate optimizer/regularization, dataset row count) is stored
alongside and validated on load: a checkpoint written under a different
configuration is discarded (with a warning) instead of silently resuming
the wrong run.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
from typing import Optional

import numpy as np

from photon_ml_tpu.game.models import CoordinateModel, GameModel
from photon_ml_tpu.models import io as model_io
from photon_ml_tpu.types import TaskType

logger = logging.getLogger("photon_ml_tpu.game")

_STATE = "state.json"
_MODEL = "model"
_RESIDUALS = "residuals.npz"


@dataclasses.dataclass
class CheckpointState:
    """Restart state: the newest models + how far the loop got."""

    models: dict[str, CoordinateModel]
    done_steps: int  # completed (iteration, coordinate) updates (linear)
    records: list[dict]  # CoordinateDescentHistory records so far
    complete: bool  # descent finished; models are the final result
    fingerprint: Optional[dict]  # config the checkpoint was written under
    residual_total: Optional["np.ndarray"] = None  # (n,) score total


class CheckpointManager:
    """Save/restore coordinate-descent state under one directory."""

    def __init__(self, directory: str):
        self.directory = directory
        # Until this process has written one FULL snapshot, incremental
        # saves are upgraded to full ones. Guards against a stale model
        # directory left by a discarded (fingerprint-mismatched) or
        # unrelated earlier run contaminating coordinates that this run's
        # `updated` lists haven't touched yet.
        self._full_snapshot_written = False

    # -- write -------------------------------------------------------------

    def save(
        self,
        task: TaskType,
        models: dict[str, CoordinateModel],
        *,
        done_steps: int,
        records: list[dict],
        complete: bool = False,
        fingerprint: Optional[dict] = None,
        updated: Optional[list[str]] = None,
        residual_total: Optional["np.ndarray"] = None,
    ) -> None:
        """Persist state. ``updated`` names the coordinates whose
        coefficients changed since the last save (all, if None or if the
        model directory does not exist yet).

        Multi-host: only process 0 writes (the checkpoint dir is a shared
        filesystem; concurrent writers would corrupt the incremental
        layout). Loads run on every rank so control flow stays identical.
        """
        import jax

        if jax.process_index() != 0:
            return
        model_dir = os.path.join(self.directory, _MODEL)
        os.makedirs(model_dir, exist_ok=True)
        write_set = (set(models)
                     if updated is None or not self._full_snapshot_written
                     else set(updated))
        meta = {}
        for cid, m in models.items():
            if cid in write_set:
                meta[cid] = model_io.save_coordinate(model_dir, cid, m)
            else:
                meta[cid] = model_io.coordinate_meta(m)
        model_io.write_metadata(model_dir, task, meta)
        # Residuals before the commit point, atomically; stale files are
        # removed rather than left to pair with a state they don't match.
        res_path = os.path.join(self.directory, _RESIDUALS)
        if residual_total is not None:
            tmp = res_path + ".tmp"
            with open(tmp, "wb") as f:
                np.savez(f, total=np.asarray(residual_total))
            os.replace(tmp, res_path)
        elif os.path.exists(res_path):
            os.remove(res_path)
        # Commit point: state.json last, atomically.
        tmp = os.path.join(self.directory, _STATE + ".tmp")
        with open(tmp, "w") as f:
            json.dump({
                "done_steps": done_steps,
                "records": records,
                "complete": complete,
                "fingerprint": fingerprint,
            }, f, indent=2)
        os.replace(tmp, os.path.join(self.directory, _STATE))
        self._full_snapshot_written = True
        logger.info("checkpoint committed: %d step(s) -> %s", done_steps,
                    self.directory)

    # -- read --------------------------------------------------------------

    def load(self, expected_fingerprint: Optional[dict] = None
             ) -> Optional[CheckpointState]:
        """Return the committed state, or None if absent or written under a
        different configuration than ``expected_fingerprint``."""
        state_path = os.path.join(self.directory, _STATE)
        if not os.path.exists(state_path):
            return None
        with open(state_path) as f:
            state = json.load(f)
        saved_fp = state.get("fingerprint")
        if (expected_fingerprint is not None and saved_fp is not None
                and saved_fp != expected_fingerprint):
            logger.warning(
                "checkpoint at %s was written under a different "
                "configuration — discarding it and training from scratch "
                "(saved=%s expected=%s)",
                self.directory, saved_fp, expected_fingerprint)
            return None
        game = model_io.load_game_model(os.path.join(self.directory, _MODEL))
        residual_total = None
        res_path = os.path.join(self.directory, _RESIDUALS)
        if os.path.exists(res_path):
            with np.load(res_path) as z:
                residual_total = z["total"]
        return CheckpointState(
            models=dict(game.models),
            done_steps=int(state["done_steps"]),
            records=list(state["records"]),
            complete=bool(state["complete"]),
            fingerprint=saved_fp,
            residual_total=residual_total,
        )
