"""Per-entity feature-subspace projection for random effects.

Reference parity: photon-lib ``projector/LinearSubspaceProjector.scala``
(global feature space ↔ the subspace of features actually present in one
entity's data; pure index-set math) and photon-api
``projector/IndexMapProjectorRDD.scala`` (build one projector per entity,
project active data forward and trained models back).

TPU-first design: instead of one projector object per entity, a bucket of
entities carries ONE (E_b, d_active) int32 column-index matrix ``cols``:

    cols[e, j] = global column of entity e's j-th active feature (−1 pad)

Features are gathered straight into projected bucket layout on the host —
``X[example_idx[:, :, None], cols[:, None, :]]`` — so the dense
(E_b, cap, d) block is never materialized; solves run at d_active ≪ d.
Coefficients live in the full space (the (E, d) table) and are
gathered/scattered through ``cols`` on device (projectForward /
projectBackward).

Conventions:
- If the shard has an intercept column it is ALWAYS active and is placed at
  projected slot 0, giving a static intercept index for regularization
  masks and normalization shift-folding under ``vmap``.
- Padded slots (cols == −1) have features zeroed, normalization factor 1 and
  shift 0 (factor 1, not 0 — ``model_to_transformed_space`` divides by the
  factor), and warm starts zeroed, so their coefficients stay exactly 0 and
  contribute nothing to value/gradient; the backward scatter drops them.
- ``d_active`` is one power-of-two bucket-wide width (max over the bucket's
  entities) — entities in a bucket share one padded projected width, the
  shape-bucketing trick applied to the feature axis.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from photon_ml_tpu.game.buckets import EntityBucket


@dataclasses.dataclass
class BucketProjection:
    """Per-entity active-column index map for one bucket."""

    cols: np.ndarray  # (E_b, d_active) int32 global column ids; -1 pad
    d_active: int

    @property
    def num_entities(self) -> int:
        return int(self.cols.shape[0])


def _next_pow2(x: int) -> int:
    return 1 << max(0, int(np.ceil(np.log2(max(1, x)))))


def pearson_scores(X: np.ndarray, y: np.ndarray) -> np.ndarray:
    """|Pearson correlation| of each feature column with the labels.

    Reference parity: photon-api ``data/LocalDataset.scala``
    ``stableComputePearsonCorrelationScore`` — zero-variance columns (and a
    zero-variance label) score 0 rather than NaN, so constant features are
    filtered out unless they are the intercept (which the caller always
    keeps).
    """
    y = y.astype(np.float64)
    Xc = X.astype(np.float64) - X.mean(axis=0, dtype=np.float64)
    yc = y - y.mean()
    cov = Xc.T @ yc
    denom = np.sqrt((Xc * Xc).sum(axis=0) * (yc * yc).sum())
    out = np.zeros(X.shape[1], np.float64)
    np.divide(np.abs(cov), denom, out=out, where=denom > 1e-12)
    return out


def build_bucket_projection(
    bucket: EntityBucket,
    X: np.ndarray,
    intercept_index: Optional[int],
    min_dim: int = 8,
    labels: Optional[np.ndarray] = None,
    features_to_samples_ratio: Optional[float] = None,
) -> BucketProjection:
    """Compute each entity's active feature subspace for one bucket.

    A column is active for an entity iff any of the entity's (kept) examples
    has a nonzero value there (reference LinearSubspaceProjector: the index
    set of features present in the entity's data).

    ``features_to_samples_ratio`` additionally caps each entity's subspace
    at ``ceil(ratio · num_samples)`` columns, keeping the highest
    |Pearson corr(feature, label)| ones (reference
    ``LocalDataset.filterFeaturesByPearsonCorrelationScore`` driven by
    ``RandomEffectDataConfiguration.numFeaturesToSamplesRatio``). The
    intercept is always kept and counts toward the cap, matching the
    reference (it assigns the intercept the maximal score).
    """
    if features_to_samples_ratio is not None and labels is None:
        raise ValueError("features_to_samples_ratio needs labels")
    d = X.shape[1]
    ex = bucket.example_idx  # (E_b, cap), -1 pad
    live_rows = bucket.entity_rows >= 0
    # (E_b, cap, d) boolean would be large; go entity-by-entity (one-time
    # host staging cost, ~O(nnz)).
    active_sets: list[np.ndarray] = []
    max_active = 1
    for e in range(ex.shape[0]):
        if not live_rows[e]:
            active_sets.append(np.empty((0,), np.int64))
            continue
        idx = ex[e]
        idx = idx[idx >= 0]
        Xe = X[idx]
        mask = np.any(Xe != 0.0, axis=0)
        if intercept_index is not None:
            mask[intercept_index] = True
        cols_e = np.flatnonzero(mask)
        if features_to_samples_ratio is not None:
            keep = int(np.ceil(features_to_samples_ratio * len(idx)))
            keep = max(1, keep)
            if len(cols_e) > keep:
                scores = pearson_scores(Xe[:, cols_e], labels[idx])
                if intercept_index is not None:
                    scores[cols_e == intercept_index] = np.inf
                # Stable top-k: sort by (-score, col) so ties break on the
                # lower column id deterministically.
                order_e = np.lexsort((cols_e, -scores))[:keep]
                cols_e = np.sort(cols_e[order_e])
        if intercept_index is not None:
            # Intercept first: static projected intercept slot 0.
            cols_e = np.concatenate(
                [[intercept_index], cols_e[cols_e != intercept_index]])
        active_sets.append(cols_e)
        max_active = max(max_active, len(cols_e))

    d_active = min(d, max(min_dim, _next_pow2(max_active)))
    # An entity with more active columns than d_active cannot be truncated —
    # widen (can only happen via min() capping above, where d_active == d).
    cols = np.full((ex.shape[0], d_active), -1, np.int32)
    for e, cols_e in enumerate(active_sets):
        cols[e, : len(cols_e)] = cols_e
    return BucketProjection(cols=cols, d_active=d_active)


def gather_projected_features(
    bucket: EntityBucket,
    projection: BucketProjection,
    X: np.ndarray,
) -> np.ndarray:
    """Project features forward into (E_b, cap, d_active) bucket layout.

    Padded example rows and padded column slots are zeroed (inert under the
    zero-weight / zero-feature contracts).
    """
    ex = np.maximum(bucket.example_idx, 0)  # (E_b, cap)
    cols = np.maximum(projection.cols, 0)  # (E_b, d_active)
    Xp = X[ex[:, :, None], cols[:, None, :]].astype(X.dtype, copy=False)
    Xp = np.where(projection.cols[:, None, :] < 0, 0.0, Xp)
    Xp = np.where(bucket.example_idx[:, :, None] < 0, 0.0, Xp)
    return np.ascontiguousarray(Xp)


def project_norm_arrays(
    projection: BucketProjection,
    factors: Optional[np.ndarray],
    shifts: Optional[np.ndarray],
) -> tuple[Optional[np.ndarray], Optional[np.ndarray]]:
    """Project normalization factors/shifts into each entity's subspace.

    Padded slots get factor 1 / shift 0 (the intercept-column convention):
    with their features zeroed by ``gather_projected_features`` the
    transformed feature (0 − 0)·1 is identically 0, so padded coordinates
    see zero gradient and stay at their (zeroed) warm start, while the
    model-space transforms (divide by factor, shift-mass sums) remain
    well-defined.
    """
    cols = np.maximum(projection.cols, 0)
    pad = projection.cols < 0
    f_p = None
    if factors is not None:
        f_p = np.asarray(factors)[cols].astype(np.float32)
        f_p[pad] = 1.0
    s_p = None
    if shifts is not None:
        s_p = np.asarray(shifts)[cols].astype(np.float32)
        s_p[pad] = 0.0
    return f_p, s_p
