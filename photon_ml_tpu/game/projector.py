"""Per-entity feature-subspace projection for random effects.

Reference parity: photon-lib ``projector/LinearSubspaceProjector.scala``
(global feature space ↔ the subspace of features actually present in one
entity's data; pure index-set math) and photon-api
``projector/IndexMapProjectorRDD.scala`` (build one projector per entity,
project active data forward and trained models back).

TPU-first design: instead of one projector object per entity, a bucket of
entities carries ONE (E_b, d_active) int32 column-index matrix ``cols``:

    cols[e, j] = global column of entity e's j-th active feature (−1 pad)

Features are gathered straight into projected bucket layout on the host —
dense shards via ``X[example_idx[:, :, None], cols[:, None, :]]``, sparse
(ELL) shards via an O(nnz) scatter of their stored triplets — so the dense
(E_b, cap, d) block is never materialized; solves run at d_active ≪ d.
For sparse shards not even the (n, d) matrix ever exists: the ELL indices
ARE the per-entity active sets (the reference's RandomEffectDataset keeps
per-entity sparse Breeze rows for exactly this reason). Coefficients live
in the full space (the (E, d) table) and are gathered/scattered through
``cols`` on device (projectForward / projectBackward).

Everything here is vectorized numpy over nonzero triplets — one sort +
segment pass per bucket, no per-entity Python loops — so staging scales to
10⁶ entities (SURVEY §2.1: RandomEffectDatasetPartitioner runs over every
entity; this is the one-time host cost that must not dominate).

Conventions:
- If the shard has an intercept column it is ALWAYS active and is placed at
  projected slot 0, giving a static intercept index for regularization
  masks and normalization shift-folding under ``vmap``.
- Padded slots (cols == −1) have features zeroed, normalization factor 1 and
  shift 0 (factor 1, not 0 — ``model_to_transformed_space`` divides by the
  factor), and warm starts zeroed, so their coefficients stay exactly 0 and
  contribute nothing to value/gradient; the backward scatter drops them.
- ``d_active`` is one power-of-two bucket-wide width (max over the bucket's
  entities) — entities in a bucket share one padded projected width, the
  shape-bucketing trick applied to the feature axis.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from photon_ml_tpu.game.buckets import EntityBucket


@dataclasses.dataclass
class BucketProjection:
    """Per-entity active-column index map for one bucket."""

    cols: np.ndarray  # (E_b, d_active) int32 global column ids; -1 pad
    d_active: int

    @property
    def num_entities(self) -> int:
        return int(self.cols.shape[0])


def _next_pow2(x: int) -> int:
    return 1 << max(0, int(np.ceil(np.log2(max(1, x)))))


def pearson_scores(X: np.ndarray, y: np.ndarray) -> np.ndarray:
    """|Pearson correlation| of each feature column with the labels.

    Reference parity: photon-api ``data/LocalDataset.scala``
    ``stableComputePearsonCorrelationScore`` — zero-variance columns (and a
    zero-variance label) score 0 rather than NaN, so constant features are
    filtered out unless they are the intercept (which the caller always
    keeps).
    """
    y = y.astype(np.float64)
    Xc = X.astype(np.float64) - X.mean(axis=0, dtype=np.float64)
    yc = y - y.mean()
    cov = Xc.T @ yc
    denom = np.sqrt((Xc * Xc).sum(axis=0) * (yc * yc).sum())
    out = np.zeros(X.shape[1], np.float64)
    np.divide(np.abs(cov), denom, out=out, where=denom > 1e-12)
    return out


def shard_coo(X) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(rows, cols, vals) nonzero triplets of a dense matrix or SparseShard.

    For sparse shards this reads straight off the ELL arrays in O(nnz) —
    no dense (n, d) scan ever happens; padding slots (index == d, value 0)
    and explicit zeros are dropped. Callers staging several buckets compute
    this once per shard and pass it down.
    """
    from photon_ml_tpu.data.game_data import SparseShard

    if isinstance(X, SparseShard):
        idx = np.asarray(X.indices)
        val = np.asarray(X.values)
        valid = (idx < X.num_features) & (val != 0.0)
        rows = np.broadcast_to(
            np.arange(idx.shape[0], dtype=np.int32)[:, None],
            idx.shape)[valid]
        return rows, idx[valid].astype(np.int32), val[valid]
    X = np.asarray(X)
    rows, cols = np.nonzero(X)
    # Values keep the shard's own dtype (f32 shards stay compact; f64
    # inputs keep full precision for the Pearson moments).
    return rows.astype(np.int32), cols.astype(np.int32), X[rows, cols]


def _shard_shape(X) -> tuple[int, int]:
    from photon_ml_tpu.data.game_data import SparseShard

    if isinstance(X, SparseShard):
        return X.shape
    return int(X.shape[0]), int(X.shape[1])


@dataclasses.dataclass
class BucketTriplets:
    """One bucket's slice of a shard's nonzero triplets plus the reverse
    example-row maps — computed once per bucket and shared by
    ``build_bucket_projection`` and ``gather_projected_features`` so the
    O(n_rows) map build and O(nnz) filtering run once, not twice.

    The parallel staging pipeline (game/staging.py) builds these for lane
    SLICES of a bucket: ``lanes`` are then local to the slice, the map
    arrays are None, and the per-triplet ``cappos`` carries what
    ``cappos_of[rows]`` would have gathered — the slice never needs the
    O(n_rows) global maps (which would have to be pickled per task in
    process mode)."""

    rows: np.ndarray  # filtered triplet rows (this bucket's kept examples)
    cols: np.ndarray  # int64 global columns
    vals: np.ndarray  # shard-dtype values
    lanes: np.ndarray  # int64 lane per triplet
    lane_of: Optional[np.ndarray] = None  # (n_rows,) int32 lane; -1 outside
    cappos_of: Optional[np.ndarray] = None  # (n_rows,) int32 slot within cap
    cappos: Optional[np.ndarray] = None  # per-triplet slot (replaces map)


def bucket_triplets(
    bucket: EntityBucket,
    X,
    coo: Optional[tuple[np.ndarray, np.ndarray, np.ndarray]] = None,
) -> BucketTriplets:
    n_rows, _ = _shard_shape(X)
    if coo is None:
        coo = shard_coo(X)
    rows_nz, cols_nz, vals_nz = coo
    lane_of, cappos_of = _lane_maps(bucket, n_rows)
    sel = lane_of[rows_nz] >= 0
    r = rows_nz[sel]
    return BucketTriplets(
        lane_of=lane_of, cappos_of=cappos_of, rows=r,
        cols=cols_nz[sel].astype(np.int64), vals=vals_nz[sel],
        lanes=lane_of[r].astype(np.int64))


def all_bucket_triplets(
    buckets: list,
    X,
    coo: Optional[tuple[np.ndarray, np.ndarray, np.ndarray]] = None,
) -> list[BucketTriplets]:
    """Per-bucket triplet slices for EVERY bucket in one pass.

    ``bucket_triplets`` rebuilds an O(n_rows) reverse map and re-gathers it
    over all O(nnz) triplets per bucket; since each example row belongs to
    at most one bucket, one global (bucket, lane, slot) map and ONE nnz
    gather serve every bucket — at 10M rows / 80M nnz / 4 buckets this is
    the difference between ~10 s and ~2 s of staging. The returned
    ``lane_of``/``cappos_of`` maps are the shared GLOBAL maps (lanes of
    other buckets included); per-bucket consumers only ever read them at
    their own bucket's rows, where the values agree with the per-bucket
    build."""
    n_rows, _ = _shard_shape(X)
    if coo is None:
        coo = shard_coo(X)
    rows_nz, cols_nz, vals_nz = coo
    bucket_of = np.full(n_rows, -1, np.int16)
    lane_of = np.full(n_rows, -1, np.int32)
    cappos_of = np.zeros(n_rows, np.int32)
    if len(buckets) >= 2 ** 15:
        raise ValueError(f"{len(buckets)} buckets overflow the int16 map")
    for bi, b in enumerate(buckets):
        ex = b.example_idx
        kept = ex >= 0
        rk = ex[kept]
        bucket_of[rk] = bi
        lane_of[rk] = np.broadcast_to(
            np.arange(ex.shape[0], dtype=np.int32)[:, None], ex.shape)[kept]
        cappos_of[rk] = np.broadcast_to(
            np.arange(ex.shape[1], dtype=np.int32)[None, :], ex.shape)[kept]
    tb = bucket_of[rows_nz]  # the one nnz-sized gather
    out = []
    for bi in range(len(buckets)):
        sel = tb == bi
        r = rows_nz[sel]
        out.append(BucketTriplets(
            lane_of=lane_of, cappos_of=cappos_of, rows=r,
            cols=cols_nz[sel].astype(np.int64), vals=vals_nz[sel],
            lanes=lane_of[r].astype(np.int64)))
    return out


def _lane_maps(bucket: EntityBucket, n_rows: int
               ) -> tuple[np.ndarray, np.ndarray]:
    """Reverse maps example row → (bucket lane, slot within cap); −1 lane
    for rows outside this bucket (other buckets / dropped by upper_bound)."""
    ex = bucket.example_idx
    kept = ex >= 0
    lane_of = np.full(n_rows, -1, np.int32)
    cappos_of = np.zeros(n_rows, np.int32)
    lane_of[ex[kept]] = np.broadcast_to(
        np.arange(ex.shape[0], dtype=np.int32)[:, None], ex.shape)[kept]
    cappos_of[ex[kept]] = np.broadcast_to(
        np.arange(ex.shape[1], dtype=np.int32)[None, :], ex.shape)[kept]
    return lane_of, cappos_of


def build_bucket_projection(
    bucket: EntityBucket,
    X,
    intercept_index: Optional[int],
    min_dim: int = 8,
    labels: Optional[np.ndarray] = None,
    features_to_samples_ratio: Optional[float] = None,
    coo: Optional[tuple[np.ndarray, np.ndarray, np.ndarray]] = None,
    triplets: Optional[BucketTriplets] = None,
) -> BucketProjection:
    """Compute each entity's active feature subspace for one bucket.

    A column is active for an entity iff any of the entity's (kept) examples
    has a nonzero value there (reference LinearSubspaceProjector: the index
    set of features present in the entity's data). ``X`` may be a dense
    (n, d) matrix or a SparseShard; pass ``coo=shard_coo(X)`` to reuse the
    triplet extraction across buckets, and ``triplets`` to additionally
    share the per-bucket filtering with ``gather_projected_features``.

    ``features_to_samples_ratio`` additionally caps each entity's subspace
    at ``ceil(ratio · num_samples)`` columns, keeping the highest
    |Pearson corr(feature, label)| ones (reference
    ``LocalDataset.filterFeaturesByPearsonCorrelationScore`` driven by
    ``RandomEffectDataConfiguration.numFeaturesToSamplesRatio``). The
    intercept is always kept and counts toward the cap, matching the
    reference (it assigns the intercept the maximal score). Pearson moments
    come from the same nonzero triplets (zeros contribute only to counts),
    identical in exact arithmetic to ``pearson_scores`` on dense columns.

    One sort + segment-reduce pass over the bucket's nonzeros — no
    per-entity loops.
    """
    if features_to_samples_ratio is not None and labels is None:
        raise ValueError("features_to_samples_ratio needs labels")
    _, d = _shard_shape(X)
    ex = bucket.example_idx  # (E_b, cap), -1 pad
    E_b = ex.shape[0]
    if triplets is None:
        triplets = bucket_triplets(bucket, X, coo)
    live = np.flatnonzero(np.asarray(bucket.entity_rows) >= 0).astype(
        np.int64)
    t_y = None
    yb = None
    y0 = 0.0
    if features_to_samples_ratio is not None:
        y = np.asarray(labels, np.float64)
        t_y = y[triplets.rows]
        y0 = float(y[0]) if y.size else 0.0
        yb = y[np.maximum(ex, 0)]
        yb[ex < 0] = 0.0
    u_lane, u_col = active_pairs(
        E_b, d, intercept_index, live,
        triplets.cols, triplets.vals, triplets.lanes,
        ratio=features_to_samples_ratio, t_y=t_y, y0=y0, yb=yb,
        kept=ex >= 0)
    d_active = projection_width(
        active_lane_counts(u_lane, E_b), d, min_dim)
    cols = fill_cols(u_lane, u_col, E_b, d_active, intercept_index)
    return BucketProjection(cols=cols, d_active=int(d_active))


def active_pairs(
    E_b: int,
    d: int,
    intercept_index: Optional[int],
    live: np.ndarray,
    c: np.ndarray,
    v: np.ndarray,
    l: np.ndarray,
    ratio: Optional[float] = None,
    t_y: Optional[np.ndarray] = None,
    y0: float = 0.0,
    yb: Optional[np.ndarray] = None,
    kept: Optional[np.ndarray] = None,
) -> tuple[np.ndarray, np.ndarray]:
    """Unique active (lane, col) pairs of one bucket — or of any lane
    SLICE of one bucket, which is what makes the staging pipeline's
    entity-axis sharding exact: every computation here is per-lane
    (sorted runs never span lanes), so pairs of a slice are exactly the
    full bucket's pairs restricted to the slice's lanes.

    ``c``/``v``/``l`` are the slice's nonzero triplets (lanes LOCAL to the
    slice); ``live`` the local lanes holding a real entity. The Pearson
    cap (``ratio``) additionally needs per-triplet labels ``t_y``, the
    label of example 0 (``y0``, for the synthetic intercept entries), and
    the slice's bucket-layout labels ``yb`` + kept mask.
    """
    if intercept_index is not None:
        # Force the intercept active for every live entity via synthetic
        # zero-valued entries (harmless: the intercept's Pearson score is
        # overridden to +inf below, so its moments are never consulted).
        l = np.concatenate([l, live])
        c = np.concatenate(
            [c, np.full(live.shape, intercept_index, np.int64)])
        v = np.concatenate([v, np.zeros(live.shape, np.float32)])
        if t_y is not None:
            t_y = np.concatenate([t_y, np.full(live.shape, y0, np.float64)])

    # Unique (lane, col) pairs in (lane, col)-ascending order; key_s is
    # already sorted, so run boundaries replace a second sort in unique().
    # Keys pack as lane << shift | col when that fits int64 (cols < d ≤
    # 2^shift): the unpack is then two bit ops instead of an int64
    # divmod — measured ~5x cheaper at the 10⁷-row staging scale — and
    # the sort order is the same lexicographic (lane, col). Sort kind is
    # numpy's default introsort: keys are sorted for their VALUES only
    # (uniques + run boundaries; equal keys are indistinguishable), and
    # for int64 the "stable" kind falls back to mergesort at ~7x the cost.
    shift = int(max(d, 1)).bit_length()
    lane_bits = int(max(E_b, 1)).bit_length()
    if shift + lane_bits <= 63:
        key = (l << shift) | c
    else:  # astronomically wide: keep the exact multiplicative packing
        shift = None
        key = l * np.int64(d + 1) + c
    if ratio is None:
        key_s = np.sort(key)
    else:
        # The Pearson pass additionally needs triplet values/labels in
        # sorted order, so keep the permutation. STABLE sort: equal keys
        # (several examples of one entity hitting one column) keep their
        # original triplet order, making the per-pair reduceat moment
        # sums reproducible to the BIT between the whole-bucket build and
        # the lane-sharded parallel build (fp addition is order-
        # sensitive; introsort's equal-key order depends on array size).
        order = np.argsort(key, kind="stable")
        key_s = key[order]
    newrun_k = np.ones(key_s.shape, bool)
    if key_s.size:
        newrun_k[1:] = key_s[1:] != key_s[:-1]
    first = np.flatnonzero(newrun_k)
    uniq = key_s[first]
    if shift is not None:
        u_lane = uniq >> shift
        u_col = uniq & ((np.int64(1) << shift) - 1)
    else:
        u_lane = (uniq // (d + 1)).astype(np.int64)
        u_col = (uniq % (d + 1)).astype(np.int64)

    if ratio is not None and uniq.size:
        # Centered (two-pass) Pearson moments, the stable computation the
        # reference's stableComputePearsonCorrelationScore / the dense
        # ``pearson_scores`` use: every accumulated term is a centered
        # square or product, so a column with a huge mean and small
        # variance cannot cancel to zero. Zero entries of a column enter
        # the centered sums analytically: Σ_all (x−mx)² =
        # Σ_nz (x−mx)² + n_zero·mx², and Σ_all (x−mx)(y−my) =
        # Σ_nz (x−mx)(y−my) − mx·(Σ_zero y − n_zero·my).
        inv = np.cumsum(newrun_k) - 1  # sorted entry -> pair id
        v_s = v[order].astype(np.float64)
        y_s = t_y[order]
        cnt = np.diff(np.append(first, key_s.shape[0])).astype(np.float64)
        yb = np.where(kept, yb, 0.0)
        n_e = kept.sum(axis=1).astype(np.float64)
        ne_safe = np.maximum(n_e, 1.0)
        sy = yb.sum(axis=1)
        my = sy / ne_safe
        dyb = np.where(kept, yb - my[:, None], 0.0)
        vary_lane = (dyb * dyb).sum(axis=1)
        sx = np.add.reduceat(v_s, first)
        ne_u = ne_safe[u_lane]
        mx = sx / ne_u
        dx = v_s - mx[inv]
        dy = y_s - my[u_lane][inv]
        n_zero = ne_u - cnt
        varx = np.add.reduceat(dx * dx, first) + n_zero * mx * mx
        sy_nz = np.add.reduceat(y_s, first)
        cov = np.add.reduceat(dx * dy, first) \
            - mx * ((sy[u_lane] - sy_nz) - n_zero * my[u_lane])
        vary = vary_lane[u_lane]
        denom = np.sqrt(np.maximum(varx * vary, 0.0))
        score = np.zeros(uniq.shape, np.float64)
        np.divide(np.abs(cov), denom, out=score, where=denom > 1e-12)
        if intercept_index is not None:
            score[u_col == intercept_index] = np.inf
        keep_e = np.maximum(
            1, np.ceil(ratio * n_e)).astype(np.int64)
        # Within each lane order by (-score, col) — ties break on the lower
        # column id deterministically — and keep the first keep_e.
        ordr = np.lexsort((u_col, -score, u_lane))
        lane_o = u_lane[ordr]
        newrun = np.ones(lane_o.shape, bool)
        newrun[1:] = lane_o[1:] != lane_o[:-1]
        run_starts = np.flatnonzero(newrun)
        start_of = np.repeat(
            run_starts, np.diff(np.append(run_starts, lane_o.shape[0])))
        rank = np.arange(lane_o.shape[0]) - start_of
        kept_idx = np.sort(ordr[rank < keep_e[lane_o]])
        u_lane = u_lane[kept_idx]
        u_col = u_col[kept_idx]
    return u_lane, u_col


def active_lane_counts(u_lane: np.ndarray, E_b: int) -> np.ndarray:
    """Active-column count per lane from the unique-pair lane ids."""
    return (np.bincount(u_lane, minlength=E_b) if u_lane.size
            else np.zeros(E_b, np.int64))


def projection_width(seg_counts: np.ndarray, d: int, min_dim: int = 8
                     ) -> int:
    """Bucket-wide projected width: pow-2 of the max per-lane active
    count, floored at ``min_dim``, capped at ``d``. An entity with more
    active columns than d_active cannot be truncated — widen (can only
    happen via the min() cap, where d_active == d)."""
    max_active = max(1, int(seg_counts.max()) if seg_counts.size else 1)
    return min(d, max(min_dim, _next_pow2(max_active)))


def fill_cols(
    u_lane: np.ndarray,
    u_col: np.ndarray,
    E_b: int,
    d_active: int,
    intercept_index: Optional[int],
) -> np.ndarray:
    """(E_b, d_active) column map from sorted unique pairs, intercept
    pinned to slot 0. Pure per-lane math — exact on any lane slice."""
    seg_counts = active_lane_counts(u_lane, E_b)
    starts = np.concatenate([[0], np.cumsum(seg_counts)[:-1]])
    pos = np.arange(u_lane.shape[0]) - starts[u_lane]
    if intercept_index is not None and u_lane.size:
        # Intercept first: static projected intercept slot 0; columns below
        # the intercept's sorted position shift up by one.
        is_int = u_col == intercept_index
        p_lane = np.zeros(E_b, np.int64)
        p_lane[u_lane[is_int]] = pos[is_int]
        slot = np.where(is_int, 0,
                        np.where(pos < p_lane[u_lane], pos + 1, pos))
    else:
        slot = pos
    cols = np.full((E_b, d_active), -1, np.int32)
    cols[u_lane, slot] = u_col.astype(np.int32)
    return cols


def gather_projected_features(
    bucket: EntityBucket,
    projection: BucketProjection,
    X,
    coo: Optional[tuple[np.ndarray, np.ndarray, np.ndarray]] = None,
    triplets: Optional[BucketTriplets] = None,
) -> np.ndarray:
    """Project features forward into (E_b, cap, d_active) bucket layout.

    Padded example rows and padded column slots are zeroed (inert under the
    zero-weight / zero-feature contracts). Dense shards use one fancy
    gather; SparseShards scatter their O(nnz) triplets straight into the
    projected block — the dense (n, d) matrix never exists. Entries whose
    column was filtered out of the subspace (the Pearson cap) are dropped,
    exactly as the dense gather reads only the kept columns.
    """
    from photon_ml_tpu.data.game_data import SparseShard

    if not isinstance(X, SparseShard):
        ex = np.maximum(bucket.example_idx, 0)  # (E_b, cap)
        cols = np.maximum(projection.cols, 0)  # (E_b, d_active)
        Xp = X[ex[:, :, None], cols[:, None, :]].astype(X.dtype, copy=False)
        Xp = np.where(projection.cols[:, None, :] < 0, 0.0, Xp)
        Xp = np.where(bucket.example_idx[:, :, None] < 0, 0.0, Xp)
        return np.ascontiguousarray(Xp)

    _, d = X.shape
    E_b, cap = bucket.example_idx.shape
    if triplets is None:
        triplets = bucket_triplets(bucket, X, coo)
    return scatter_projected(E_b, cap, d, projection, triplets)


def scatter_projected(
    E_b: int,
    cap: int,
    d: int,
    projection: BucketProjection,
    triplets: BucketTriplets,
) -> np.ndarray:
    """Sparse-shard projected gather over explicit triplets: per-lane
    math only, so it is exact on lane slices of a bucket (the parallel
    staging path calls it with slice-local triplets and never needs the
    shard arrays themselves)."""
    d_active = projection.d_active
    c, v, l = triplets.cols, triplets.vals, triplets.lanes
    cp = triplets.cappos
    if cp is None:
        cp = triplets.cappos_of[triplets.rows]
    # Map (lane, global col) → projected slot through each lane's SORTED
    # active set: the flattened (lane-major, within-lane ascending) key
    # array is globally sorted, so one searchsorted resolves every entry;
    # ``perm`` carries sorted position → actual slot (intercept-first
    # reordering included, since it permutes ``projection.cols`` itself).
    cw = np.where(projection.cols < 0, d + 1, projection.cols).astype(
        np.int64)
    perm = np.argsort(cw, axis=1, kind="stable")
    sorted_cols = np.take_along_axis(cw, perm, axis=1)
    flat_keys = (np.arange(E_b, dtype=np.int64)[:, None] * (d + 2)
                 + sorted_cols).reshape(-1)
    want = l * np.int64(d + 2) + c
    gpos = np.searchsorted(flat_keys, want)
    inset = flat_keys[np.minimum(gpos, flat_keys.size - 1)] == want
    cp, v, l, gpos = cp[inset], v[inset], l[inset], gpos[inset]
    slot = perm[l, gpos - l * d_active]
    Xp = np.zeros((E_b, cap, d_active), np.float32)
    Xp[l, cp, slot] = v.astype(np.float32)
    return Xp


def project_norm_arrays(
    projection: BucketProjection,
    factors: Optional[np.ndarray],
    shifts: Optional[np.ndarray],
) -> tuple[Optional[np.ndarray], Optional[np.ndarray]]:
    """Project normalization factors/shifts into each entity's subspace.

    Padded slots get factor 1 / shift 0 (the intercept-column convention):
    with their features zeroed by ``gather_projected_features`` the
    transformed feature (0 − 0)·1 is identically 0, so padded coordinates
    see zero gradient and stay at their (zeroed) warm start, while the
    model-space transforms (divide by factor, shift-mass sums) remain
    well-defined.
    """
    cols = np.maximum(projection.cols, 0)
    pad = projection.cols < 0
    f_p = None
    if factors is not None:
        f_p = np.asarray(factors)[cols].astype(np.float32)
        f_p[pad] = 1.0
    s_p = None
    if shifts is not None:
        s_p = np.asarray(shifts)[cols].astype(np.float32)
        s_p[pad] = 0.0
    return f_p, s_p
