"""Replicated serving driver: N scoring replicas behind an
entity-affinity router (docs/SERVING.md "Scaling out").

One process, one device cannot serve "millions of users" (ROADMAP item
3); this driver spawns ``--replicas`` full ``photon-game-serve``
subprocesses over the same model, hash-assigns routing shards to them so
every entity's requests land on one replica (its device LRU stays hot),
and fronts them with one HTTP door that survives replica death:
health probes + heartbeat deadlines, shard re-homing to survivors within
``--rehome-deadline-s``, bounded-retry forwards with optional hedged
second-sends, and supervised restart (photon_ml_tpu/serving/fleet.py).

Quickstart:

    photon-game-fleet --model-dir out/best --replicas 4 --port 8080
    curl -s localhost:8080/score -d '{"requests": [{"features": \
        {"global": [0.1, ...]}, "entity_ids": {"userId": 7}}]}'
    curl -s localhost:8080/healthz   # degraded flag while re-homing
    curl -s localhost:8080/metrics   # photon_fleet_* lines
"""

from __future__ import annotations

import argparse
import logging
import tempfile

from photon_ml_tpu.serving.elastic import parse_elastic_config
from photon_ml_tpu.serving.fleet import (ServingFleet,
                                         make_fleet_http_server)
from photon_ml_tpu.utils.logging import setup_logging

logger = logging.getLogger("photon_ml_tpu.cli")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    # -- model flags, forwarded verbatim to every replica ----------------
    p.add_argument("--model-dir", required=True,
                   help="GameModel directory — an npz layout, a mapped "
                        "model, or a photon-boot GENERATION ROOT "
                        "(gen-*/current): replicas auto-detect the "
                        "layout and mmap-boot the current generation "
                        "for sub-second restart (docs/SERVING.md)")
    p.add_argument("--model-format", default="NPZ",
                   choices=["NPZ", "AVRO"])
    p.add_argument("--feature-index-dir",
                   help="REQUIRED with --model-format AVRO")
    p.add_argument("--entity-vocabs",
                   help="entity-vocabs.json for raw-key entity ids")
    p.add_argument("--as-mean", action="store_true")
    # -- fleet shape -----------------------------------------------------
    p.add_argument("--replicas", type=int, default=2,
                   help="scoring replica subprocesses")
    p.add_argument("--num-shards", type=int, default=None,
                   help="routing shards hash-assigned to replicas "
                        "(default max(8, 2*replicas); more shards = "
                        "finer re-home granularity)")
    p.add_argument("--route-re-type",
                   help="which entity id carries routing affinity when "
                        "requests name several (default: "
                        "lexicographically first)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8080,
                   help="fleet front door; 0 picks a free port")
    p.add_argument("--workdir", default=None,
                   help="replica logs + ready files (default: a fresh "
                        "temp dir)")
    # -- failure knobs (docs/SERVING.md failure ladder) ------------------
    p.add_argument("--probe-interval-s", type=float, default=0.25,
                   help="health-probe cadence per replica")
    p.add_argument("--heartbeat-deadline-s", type=float, default=2.0,
                   help="a replica silent this long is declared dead")
    p.add_argument("--rehome-deadline-s", type=float, default=5.0,
                   help="detection -> shards re-homed + new owners "
                        "confirmed; over it counts a deadline miss "
                        "(photon_fleet_rehome_deadline_misses_total)")
    p.add_argument("--retries", type=int, default=3,
                   help="bounded forward retries (connection-class "
                        "failures only; deterministic backoff)")
    p.add_argument("--retry-backoff-s", type=float, default=0.1,
                   help="deterministic backoff step; the ladder's "
                        "total patience (sum of backoffs) covers "
                        "death detection at the default probe "
                        "interval, so a SIGKILL retries onto the "
                        "re-homed owner instead of shedding")
    p.add_argument("--hedge-after-ms", type=float, default=None,
                   help="send a duplicate to the next healthy replica "
                        "when the primary is slower than this; first "
                        "response wins (off by default)")
    p.add_argument("--request-timeout-s", type=float, default=30.0,
                   help="per-forward HTTP timeout (every blocking call "
                        "carries one - PML011)")
    p.add_argument("--max-restarts", type=int, default=3,
                   help="restart budget per replica before it is "
                        "declared failed (fleet stays degraded)")
    p.add_argument("--backoff-reset-s", type=float, default=60.0,
                   help="healthy uptime after which a replica's "
                        "restart-backoff ladder (and budget) resets — "
                        "a crash-once-then-healthy replica does not "
                        "pay escalated backoff on its next death")
    p.add_argument("--max-inflight", type=int, default=None,
                   help="fleet admission bound on in-flight /score "
                        "bodies (default 16*replicas); overflow sheds "
                        "503 with fleet depth in the body")
    p.add_argument("--start-timeout-s", type=float, default=120.0)
    p.add_argument("--fault-plan",
                   help="JSON FaultPlan armed in the DRIVER and every "
                        "replica (chaos drills: replica_kill, delay, "
                        "partition - docs/ROBUSTNESS.md)")
    # -- replica knobs, forwarded --------------------------------------
    p.add_argument("--max-batch", type=int, default=64)
    p.add_argument("--max-wait-ms", type=float, default=2.0)
    p.add_argument("--cache-entities", type=int, default=4096)
    p.add_argument("--cache-dtype", default="float32",
                   choices=["float32", "int8"],
                   help="replica device-LRU storage dtype: int8 caches "
                        "~4x the entities per HBM byte, a direct "
                        "hit-rate -> p99 lever at million-entity host "
                        "stores (docs/SERVING.md)")
    p.add_argument("--store-shards", type=int, default=8)
    p.add_argument("--max-queue", type=int, default=None)
    p.add_argument("--request-deadline-s", type=float, default=30.0)
    p.add_argument("--boot-warmup", action="store_true",
                   help="replicas touch every bucket shape before "
                        "answering /healthz — a restarted replica "
                        "re-homes with its programs already warm "
                        "(docs/SERVING.md \"Sub-second restart\")")
    # -- elastic fleet (docs/SERVING.md "Elastic fleet") -----------------
    p.add_argument("--elastic", nargs="?", const="", default=None,
                   metavar="KEY=VAL,...",
                   help="arm the elastic control loop: load-aware "
                        "rebalancing, live hot-shard splitting, "
                        "burn-driven autoscaling, adaptive hedging, "
                        "and the per-shard brownout ladder. Bare "
                        "--elastic takes every default; the mini-DSL "
                        "tunes it (e.g. 'split_factor=3,interval=0.5,"
                        "max_replicas=6' — see "
                        "photon_ml_tpu/serving/elastic.py)")
    # -- multi-host fleet (docs/SERVING.md "Multi-host fleet") -----------
    p.add_argument("--machines", default=None,
                   metavar="URL,URL,...",
                   help="comma-separated machine-agent base URLs "
                        "(python -m photon_ml_tpu.fabric.agent); when "
                        "set, replicas run UNDER those agents "
                        "(RemoteTransport: probe/adopt/restart by "
                        "host:port) instead of as local subprocesses. "
                        "Replica rid homes on machine rid %% N, with "
                        "cross-machine failover on whole-machine death")
    p.add_argument("--machine-timeout-s", type=float, default=5.0,
                   help="per-call timeout for the agent control plane")
    p.add_argument("--delta-base-url", default=None,
                   help="replicas PULL publish deltas from this URL "
                        "(a DeltaArtifactServer over the publish dir) "
                        "instead of a shared-filesystem path; 'auto' "
                        "starts one over --publish-dir and uses it")
    # -- fleet SLO -------------------------------------------------------
    p.add_argument("--slo-window-s", type=float, default=60.0)
    p.add_argument("--slo-availability", type=float, default=0.999)
    p.add_argument("--slo-latency-ms", type=float, default=None)
    # -- continuous publication (docs/SERVING.md) ------------------------
    p.add_argument("--publish-dir", default=None,
                   help="publish-ledger home: POST /publish canary "
                        "ladders record their rows here (photon-obs "
                        "tail --publish renders them)")
    p.add_argument("--publish-bake-s", type=float, default=0.5,
                   help="default canary bake window of POST /publish")
    p.add_argument("--publish-burn-threshold", type=float, default=1.0,
                   help="default max canary error-budget burn rate "
                        "before auto-rollback")
    return p


def replica_args_from(args) -> list[str]:
    """The ``photon_ml_tpu.cli.serve`` argv tail every replica shares."""
    out = ["--model-dir", args.model_dir,
           "--model-format", args.model_format,
           "--max-batch", str(args.max_batch),
           "--max-wait-ms", str(args.max_wait_ms),
           "--cache-entities", str(args.cache_entities),
           "--cache-dtype", str(getattr(args, "cache_dtype", "float32")),
           "--store-shards", str(args.store_shards),
           "--request-deadline-s", str(args.request_deadline_s)]
    if args.feature_index_dir:
        out += ["--feature-index-dir", args.feature_index_dir]
    if args.entity_vocabs:
        out += ["--entity-vocabs", args.entity_vocabs]
    if args.as_mean:
        out += ["--as-mean"]
    if args.max_queue is not None:
        out += ["--max-queue", str(args.max_queue)]
    if getattr(args, "boot_warmup", False):
        out += ["--boot-warmup"]
    return out


def create_fleet(args) -> ServingFleet:
    """Build (not yet started) the fleet from parsed CLI args — split
    out so tests and the bench drive the same construction path."""
    workdir = args.workdir or tempfile.mkdtemp(prefix="photon-fleet-")
    if args.fault_plan:
        # Arm the driver-side sites (fleet.route, fleet.probe) here;
        # replicas arm their own copy through the forwarded flag.
        from photon_ml_tpu import faults as flt

        with open(args.fault_plan) as f:
            flt.install(flt.FaultPlan.from_json(f.read()))
        logger.warning("fault plan %s ARMED in the fleet driver",
                       args.fault_plan)
    transport = None
    machines = [m for m in (args.machines or "").split(",") if m]
    delta_base_url = getattr(args, "delta_base_url", None)
    delta_server = None
    if delta_base_url == "auto":
        if not args.publish_dir:
            raise SystemExit("--delta-base-url auto needs --publish-dir")
        from photon_ml_tpu.fabric.transport import DeltaArtifactServer

        delta_server = DeltaArtifactServer(args.publish_dir)
        delta_base_url = delta_server.base_url
        logger.info("delta artifacts served at %s (over %s)",
                    delta_base_url, args.publish_dir)
    fleet = ServingFleet(
        replica_args=replica_args_from(args),
        num_replicas=args.replicas,
        workdir=workdir,
        num_shards=args.num_shards,
        route_re_type=args.route_re_type,
        request_timeout_s=args.request_timeout_s,
        retries=args.retries,
        retry_backoff_s=args.retry_backoff_s,
        hedge_after_s=(None if args.hedge_after_ms is None
                       else args.hedge_after_ms / 1e3),
        probe_interval_s=args.probe_interval_s,
        heartbeat_deadline_s=args.heartbeat_deadline_s,
        rehome_deadline_s=args.rehome_deadline_s,
        start_timeout_s=args.start_timeout_s,
        max_restarts=args.max_restarts,
        backoff_reset_s=args.backoff_reset_s,
        max_inflight=args.max_inflight,
        elastic=(parse_elastic_config(args.elastic)
                 if args.elastic is not None else None),
        fault_plan_file=args.fault_plan,
        slo_window_s=args.slo_window_s,
        slo_availability=args.slo_availability,
        slo_latency_ms=args.slo_latency_ms,
        publish_dir=args.publish_dir,
        publish_bake_s=args.publish_bake_s,
        publish_burn_threshold=args.publish_burn_threshold,
        transport=transport,
        delta_base_url=delta_base_url)
    if machines:
        # The transport needs the fleet's argv builder — constructed
        # after so the supervisor's default LocalTransport is simply
        # replaced before anything spawned.
        from photon_ml_tpu.fabric.transport import RemoteTransport

        fleet.supervisor.transport = RemoteTransport(
            machines, fleet._replica_argv,
            timeout_s=args.machine_timeout_s)
        logger.info("fleet runs REMOTE: %d machine agent(s) %s",
                    len(machines), machines)
    fleet.delta_server = delta_server
    return fleet


def run(args) -> None:
    setup_logging()
    fleet = create_fleet(args)
    server = None
    # The finally covers the whole acquire sequence (PML016's shape):
    # a front-door bind failure (port in use) after fleet.start() must
    # still tear the replica subprocesses down, or they leak and keep
    # serving stale shards with no supervisor.
    try:
        fleet.start()
        server = make_fleet_http_server(fleet, host=args.host,
                                        port=args.port)
        host, port = server.server_address[:2]
        logger.info(
            "fleet of %d replica(s) x %d shard(s) on http://%s:%d "
            "(POST /score, GET /metrics, /slo, /healthz); replica logs "
            "in %s",
            fleet.num_replicas, fleet.num_shards, host, port,
            fleet.workdir)
        server.serve_forever()
    except KeyboardInterrupt:
        logger.info("shutting down fleet")
    finally:
        if server is not None:
            server.server_close()
        fleet.close()
        if getattr(fleet, "delta_server", None) is not None:
            fleet.delta_server.close()


def main(argv=None):
    run(build_parser().parse_args(argv))


if __name__ == "__main__":
    main()
