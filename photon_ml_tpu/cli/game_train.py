"""GAME training driver.

Reference parity: photon-client ``cli/game/training/GameTrainingDriver.
scala`` + ``cli/game/GameDriver.scala`` — parse params, read train/validation
data, run GameEstimator.fit over the regularization grid, select the best
model by the primary validation evaluator, write model + summary. Supports
warm start (``--model-input-dir``) and partial retraining
(``--locked-coordinates``).

Coordinate specs use the same mini-DSL style as the reference's config
strings, e.g.:

    --coordinate "name=fixed,type=fixed,shard=global"
    --coordinate "name=per-user,type=random,shard=re_userId,re=userId,min_samples=2"
    --opt-config "fixed:optimizer=LBFGS,reg=L2,reg_weight=1.0"
"""

from __future__ import annotations

import argparse
import contextlib
import dataclasses
import json
import logging
import os
import time

from photon_ml_tpu.api.configs import (CoordinateConfiguration,
                                       FactoredRandomEffectDataConfiguration,
                                       FixedEffectDataConfiguration,
                                       RandomEffectDataConfiguration,
                                       parse_ingest_config, parse_kv,
                                       parse_optimizer_config,
                                       parse_staging_config,
                                       parse_streaming_config,
                                       parse_sweep_config)
from photon_ml_tpu.api.estimator import GameEstimator
from photon_ml_tpu.data.io import load_game_dataset
from photon_ml_tpu.data.validators import (DataValidationLevel,
                                           validate_game_dataset)
from photon_ml_tpu.models import io as model_io
from photon_ml_tpu.optim.problem import GLMOptimizationConfiguration
from photon_ml_tpu.parallel.mesh import make_mesh
from photon_ml_tpu.types import TaskType
from photon_ml_tpu.utils.compile_cache import enable_compilation_cache
from photon_ml_tpu.utils.logging import setup_logging

logger = logging.getLogger("photon_ml_tpu.cli")


def parse_coordinate(spec: str) -> tuple[str, dict]:
    kv = parse_kv(spec)
    if "name" not in kv or "type" not in kv or "shard" not in kv:
        raise ValueError(f"coordinate spec needs name/type/shard: {spec!r}")
    return kv.pop("name"), kv


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--train", required=True,
                   help="training data: a GameDataset directory "
                        "(data/io.py format) or a LIBSVM text FILE "
                        "(loaded as one sparse 'global' shard — the "
                        "Criteo-style fixed-effect-only configuration)")
    p.add_argument("--validation")
    p.add_argument("--task", default="LOGISTIC_REGRESSION",
                   choices=[t.value for t in TaskType])
    p.add_argument("--coordinate", action="append", required=True,
                   help="coordinate spec (repeatable): name=,type=fixed|"
                        "random|factored,shard=[,re=,min_samples=,"
                        "max_samples=,projector=NONE|INDEX_MAP|RANDOM,"
                        "projected_dim=,features_to_samples_ratio=,"
                        "subspace=auto|true|false (keep the trained "
                        "random-effect model in per-entity subspace form),"
                        "rank=,alternations=,hybrid=,dtype=]")
    p.add_argument("--opt-config", action="append", default=[],
                   help="'<coordinate>:<optimizer mini-DSL>' (repeatable)")
    p.add_argument("--update-sequence", required=True,
                   help="comma-separated coordinate order")
    p.add_argument("--iterations", type=int, default=1)
    p.add_argument("--evaluators", default="",
                   help="comma-separated, first is primary (e.g. AUC,AUC@userId)")
    p.add_argument("--reg-weight-grid", default=[],
                   help="'<coordinate>:w1,w2,...' (repeatable)",
                   action="append")
    p.add_argument("--model-input-dir", help="warm-start GameModel directory")
    p.add_argument("--locked-coordinates", default="",
                   help="comma-separated coordinates to keep fixed")
    p.add_argument("--output-dir", required=True)
    p.add_argument("--output-mode", default="BEST", choices=["BEST", "ALL"])
    p.add_argument("--checkpoint", action=argparse.BooleanOptionalAction,
                   default=True,
                   help="checkpoint descent progress under "
                        "<output-dir>/checkpoints after every coordinate "
                        "update (--no-checkpoint disables)")
    p.add_argument("--resume", action="store_true",
                   help="resume from an existing checkpoint directory "
                        "instead of starting fresh")
    p.add_argument("--tuning", default="NONE",
                   choices=["NONE", "RANDOM", "BAYESIAN"],
                   help="hyperparameter-tuning mode: search per-coordinate "
                        "regularization weights after the grid sweep "
                        "(reference: GameTrainingDriver hyperParameterTuning)")
    p.add_argument("--tuning-iters", type=int, default=10,
                   help="number of tuning trials")
    p.add_argument("--tuning-range", default="1e-4:1e4",
                   help="lo:hi regularization-weight search range "
                        "(log scale)")
    p.add_argument("--profile-dir",
                   help="capture a jax.profiler trace of the fit into this "
                        "directory (TensorBoard/Perfetto viewable)")
    p.add_argument("--data-validation", default="VALIDATE_FULL",
                   choices=[v.value for v in DataValidationLevel],
                   help="input sanity checks (reference DataValidators: "
                        "task-valid labels, finite features/offsets, "
                        "non-negative weights)")
    p.add_argument("--avro-feature-shard", action="append", default=[],
                   help='Avro-input shard spec '
                        '"name=global,bags=features[+moreBags],'
                        'intercept=true,sparse=false" (repeatable). Any '
                        "spec switches --train/--validation to Avro "
                        "container files/directories, the reference's "
                        "AvroDataReader flow")
    p.add_argument("--avro-re-types", default="",
                   help="comma-separated random-effect id keys read from "
                        "the Avro records' metadataMap")
    p.add_argument("--feature-index-dir",
                   help="saved index maps (<shard>.json) freezing the "
                        "feature space (reference PalDB feature maps); "
                        "built from the data when omitted")
    p.add_argument("--date-range",
                   help="yyyyMMdd-yyyyMMdd or ISO a:b — expand --train as "
                        "daily partitions <root>/yyyy/mm/dd (reference "
                        "inputDataDateRange)")
    p.add_argument("--model-output-format", default="NPZ",
                   choices=["NPZ", "AVRO", "BOTH"],
                   help="AVRO additionally writes the reference's "
                        "BayesianLinearModelAvro layout under "
                        "<output-dir>/best-avro, together with the index "
                        "maps and entity vocabularies needed to reload it "
                        "(requires Avro input via --avro-feature-shard)")
    p.add_argument("--distributed", action="store_true",
                   help="join the multi-host world before building the "
                        "mesh (JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES "
                        "/ JAX_PROCESS_ID; automatic on Cloud TPU). "
                        "Recovery from a lost host is restart + --resume.")
    p.add_argument("--fabric", action="store_true",
                   help="arm the host-level DCN fabric for streamed "
                        "fixed-effect fits (PHOTON_FABRIC_WORLD / "
                        "PHOTON_FABRIC_RANK / PHOTON_FABRIC_COORDINATOR; "
                        "docs/STREAMING.md \"Multi-host streaming\"): "
                        "chunk ranges shard over hosts, host partials "
                        "meet in one cross-host allreduce per pass, and "
                        "every accepted iteration exchanges cross-rank "
                        "digests. Composes with --distributed; the mesh "
                        "then spans LOCAL devices only.")
    p.add_argument("--staging-cache-dir",
                   help="persist projected random-effect staging artifacts "
                        "here, keyed by dataset content digest — a re-run "
                        "on the same data memory-maps the staged blocks "
                        "(shard-granular: a killed run resumes with "
                        "partial credit) instead of re-paying the "
                        "projection pass")
    p.add_argument("--staging",
                   help="parallel staging pipeline knobs, "
                        "'workers=8,mode=thread|process,depth=10,"
                        "shard_entities=65536,retries=2,backoff=0.05,"
                        "straggler=30' (docs/STAGING.md, "
                        "docs/ROBUSTNESS.md); default: one worker per "
                        "host core, thread mode, depth=workers+2")
    p.add_argument("--ingest",
                   help="parallel Avro ingestion knobs, "
                        "'workers=8,mode=thread|process,depth=2,"
                        "chunk_records=65536' (docs/INGEST.md); applies "
                        "to Avro inputs (--avro-feature-shard). Default: "
                        "one decode worker per host core, thread mode")
    p.add_argument("--streaming", nargs="?", const="",
                   help="route sparse fixed-effect coordinates onto the "
                        "row-streamed path (docs/STREAMING.md): the shard "
                        "stages into host-resident chunks, chunk ranges "
                        "partition over the mesh's data axis, and every "
                        "L-BFGS value/gradient streams each device's "
                        "range with psum-merged partials — n bounded by "
                        "host RAM, not HBM; the fit checkpoints mid-"
                        "optimization. Optional mini-DSL "
                        "'chunk_rows=262144,num_hot=512,"
                        "dtype=float32|bfloat16|int8,depth=2,pin=0,"
                        "workers=8,solver=lbfgs|sdca|sgd' (bare "
                        "--streaming takes every default; dtype=int8 "
                        "quarters the streamed bytes — symmetric "
                        "per-column quantization with f32 accumulation; "
                        "solver=sdca|sgd runs the duality-gap-certified "
                        "stochastic solvers over the same chunk feed, "
                        "docs/STREAMING.md)")
    p.add_argument("--sweep", nargs="?", const="",
                   help="dirty-gated incremental sweeps for random-effect "
                        "coordinates (docs/SWEEPS.md): outer iterations "
                        "past min_sweeps_full refit only entities whose "
                        "residual offsets drifted past theta or whose "
                        "last solve left gradient norm above grad_tol, "
                        "compacted into dense active waves, with "
                        "incremental residual rescoring. Mini-DSL "
                        "'theta=1e-4,grad_tol=1e-5,min_sweeps_full=1,"
                        "final_full=true,gram=false' (bare --sweep takes "
                        "every default — gate=0, bit-identical to an "
                        "ungated run; gram=true reuses per-bucket "
                        "normal-equation blocks for squared-loss bucket "
                        "solves)")
    p.add_argument("--ingest-cache-dir",
                   help="persist decoded Avro columns here (columnar "
                        "mmap ingest cache, keyed by file identity + "
                        "decode plan) — a re-run on the same inputs "
                        "memory-maps columns instead of re-decoding "
                        "Avro, and a killed run resumes with per-chunk "
                        "partial credit (docs/INGEST.md)")
    p.add_argument("--fault-plan",
                   help="TESTING ONLY: install a deterministic "
                        "fault-injection plan (photon_ml_tpu/faults "
                        "FaultPlan JSON) for this run — the chaos "
                        "suite's process-level kill/corruption drills "
                        "drive the trainer through this flag "
                        "(docs/ROBUSTNESS.md)")
    p.add_argument("--trace-out",
                   help="write a Chrome trace-event JSON of this run "
                        "(photon-obs span tracing: lifecycle scopes, "
                        "streamed passes, chunk transfers, checkpoint "
                        "writes) — load in chrome://tracing or "
                        "ui.perfetto.dev, or render with `photon-obs "
                        "summarize` (docs/OBSERVABILITY.md). Off by "
                        "default: the instrumentation then costs one "
                        "None check per site")
    p.add_argument("--metrics-dump",
                   help="write the cross-stack metrics registry "
                        "(transfer bytes/seconds, compile-cache misses, "
                        "peak in-flight chunks, retry/recovery counters) "
                        "as Prometheus text at exit — the batch-run "
                        "form of the serving /metrics endpoint "
                        "(docs/OBSERVABILITY.md)")
    p.add_argument("--ledger-dir", default=None,
                   help="run-ledger directory (docs/OBSERVABILITY.md "
                        "\"The run ledger\"): manifest + append-as-"
                        "produced per-iteration convergence telemetry, "
                        "committed under the atomic-marker/CRC "
                        "discipline so a crashed run keeps its curve. "
                        "Default: <output-dir>/ledger; pass '' to "
                        "disable. A fresh run replaces a stale ledger; "
                        "--resume validates run identity and APPENDS. "
                        "Inspect with `photon-obs tail/diff/verify`")
    p.add_argument("--watchdog", nargs="?", const="",
                   help="arm the convergence watchdogs "
                        "(obs/watchdog.py): 'nan=raise|warn|stop|off,"
                        "stall=K[:action],divergence=F[:action],"
                        "slow_iter=F[:action]'. Bare --watchdog arms "
                        "the NaN detector (raise). Off by default at "
                        "one None check per optimizer iteration")
    return p


def _arm_observability(args, stack, is_primary, est) -> None:
    """Install the run ledger + convergence watchdogs for the span of
    the fit/tuning phase (docs/OBSERVABILITY.md "The run ledger").

    The ledger defaults ON (``<output-dir>/ledger``; ``--ledger-dir ''``
    disables): every ``game_train`` run leaves its convergence curve on
    disk. A fresh run replaces a stale ledger (exactly the checkpoint
    cleanup discipline); ``--resume`` appends after descent validates
    run identity against the checkpoint fingerprint. Rank 0 only — one
    writer per shared filesystem; close() runs via the stack so a
    crashed fit keeps its curve prefix.
    """
    from photon_ml_tpu import obs

    spec = getattr(args, "watchdog", None)
    if spec is not None:
        prev_wd = obs.set_watchdog(obs.parse_watchdog_config(spec))
        stack.callback(obs.set_watchdog, prev_wd)
    ledger_dir = getattr(args, "ledger_dir", None)
    if ledger_dir is None:
        ledger_dir = os.path.join(args.output_dir, "ledger")
    if not ledger_dir or not is_primary:
        return
    if not getattr(args, "resume", False) and os.path.exists(ledger_dir):
        import shutil

        logger.info("fresh run: removing stale run ledger at %s",
                    ledger_dir)
        shutil.rmtree(ledger_dir)
    led = obs.RunLedger.resume(ledger_dir,
                               manifest=est.ledger_manifest())
    prev_led = obs.set_ledger(led)

    def _close(exc_type, exc, tb):
        led.close(status="ok" if exc_type is None else "error")
        obs.set_ledger(prev_led)
        return False

    stack.push(_close)


def _load_dataset(path: str, num_features=None):
    """GameDataset directory, or a LIBSVM file → sparse 'global' shard."""
    if os.path.isdir(path):
        return load_game_dataset(path)
    from photon_ml_tpu.data.game_data import from_sparse_batch
    from photon_ml_tpu.data.libsvm import read_libsvm
    from photon_ml_tpu.data.sparse import from_libsvm

    data = read_libsvm(path, dense=False, num_features=num_features)
    return from_sparse_batch(from_libsvm(data))


def _parse_avro_shards(specs):
    """--avro-feature-shard mini-DSL → {shard: FeatureShardConfig}."""
    from photon_ml_tpu.avro.data_reader import FeatureShardConfig

    out = {}
    for spec in specs:
        kv = parse_kv(spec)
        if "name" not in kv:
            raise ValueError(f"avro shard spec needs name=: {spec!r}")
        out[kv["name"]] = FeatureShardConfig(
            feature_bags=tuple(
                b for b in kv.get("bags", "features").split("+") if b),
            has_intercept=kv.get("intercept", "true").lower() == "true",
            sparse=kv.get("sparse", "false").lower() == "true")
    return out


def _ingest_config(args):
    """--ingest / --ingest-cache-dir → IngestConfig (None when neither
    flag is set: the reader then uses its defaults)."""
    from photon_ml_tpu.ingest import IngestConfig

    cfg = (parse_ingest_config(args.ingest)
           if getattr(args, "ingest", None) else None)
    if getattr(args, "ingest_cache_dir", None):
        cfg = dataclasses.replace(cfg or IngestConfig(),
                                  cache_dir=args.ingest_cache_dir)
    return cfg


def _load_avro_inputs(args):
    """The reference GameTrainingDriver flow: feature maps → AvroDataReader
    → (train, validation) GameDatasets sharing one feature space."""
    from photon_ml_tpu.avro.data_reader import AvroDataReader
    from photon_ml_tpu.avro.model_io import load_index_maps
    from photon_ml_tpu.utils.ranges import (DateRange,
                                            input_paths_within_date_range)

    ingest_cfg = _ingest_config(args)
    shard_cfgs = _parse_avro_shards(args.avro_feature_shard)
    re_types = [t for t in args.avro_re_types.split(",") if t]
    index_maps = (load_index_maps(args.feature_index_dir)
                  if args.feature_index_dir else None)
    train_paths = [args.train]
    if args.date_range:
        train_paths = input_paths_within_date_range(
            args.train, DateRange.parse(args.date_range))
        if not train_paths:
            raise ValueError(
                f"no daily partitions under {args.train} within "
                f"{args.date_range}")
        logger.info("date range %s: %d daily partitions", args.date_range,
                    len(train_paths))
    reader = AvroDataReader()
    train, meta = reader.read(train_paths, shard_cfgs,
                              random_effect_types=re_types,
                              index_maps=index_maps, ingest=ingest_cfg)
    validation = None
    if args.validation:
        # Frozen feature space + entity vocabulary from training
        # (reference: validation reads through the same index maps).
        # Unseen validation entities are routine (new users appear every
        # day) — they get rows past the frozen range and score with the
        # fixed effect only, the reference's unseen-entity semantics.
        validation, val_meta = reader.read(
            args.validation, shard_cfgs, random_effect_types=re_types,
            index_maps=meta.index_maps, entity_vocabs=meta.entity_vocabs,
            allow_unseen_entities=True, ingest=ingest_cfg)
        for t in re_types:
            unseen = (len(val_meta.entity_vocabs[t])
                      - len(meta.entity_vocabs[t]))
            if unseen:
                logger.info(
                    "validation has %d unseen %s entities (scored with "
                    "the fixed effect only)", unseen, t)
    return train, validation, meta


def _sync_global_devices_or_skip(tag: str) -> None:
    """``multihost_utils.sync_global_devices`` where the backend can,
    a loud skip where it cannot.

    The barrier is a device collective, and the CPU backend cannot run
    multi-process collectives at all ("Multiprocess computations aren't
    implemented" — the pre-existing DCN dryrun crash, CHANGES PR 7).
    On such a backend the sync seam degrades to a logged no-op: the
    checkpoint-cleanup race it guards is a real-filesystem concern that
    CPU multi-process runs (localhost test worlds) do not actually
    have, and crashing the whole distributed dryrun over an
    unimplementable barrier inverts the robustness contract. Any OTHER
    failure still raises — a silently skipped barrier on a backend that
    needed one would be resuming-from-wrong-state by another name.
    """
    import jax

    if jax.default_backend() == "cpu":
        logger.warning(
            "SKIPPING sync_global_devices(%r): the CPU backend has no "
            "multi-process collectives — ranks proceed unbarriered "
            "(safe for localhost test worlds; use a real accelerator "
            "backend for shared-filesystem runs)", tag)
        return
    from jax.experimental import multihost_utils

    try:
        multihost_utils.sync_global_devices(tag)
    except (NotImplementedError, RuntimeError) as e:
        # XLA surfaces UNIMPLEMENTED as an XlaRuntimeError (a
        # RuntimeError); anything else is a real failure and re-raises.
        if "implemented" not in str(e).lower():
            raise
        logger.warning(
            "SKIPPING sync_global_devices(%r): backend %s cannot run "
            "it (%s) — ranks proceed unbarriered", tag,
            jax.default_backend(), e)


def _disarm_fabric() -> None:
    """Release the process-wide fabric (run bracket: an in-process
    caller — tests, the smoke drivers — must not leak an armed comm or
    a bound coordinator socket into the next run)."""
    from photon_ml_tpu.fabric import runtime as fabric_runtime

    comm = fabric_runtime.active()
    if comm is not None:
        fabric_runtime.install(None)
        comm.close()


def run(args) -> dict:
    """Driver entry: observability bracket around the real run (the
    trace/metrics dumps happen in a ``finally`` so a crashed fit still
    leaves its timeline on disk — the crash is exactly when you want
    it)."""
    trace_out = getattr(args, "trace_out", None)
    metrics_dump = getattr(args, "metrics_dump", None)
    if trace_out or metrics_dump:
        from photon_ml_tpu import obs

        obs.enable(trace=bool(trace_out), metrics=True,
                   spill=(trace_out + ".spill") if trace_out else None)
        try:
            with obs.span("game_train", cat="driver"):
                return _run(args)
        finally:
            import jax

            if jax.process_index() == 0:
                # One writer on a shared checkpoint/output filesystem.
                if trace_out:
                    obs.dump_trace(trace_out)
                    logger.info("wrote trace %s (chrome://tracing, "
                                "ui.perfetto.dev, or `photon-obs "
                                "summarize`)", trace_out)
                if metrics_dump:
                    obs.dump_metrics(metrics_dump)
                    logger.info("wrote metrics %s", metrics_dump)
            obs.disable()
            _disarm_fabric()
    try:
        return _run(args)
    finally:
        _disarm_fabric()


def _run(args) -> dict:
    setup_logging()
    enable_compilation_cache()
    if getattr(args, "fault_plan", None):
        from photon_ml_tpu import faults

        with open(args.fault_plan) as f:
            faults.install(faults.FaultPlan.from_json(f.read()))
        logger.warning("fault injection ACTIVE from %s — this run will "
                       "deliberately fail in the planned ways",
                       args.fault_plan)
    t0 = time.perf_counter()  # duration base (PML004)
    task = TaskType(args.task)
    if (args.model_output_format in ("AVRO", "BOTH")
            and not args.avro_feature_shard):
        # Fail at argument time, not after an hours-long fit.
        raise ValueError(
            "--model-output-format AVRO needs Avro input "
            "(--avro-feature-shard) to supply feature index maps and "
            "entity vocabularies")
    avro_meta = None
    if args.avro_feature_shard:
        train, validation, avro_meta = _load_avro_inputs(args)
    else:
        for flag, value in (("--date-range", args.date_range),
                            ("--avro-re-types", args.avro_re_types),
                            ("--feature-index-dir",
                             args.feature_index_dir),
                            ("--ingest", getattr(args, "ingest", None)),
                            ("--ingest-cache-dir",
                             getattr(args, "ingest_cache_dir", None))):
            if value:
                raise ValueError(
                    f"{flag} applies to Avro inputs "
                    f"(--avro-feature-shard)")
        train = _load_dataset(args.train)
        validation = None
        if args.validation:
            nf = None
            if not os.path.isdir(args.validation):
                # LIBSVM validation must share the training feature space —
                # whatever form training was loaded from.
                if "global" not in train.feature_shards:
                    raise ValueError(
                        "LIBSVM validation requires a 'global' feature "
                        "shard in the training data")
                nf = train.shard_dim("global")
            validation = _load_dataset(args.validation, num_features=nf)
    vlevel = DataValidationLevel(args.data_validation)
    validate_game_dataset(task, train, level=vlevel)
    if validation is not None:
        validate_game_dataset(task, validation, level=vlevel)

    opt_by_coord: dict[str, GLMOptimizationConfiguration] = {}
    for spec in args.opt_config:
        cid, _, dsl = spec.partition(":")
        opt_by_coord[cid.strip()] = parse_optimizer_config(dsl)

    grid_by_coord: dict[str, tuple[float, ...]] = {}
    for spec in args.reg_weight_grid:
        if not spec:
            continue
        cid, _, ws = spec.partition(":")
        grid_by_coord[cid.strip()] = tuple(
            float(w) for w in ws.split(",") if w)

    locked = {c for c in args.locked_coordinates.split(",") if c}
    coordinates: dict[str, CoordinateConfiguration] = {}
    for spec in args.coordinate:
        name, kv = parse_coordinate(spec)
        if kv["type"] == "fixed":
            hybrid_kv = kv.get("hybrid", "auto").lower()
            if hybrid_kv not in ("auto", "true", "false"):
                raise ValueError(
                    f"hybrid= must be auto, true, or false "
                    f"(got {hybrid_kv!r})")
            data = FixedEffectDataConfiguration(
                kv["shard"],
                feature_sharded=kv.get("feature_sharded",
                                       "false").lower() == "true",
                feature_dtype=kv.get("dtype", "float32"),
                hybrid=(None if hybrid_kv == "auto"
                        else hybrid_kv == "true"))
        elif kv["type"] == "random":
            sub_kv = kv.get("subspace", "auto").lower()
            if sub_kv not in ("auto", "true", "false"):
                raise ValueError(
                    f"subspace= must be auto, true, or false "
                    f"(got {sub_kv!r})")
            kv["subspace"] = sub_kv
            data = RandomEffectDataConfiguration(
                random_effect_type=kv["re"],
                feature_shard_id=kv["shard"],
                active_data_lower_bound=int(kv.get("min_samples", 1)),
                active_data_upper_bound=(int(kv["max_samples"])
                                         if "max_samples" in kv else None),
                projector=kv.get("projector", "NONE").upper(),
                projected_dimension=(int(kv["projected_dim"])
                                     if "projected_dim" in kv else None),
                features_to_samples_ratio=(
                    float(kv["features_to_samples_ratio"])
                    if "features_to_samples_ratio" in kv else None),
                subspace_model=(
                    None if kv.get("subspace", "auto") == "auto"
                    else kv["subspace"] == "true"),
                feature_dtype=kv.get("dtype", "float32"))
        elif kv["type"] == "factored":
            data = FactoredRandomEffectDataConfiguration(
                random_effect_type=kv["re"],
                feature_shard_id=kv["shard"],
                rank=int(kv.get("rank", 4)),
                alternations=int(kv.get("alternations", 2)),
                active_data_lower_bound=int(kv.get("min_samples", 1)),
                active_data_upper_bound=(int(kv["max_samples"])
                                         if "max_samples" in kv else None))
        else:
            raise ValueError(f"unknown coordinate type {kv['type']!r}")
        opt = opt_by_coord.get(name, GLMOptimizationConfiguration())
        grid = grid_by_coord.get(name, ())
        # Locked coordinates are never retrained, so tuning/grids don't
        # apply to them — don't demand a regularizer for them.
        if ((grid or (args.tuning != "NONE" and name not in locked))
                and opt.regularization.reg_type.value == "NONE"):
            # A reg-weight grid / tuning sweep over a NONE-regularized
            # coordinate silently fits the identical model at every point.
            raise ValueError(
                f"coordinate {name!r} has regularization NONE; "
                f"--reg-weight-grid/--tuning need an --opt-config with "
                f"reg=L1|L2|ELASTIC_NET for it")
        coordinates[name] = CoordinateConfiguration(
            data=data, optimization=opt, reg_weight_grid=grid)

    evaluators = [e for e in args.evaluators.split(",") if e]
    if args.tuning != "NONE" and (not args.validation or not evaluators):
        # Fail at argument time, not after an hours-long grid sweep.
        raise ValueError("--tuning requires --validation and --evaluators")
    fabric_comm = None
    if getattr(args, "fabric", False):
        # Arm the process-wide fabric BEFORE the estimator stages any
        # streamed coordinate (fabric/runtime.py). The mesh goes LOCAL:
        # cross-host traffic rides the FabricComm allreduce, never an
        # XLA collective (unimplemented on CPU process groups).
        from photon_ml_tpu.fabric import runtime as fabric_runtime

        fabric_comm = fabric_runtime.comm_from_env()
        if fabric_comm is None:
            raise ValueError(
                "--fabric needs PHOTON_FABRIC_WORLD >= 2 plus "
                "PHOTON_FABRIC_RANK / PHOTON_FABRIC_COORDINATOR in the "
                "environment (fabric/runtime.comm_from_env)")
        fabric_runtime.install(fabric_comm)
        logger.info("fabric armed: rank %d/%d (coordinator %s:%d)",
                    fabric_comm.rank, fabric_comm.world,
                    *fabric_comm.coordinator)
    est = GameEstimator(
        task=task,
        coordinates=coordinates,
        update_sequence=[c for c in args.update_sequence.split(",") if c],
        mesh=make_mesh(distributed=getattr(args, "distributed", False),
                       local=fabric_comm is not None),
        descent_iterations=args.iterations,
        validation_evaluators=evaluators,
        staging_cache_dir=args.staging_cache_dir,
        staging=(parse_staging_config(args.staging)
                 if getattr(args, "staging", None) else None),
        ingest=_ingest_config(args) if args.avro_feature_shard else None,
        streaming=(parse_streaming_config(args.streaming)
                   if getattr(args, "streaming", None) is not None
                   else None),
        sweep=(parse_sweep_config(args.sweep)
               if getattr(args, "sweep", None) is not None
               else None))

    initial_models = None
    if args.model_input_dir:
        initial_models = dict(
            model_io.load_game_model(args.model_input_dir).models)

    # Multi-host: every process runs the same device program, but only the
    # primary touches shared files (checkpoint cleanup, model/summary
    # output). Checkpoint LOADS happen on every rank (identical control
    # flow needs identical resume state — checkpoint_dir must be a shared
    # filesystem); SAVES are rank-0-only inside CheckpointManager.
    import jax
    is_primary = jax.process_index() == 0 and (
        fabric_comm is None or fabric_comm.rank == 0)

    if getattr(args, "resume", False) and not getattr(args, "checkpoint", True):
        raise ValueError("--resume requires checkpointing; "
                         "drop --no-checkpoint")
    checkpoint_dir = None
    if getattr(args, "checkpoint", True):
        checkpoint_dir = os.path.join(args.output_dir, "checkpoints")
        if (is_primary and not getattr(args, "resume", False)
                and os.path.exists(checkpoint_dir)):
            # Fresh run: stale checkpoints must not silently short-circuit
            # training (resume is an explicit opt-in).
            import shutil
            logger.info("fresh run: removing stale checkpoints at %s",
                        checkpoint_dir)
            shutil.rmtree(checkpoint_dir)
        if jax.process_count() > 1:
            # All ranks load checkpoints inside fit; none may read before
            # rank 0's cleanup above lands on the shared filesystem.
            _sync_global_devices_or_skip("checkpoint-cleanup")

    from photon_ml_tpu.utils.logging import profile_trace

    with contextlib.ExitStack() as obs_stack:
        _arm_observability(args, obs_stack, is_primary, est)
        from photon_ml_tpu import obs

        led = obs.ledger()
        ledger_info = (None if led is None else
                       {"dir": led.directory,
                        "run_id": led.manifest.get("run_id")})

        with profile_trace(getattr(args, "profile_dir", None)):
            results = est.fit(train, validation,
                              initial_models=initial_models,
                              locked_coordinates=locked or None,
                              checkpoint_dir=checkpoint_dir)

        tuning_summary = None
        if args.tuning != "NONE":
            # Reference: GameTrainingDriver's hyperparameter-tuning mode
            # — the grid results seed the search as prior observations,
            # then RANDOM / BAYESIAN (GP + expected improvement) trials
            # refine the per-coordinate regularization weights on the
            # validation metric. The search runs INSIDE the ledger
            # scope: per-trial rows land in the same run ledger.
            from photon_ml_tpu.hyperparameter.evaluation import \
                GameEvaluationFunction
            from photon_ml_tpu.hyperparameter.search import (
                GaussianProcessSearch, RandomSearch)
            from photon_ml_tpu.utils.ranges import DoubleRange

            lo, _, hi = args.tuning_range.partition(":")
            evalfn = GameEvaluationFunction(
                est, train, validation,
                coordinate_ids=[c for c in est.update_sequence
                                if c not in locked],
                reg_weight_range=DoubleRange(float(lo), float(hi)),
                initial_models=initial_models,
                locked_coordinates=locked or None)
            dims = evalfn.dimensions()
            searcher_cls = (GaussianProcessSearch
                            if args.tuning == "BAYESIAN" else RandomSearch)
            searcher = searcher_cls(dims, evalfn)
            priors = evalfn.observations_from_results(results)
            search = searcher.find_with_priors(args.tuning_iters, priors)
            best_trial = evalfn.best_trial()
            if (best_trial is not None
                    and best_trial[0] <= search.best_value + 1e-12):
                # The winning trial's model was already trained during
                # the search — reuse it instead of refitting an
                # (n+1)-th time.
                results = results + best_trial[2]
            # else: the winner is a grid prior, already in `results`.
            tuning_summary = {
                "mode": args.tuning,
                "iterations": args.tuning_iters,
                "best_config": search.best_config(dims),
                "trials": [
                    {"point": {d.name: float(p)
                               for d, p in zip(dims, o.point)},
                     "objective": float(o.value)}
                    for o in search.observations],
            }

    best = est.select_best_model(results)

    os.makedirs(args.output_dir, exist_ok=True)
    if is_primary:
        if args.output_mode == "ALL":
            for i, r in enumerate(results):
                model_io.save_game_model(
                    r.model, os.path.join(args.output_dir, f"model-{i}"))
        if args.model_output_format in ("NPZ", "BOTH"):
            model_io.save_game_model(best.model,
                                     os.path.join(args.output_dir, "best"))
        if args.model_output_format in ("AVRO", "BOTH"):
            from photon_ml_tpu.avro.model_io import (save_game_model_avro,
                                                     save_index_maps)

            avro_dir = os.path.join(args.output_dir, "best-avro")
            save_game_model_avro(
                best.model, avro_dir, avro_meta.index_maps,
                entity_vocabs=avro_meta.entity_vocabs)
            # Make the directory self-contained: reloading needs the same
            # index maps and vocabularies that wrote it, not a re-read of
            # the training data.
            save_index_maps(avro_meta.index_maps,
                            os.path.join(avro_dir, "index-maps"))
            with open(os.path.join(avro_dir, "entity-vocabs.json"),
                      "w") as f:
                json.dump(avro_meta.entity_vocabs, f)
    summary = {
        "task": task.value,
        # Byte-level fingerprint of the selected model: two runs (or two
        # DCN ranks) trained the SAME model iff these agree — a far
        # sharper probe than any rounded metric (VERDICT Weak #6).
        "model_digest": model_io.game_model_digest(best.model),
        "candidates": [
            {"configs": {
                c: {"reg_type": o.regularization.reg_type.value,
                    "reg_weight": o.regularization.reg_weight}
                for c, o in r.configs.items()},
             "metrics": r.evaluation.metrics if r.evaluation else None}
            for r in results],
        "best_metrics": (best.evaluation.metrics if best.evaluation else None),
        "tuning": tuning_summary,
        # Provenance pointer: summary and convergence curve are the
        # same run (photon-obs tail/diff on this directory).
        "ledger": ledger_info,
        "wall_seconds": time.perf_counter() - t0,
    }
    if is_primary:
        with open(os.path.join(args.output_dir, "summary.json"), "w") as f:
            json.dump(summary, f, indent=2)
        logger.info("wrote %s", args.output_dir)
    return summary


def main(argv=None):
    run(build_parser().parse_args(argv))


if __name__ == "__main__":
    main()
