"""GAME scoring driver.

Reference parity: photon-client ``cli/game/scoring/GameScoringDriver.scala``
— load a GameModel, score a dataset, write scoring results (uid, score +
label/offset/weight passthrough), optionally evaluate.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import time

import numpy as np

from photon_ml_tpu.api.transformer import GameTransformer
from photon_ml_tpu.data.io import load_game_dataset
from photon_ml_tpu.models import io as model_io
from photon_ml_tpu.utils.compile_cache import enable_compilation_cache
from photon_ml_tpu.utils.logging import setup_logging

logger = logging.getLogger("photon_ml_tpu.cli")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--data", required=True, help="GameDataset directory")
    p.add_argument("--model-dir", required=True, help="GameModel directory")
    p.add_argument("--output-dir", required=True)
    p.add_argument("--evaluators", default="",
                   help="optional comma-separated evaluators")
    p.add_argument("--as-mean", action="store_true",
                   help="apply the inverse link (probabilities/rates)")
    p.add_argument("--output-format", default="NPZ",
                   choices=["NPZ", "AVRO", "BOTH"],
                   help="AVRO writes the reference's ScoringResultAvro "
                        "container (scores.avro)")
    return p


def run(args) -> dict:
    setup_logging()
    enable_compilation_cache()
    t0 = time.time()
    data = load_game_dataset(args.data)
    model = model_io.load_game_model(args.model_dir)
    evaluators = [e for e in args.evaluators.split(",") if e]
    transformer = GameTransformer(model, evaluators)

    os.makedirs(args.output_dir, exist_ok=True)
    summary = {"num_rows": data.num_rows}
    if evaluators:
        result, evaluation = transformer.transform_and_evaluate(
            data, as_mean=args.as_mean)
        summary["metrics"] = evaluation.metrics
    else:
        result = transformer.transform(data, as_mean=args.as_mean)
    if args.output_format in ("NPZ", "BOTH"):
        np.savez_compressed(
            os.path.join(args.output_dir, "scores.npz"),
            uid=result.uids, score=result.scores, label=result.labels,
            offset=result.offsets, weight=result.weights)
    if args.output_format in ("AVRO", "BOTH"):
        from photon_ml_tpu.avro.scoring import write_scoring_results

        write_scoring_results(
            os.path.join(args.output_dir, "scores.avro"),
            result.scores, uids=result.uids, labels=result.labels,
            weights=result.weights, offsets=result.offsets)
    summary["wall_seconds"] = time.time() - t0
    with open(os.path.join(args.output_dir, "summary.json"), "w") as f:
        json.dump(summary, f, indent=2)
    logger.info("wrote %s", args.output_dir)
    return summary


def main(argv=None):
    run(build_parser().parse_args(argv))


if __name__ == "__main__":
    main()
