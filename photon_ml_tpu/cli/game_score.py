"""GAME scoring driver.

Reference parity: photon-client ``cli/game/scoring/GameScoringDriver.scala``
— load a GameModel, score a dataset, write scoring results (uid, score +
label/offset/weight passthrough), optionally evaluate.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import time

import numpy as np

from photon_ml_tpu.api.transformer import GameTransformer
from photon_ml_tpu.data.io import load_game_dataset
from photon_ml_tpu.models import io as model_io
from photon_ml_tpu.utils.compile_cache import enable_compilation_cache
from photon_ml_tpu.utils.events import (ScoringFinish, ScoringStart,
                                        default_emitter)
from photon_ml_tpu.utils.logging import setup_logging

logger = logging.getLogger("photon_ml_tpu.cli")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--data", required=True,
                   help="GameDataset directory, or Avro container "
                        "file(s)/directory when --avro-feature-shard is "
                        "given")
    p.add_argument("--model-dir", required=True, help="GameModel directory")
    p.add_argument("--avro-feature-shard", action="append", default=[],
                   help="Avro-input shard spec (same mini-DSL as "
                        "game_train); switches --data to Avro input")
    p.add_argument("--avro-re-types", default="",
                   help="comma-separated random-effect id keys (Avro "
                        "input)")
    p.add_argument("--feature-index-dir",
                   help="REQUIRED with Avro input: the training run's "
                        "saved index maps (e.g. <train-out>/best-avro/"
                        "index-maps); entity vocabularies load from the "
                        "sibling entity-vocabs.json. Unseen entities "
                        "score with the fixed effect only")
    p.add_argument("--ingest",
                   help="parallel Avro ingestion knobs, "
                        "'workers=8,mode=thread|process,depth=2,"
                        "chunk_records=65536' (docs/INGEST.md); applies "
                        "to Avro inputs (--avro-feature-shard)")
    p.add_argument("--model-format", default="NPZ",
                   choices=["NPZ", "AVRO"],
                   help="AVRO loads the BayesianLinearModelAvro layout "
                        "(e.g. a best-avro directory) through the same "
                        "index maps")
    p.add_argument("--output-dir", required=True)
    p.add_argument("--evaluators", default="",
                   help="optional comma-separated evaluators")
    p.add_argument("--batch-rows", type=int, default=None,
                   help="score in bounded device batches of this many rows "
                        "through the host->device prefetch pipeline "
                        "(inputs larger than device memory; identical "
                        "scores)")
    p.add_argument("--as-mean", action="store_true",
                   help="apply the inverse link (probabilities/rates)")
    p.add_argument("--output-format", default="NPZ",
                   choices=["NPZ", "AVRO", "BOTH"],
                   help="AVRO writes the reference's ScoringResultAvro "
                        "container (scores.avro)")
    return p


def run(args) -> dict:
    setup_logging()
    enable_compilation_cache()
    t0 = time.perf_counter()  # duration base — wall time only for stamps
    imaps = vocabs = None
    if args.avro_feature_shard:
        from photon_ml_tpu.avro.data_reader import AvroDataReader
        from photon_ml_tpu.avro.model_io import load_index_maps
        from photon_ml_tpu.cli.game_train import _parse_avro_shards

        if not args.feature_index_dir:
            raise ValueError(
                "Avro scoring input needs --feature-index-dir (the "
                "training run's saved index maps — scoring must use the "
                "SAME feature space the model was trained in)")
        imaps = load_index_maps(args.feature_index_dir)
        re_types = [t for t in args.avro_re_types.split(",") if t]
        vocab_path = os.path.join(
            os.path.dirname(args.feature_index_dir.rstrip("/")),
            "entity-vocabs.json")
        vocabs = None
        if os.path.exists(vocab_path):
            vocabs = json.load(open(vocab_path))
        elif re_types:
            # Without the training vocabularies, entity ids would be
            # assigned in scoring-data encounter order and every
            # random-effect row gather would silently hit the wrong
            # entity.
            raise ValueError(
                f"scoring with random-effect types {re_types} needs the "
                f"training entity vocabularies; expected {vocab_path} "
                f"(written beside the index maps by game_train "
                f"--model-output-format AVRO)")
        from photon_ml_tpu.api.configs import parse_ingest_config

        data, read_meta = AvroDataReader().read(
            args.data, _parse_avro_shards(args.avro_feature_shard),
            random_effect_types=re_types,
            index_maps=imaps, entity_vocabs=vocabs,
            allow_unseen_entities=True,
            ingest=(parse_ingest_config(args.ingest)
                    if getattr(args, "ingest", None) else None))
    else:
        for flag, value in (("--avro-re-types", args.avro_re_types),
                            ("--feature-index-dir",
                             args.feature_index_dir),
                            ("--ingest", getattr(args, "ingest", None))):
            if value:
                raise ValueError(f"{flag} applies to Avro inputs "
                                 f"(--avro-feature-shard)")
        data = load_game_dataset(args.data)
    if args.model_format == "AVRO":
        from photon_ml_tpu.avro.model_io import load_game_model_avro

        if imaps is None:
            raise ValueError(
                "--model-format AVRO needs Avro input with "
                "--feature-index-dir (the model's feature space)")
        model = load_game_model_avro(args.model_dir, imaps,
                                     entity_vocabs=vocabs)
    else:
        model = model_io.load_game_model(args.model_dir)
    evaluators = [e for e in args.evaluators.split(",") if e]
    transformer = GameTransformer(model, evaluators)

    os.makedirs(args.output_dir, exist_ok=True)
    default_emitter.emit(ScoringStart(source="game_score",
                                      num_rows=data.num_rows))
    summary = {"num_rows": data.num_rows}
    try:
        if evaluators:
            result, evaluation = transformer.transform_and_evaluate(
                data, as_mean=args.as_mean, batch_rows=args.batch_rows)
            summary["metrics"] = evaluation.metrics
        else:
            result = (transformer.transform_batched(
                          data, args.batch_rows, as_mean=args.as_mean)
                      if args.batch_rows
                      else transformer.transform(data, as_mean=args.as_mean))
        if args.avro_feature_shard:
            # Preserve the input records' real uids (ReadMeta) so downstream
            # joins of the scoring output back to the source data hold — the
            # transformer only knows row indices.
            import dataclasses

            result = dataclasses.replace(result, uids=read_meta.uids)
        if args.output_format in ("NPZ", "BOTH"):
            uids = result.uids
            if uids.dtype == object:
                # Mixed int/str uids (Avro input): store as strings so the
                # npz needs no pickle to load.
                uids = np.asarray([str(u) for u in uids])
            np.savez_compressed(
                os.path.join(args.output_dir, "scores.npz"),
                uid=uids, score=result.scores, label=result.labels,
                offset=result.offsets, weight=result.weights)
        if args.output_format in ("AVRO", "BOTH"):
            from photon_ml_tpu.avro.scoring import write_scoring_results

            write_scoring_results(
                os.path.join(args.output_dir, "scores.avro"),
                result.scores, uids=result.uids, labels=result.labels,
                weights=result.weights, offsets=result.offsets)
    finally:
        # Balanced lifecycle (PML007): listeners tracking open scoring
        # scopes must see the Finish even when the run raises mid-write.
        summary["wall_seconds"] = time.perf_counter() - t0
        default_emitter.emit(ScoringFinish(
            source="game_score", num_rows=data.num_rows,
            wall_seconds=summary["wall_seconds"]))
    with open(os.path.join(args.output_dir, "summary.json"), "w") as f:
        json.dump(summary, f, indent=2)
    logger.info("wrote %s", args.output_dir)
    return summary


def main(argv=None):
    run(build_parser().parse_args(argv))


if __name__ == "__main__":
    main()
