"""Feature indexing driver.

Reference parity: photon-client ``index/FeatureIndexingDriver.scala`` — the
standalone job that scans feature (name, term) pairs in training data and
builds per-shard read-only index stores, later opened by the training /
scoring drivers. Output per shard is either a ``.pidx`` native mmap store
(PalDB analogue, photon_ml_tpu/index/native_store.py) or a ``.json`` map.

Usage:

    python -m photon_ml_tpu.cli.feature_index \\
        --data /path/train.avro --output /path/index \\
        --shard "global:features" --shard "user:userFeatures" \\
        --format pidx --add-intercept
"""

from __future__ import annotations

import argparse
import json
import logging
import os

from photon_ml_tpu.avro.container import read_records
from photon_ml_tpu.index.indexmap import (DefaultIndexMap, INTERCEPT_KEY,
                                          feature_key)
from photon_ml_tpu.index.native_store import build_store
from photon_ml_tpu.utils.logging import setup_logging

logger = logging.getLogger("photon_ml_tpu.cli")


def parse_shard(spec: str) -> tuple[str, list[str]]:
    """``shardName:bag1+bag2`` -> (shardName, [bag1, bag2])."""
    shard, _, bags = spec.partition(":")
    if not bags:
        raise ValueError(f"shard spec needs '<name>:<bag>[+<bag>...]': "
                         f"{spec!r}")
    return shard, bags.split("+")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--data", action="append", required=True,
                   help="Avro file or directory (repeatable)")
    p.add_argument("--output", required=True, help="output directory")
    p.add_argument("--shard", action="append", required=True,
                   help="'<shardName>:<bag>[+<bag>...]' (repeatable)")
    p.add_argument("--format", default="pidx", choices=["pidx", "json"])
    p.add_argument("--add-intercept", action="store_true", default=True)
    p.add_argument("--no-intercept", dest="add_intercept",
                   action="store_false")
    return p


def run(args) -> dict:
    shards = dict(parse_shard(s) for s in args.shard)
    keys: dict[str, set[str]] = {s: set() for s in shards}
    num_records = 0
    for path in args.data:
        for rec in read_records(path):
            num_records += 1
            for shard, bags in shards.items():
                dst = keys[shard]
                for bag in bags:
                    for f in rec.get(bag) or ():
                        dst.add(feature_key(f["name"], f.get("term", "")))

    os.makedirs(args.output, exist_ok=True)
    summary = {"num_records": num_records, "shards": {}}
    for shard, ks in keys.items():
        ordered = sorted(ks)
        if args.add_intercept and INTERCEPT_KEY not in ks:
            ordered.append(INTERCEPT_KEY)
        if args.format == "pidx":
            out = os.path.join(args.output, f"{shard}.pidx")
            build_store(ordered, out)
        else:
            out = os.path.join(args.output, f"{shard}.json")
            DefaultIndexMap(
                {k: i for i, k in enumerate(ordered)}).save(out)
        summary["shards"][shard] = {"num_features": len(ordered),
                                    "path": out}
        logger.info("shard %s: %d features -> %s", shard, len(ordered), out)
    with open(os.path.join(args.output, "_summary.json"), "w") as fh:
        json.dump(summary, fh, indent=2)
    return summary


def main(argv=None) -> None:
    setup_logging()
    run(build_parser().parse_args(argv))


if __name__ == "__main__":
    main()
