"""photon-obs: trace-file + run-ledger tooling (docs/OBSERVABILITY.md).

``photon-obs summarize trace.json`` renders the phase waterfall, the
top-span table, and the transfer-vs-compute attribution from a Chrome
trace-event file produced by ``game_train --trace-out`` /
``GameEstimator(trace=...)`` / ``flagship_criteo_stream.py`` — the
machine-checkable replacement for the hand-computed subtraction that
produced the "~95% host→device transfer" figure.

``photon-obs tail <ledger-dir>`` renders a LIVE run from its run ledger
(obs/ledger.py): current coordinate/iteration, objective value, an ETA
from the iteration-time EMA, and the transfer fraction — the flagship is
no longer a black box until it exits.

``photon-obs diff <runA> <runB>`` compares two ledgers: config delta,
value-vs-wall-clock and value-vs-passes convergence overlay,
time-to-target-value, final metric deltas — the instrument ROADMAP items
2/5 need before "warm-start day N+1" claims are checkable.

``photon-obs verify <trace.json | ledger-dir>`` is the CI smoke contract
(run_tier1.sh): traces must load with closed, properly nested spans;
ledgers must have a CRC-committed manifest and contiguous, CRC-clean,
monotone telemetry rows.

No JAX anywhere on these paths — the CLI runs on a box that has never
seen an accelerator.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
from typing import Optional

from photon_ml_tpu.obs.ledger import (LedgerError, diff_ledgers,
                                      read_manifest, read_rows,
                                      verify_ledger)

# Child spans may start marginally before their parent's exported ts:
# the parent's wall anchor and the child's are sampled by different
# clock reads microseconds apart. Containment is asserted with slack.
_NEST_SLACK_US = 500.0


def load_trace(path: str) -> dict:
    with open(path) as f:
        obj = json.load(f)
    if not isinstance(obj, dict) or "traceEvents" not in obj:
        raise ValueError(f"{path} is not a Chrome trace-event file "
                         f"(no traceEvents key)")
    return obj


def _spans(trace: dict) -> list[dict]:
    return [e for e in trace["traceEvents"] if e.get("ph") == "X"]


def _instants(trace: dict, name: Optional[str] = None) -> list[dict]:
    evs = [e for e in trace["traceEvents"] if e.get("ph") == "i"]
    if name is not None:
        evs = [e for e in evs if e.get("name") == name]
    return evs


# -- verify -----------------------------------------------------------------


def verify_trace(trace: dict) -> list[str]:
    """Structural violations (empty list = healthy). The contract CI
    smokes: spans closed, parents resolvable, children contained."""
    problems = []
    spans = _spans(trace)
    if not spans:
        problems.append("trace contains no spans")
        return problems
    by_id = {}
    for e in spans:
        sid = e.get("args", {}).get("span_id")
        if sid is not None:
            by_id[sid] = e
    for e in spans:
        args = e.get("args", {})
        label = f"{e.get('name')}@{e.get('ts'):.0f}us"
        if args.get("unfinished"):
            problems.append(f"span {label} never closed")
        if e.get("dur", 0) < 0:
            problems.append(f"span {label} has negative duration")
        pid_ = args.get("parent_id")
        if pid_ is None:
            continue
        parent = by_id.get(pid_)
        if parent is None:
            problems.append(f"span {label} parent {pid_} not in trace")
            continue
        # Queue-crossing spans (args.crosses_queue — a serving request's
        # enqueue→respond life parented into the flush that scored it)
        # START before their parent by design: the queue wait precedes
        # the flush. Containment is then asserted at the tail only.
        if not args.get("crosses_queue") \
                and e["ts"] + _NEST_SLACK_US < parent["ts"]:
            problems.append(
                f"span {label} is not contained in its parent "
                f"{parent.get('name')} interval")
        elif e["ts"] + e["dur"] > parent["ts"] + parent["dur"] \
                + _NEST_SLACK_US:
            problems.append(
                f"span {label} is not contained in its parent "
                f"{parent.get('name')} interval")
    meta = trace.get("otherData", {})
    if meta.get("open_spans"):
        problems.append(f"{meta['open_spans']} span(s) still open at dump")
    opened = meta.get("bridge_spans_opened")
    closed = meta.get("bridge_spans_closed")
    if opened is not None and opened != closed:
        problems.append(
            f"event bridge opened {opened} lifecycle span(s) but closed "
            f"{closed} — a Start/Finish pair leaked")
    if meta.get("bridge_spans_leaked"):
        problems.append(
            f"{meta['bridge_spans_leaked']} bridged scope(s) never saw "
            f"their Finish event")
    return problems


# -- summarize --------------------------------------------------------------


def summarize_trace(trace: dict, top: int = 12) -> dict:
    """Waterfall + top spans + transfer-vs-compute attribution."""
    spans = _spans(trace)
    if not spans:
        return {"wall_seconds": 0.0, "waterfall": [], "top_spans": [],
                "attribution": {}}
    t_min = min(e["ts"] for e in spans)
    t_max = max(e["ts"] + e["dur"] for e in spans)
    wall_us = max(t_max - t_min, 1e-9)

    ids = {e["args"]["span_id"] for e in spans
           if "span_id" in e.get("args", {})}
    roots = [e for e in spans
             if e.get("args", {}).get("parent_id") not in ids]
    roots.sort(key=lambda e: e["ts"])
    waterfall = [{
        "name": e["name"], "cat": e.get("cat", ""),
        "start_s": (e["ts"] - t_min) / 1e6, "dur_s": e["dur"] / 1e6,
        "frac": e["dur"] / wall_us,
    } for e in roots]

    agg: dict[tuple, dict] = {}
    for e in spans:
        a = agg.setdefault((e["name"], e.get("cat", "")),
                           {"count": 0, "total_us": 0.0, "max_us": 0.0})
        a["count"] += 1
        a["total_us"] += e["dur"]
        a["max_us"] = max(a["max_us"], e["dur"])
    top_spans = [{
        "name": k[0], "cat": k[1], "count": v["count"],
        "total_s": v["total_us"] / 1e6, "max_s": v["max_us"] / 1e6,
        "frac_of_wall": v["total_us"] / wall_us,
    } for k, v in sorted(agg.items(), key=lambda kv: -kv[1]["total_us"])]

    # Transfer vs compute: transfer = the device_put accounting spans
    # (cat "transfer"); the denominator is the streamed-pass time when
    # passes exist (the bench-comparable fraction), else the wall.
    transfer_us = sum(e["dur"] for e in spans
                      if e.get("cat") == "transfer")
    pass_us = sum(e["dur"] for e in spans
                  if e["name"] == "stream.pass")
    # Per-dtype attribution: every chunk-transfer span carries its
    # chunk's storage dtype (f32/bf16/int8 — the quantized-streaming
    # lever), so the stream's byte/second split per dtype falls out of
    # the same spans (counter counterpart:
    # photon_transfer_bytes_total{kind="stream",dtype=...}).
    by_dtype: dict = {}
    for e in spans:
        if e.get("cat") != "transfer":
            continue
        args = e.get("args", {})
        d = by_dtype.setdefault(str(args.get("dtype", "unknown")),
                                {"seconds": 0.0, "bytes": 0, "chunks": 0})
        d["seconds"] += e["dur"] / 1e6
        d["bytes"] += int(args.get("bytes", 0) or 0)
        d["chunks"] += 1
    denom = pass_us if pass_us > 0 else wall_us
    attribution = {
        "transfer_seconds": transfer_us / 1e6,
        "stream_pass_seconds": pass_us / 1e6,
        "wall_seconds": wall_us / 1e6,
        "transfer_fraction_of_stream": transfer_us / denom,
        "transfer_fraction_of_wall": transfer_us / wall_us,
        "transfer_by_dtype": by_dtype,
    }
    root_cover = sum(e["dur"] for e in roots)
    return {
        "wall_seconds": wall_us / 1e6,
        "top_level_coverage": min(root_cover / wall_us, 1.0),
        "waterfall": waterfall[:max(top, len(waterfall))],
        "top_spans": top_spans[:top],
        "attribution": attribution,
    }


_REQUEST_STAGES = ("serving.queue_wait", "serving.assemble",
                   "serving.device_score", "serving.respond")


def _pctl(sorted_vals: list, p: float) -> float:
    """Nearest-rank percentile over a pre-sorted list (stdlib-only —
    this module must run without numpy)."""
    if not sorted_vals:
        return 0.0
    k = max(0, min(len(sorted_vals) - 1,
                   round(p / 100.0 * (len(sorted_vals) - 1))))
    return float(sorted_vals[k])


def _boot_waterfall(spans: list[dict]) -> Optional[dict]:
    """The ``serving.boot`` span + its ``boot.*`` children (map /
    compile / warmup) as a phase waterfall — the restart tail,
    attributed (docs/SERVING.md "Sub-second restart"). None when the
    trace holds no boot span (a service traced after construction)."""
    boots = [e for e in spans if e["name"] == "serving.boot"]
    if not boots:
        return None
    boot = max(boots, key=lambda e: e["ts"])  # the newest (re)boot
    bid = boot.get("args", {}).get("span_id")
    phases = [{
        "phase": c["name"],
        "start_ms": (c["ts"] - boot["ts"]) / 1e3,
        "dur_ms": c["dur"] / 1e3,
        "frac": c["dur"] / max(boot["dur"], 1e-9),
    } for c in sorted((e for e in spans
                       if e["name"].startswith("boot.")
                       and e.get("args", {}).get("parent_id") == bid),
                      key=lambda c: c["ts"])]
    return {"total_ms": boot["dur"] / 1e3, "boots": len(boots),
            "phases": phases}


def summarize_serving(trace: dict) -> dict:
    """Request-path view of a serving trace (``summarize --serving``):
    request latency percentiles from the ``serving.request`` spans,
    stage attribution (where request time went across queue wait /
    assemble / device score / respond), flush stats, and the slowest
    request's waterfall — the per-request counterpart of the batch-side
    transfer attribution."""
    spans = _spans(trace)
    requests = [e for e in spans if e["name"] == "serving.request"]
    flushes = [e for e in spans if e["name"] == "serving.flush"]
    boot = _boot_waterfall(spans)
    if not requests:
        return {"requests": 0, "flushes": len(flushes), "boot": boot}
    durs_ms = sorted(e["dur"] / 1e3 for e in requests)
    total_ms = sum(durs_ms)
    by_parent: dict = {}
    for e in spans:
        pid_ = e.get("args", {}).get("parent_id")
        if pid_ is not None and e["name"] in _REQUEST_STAGES:
            by_parent.setdefault(pid_, []).append(e)
    stage_ms = {s: 0.0 for s in _REQUEST_STAGES}
    for e in spans:
        if e["name"] in stage_ms:
            stage_ms[e["name"]] += e["dur"] / 1e3
    attributed = sum(stage_ms.values())
    slowest = max(requests, key=lambda e: e["dur"])
    slow_id = slowest.get("args", {}).get("span_id")
    waterfall = [{
        "stage": c["name"], "start_ms": (c["ts"] - slowest["ts"]) / 1e3,
        "dur_ms": c["dur"] / 1e3,
        "frac": c["dur"] / max(slowest["dur"], 1e-9),
    } for c in sorted(by_parent.get(slow_id, []), key=lambda c: c["ts"])]
    return {
        "requests": len(requests),
        "flushes": len(flushes),
        "boot": boot,
        "request_latency_ms": {
            "p50": _pctl(durs_ms, 50), "p95": _pctl(durs_ms, 95),
            "p99": _pctl(durs_ms, 99), "max": durs_ms[-1],
            "mean": total_ms / len(durs_ms),
        },
        "request_seconds_total": total_ms / 1e3,
        "stage_attribution": {
            s: {"seconds": stage_ms[s] / 1e3,
                "frac_of_request_time": stage_ms[s] / max(total_ms, 1e-9)}
            for s in _REQUEST_STAGES},
        "attributed_fraction": attributed / max(total_ms, 1e-9),
        "slowest_request": {
            "request_id": slowest.get("args", {}).get("request_id"),
            "total_ms": slowest["dur"] / 1e3,
            "waterfall": waterfall,
        },
    }


def _render_boot(boot: Optional[dict]) -> list:
    if not boot:
        return []
    out = [f"boot waterfall (serving.boot, {boot['total_ms']:.1f}ms"
           + (f", {boot['boots']} boot(s) in trace — newest shown"
              if boot["boots"] > 1 else "") + "):"]
    for p in boot["phases"]:
        out.append(f"  {p['start_ms']:8.1f}ms  {_bar(p['frac'])} "
                   f"{p['dur_ms']:8.1f}ms  {p['phase']}")
    out.append("")
    return out


def render_serving_summary(summary: dict) -> str:
    if not summary.get("requests"):
        head = _render_boot(summary.get("boot"))
        return "\n".join(head) + (
            f"no serving.request spans in this trace "
            f"({summary.get('flushes', 0)} flush span(s)) — was the "
            f"service traced? (obs.enable() before requests arrive)")
    lat = summary["request_latency_ms"]
    out = _render_boot(summary.get("boot"))
    out += [f"{summary['requests']} request(s) over "
            f"{summary['flushes']} flush(es); request latency "
            f"p50 {lat['p50']:.2f}ms  p95 {lat['p95']:.2f}ms  "
            f"p99 {lat['p99']:.2f}ms  max {lat['max']:.2f}ms", "",
            "stage attribution (of total request time, "
            f"{summary['request_seconds_total']:.3f}s):"]
    for stage, a in summary["stage_attribution"].items():
        out.append(f"  {stage:<22} {_bar(a['frac_of_request_time'])} "
                   f"{a['frac_of_request_time']:>6.1%}  "
                   f"{a['seconds']:.3f}s")
    out.append(f"  (stages cover {summary['attributed_fraction']:.1%} "
               f"of request time; the gap is batcher wakeup jitter)")
    slow = summary["slowest_request"]
    out += ["", f"slowest request (id {slow['request_id']}, "
                f"{slow['total_ms']:.2f}ms):"]
    for w in slow["waterfall"]:
        out.append(f"  {w['start_ms']:8.2f}ms  {_bar(w['frac'])} "
                   f"{w['dur_ms']:8.2f}ms  {w['stage']}")
    return "\n".join(out)


def _bar(frac: float, width: int = 30) -> str:
    n = max(0, min(width, round(frac * width)))
    return "#" * n + "." * (width - n)


# -- kernel view (summarize --kernels) --------------------------------------


def summarize_kernels(trace: dict) -> dict:
    """Per-kernel attribution from the registry's timeline markers.

    Every fresh (kernel, dtype, backend) resolution drops a
    ``kernel.resolve`` instant (ops/kernels/registry.py), and every loud
    degradation drops a ``kernel_fallback`` instant via the event bridge
    — so a trace carries the full build ledger: which fused programs
    were built, on which backend, for which dtypes, and why any of them
    fell back to XLA. A kernel that resolved to BOTH backends in one
    trace is flagged mixed-backend: flag flips mid-run mean two compiled
    programs for one site (docs/KERNELS.md "The failure ladder")."""
    kernels: dict[str, dict] = {}

    def row(name: str) -> dict:
        return kernels.setdefault(name, {
            "builds": 0, "backends": set(), "dtypes": set(),
            "interpret": False, "fallbacks": 0, "fallback_reasons": []})

    for e in _instants(trace, "kernel.resolve"):
        a = e.get("args", {})
        r = row(str(a.get("kernel")))
        r["builds"] += 1
        r["backends"].add(str(a.get("backend")))
        r["dtypes"].add(str(a.get("dtype")))
        r["interpret"] = r["interpret"] or bool(a.get("interpret"))
    for e in _instants(trace, "kernel_fallback"):
        a = e.get("args", {})
        r = row(str(a.get("kernel")))
        r["fallbacks"] += 1
        reason = str(a.get("reason"))
        if reason not in r["fallback_reasons"]:
            r["fallback_reasons"].append(reason)

    out_rows = []
    for name in sorted(kernels):
        r = kernels[name]
        out_rows.append({
            "kernel": name, "builds": r["builds"],
            "backends": sorted(r["backends"]),
            "dtypes": sorted(r["dtypes"]),
            "interpret": r["interpret"],
            "mixed_backend": len(r["backends"]) > 1,
            "fallbacks": r["fallbacks"],
            "fallback_reasons": r["fallback_reasons"]})
    return {
        "kernels": out_rows,
        "builds": sum(r["builds"] for r in out_rows),
        "fallbacks": sum(r["fallbacks"] for r in out_rows),
        "mixed_backend": [r["kernel"] for r in out_rows
                          if r["mixed_backend"]],
    }


def render_kernel_summary(summary: dict) -> str:
    rows = summary["kernels"]
    if not rows:
        return ("no kernel.resolve markers in this trace — either no "
                "registry kernel was enabled, or the run predates the "
                "kernel registry (docs/KERNELS.md)")
    out = [f"{summary['builds']} kernel program build(s) across "
           f"{len(rows)} kernel(s); {summary['fallbacks']} fallback(s)",
           "",
           f"  {'kernel':<18} {'builds':>6}  {'backend(s)':<22} "
           f"{'dtype(s)':<14} {'fallbacks':>9}"]
    for r in rows:
        backends = ",".join(r["backends"])
        if r["interpret"]:
            backends += " (interpret)"
        out.append(f"  {r['kernel']:<18} {r['builds']:>6}  "
                   f"{backends:<22} {','.join(r['dtypes']):<14} "
                   f"{r['fallbacks']:>9}")
    for r in rows:
        for reason in r["fallback_reasons"]:
            out.append(f"    {r['kernel']}: fell back — {reason}")
    if summary["mixed_backend"]:
        out += ["", "  WARNING: mixed backends in one trace for "
                    f"{', '.join(summary['mixed_backend'])} — a flag "
                    f"flip mid-run built two programs for one site"]
    return "\n".join(out)


def render_summary(summary: dict) -> str:
    out = [f"wall {summary['wall_seconds']:.3f}s; top-level spans cover "
           f"{summary.get('top_level_coverage', 0.0):.0%} of it", "",
           "phase waterfall (top-level spans):"]
    for w in summary["waterfall"]:
        out.append(f"  {w['start_s']:9.3f}s  {_bar(w['frac'])} "
                   f"{w['dur_s']:9.3f}s  {w['name']} [{w['cat']}]")
    out += ["", f"top spans by total time:"]
    out.append(f"  {'name':<28} {'cat':<10} {'count':>6} {'total_s':>9} "
               f"{'max_s':>8} {'% wall':>7}")
    for t in summary["top_spans"]:
        out.append(f"  {t['name']:<28} {t['cat']:<10} {t['count']:>6} "
                   f"{t['total_s']:>9.3f} {t['max_s']:>8.3f} "
                   f"{t['frac_of_wall']:>6.1%}")
    a = summary["attribution"]
    out += ["", "transfer vs compute:"]
    out.append(f"  host→device transfer {a['transfer_seconds']:.3f}s of "
               f"{a['stream_pass_seconds']:.3f}s streamed-pass time "
               f"({a['transfer_fraction_of_stream']:.1%}); "
               f"{a['transfer_fraction_of_wall']:.1%} of wall")
    for dt, d in sorted(a.get("transfer_by_dtype", {}).items()):
        out.append(f"    dtype={dt:<9} {d['seconds']:.3f}s  "
                   f"{d['bytes'] / 2**20:.2f} MiB over "
                   f"{d['chunks']} chunk transfer(s)")
    return "\n".join(out)


# -- run-ledger views (docs/OBSERVABILITY.md "The run ledger") --------------


def _find_max_iterations(node, coordinate: Optional[str]) -> Optional[int]:
    """Best-effort ``max_iterations`` for the coordinate from the
    manifest config tree (for the tail ETA; None when undiscoverable)."""
    if isinstance(node, dict):
        coords = node.get("coordinates")
        if coordinate and isinstance(coords, dict) \
                and coordinate in coords:
            node = coords[coordinate]
        stack = [node]
        while stack:
            cur = stack.pop()
            if isinstance(cur, dict):
                if isinstance(cur.get("max_iterations"), int):
                    return cur["max_iterations"]
                stack.extend(cur.values())
            elif isinstance(cur, list):
                stack.extend(cur)
    return None


def publish_summary(rows: list[dict]) -> dict:
    """The publication view of a ledger's ``publish`` rows (the
    serving/publish.py ladder records one per phase): delta versions,
    canary verdicts, rollbacks — what ``tail --publish`` renders."""
    pubs = [r for r in rows if r.get("kind") == "publish"]
    if not pubs:
        return {}
    published = [r for r in pubs if r.get("phase") == "published"]
    out: dict = {
        "rows": len(pubs),
        "published": len(published),
        "current_version": (int(published[-1].get("version", 0))
                            if published else 0),
        "canary_verdicts": [
            {"version": r.get("version"), "replica": r.get("replica"),
             "accepted": bool(r.get("accepted")),
             "reason": r.get("reason"),
             "burn_rate": r.get("burn_rate")}
            for r in pubs if r.get("phase") == "canary_verdict"],
        "rollbacks": [
            {"version": r.get("version"), "reason": r.get("reason"),
             "replicas": r.get("replicas")}
            for r in pubs if r.get("phase") == "rollback"],
        "events": [
            {k: r.get(k) for k in ("t", "phase", "version", "replica",
                                   "accepted", "reason", "entities",
                                   "swap_seconds", "burn_rate")
             if r.get(k) is not None}
            for r in pubs],
    }
    if published:
        out["last_swap_seconds"] = published[-1].get("swap_seconds")
        out["last_entities"] = published[-1].get("entities")
    return out


def elastic_summary(rows: list[dict]) -> dict:
    """The elastic-control view of a ledger's ``elastic`` rows (the
    serving/elastic.py controller records one per decision): splits,
    migrations, scale events, brownouts, hedge re-tunes — what ``tail
    --elastic`` renders."""
    el = [r for r in rows if r.get("kind") == "elastic"]
    if not el:
        return {}
    by_action: dict[str, int] = {}
    for r in el:
        a = str(r.get("action", "?"))
        by_action[a] = by_action.get(a, 0) + 1
    out = {
        "decisions": len(el),
        "by_action": by_action,
        "map_version": el[-1].get("map_snapshot_version"),
        "events": [
            {k: r.get(k) for k in
             ("t", "action", "shard", "children", "replica", "target",
              "source", "num_replicas", "reason", "heat_fraction",
              "burn_rate", "inflight_frac", "hedge_after_s",
              "hot_shards", "map_version")
             if r.get(k) is not None} for r in el],
    }
    last_hedge = [r for r in el if r.get("action") == "hedge_tune"]
    if last_hedge:
        out["hedge_after_s"] = last_hedge[-1].get("hedge_after_s")
    return out


def render_elastic_tail(tail: dict) -> str:
    """``tail --elastic``: the control loop's decision tape,
    chronologically — splits, migrations, scale events, brownouts,
    with each decision's triggering evidence."""
    el = tail.get("elastic")
    head = (f"run {tail.get('run_id', '?')}  [{tail['status']}]  "
            f"{tail['rows']} rows")
    if not el:
        return head + "\n  no elastic rows in this ledger"
    acts = ", ".join(f"{k} ×{v}" for k, v in
                     sorted(el["by_action"].items()))
    out = [head,
           f"  {el['decisions']} decision(s): {acts}  "
           f"(map v{el.get('map_version', '?')})"]
    if el.get("hedge_after_s") is not None:
        out.append(f"  hedge_after auto-tuned to "
                   f"{el['hedge_after_s']:.3f}s")
    for e in el["events"]:
        t = f"{e.get('t', 0):9.3f}s"
        action = e.get("action", "?")
        line = f"  {t}  {action}"
        if action == "split":
            line += (f" shard {e.get('shard')} → {e.get('children')} "
                     f"({e.get('heat_fraction', 0):.0%} of window "
                     f"heat)")
        elif action == "migrate":
            line += (f" shard {e.get('shard')}: replica "
                     f"{e.get('source')} → {e.get('target')} "
                     f"({e.get('reason', '')})")
        elif action in ("scale_up", "scale_down"):
            line += (f" replica {e.get('replica')} "
                     f"(fleet now {e.get('num_replicas')}): "
                     f"{e.get('reason', '')}")
        elif action == "brownout":
            line += f" shard(s) {e.get('hot_shards')}: " \
                    f"{e.get('reason', '')}"
        elif action == "hedge_tune":
            line += f" → {e.get('hedge_after_s', 0):.3f}s"
        elif e.get("reason"):
            line += f" — {e['reason']}"
        if e.get("map_version") is not None:
            line += f"  [map v{e['map_version']}]"
        out.append(line)
    for p in tail.get("problems", []):
        out.append(f"  (tail problem: {p})")
    return "\n".join(out)


def tail_ledger(directory: str) -> dict:
    """Snapshot of a (possibly live) run from its ledger: run identity,
    last position, iteration-time EMA + ETA, transfer fraction."""
    manifest = read_manifest(directory)
    if manifest is None:
        raise LedgerError(f"no run ledger at {directory}")
    rows, problems = read_rows(directory)
    out: dict = {
        "run_id": manifest.get("run_id"),
        "identity": manifest.get("identity"),
        "rows": len(rows),
        "problems": problems,
        "status": "in progress (or killed)",
    }
    ends = [r for r in rows if r.get("kind") == "run_end"]
    if ends:
        out["status"] = f"finished ({ends[-1].get('status', 'ok')})"
    if rows:
        out["wall_seconds"] = float(rows[-1]["t"])
    publish = publish_summary(rows)
    if publish:
        out["publish"] = publish
    elastic = elastic_summary(rows)
    if elastic:
        out["elastic"] = elastic
    alerts = [r for r in rows if r.get("kind") == "watchdog"]
    if alerts:
        out["watchdog_alerts"] = [
            {"kind": a.get("watchdog_kind"), "action": a.get("action"),
             "detail": a.get("detail")} for a in alerts]
    iters = [r for r in rows if r.get("kind") == "opt_iter"]
    updates = [r for r in rows if r.get("kind") == "coordinate_update"]
    if updates:
        out["completed_updates"] = len(updates)
    trials = [r for r in rows if r.get("kind") == "tuning_trial"]
    if trials:
        out["tuning_trials"] = len(trials)
    if not iters:
        return out
    last = iters[-1]
    cur: dict = {
        "coordinate": last.get("coordinate"),
        "outer_iteration": last.get("outer_iteration"),
        "iteration": last.get("iteration"),
        "value": last.get("value"),
        "grad_norm": last.get("grad_norm"),
    }
    # Iteration-time EMA over the live rows of the current coordinate
    # (post_fit spills carry no per-iteration wall).
    live = [r for r in iters
            if r.get("coordinate") == last.get("coordinate")
            and r.get("seconds") is not None]
    if live:
        ema = None
        for r in live:
            s = float(r["seconds"])
            ema = s if ema is None else 0.7 * ema + 0.3 * s
        cur["iteration_seconds_ema"] = round(ema, 4)
        max_it = _find_max_iterations(manifest.get("config"),
                                      last.get("coordinate"))
        if max_it and last.get("iteration") is not None:
            remaining = max(0, max_it - int(last["iteration"]))
            cur["max_iterations"] = max_it
            cur["eta_seconds"] = round(remaining * ema, 1)
    if last.get("transfer_seconds") is not None and \
            float(last["t"]) > 0:
        cur["transfer_fraction_of_wall"] = round(
            float(last["transfer_seconds"]) / float(last["t"]), 4)
    out["current"] = cur
    return out


def render_tail(tail: dict) -> str:
    out = [f"run {tail.get('run_id', '?')}  [{tail['status']}]  "
           f"{tail['rows']} rows"
           + (f", wall {tail['wall_seconds']:.1f}s"
              if "wall_seconds" in tail else "")]
    if tail.get("completed_updates"):
        out.append(f"  completed coordinate updates: "
                   f"{tail['completed_updates']}")
    if tail.get("tuning_trials"):
        out.append(f"  tuning trials: {tail['tuning_trials']}")
    cur = tail.get("current")
    if cur:
        pos = (f"  at: coordinate {cur.get('coordinate') or '(run)'}"
               f" outer {cur.get('outer_iteration', '-')}"
               f" iteration {cur.get('iteration', '-')}")
        if cur.get("max_iterations"):
            pos += f"/{cur['max_iterations']}"
        out.append(pos)
        val = cur.get("value")
        gn = cur.get("grad_norm")
        out.append(f"  objective {val:.6g}" if val is not None else
                   "  objective -")
        if gn is not None:
            out[-1] += f"  |g| {gn:.3g}"
        if cur.get("iteration_seconds_ema") is not None:
            line = f"  {cur['iteration_seconds_ema']:.3g}s/iteration (EMA)"
            if cur.get("eta_seconds") is not None:
                line += f", ETA ~{cur['eta_seconds']:.0f}s"
            out.append(line)
        if cur.get("transfer_fraction_of_wall") is not None:
            out.append(f"  transfer "
                       f"{cur['transfer_fraction_of_wall']:.1%} of wall")
    for a in tail.get("watchdog_alerts", []):
        out.append(f"  WATCHDOG[{a['kind']}/{a['action']}]: {a['detail']}")
    pub = tail.get("publish")
    if pub:
        out.append(f"  publication: v{pub['current_version']} live, "
                   f"{pub['published']} publish(es), "
                   f"{len(pub['rollbacks'])} rollback(s) "
                   f"(--publish for the ladder view)")
    el = tail.get("elastic")
    if el:
        out.append(f"  elastic: {el['decisions']} decision(s), "
                   f"map v{el.get('map_version', '?')} "
                   f"(--elastic for the decision tape)")
    for p in tail.get("problems", []):
        out.append(f"  (tail problem: {p})")
    return "\n".join(out)


def render_publish_tail(tail: dict) -> str:
    """``tail --publish``: the publication ladder, chronologically —
    delta versions, canary verdicts, rollback events."""
    pub = tail.get("publish")
    head = (f"run {tail.get('run_id', '?')}  [{tail['status']}]  "
            f"{tail['rows']} rows")
    if not pub:
        return head + "\n  no publish rows in this ledger"
    out = [head,
           f"  serving v{pub['current_version']}  "
           f"({pub['published']} published, "
           f"{len(pub['canary_verdicts'])} canary verdict(s), "
           f"{len(pub['rollbacks'])} rollback(s))"]
    if pub.get("last_swap_seconds") is not None:
        out.append(f"  last swap {pub['last_swap_seconds']:.3f}s "
                   f"({pub.get('last_entities', '?')} row(s))")
    for e in pub["events"]:
        t = f"{e.get('t', 0):9.3f}s"
        phase = e.get("phase", "?")
        line = f"  {t}  v{e.get('version', '?')} {phase}"
        if phase == "canary_verdict":
            line += (" ACCEPTED" if e.get("accepted")
                     else f" REJECTED: {e.get('reason', '')}")
            if e.get("burn_rate") is not None:
                line += f" (burn {e['burn_rate']:.3f})"
        elif phase == "rollback":
            line += f" — {e.get('reason', '')}"
        elif phase == "published":
            line += (f" ({e.get('entities', '?')} row(s), swap "
                     f"{e.get('swap_seconds', 0):.3f}s)")
        elif e.get("replica") is not None:
            line += f" (replica {e['replica']})"
        out.append(line)
    for p in tail.get("problems", []):
        out.append(f"  (tail problem: {p})")
    return "\n".join(out)


def _overlay(curve_a: list, curve_b: list, x_key: str,
             width: int = 56, height: int = 12,
             y_key: str = "value") -> list[str]:
    """Two convergence curves on one downsampled text grid
    (A = ``a``/``*`` where they overlap, B = ``b``). ``y_key`` picks the
    plotted series (``value`` default; ``gap`` for the duality-gap
    certificate of the stochastic solvers) — points where the series is
    absent/None are skipped."""
    pts = [(float(p[x_key]), float(p[y_key]), 0) for p in curve_a
           if p.get(y_key) is not None] + \
          [(float(p[x_key]), float(p[y_key]), 1) for p in curve_b
           if p.get(y_key) is not None]
    if not pts:
        return []
    xs = [p[0] for p in pts]
    vs = [p[1] for p in pts]
    x_lo, x_hi = min(xs), max(xs)
    v_lo, v_hi = min(vs), max(vs)
    x_span = max(x_hi - x_lo, 1e-12)
    v_span = max(v_hi - v_lo, 1e-12)
    grid = [[" "] * width for _ in range(height)]
    marks = ("a", "b")
    for x, v, who in pts:
        col = min(width - 1, int((x - x_lo) / x_span * (width - 1)))
        row = min(height - 1, int((v_hi - v) / v_span * (height - 1)))
        cell = grid[row][col]
        grid[row][col] = ("*" if cell not in (" ", marks[who])
                          else marks[who])
    unit = "s" if x_key == "t" else " passes"
    lines = [f"  {v_hi:>12.6g} |" + "".join(grid[0])]
    lines += ["  " + " " * 12 + " |" + "".join(r) for r in grid[1:-1]]
    lines.append(f"  {v_lo:>12.6g} |" + "".join(grid[-1]))
    lines.append("  " + " " * 12 + " +" + "-" * width)
    lines.append(f"  {'':12}  {x_lo:.3g}{unit}"
                 f"{'':>{max(1, width - 24)}}{x_hi:.3g}{unit}")
    return lines


def _fit_wave_table(entry: dict) -> list[str]:
    """Per-outer-iteration entities_fit/skipped/seconds table for one
    coordinate's ``re_fit_wave`` aggregates — where a gated-vs-full run
    pair's wall time went (docs/SWEEPS.md). A plain table, not an
    _overlay: lane counts are discrete per-iteration totals, not a
    convergence curve."""
    wa = {w["outer_iteration"]: w for w in entry.get("fit_waves_a", ())}
    wb = {w["outer_iteration"]: w for w in entry.get("fit_waves_b", ())}

    def _cells(w):
        if w is None:
            return f"{'-':>9} {'-':>9} {'-':>8}"
        return (f"{w['entities_fit']:>9} {w['entities_skipped']:>9} "
                f"{w['seconds']:>8.3f}")

    lines = ["  entities fit per outer iteration (A | B):",
             f"  {'iter':>6} {'A fit':>9} {'A skip':>9} {'A secs':>8}  "
             f"{'B fit':>9} {'B skip':>9} {'B secs':>8}"]
    for it in sorted(set(wa) | set(wb)):
        lines.append(f"  {it:>6} {_cells(wa.get(it))}  "
                     f"{_cells(wb.get(it))}")
    return lines


def render_diff(diff: dict) -> str:
    out = [f"run A: {diff['a']}  (run_id {diff['run_ids']['a']})",
           f"run B: {diff['b']}  (run_id {diff['run_ids']['b']})"]
    for side in ("a", "b"):
        for p in diff["problems"][side]:
            out.append(f"  ({side} tail problem: {p})")
    delta = diff["config_delta"]
    if delta:
        out += ["", f"config delta ({len(delta)} key(s)):"]
        for d in delta[:20]:
            out.append(f"  {d['key']}: {d['a']!r} -> {d['b']!r}")
        if len(delta) > 20:
            out.append(f"  ... {len(delta) - 20} more")
    else:
        out += ["", "config delta: none (identical configuration)"]
    for coord, entry in diff["coordinates"].items():
        has_waves = "fit_waves_a" in entry or "fit_waves_b" in entry
        if "curve_a" not in entry and not has_waves:
            out += ["", f"coordinate {coord}: present in only one run"]
            continue
        out += ["", f"coordinate {coord}:"]
        if "curve_a" in entry:
            out.append(f"  final value  A {entry['final_value_a']:.6g}   "
                       f"B {entry['final_value_b']:.6g}   "
                       f"(delta {entry['final_value_delta']:+.3g})")
            tta, ttb = entry["time_to_target_a"], entry["time_to_target_b"]
            if tta and ttb:
                out.append(
                    f"  time to target {entry['target_value']:.6g}:  "
                    f"A {tta['seconds']:.3f}s / {tta['passes']:.0f} passes   "
                    f"B {ttb['seconds']:.3f}s / {ttb['passes']:.0f} passes"
                    + (f"   (B/A {entry['time_to_target_ratio']:.2f}x)"
                       if entry.get("time_to_target_ratio") is not None
                       else ""))
            out.append("  value vs wall clock (a=A, b=B, *=both):")
            out += _overlay(entry["curve_a"], entry["curve_b"], "t")
            out.append("  value vs streamed passes:")
            out += _overlay(entry["curve_a"], entry["curve_b"], "passes")
            if any(math.isfinite(p["gap"]) for c in ("curve_a", "curve_b")
                   for p in entry[c] if p.get("gap") is not None):
                out.append("  duality gap vs wall clock "
                           "(a=A, b=B, *=both):")
                out += _overlay(entry["curve_a"], entry["curve_b"], "t",
                                y_key="gap")
        if has_waves:
            out += _fit_wave_table(entry)
    fm = diff["final_metrics"]
    coords = sorted(set(fm["a"]) | set(fm["b"]))
    if coords:
        out += ["", "final validation metrics:"]
        for c in coords:
            ma, mb = fm["a"].get(c, {}), fm["b"].get(c, {})
            for metric in sorted(set(ma) | set(mb)):
                va, vb = ma.get(metric), mb.get(metric)
                d = ("" if va is None or vb is None
                     else f"   (delta {vb - va:+.6g})")
                out.append(f"  {c}/{metric}: A {va}   B {vb}{d}")
    return "\n".join(out)


def _is_ledger(path: str) -> bool:
    return os.path.isdir(path) and \
        os.path.exists(os.path.join(path, "manifest.json"))


# -- CLI --------------------------------------------------------------------


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="photon-obs", description=__doc__)
    sub = p.add_subparsers(dest="command", required=True)
    s = sub.add_parser("summarize",
                       help="phase waterfall + top spans + transfer "
                            "attribution from a trace file")
    s.add_argument("trace", help="Chrome trace-event JSON "
                                 "(game_train --trace-out)")
    s.add_argument("--top", type=int, default=12,
                   help="rows in the top-span table")
    s.add_argument("--json", action="store_true",
                   help="machine-readable summary instead of text")
    s.add_argument("--serving", action="store_true",
                   help="request-path view: request latency percentiles, "
                        "stage attribution (queue wait / assemble / "
                        "device score / respond), and the slowest "
                        "request's waterfall (docs/SERVING.md)")
    s.add_argument("--kernels", action="store_true",
                   help="kernel-registry view: per-kernel program "
                        "builds by backend/dtype, interpret-mode "
                        "markers, and fallback events with reasons "
                        "(docs/KERNELS.md)")
    v = sub.add_parser("verify",
                       help="structural health check (CI smoke): trace "
                            "spans closed/nested, or — for a ledger "
                            "directory — manifest CRC committed + "
                            "telemetry rows contiguous and CRC-clean")
    v.add_argument("trace", help="trace JSON or run-ledger directory")
    t = sub.add_parser("tail",
                       help="live view of a run from its ledger: "
                            "current coordinate/iteration, ETA from the "
                            "iteration-time EMA, transfer fraction")
    t.add_argument("ledger", help="run-ledger directory "
                                  "(game_train --ledger-dir)")
    t.add_argument("--json", action="store_true")
    t.add_argument("--publish", action="store_true",
                   help="publication view: delta versions, canary "
                        "verdicts, rollback events from the ledger's "
                        "publish rows (serving/publish.py ladder)")
    t.add_argument("--elastic", action="store_true",
                   help="elastic-control view: splits, migrations, "
                        "scale events, brownouts and their triggering "
                        "evidence from the ledger's elastic rows "
                        "(serving/elastic.py controller)")
    d = sub.add_parser("diff",
                       help="compare two run ledgers: config delta, "
                            "convergence overlay, time-to-target, "
                            "final metric deltas")
    d.add_argument("run_a", help="run-ledger directory A (baseline)")
    d.add_argument("run_b", help="run-ledger directory B")
    d.add_argument("--json", action="store_true")
    return p


def _main_ledger(args) -> int:
    try:
        if args.command == "tail":
            tail = tail_ledger(args.ledger)
            if getattr(args, "publish", False):
                print(json.dumps(tail.get("publish", {}))
                      if args.json else render_publish_tail(tail))
            elif getattr(args, "elastic", False):
                print(json.dumps(tail.get("elastic", {}))
                      if args.json else render_elastic_tail(tail))
            else:
                print(json.dumps(tail) if args.json
                      else render_tail(tail))
            return 0
        diff = diff_ledgers(args.run_a, args.run_b)
        if args.json:
            print(json.dumps(diff))
        else:
            print(render_diff(diff))
        return 0
    except LedgerError as e:
        print(f"ledger error: {e}", file=sys.stderr)
        return 2


def main(argv: Optional[list] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.command in ("tail", "diff"):
        return _main_ledger(args)
    if args.command == "verify" and _is_ledger(args.trace):
        problems = verify_ledger(args.trace)
        if problems:
            print(f"{len(problems)} ledger violation(s):")
            for pr in problems:
                print(f"  - {pr}")
            return 1
        rows, _ = read_rows(args.trace)
        print(f"ledger ok: {len(rows)} rows, seq contiguous, CRCs clean, "
              f"manifest committed")
        return 0
    try:
        trace = load_trace(args.trace)
    except (OSError, ValueError) as e:
        print(f"cannot load {args.trace}: {e}", file=sys.stderr)
        return 2
    if args.command == "verify":
        problems = verify_trace(trace)
        if problems:
            print(f"{len(problems)} trace violation(s):")
            for pr in problems:
                print(f"  - {pr}")
            return 1
        spans = len(_spans(trace))
        print(f"trace ok: {spans} spans, all closed, nesting consistent")
        return 0
    if getattr(args, "serving", False):
        summary = summarize_serving(trace)
        print(json.dumps(summary) if args.json
              else render_serving_summary(summary))
        return 0
    if getattr(args, "kernels", False):
        summary = summarize_kernels(trace)
        print(json.dumps(summary) if args.json
              else render_kernel_summary(summary))
        return 0
    summary = summarize_trace(trace, top=args.top)
    if args.json:
        print(json.dumps(summary))
    else:
        print(render_summary(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
