"""photon-obs: trace-file tooling (docs/OBSERVABILITY.md).

``photon-obs summarize trace.json`` renders the phase waterfall, the
top-span table, and the transfer-vs-compute attribution from a Chrome
trace-event file produced by ``game_train --trace-out`` /
``GameEstimator(trace=...)`` / ``flagship_criteo_stream.py`` — the
machine-checkable replacement for the hand-computed subtraction that
produced the "~95% host→device transfer" figure.

``photon-obs verify trace.json`` is the CI smoke contract (run_tier1.sh):
the JSON loads, spans nest (parents resolve and contain their children),
and every bridged Start/Finish pair produced a CLOSED span.

Pure stdlib — no JAX, no numpy — so it runs anywhere the lint CLI does.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Optional

# Child spans may start marginally before their parent's exported ts:
# the parent's wall anchor and the child's are sampled by different
# clock reads microseconds apart. Containment is asserted with slack.
_NEST_SLACK_US = 500.0


def load_trace(path: str) -> dict:
    with open(path) as f:
        obj = json.load(f)
    if not isinstance(obj, dict) or "traceEvents" not in obj:
        raise ValueError(f"{path} is not a Chrome trace-event file "
                         f"(no traceEvents key)")
    return obj


def _spans(trace: dict) -> list[dict]:
    return [e for e in trace["traceEvents"] if e.get("ph") == "X"]


# -- verify -----------------------------------------------------------------


def verify_trace(trace: dict) -> list[str]:
    """Structural violations (empty list = healthy). The contract CI
    smokes: spans closed, parents resolvable, children contained."""
    problems = []
    spans = _spans(trace)
    if not spans:
        problems.append("trace contains no spans")
        return problems
    by_id = {}
    for e in spans:
        sid = e.get("args", {}).get("span_id")
        if sid is not None:
            by_id[sid] = e
    for e in spans:
        args = e.get("args", {})
        label = f"{e.get('name')}@{e.get('ts'):.0f}us"
        if args.get("unfinished"):
            problems.append(f"span {label} never closed")
        if e.get("dur", 0) < 0:
            problems.append(f"span {label} has negative duration")
        pid_ = args.get("parent_id")
        if pid_ is None:
            continue
        parent = by_id.get(pid_)
        if parent is None:
            problems.append(f"span {label} parent {pid_} not in trace")
            continue
        # Queue-crossing spans (args.crosses_queue — a serving request's
        # enqueue→respond life parented into the flush that scored it)
        # START before their parent by design: the queue wait precedes
        # the flush. Containment is then asserted at the tail only.
        if not args.get("crosses_queue") \
                and e["ts"] + _NEST_SLACK_US < parent["ts"]:
            problems.append(
                f"span {label} is not contained in its parent "
                f"{parent.get('name')} interval")
        elif e["ts"] + e["dur"] > parent["ts"] + parent["dur"] \
                + _NEST_SLACK_US:
            problems.append(
                f"span {label} is not contained in its parent "
                f"{parent.get('name')} interval")
    meta = trace.get("otherData", {})
    if meta.get("open_spans"):
        problems.append(f"{meta['open_spans']} span(s) still open at dump")
    opened = meta.get("bridge_spans_opened")
    closed = meta.get("bridge_spans_closed")
    if opened is not None and opened != closed:
        problems.append(
            f"event bridge opened {opened} lifecycle span(s) but closed "
            f"{closed} — a Start/Finish pair leaked")
    if meta.get("bridge_spans_leaked"):
        problems.append(
            f"{meta['bridge_spans_leaked']} bridged scope(s) never saw "
            f"their Finish event")
    return problems


# -- summarize --------------------------------------------------------------


def summarize_trace(trace: dict, top: int = 12) -> dict:
    """Waterfall + top spans + transfer-vs-compute attribution."""
    spans = _spans(trace)
    if not spans:
        return {"wall_seconds": 0.0, "waterfall": [], "top_spans": [],
                "attribution": {}}
    t_min = min(e["ts"] for e in spans)
    t_max = max(e["ts"] + e["dur"] for e in spans)
    wall_us = max(t_max - t_min, 1e-9)

    ids = {e["args"]["span_id"] for e in spans
           if "span_id" in e.get("args", {})}
    roots = [e for e in spans
             if e.get("args", {}).get("parent_id") not in ids]
    roots.sort(key=lambda e: e["ts"])
    waterfall = [{
        "name": e["name"], "cat": e.get("cat", ""),
        "start_s": (e["ts"] - t_min) / 1e6, "dur_s": e["dur"] / 1e6,
        "frac": e["dur"] / wall_us,
    } for e in roots]

    agg: dict[tuple, dict] = {}
    for e in spans:
        a = agg.setdefault((e["name"], e.get("cat", "")),
                           {"count": 0, "total_us": 0.0, "max_us": 0.0})
        a["count"] += 1
        a["total_us"] += e["dur"]
        a["max_us"] = max(a["max_us"], e["dur"])
    top_spans = [{
        "name": k[0], "cat": k[1], "count": v["count"],
        "total_s": v["total_us"] / 1e6, "max_s": v["max_us"] / 1e6,
        "frac_of_wall": v["total_us"] / wall_us,
    } for k, v in sorted(agg.items(), key=lambda kv: -kv[1]["total_us"])]

    # Transfer vs compute: transfer = the device_put accounting spans
    # (cat "transfer"); the denominator is the streamed-pass time when
    # passes exist (the bench-comparable fraction), else the wall.
    transfer_us = sum(e["dur"] for e in spans
                      if e.get("cat") == "transfer")
    pass_us = sum(e["dur"] for e in spans
                  if e["name"] == "stream.pass")
    denom = pass_us if pass_us > 0 else wall_us
    attribution = {
        "transfer_seconds": transfer_us / 1e6,
        "stream_pass_seconds": pass_us / 1e6,
        "wall_seconds": wall_us / 1e6,
        "transfer_fraction_of_stream": transfer_us / denom,
        "transfer_fraction_of_wall": transfer_us / wall_us,
    }
    root_cover = sum(e["dur"] for e in roots)
    return {
        "wall_seconds": wall_us / 1e6,
        "top_level_coverage": min(root_cover / wall_us, 1.0),
        "waterfall": waterfall[:max(top, len(waterfall))],
        "top_spans": top_spans[:top],
        "attribution": attribution,
    }


_REQUEST_STAGES = ("serving.queue_wait", "serving.assemble",
                   "serving.device_score", "serving.respond")


def _pctl(sorted_vals: list, p: float) -> float:
    """Nearest-rank percentile over a pre-sorted list (stdlib-only —
    this module must run without numpy)."""
    if not sorted_vals:
        return 0.0
    k = max(0, min(len(sorted_vals) - 1,
                   round(p / 100.0 * (len(sorted_vals) - 1))))
    return float(sorted_vals[k])


def summarize_serving(trace: dict) -> dict:
    """Request-path view of a serving trace (``summarize --serving``):
    request latency percentiles from the ``serving.request`` spans,
    stage attribution (where request time went across queue wait /
    assemble / device score / respond), flush stats, and the slowest
    request's waterfall — the per-request counterpart of the batch-side
    transfer attribution."""
    spans = _spans(trace)
    requests = [e for e in spans if e["name"] == "serving.request"]
    flushes = [e for e in spans if e["name"] == "serving.flush"]
    if not requests:
        return {"requests": 0, "flushes": len(flushes)}
    durs_ms = sorted(e["dur"] / 1e3 for e in requests)
    total_ms = sum(durs_ms)
    by_parent: dict = {}
    for e in spans:
        pid_ = e.get("args", {}).get("parent_id")
        if pid_ is not None and e["name"] in _REQUEST_STAGES:
            by_parent.setdefault(pid_, []).append(e)
    stage_ms = {s: 0.0 for s in _REQUEST_STAGES}
    for e in spans:
        if e["name"] in stage_ms:
            stage_ms[e["name"]] += e["dur"] / 1e3
    attributed = sum(stage_ms.values())
    slowest = max(requests, key=lambda e: e["dur"])
    slow_id = slowest.get("args", {}).get("span_id")
    waterfall = [{
        "stage": c["name"], "start_ms": (c["ts"] - slowest["ts"]) / 1e3,
        "dur_ms": c["dur"] / 1e3,
        "frac": c["dur"] / max(slowest["dur"], 1e-9),
    } for c in sorted(by_parent.get(slow_id, []), key=lambda c: c["ts"])]
    return {
        "requests": len(requests),
        "flushes": len(flushes),
        "request_latency_ms": {
            "p50": _pctl(durs_ms, 50), "p95": _pctl(durs_ms, 95),
            "p99": _pctl(durs_ms, 99), "max": durs_ms[-1],
            "mean": total_ms / len(durs_ms),
        },
        "request_seconds_total": total_ms / 1e3,
        "stage_attribution": {
            s: {"seconds": stage_ms[s] / 1e3,
                "frac_of_request_time": stage_ms[s] / max(total_ms, 1e-9)}
            for s in _REQUEST_STAGES},
        "attributed_fraction": attributed / max(total_ms, 1e-9),
        "slowest_request": {
            "request_id": slowest.get("args", {}).get("request_id"),
            "total_ms": slowest["dur"] / 1e3,
            "waterfall": waterfall,
        },
    }


def render_serving_summary(summary: dict) -> str:
    if not summary.get("requests"):
        return (f"no serving.request spans in this trace "
                f"({summary.get('flushes', 0)} flush span(s)) — was the "
                f"service traced? (obs.enable() before requests arrive)")
    lat = summary["request_latency_ms"]
    out = [f"{summary['requests']} request(s) over "
           f"{summary['flushes']} flush(es); request latency "
           f"p50 {lat['p50']:.2f}ms  p95 {lat['p95']:.2f}ms  "
           f"p99 {lat['p99']:.2f}ms  max {lat['max']:.2f}ms", "",
           "stage attribution (of total request time, "
           f"{summary['request_seconds_total']:.3f}s):"]
    for stage, a in summary["stage_attribution"].items():
        out.append(f"  {stage:<22} {_bar(a['frac_of_request_time'])} "
                   f"{a['frac_of_request_time']:>6.1%}  "
                   f"{a['seconds']:.3f}s")
    out.append(f"  (stages cover {summary['attributed_fraction']:.1%} "
               f"of request time; the gap is batcher wakeup jitter)")
    slow = summary["slowest_request"]
    out += ["", f"slowest request (id {slow['request_id']}, "
                f"{slow['total_ms']:.2f}ms):"]
    for w in slow["waterfall"]:
        out.append(f"  {w['start_ms']:8.2f}ms  {_bar(w['frac'])} "
                   f"{w['dur_ms']:8.2f}ms  {w['stage']}")
    return "\n".join(out)


def _bar(frac: float, width: int = 30) -> str:
    n = max(0, min(width, round(frac * width)))
    return "#" * n + "." * (width - n)


def render_summary(summary: dict) -> str:
    out = [f"wall {summary['wall_seconds']:.3f}s; top-level spans cover "
           f"{summary.get('top_level_coverage', 0.0):.0%} of it", "",
           "phase waterfall (top-level spans):"]
    for w in summary["waterfall"]:
        out.append(f"  {w['start_s']:9.3f}s  {_bar(w['frac'])} "
                   f"{w['dur_s']:9.3f}s  {w['name']} [{w['cat']}]")
    out += ["", f"top spans by total time:"]
    out.append(f"  {'name':<28} {'cat':<10} {'count':>6} {'total_s':>9} "
               f"{'max_s':>8} {'% wall':>7}")
    for t in summary["top_spans"]:
        out.append(f"  {t['name']:<28} {t['cat']:<10} {t['count']:>6} "
                   f"{t['total_s']:>9.3f} {t['max_s']:>8.3f} "
                   f"{t['frac_of_wall']:>6.1%}")
    a = summary["attribution"]
    out += ["", "transfer vs compute:"]
    out.append(f"  host→device transfer {a['transfer_seconds']:.3f}s of "
               f"{a['stream_pass_seconds']:.3f}s streamed-pass time "
               f"({a['transfer_fraction_of_stream']:.1%}); "
               f"{a['transfer_fraction_of_wall']:.1%} of wall")
    return "\n".join(out)


# -- CLI --------------------------------------------------------------------


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="photon-obs", description=__doc__)
    sub = p.add_subparsers(dest="command", required=True)
    s = sub.add_parser("summarize",
                       help="phase waterfall + top spans + transfer "
                            "attribution from a trace file")
    s.add_argument("trace", help="Chrome trace-event JSON "
                                 "(game_train --trace-out)")
    s.add_argument("--top", type=int, default=12,
                   help="rows in the top-span table")
    s.add_argument("--json", action="store_true",
                   help="machine-readable summary instead of text")
    s.add_argument("--serving", action="store_true",
                   help="request-path view: request latency percentiles, "
                        "stage attribution (queue wait / assemble / "
                        "device score / respond), and the slowest "
                        "request's waterfall (docs/SERVING.md)")
    v = sub.add_parser("verify",
                       help="structural health check (CI smoke): spans "
                            "closed, parents resolve, children nested")
    v.add_argument("trace")
    return p


def main(argv: Optional[list] = None) -> int:
    args = build_parser().parse_args(argv)
    try:
        trace = load_trace(args.trace)
    except (OSError, ValueError) as e:
        print(f"cannot load {args.trace}: {e}", file=sys.stderr)
        return 2
    if args.command == "verify":
        problems = verify_trace(trace)
        if problems:
            print(f"{len(problems)} trace violation(s):")
            for pr in problems:
                print(f"  - {pr}")
            return 1
        spans = len(_spans(trace))
        print(f"trace ok: {spans} spans, all closed, nesting consistent")
        return 0
    if getattr(args, "serving", False):
        summary = summarize_serving(trace)
        print(json.dumps(summary) if args.json
              else render_serving_summary(summary))
        return 0
    summary = summarize_trace(trace, top=args.top)
    if args.json:
        print(json.dumps(summary))
    else:
        print(render_summary(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
