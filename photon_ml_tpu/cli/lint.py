"""photon-lint — the repo's static-analysis gate.

    photon-lint photon_ml_tpu/                 # human output, exit 0/1
    photon-lint --format json photon_ml_tpu/   # machine output
    photon-lint --catalog                      # string-registry JSON
    photon-lint --locks                        # global lock graph JSON
    photon-lint --locks --reconcile .photon-lockdep.json
                                               # diff vs runtime lockdep
    photon-lint --write-baseline --reason "…"  # grandfather current findings

Exit codes: 0 clean (baselined findings and stale-entry warnings do not
gate), 1 findings, 2 usage/internal error. The baseline defaults to
``.photon-lint-baseline.json`` in the working directory when present.

Per-file rules (PML001-PML011) run on each file alone; project rules
(PML012-PML016) run on a repo-wide symbol table + call graph
(analysis/project.py) whose per-file summaries are cached in
``.photon-lint-cache.json`` keyed by size/mtime/CRC — a warm repo-wide
run re-parses only changed files. ``--catalog`` emits the string-keyed
registries (fault sites, events, metrics, spans) that rule PML014
resolves call-site literals against.

Deliberately JAX-free: this module (and everything under analysis/) is
pure stdlib, so the gate runs in seconds anywhere — CI sets it before the
test matrix, dev-scripts/run_tier1.sh runs it before pytest.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional

from photon_ml_tpu.analysis import (ALL_RULES, DEFAULT_BASELINE,
                                    DEFAULT_CACHE, PROJECT_RULES,
                                    entries_from_findings, lint_paths,
                                    reconcile, save_baseline)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="photon-lint",
        description="AST lint for this repo's JAX/concurrency/robustness "
                    "bug classes (per-file PML001-PML011, whole-program "
                    "PML012-PML016)")
    p.add_argument("paths", nargs="*", default=["photon_ml_tpu"],
                   help="files/directories to lint "
                        "(default: photon_ml_tpu)")
    p.add_argument("--format", default="human",
                   choices=["human", "json"])
    p.add_argument("--select", default="",
                   help="comma-separated rule ids to run (default: all)")
    p.add_argument("--ignore", default="",
                   help="comma-separated rule ids to skip")
    p.add_argument("--baseline", default=None,
                   help=f"baseline file (default: {DEFAULT_BASELINE} "
                        f"when it exists)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore any baseline file")
    p.add_argument("--no-project", action="store_true",
                   help="skip the project graph and rules PML012-016 "
                        "(fast single-file mode)")
    p.add_argument("--no-cache", action="store_true",
                   help="do not read or write the summary cache")
    p.add_argument("--cache", default=DEFAULT_CACHE,
                   help=f"summary cache file (default: {DEFAULT_CACHE})")
    p.add_argument("--catalog", action="store_true",
                   help="emit the string-keyed registries (fault sites, "
                        "events, metrics, spans) as JSON and exit 0")
    p.add_argument("--locks", action="store_true",
                   help="emit the global lock graph (nodes = class.attr "
                        "locks, edges with witness call chains) as "
                        "deterministic JSON and exit 0")
    p.add_argument("--reconcile", default=None, metavar="PATH",
                   help="with --locks: diff the static graph against a "
                        "runtime .photon-lockdep.json dump; exit 1 on "
                        "inversions or runtime-only (resolver-gap) edges")
    p.add_argument("--allow-gap", action="append", default=[],
                   metavar="SRC->DST",
                   help="with --reconcile: accept this runtime-only edge "
                        "as a tracked known gap (repeatable)")
    p.add_argument("--write-baseline", action="store_true",
                   help="write current findings to the baseline file "
                        "and exit 0 (requires --reason)")
    p.add_argument("--reason", default="",
                   help="justification recorded on each baseline entry "
                        "written by --write-baseline")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    return p


def _rule_set(spec: str) -> Optional[set[str]]:
    ids = {s.strip().upper() for s in spec.split(",") if s.strip()}
    if not ids:
        return None
    known = set(ALL_RULES) | set(PROJECT_RULES)
    unknown = ids - known
    if unknown:
        raise SystemExit(
            f"photon-lint: unknown rule id(s): {', '.join(sorted(unknown))}"
            f" (known: {', '.join(sorted(known))})")
    return ids


def main(argv: Optional[list[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        for rid, (_check, doc) in {**ALL_RULES, **PROJECT_RULES}.items():
            print(f"{rid}  {doc}")
        return 0
    baseline = None if args.no_baseline else (
        args.baseline or (DEFAULT_BASELINE
                          if os.path.exists(DEFAULT_BASELINE) else None))
    cache = None if args.no_cache else args.cache
    try:
        select = _rule_set(args.select)
        ignore = _rule_set(args.ignore)
        if args.catalog:
            result = lint_paths(args.paths, select=select, ignore=ignore,
                                baseline_path=None, project=False,
                                cache_path=cache, want_catalog=True)
            print(json.dumps(result.catalog, indent=2, sort_keys=True))
            return 0
        if args.locks or args.reconcile:
            result = lint_paths(args.paths, select=select, ignore=ignore,
                                baseline_path=None, project=False,
                                cache_path=cache, want_locks=True)
            if args.reconcile:
                try:
                    with open(args.reconcile) as fh:
                        runtime = json.load(fh)
                except (OSError, ValueError) as exc:
                    print(f"photon-lint: cannot read runtime lock dump "
                          f"{args.reconcile}: {exc}", file=sys.stderr)
                    return 2
                rep = reconcile(result.lock_graph, runtime,
                                allow_gaps=tuple(args.allow_gap))
                print(json.dumps(rep, indent=2, sort_keys=True))
                return 0 if rep["ok"] else 1
            print(json.dumps(result.lock_graph, indent=2))
            return 0
        if args.write_baseline:
            if not args.reason.strip():
                print("photon-lint: --write-baseline requires --reason "
                      "(every grandfathered finding must say why)",
                      file=sys.stderr)
                return 2
            result = lint_paths(args.paths, select=select, ignore=ignore,
                                baseline_path=None,
                                project=not args.no_project,
                                cache_path=cache)
            target = args.baseline or DEFAULT_BASELINE
            save_baseline(target, entries_from_findings(result.findings,
                                                        args.reason))
            print(f"photon-lint: wrote {len(result.findings)} entr"
                  f"{'y' if len(result.findings) == 1 else 'ies'} "
                  f"to {target}")
            return 0
        result = lint_paths(args.paths, select=select, ignore=ignore,
                            baseline_path=baseline,
                            project=not args.no_project,
                            cache_path=cache)
    except SystemExit:
        raise
    except Exception as exc:
        print(f"photon-lint: internal error: {type(exc).__name__}: {exc}",
              file=sys.stderr)
        return 2

    if args.format == "json":
        print(json.dumps({
            "files": result.files,
            "graph_files": result.graph_files,
            "cache": {"hits": result.cache_hits,
                      "misses": result.cache_misses},
            "findings": [f.to_json() for f in result.findings],
            "baselined": result.baselined,
            "stale_baseline": [e.to_json()
                               for e in result.stale_baseline],
            "unused_suppressions": [
                {"path": p, "line": ln}
                for p, ln in result.unused_suppressions],
            "exit_code": result.exit_code,
        }, indent=2))
        return result.exit_code

    for f in result.findings:
        print(f.render())
    for e in result.stale_baseline:
        print(f"stale baseline entry: {e.rule} in {e.path} "
              f"({e.fingerprint}) — finding no longer exists; delete "
              f"the entry  [{e.snippet}]")
    for path, line in result.unused_suppressions:
        print(f"unused suppression: {path}:{line} silences nothing — "
              f"delete it")
    n = len(result.findings)
    bits = [f"{result.files} files", f"{n} finding{'s' * (n != 1)}"]
    if result.graph_files > result.files:
        bits.append(f"graph over {result.graph_files}")
    if result.cache_hits or result.cache_misses:
        bits.append(f"cache {result.cache_hits}/{result.cache_hits + result.cache_misses} warm")
    if result.baselined:
        bits.append(f"{result.baselined} baselined")
    if result.stale_baseline:
        bits.append(f"{len(result.stale_baseline)} stale baseline "
                    f"entr{'y' if len(result.stale_baseline) == 1 else 'ies'}")
    print(f"photon-lint: {', '.join(bits)}")
    return result.exit_code


if __name__ == "__main__":
    raise SystemExit(main())
