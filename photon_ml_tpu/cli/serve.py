"""Online scoring service driver.

Reference parity: none — the reference stops at batch scoring
(GameScoringDriver); this driver is the serving half the ROADMAP's
"heavy traffic" north star needs. Loads a trained GameModel once, keeps it
resident (photon_ml_tpu/serving/), and answers JSON-over-HTTP scoring
requests at low latency with micro-batching and a metrics endpoint.

Quickstart (docs/SERVING.md):

    photon-game-serve --model-dir out/best --port 8080
    curl -s localhost:8080/score -d '{"requests": [{"features": \
        {"global": [0.1, ...]}, "entity_ids": {"userId": 7}}]}'
    curl -s localhost:8080/metrics
"""

from __future__ import annotations

import argparse
import json
import logging
import os

from photon_ml_tpu.models import io as model_io
from photon_ml_tpu.serving.service import ScoringService, make_http_server
from photon_ml_tpu.utils.compile_cache import enable_compilation_cache
from photon_ml_tpu.utils.logging import setup_logging

logger = logging.getLogger("photon_ml_tpu.cli")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--model-dir", required=True, help="GameModel directory")
    p.add_argument("--model-format", default="NPZ",
                   choices=["NPZ", "AVRO"],
                   help="AVRO loads a best-avro directory through "
                        "--feature-index-dir (same contract as game_score)")
    p.add_argument("--feature-index-dir",
                   help="REQUIRED with --model-format AVRO: the training "
                        "run's saved index maps")
    p.add_argument("--entity-vocabs",
                   help="entity-vocabs.json mapping raw entity keys to "
                        "vocabulary rows; lets requests carry raw string "
                        "ids. Auto-discovered beside --feature-index-dir "
                        "when present")
    p.add_argument("--as-mean", action="store_true",
                   help="serve probabilities/rates (inverse link) instead "
                        "of raw linear scores")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8080,
                   help="0 picks a free port (printed at startup)")
    p.add_argument("--max-batch", type=int, default=64,
                   help="micro-batch flush size (also the largest padded "
                        "batch shape)")
    p.add_argument("--max-wait-ms", type=float, default=2.0,
                   help="max time a queued request waits for batch-mates")
    p.add_argument("--cache-entities", type=int, default=4096,
                   help="per-coordinate LRU device cache capacity "
                        "(random-effect rows)")
    p.add_argument("--cache-dtype", default="float32",
                   choices=["float32", "int8"],
                   help="device-LRU storage dtype: int8 (symmetric "
                        "per-row quantization, dequantized in the "
                        "scoring gather) caches ~4x the entities per "
                        "HBM byte at a sub-1e-2 score perturbation "
                        "(docs/SERVING.md \"Quantized device cache\")")
    p.add_argument("--store-shards", type=int, default=8,
                   help="hash shards of the host-resident random-effect "
                        "store (mapped boots keep the generation's "
                        "tables whole and gather directly — the shard "
                        "count then only names the future RPC seam)")
    p.add_argument("--boot-warmup", action="store_true",
                   help="touch every power-of-two bucket shape before "
                        "serving, so the first real request never pays "
                        "a compile; with the persistent compilation "
                        "cache warm these are disk hits "
                        "(photon_compile_cache_hits_total) — the "
                        "boot.warmup phase of docs/SERVING.md "
                        "\"Sub-second restart\"")
    p.add_argument("--max-queue", type=int, default=None,
                   help="admission-control bound on queued requests "
                        "(default 16×max-batch); overflow sheds with "
                        "HTTP 503 instead of buffering unboundedly "
                        "(docs/ROBUSTNESS.md)")
    p.add_argument("--request-deadline-s", type=float, default=30.0,
                   help="per-request deadline: a request still queued "
                        "past this fails fast with 504 instead of "
                        "waiting forever (0 disables)")
    p.add_argument("--slo-window-s", type=float, default=60.0,
                   help="sliding window of the /slo tracker (latency "
                        "percentiles + error-budget burn)")
    p.add_argument("--slo-availability", type=float, default=0.999,
                   help="availability objective: shed/deadline/5xx "
                        "burn the 1-objective error budget")
    p.add_argument("--slo-latency-ms", type=float, default=None,
                   help="optional latency objective: answered requests "
                        "slower than this also burn error budget")
    p.add_argument("--trace-out",
                   help="write a Chrome trace-event JSON of the serving "
                        "session at shutdown (request spans with queue/"
                        "assemble/device/respond attribution children "
                        "parented into their flush spans) — written "
                        "from a finally, so a crashed server keeps its "
                        "timeline; render with `photon-obs summarize "
                        "--serving` (docs/OBSERVABILITY.md)")
    p.add_argument("--metrics-dump",
                   help="write the full /metrics exposition (serving "
                        "scoreboard + cross-stack registry) to this "
                        "file at shutdown, also from a finally — the "
                        "game_train --metrics-dump parity flag")
    # -- fleet-replica plumbing (serving/fleet.py spawns these) ----------
    p.add_argument("--ready-file",
                   help="after binding, atomically write {pid, host, "
                        "port} JSON here — the supervisor's handshake "
                        "for --port 0 replicas (no port-allocation "
                        "race, no pipe to overflow)")
    p.add_argument("--replica-id", type=int, default=None,
                   help="this server's stable fleet index: fault site "
                        "fleet.replica_flush fires with it, logs carry "
                        "it (set by the fleet supervisor)")
    p.add_argument("--fault-plan",
                   help="JSON FaultPlan installed at startup — the "
                        "game_train --fault-plan parity flag; how "
                        "fleet chaos drills reach inside a replica "
                        "(docs/ROBUSTNESS.md)")
    return p


def load_model(args):
    """Load (model, entity_vocabs, boot_meta) per the driver's format
    flags. ``boot_meta`` is ``{"generation": g, "model_version": v}``
    for a mapped/generation boot and ``{}`` for the classic layouts —
    layout auto-detection (photon_ml_tpu/boot) means a ``--model-dir``
    pointing at a generation root boots the CURRENT generation with the
    corruption fallback ladder, with zero new flags."""
    from photon_ml_tpu import boot

    vocabs = None
    if args.entity_vocabs:
        with open(args.entity_vocabs) as f:
            vocabs = json.load(f)
    kind, path, _ = boot.resolve_model_path(args.model_dir)
    if kind == "generations" and args.model_format != "AVRO":
        model, marker, gen = boot.GenerationStore(path).load_current()
        logger.info("mapped boot: generation gen-%06d (model_version "
                    "%d) of %s", gen, int(marker.get("model_version", 0)),
                    path)
        return model, vocabs, {"generation": gen,
                               "model_version":
                                   int(marker.get("model_version", 0))}
    if kind == "mapped" and args.model_format != "AVRO":
        model, marker = boot.load_mapped_model(path)
        return model, vocabs, {"generation": marker.get("generation"),
                               "model_version":
                                   int(marker.get("model_version", 0))}
    if args.model_format == "AVRO":
        from photon_ml_tpu.avro.model_io import (load_game_model_avro,
                                                 load_index_maps)

        if not args.feature_index_dir:
            raise ValueError(
                "--model-format AVRO needs --feature-index-dir (the "
                "model's feature space)")
        imaps = load_index_maps(args.feature_index_dir)
        if vocabs is None:
            vocab_path = os.path.join(
                os.path.dirname(args.feature_index_dir.rstrip("/")),
                "entity-vocabs.json")
            if os.path.exists(vocab_path):
                with open(vocab_path) as f:
                    vocabs = json.load(f)
        return load_game_model_avro(args.model_dir, imaps,
                                    entity_vocabs=vocabs), vocabs, {}
    # host=True: random-effect tables go straight to the host store —
    # never staged through device memory on the way in.
    return model_io.load_game_model(args.model_dir, host=True,
                                    mapped=False), vocabs, {}


def _boot_phase_gauges(phases: dict[str, float],
                       generation) -> None:
    """``photon_boot_seconds{phase=...}`` + ``photon_model_generation``
    — the restart tail as numbers, not a log line (one None check when
    metrics are off)."""
    from photon_ml_tpu import obs

    mx = obs.metrics()
    if mx is None:
        return
    for phase, seconds in phases.items():
        mx.gauge("photon_boot_seconds", phase=phase).set(seconds)
    if generation is not None:
        mx.gauge("photon_model_generation").set(float(generation))


def create_server(args):
    """Build the resident service + bound HTTP server (not yet serving).

    Split from ``main`` so tests and embedding callers can drive the
    server loop themselves; returns (server, service).

    Construction is attributed as a ``serving.boot`` span with
    ``boot.map`` (model load — an mmap for generation/mapped layouts, a
    parse for npz), ``boot.compile`` (service + program construction)
    and ``boot.warmup`` (bucket-shape touches, ``--boot-warmup``)
    children — recorded AFTER the fact via ``record_complete`` so the
    service's own lifecycle span (the ScoringStart/Finish bridge pair,
    which outlives boot by the whole serving session) never nests
    inside a boot phase (docs/SERVING.md "Sub-second restart")."""
    import time as _time

    from photon_ml_tpu import obs

    if getattr(args, "fault_plan", None):
        from photon_ml_tpu import faults as flt

        with open(args.fault_plan) as f:
            flt.install(flt.FaultPlan.from_json(f.read()))
        logger.warning("fault plan %s ARMED in this server",
                       args.fault_plan)
    marks = {}

    def _phase(name, t0, e0):
        marks[name] = (e0, _time.perf_counter() - t0)

    t_boot, e_boot = _time.perf_counter(), _time.time_ns()
    enable_compilation_cache()
    t0, e0 = _time.perf_counter(), _time.time_ns()
    model, vocabs, boot_meta = load_model(args)
    _phase("boot.map", t0, e0)
    t0, e0 = _time.perf_counter(), _time.time_ns()
    service = ScoringService(
        model, as_mean=args.as_mean, max_batch=args.max_batch,
        max_wait_ms=args.max_wait_ms,
        cache_entities=args.cache_entities,
        cache_dtype=getattr(args, "cache_dtype", "float32"),
        store_shards=args.store_shards, entity_vocabs=vocabs,
        max_queue=args.max_queue,
        request_deadline_s=(args.request_deadline_s or None),
        slo_window_s=getattr(args, "slo_window_s", 60.0),
        slo_availability=getattr(args, "slo_availability", 0.999),
        slo_latency_ms=getattr(args, "slo_latency_ms", None),
        replica_id=getattr(args, "replica_id", None),
        initial_version=int(boot_meta.get("model_version", 0) or 0),
        boot_generation=boot_meta.get("generation"))
    _phase("boot.compile", t0, e0)
    if getattr(args, "boot_warmup", False):
        t0, e0 = _time.perf_counter(), _time.time_ns()
        shapes = service.warmup()
        _phase("boot.warmup", t0, e0)
        logger.info("boot warmup: %d bucket shape(s) in %.3fs", shapes,
                    marks["boot.warmup"][1])
    total = _time.perf_counter() - t_boot
    tr = obs.tracer()
    if tr is not None:
        bid = tr.record_complete("serving.boot", cat="serving",
                                 t0_epoch_ns=e_boot, dur_s=total,
                                 generation=boot_meta.get("generation"))
        for name, (e0, dur) in marks.items():
            tr.record_complete(name, cat="serving", t0_epoch_ns=e0,
                               dur_s=dur, parent=bid)
    phases = {"map": marks["boot.map"][1],
              "compile": marks["boot.compile"][1],
              "warmup": marks.get("boot.warmup", (0, 0.0))[1],
              "total": total}
    t_map, t_compile, t_warm = (phases["map"], phases["compile"],
                                phases["warmup"])
    _boot_phase_gauges(phases, boot_meta.get("generation"))
    logger.info("boot: map %.3fs, compile %.3fs, warmup %.3fs "
                "(generation %s)", t_map, t_compile, t_warm,
                boot_meta.get("generation"))
    server = make_http_server(service, host=args.host, port=args.port)
    if getattr(args, "ready_file", None):
        # Atomic: the supervisor polling this file must never read a
        # torn write (same tmp+rename discipline as every commit point).
        host, port = server.server_address[:2]
        tmp = args.ready_file + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"pid": os.getpid(), "host": host, "port": port}, f)
        os.replace(tmp, args.ready_file)
    return server, service


def _dump_observability(service, trace_out, metrics_dump) -> None:
    """Shutdown/crash dump path (runs in a ``finally``): a served session
    keeps its timeline and scoreboard even when the server dies — the
    crash is exactly when you want them (game_train parity)."""
    from photon_ml_tpu import obs

    if trace_out:
        obs.dump_trace(trace_out)
        logger.info("wrote trace %s (render with `photon-obs summarize "
                    "--serving`)", trace_out)
    if metrics_dump:
        tmp = metrics_dump + ".tmp"
        with open(tmp, "w") as f:
            f.write(service.metrics_text())
        os.replace(tmp, metrics_dump)
        logger.info("wrote metrics %s", metrics_dump)


def run(args) -> None:
    setup_logging()
    trace_out = getattr(args, "trace_out", None)
    metrics_dump = getattr(args, "metrics_dump", None)
    if trace_out or metrics_dump:
        from photon_ml_tpu import obs

        # Metrics ride along with tracing (the request-span path needs
        # the tracer; the /metrics registry append needs the registry).
        obs.enable(trace=bool(trace_out), metrics=True)
    server, service = create_server(args)
    host, port = server.server_address[:2]
    logger.info("serving %s on http://%s:%d (POST /score, GET /metrics, "
                "GET /slo)", args.model_dir, host, port)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        logger.info("shutting down")
    finally:
        server.server_close()
        service.close()
        if trace_out or metrics_dump:
            from photon_ml_tpu import obs

            try:
                _dump_observability(service, trace_out, metrics_dump)
            finally:
                obs.disable()


def main(argv=None):
    run(build_parser().parse_args(argv))


if __name__ == "__main__":
    main()
