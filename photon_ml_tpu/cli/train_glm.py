"""Single-GLM training driver (the legacy Photon pipeline).

Reference parity: photon-client ``Driver.scala`` + ``io/GLMSuite.scala`` —
stages INIT → TRAIN → VALIDATE: read data, summarize/normalize, train one
model per regularization weight, evaluate each on validation data, select
and save the best model (``ModelOutputMode`` ALL/BEST).

Usage:
    python -m photon_ml_tpu.cli.train_glm \
        --train a1a.libsvm --validation a1a.t.libsvm \
        --task LOGISTIC_REGRESSION --optimizer LBFGS \
        --reg-weights 0.1,1,10 --normalization STANDARDIZATION \
        --output-dir /tmp/model
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import time

import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.data.batch import LabeledBatch
from photon_ml_tpu.data.libsvm import read_libsvm
from photon_ml_tpu.data.statistics import (normalization_from_statistics,
                                           summarize)
from photon_ml_tpu.data.validators import (DataValidationLevel,
                                           validate_arrays,
                                           validate_features)
from photon_ml_tpu.evaluation import evaluators as ev
from photon_ml_tpu.models import io as model_io
from photon_ml_tpu.models.glm import GeneralizedLinearModel
from photon_ml_tpu.models.coefficients import Coefficients
from photon_ml_tpu.normalization import NormalizationType
from photon_ml_tpu.ops import losses as losses_mod
from photon_ml_tpu.optim import OptimizerConfig, OptimizerType
from photon_ml_tpu.optim.problem import (GLMOptimizationConfiguration,
                                         VarianceComputationType)
from photon_ml_tpu.optim.regularization import (RegularizationContext,
                                                RegularizationType)
from photon_ml_tpu.parallel import problem as dist_problem
from photon_ml_tpu.parallel.mesh import make_mesh
from photon_ml_tpu.types import TaskType
from photon_ml_tpu.utils.compile_cache import enable_compilation_cache
from photon_ml_tpu.utils.logging import setup_logging

logger = logging.getLogger("photon_ml_tpu.cli")

_DEFAULT_EVALUATOR = {
    TaskType.LOGISTIC_REGRESSION: "AUC",
    TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM: "AUC",
    TaskType.LINEAR_REGRESSION: "RMSE",
    TaskType.POISSON_REGRESSION: "POISSON_LOSS",
}


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--train", required=True, help="training LIBSVM file")
    p.add_argument("--validation", help="validation LIBSVM file")
    p.add_argument("--task", default="LOGISTIC_REGRESSION",
                   choices=[t.value for t in TaskType])
    p.add_argument("--optimizer", default="LBFGS",
                   choices=[o.value for o in OptimizerType])
    p.add_argument("--reg-type", default="L2",
                   choices=[r.value for r in RegularizationType])
    p.add_argument("--reg-weights", default="1.0",
                   help="comma-separated regularization weight grid")
    p.add_argument("--elastic-net-alpha", type=float, default=0.5)
    p.add_argument("--max-iterations", type=int, default=100)
    p.add_argument("--tolerance", type=float, default=1e-7)
    p.add_argument("--normalization", default="NONE",
                   choices=[n.value for n in NormalizationType])
    p.add_argument("--no-intercept", action="store_true")
    p.add_argument("--variance", default="NONE",
                   choices=[v.value for v in VarianceComputationType])
    p.add_argument("--output-dir", required=True)
    p.add_argument("--output-mode", default="BEST", choices=["BEST", "ALL"])
    p.add_argument("--num-features", type=int,
                   help="fixed feature-space size (else inferred)")
    p.add_argument("--data-validation", default="VALIDATE_FULL",
                   choices=[v.value for v in DataValidationLevel],
                   help="input sanity checks (reference DataValidators)")
    p.add_argument("--summarization-output-dir",
                   help="write per-feature FeatureSummarizationResultAvro "
                        "records here (reference summarization output)")
    return p


def run(args) -> dict:
    setup_logging()
    enable_compilation_cache()
    task = TaskType(args.task)
    loss = losses_mod.loss_for_task(task)
    t0 = time.perf_counter()  # duration base (PML004)

    train = read_libsvm(args.train, num_features=args.num_features)
    X = train.to_dense()
    intercept_index = None
    if not args.no_intercept:
        X = np.concatenate([X, np.ones((X.shape[0], 1), np.float32)], 1)
        intercept_index = X.shape[1] - 1
    # INIT-stage sanity checks (reference: DataValidators.sanityCheckData).
    vlevel = DataValidationLevel(args.data_validation)
    validate_arrays(task, train.labels, level=vlevel)
    validate_features("train", X, level=vlevel)

    batch = LabeledBatch.build(X, train.labels)
    logger.info("read %d x %d training examples", *X.shape)

    stats = summarize(batch)
    norm = normalization_from_statistics(
        stats, NormalizationType(args.normalization), intercept_index)
    if args.summarization_output_dir:
        from photon_ml_tpu.avro.summarization import write_feature_summaries
        from photon_ml_tpu.index.indexmap import (INTERCEPT_KEY,
                                                  DefaultIndexMap)

        # LIBSVM columns carry no names; synthesize the reference's
        # name-per-column form (column index as the name).
        keys = [str(j) for j in range(X.shape[1]
                                      - (0 if args.no_intercept else 1))]
        imap = DefaultIndexMap.from_keys(keys,
                                         add_intercept=not args.no_intercept)
        os.makedirs(args.summarization_output_dir, exist_ok=True)
        write_feature_summaries(
            os.path.join(args.summarization_output_dir,
                         "feature-summaries.avro"),
            stats, imap)
        logger.info("wrote feature summaries to %s",
                    args.summarization_output_dir)

    mesh = make_mesh()
    reg_weights = [float(w) for w in args.reg_weights.split(",") if w]
    evaluator = _DEFAULT_EVALUATOR[task]
    et = ev.EvaluatorType.parse(evaluator)

    val_batch = None
    if args.validation:
        val = read_libsvm(args.validation, num_features=X.shape[1]
                          - (0 if args.no_intercept else 1))
        Xv = val.to_dense()
        if not args.no_intercept:
            Xv = np.concatenate([Xv, np.ones((Xv.shape[0], 1), np.float32)], 1)
        # Validation data gets the same sanity checks: a NaN here would
        # otherwise turn every candidate's metric into NaN and make
        # select-best arbitrary.
        validate_arrays(task, val.labels, level=vlevel)
        validate_features("validation", Xv, level=vlevel)
        val_batch = (Xv, val.labels)

    def make_cfg(lam):
        return GLMOptimizationConfiguration(
            optimizer=OptimizerConfig(
                optimizer_type=OptimizerType(args.optimizer),
                max_iterations=args.max_iterations,
                tolerance=args.tolerance),
            regularization=RegularizationContext(
                RegularizationType(args.reg_type), lam,
                args.elastic_net_alpha),
            variance_computation=VarianceComputationType(args.variance))

    # vmap-over-λ: an eligible L2 grid solves every weight in ONE compiled
    # program (SURVEY P5); L1/elastic-net grids and variance computation
    # stay on the sequential path.
    grid_eligible = (
        len(reg_weights) > 1
        and RegularizationType(args.reg_type) == RegularizationType.L2
        and VarianceComputationType(args.variance)
        == VarianceComputationType.NONE
        and OptimizerType(args.optimizer) != OptimizerType.OWLQN)
    fits = []
    if grid_eligible:
        W, results = dist_problem.run_grid(
            loss, batch, mesh, make_cfg(reg_weights[0]), reg_weights,
            norm=norm, intercept_index=intercept_index)
        logger.info("solved %d-point reg grid in one vmapped program",
                    len(reg_weights))
        for k, lam in enumerate(reg_weights):
            fits.append((lam, Coefficients(W[k]),
                         {"converged": bool(results.converged[k]),
                          "iterations": int(results.iterations[k]),
                          "final_loss": float(results.value[k])}))
    else:
        for lam in reg_weights:
            coef, result = dist_problem.run(
                loss, batch, mesh, make_cfg(lam), norm=norm,
                intercept_index=intercept_index)
            fits.append((lam, coef,
                         {"converged": bool(result.converged),
                          "iterations": int(result.iterations),
                          "final_loss": float(result.value)}))

    candidates = []
    val_scores = []
    for lam, coef, fit_stats in fits:
        # Export coefficients in the ORIGINAL feature space (reference:
        # models are transformed back before writing).
        raw_means = norm.model_to_original_space(coef.means)
        raw_vars = coef.variances
        if raw_vars is not None:
            raw_vars = norm.variances_to_original_space(raw_vars)
        model = GeneralizedLinearModel(
            task=task, coefficients=Coefficients(raw_means, raw_vars))
        record = {"reg_weight": lam, **fit_stats}
        if val_batch is not None:
            # Device work only inside the sweep — the scores stay put;
            # the metrics evaluate batched after it.
            val_scores.append(model.compute_score(jnp.asarray(
                val_batch[0])))
        candidates.append((model, record))

    if val_batch is not None:
        # Batched evaluation AFTER the sweep: every candidate's metric
        # computes in ONE vmapped program and crosses the device
        # boundary in ONE host transfer — no per-lambda sync inside the
        # model-selection loop (the last .photon-lint-baseline.json
        # debt, retired).
        import jax

        yv = jnp.asarray(val_batch[1])
        metric_vec = np.asarray(jax.vmap(
            lambda s: ev.evaluate(et, s, yv))(jnp.stack(val_scores)))
        for (_, record), value in zip(candidates, metric_vec):
            record[evaluator] = float(value)
    for _, record in candidates:
        logger.info("lambda=%g: %s", record["reg_weight"], record)

    if val_batch is not None:
        best_i = max(range(len(candidates)),
                     key=lambda i: (candidates[i][1][evaluator]
                                    if et.direction == ev.MetricDirection.HIGHER_IS_BETTER
                                    else -candidates[i][1][evaluator]))
    else:
        best_i = int(np.argmin([c[1]["final_loss"] for c in candidates]))

    os.makedirs(args.output_dir, exist_ok=True)
    to_save = (range(len(candidates)) if args.output_mode == "ALL"
               else [best_i])
    for i in to_save:
        model_io.save_glm(candidates[i][0],
                          os.path.join(args.output_dir, f"model-{i}"))
    summary = {
        "task": task.value,
        "models": [c[1] for c in candidates],
        "best_index": best_i,
        "wall_seconds": time.perf_counter() - t0,
    }
    with open(os.path.join(args.output_dir, "summary.json"), "w") as f:
        json.dump(summary, f, indent=2)
    logger.info("wrote %s", args.output_dir)
    return summary


def main(argv=None):
    run(build_parser().parse_args(argv))


if __name__ == "__main__":
    main()
