"""Continuous model publication driver (docs/SERVING.md "Continuous
publication").

Closes the ingest→fit→publish→serve loop: refit the dirty entities of a
served GameModel from logged ``(features, label, offset)`` tuples
(game/refit.py — warm-started per-entity solves against the offline
fit), commit the changed rows as a monotone-versioned delta artifact
(serving/publish.py — CRC/two-generation discipline, SIGKILL-safe), and
optionally push it through a running fleet's canary ladder
(``POST /publish`` on the photon-game-fleet front door: canary → bake →
judge → roll fleet-wide or auto-roll-back).

Quickstart::

    # cut a delta from logged traffic (no fleet needed)
    photon-game-publish --model-dir out/best --publish-dir out/publish \
        --refit per-user=logged-tuples.npz

    # same, then gate it through a live fleet
    photon-game-publish --model-dir out/best --publish-dir out/publish \
        --refit per-user=logged-tuples.npz \
        --fleet-url http://127.0.0.1:8080 --bake-window-s 2

Exit codes: 0 published (or written, without ``--fleet-url``); 3 the
canary rejected the delta (it was rolled back and RETRACTED from the
version chain); 2 anything else went wrong.

Ledgers: this publisher records its refit/delta_write/verdict rows in
``<publish-dir>/publisher-ledger``; a fleet started with
``--publish-dir`` records the canary ladder's rows in
``<publish-dir>/ledger`` — two DIFFERENT files on purpose (one
append-as-produced stream has one writer; two processes interleaving
``seq`` numbers would tear it). Render either with ``photon-obs tail
--publish``.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys
import urllib.error
import urllib.request

from photon_ml_tpu.utils.logging import setup_logging

logger = logging.getLogger("photon_ml_tpu.cli")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--model-dir", required=True,
                   help="the BASE GameModel directory (the offline fit "
                        "refits warm-start from)")
    p.add_argument("--publish-dir", required=True,
                   help="delta-store home: versioned delta artifacts + "
                        "the publish ledger live here")
    p.add_argument("--refit", action="append", default=[],
                   metavar="CID=TUPLES.npz",
                   help="refit one coordinate from a logged-tuple batch "
                        "(game/refit.py npz format; repeatable). A batch "
                        "must carry each dirty entity's COMPLETE logged "
                        "history — that contract is what keeps served "
                        "scores bit-identical to an offline full refit")
    p.add_argument("--delta-dir",
                   help="publish an ALREADY-CUT delta directory instead "
                        "of refitting (mutually exclusive with --refit)")
    p.add_argument("--fleet-url",
                   help="photon-game-fleet front door; when set, the "
                        "committed delta goes through the canary ladder "
                        "(POST /publish). Without it the delta is only "
                        "written (--write-only mode)")
    p.add_argument("--bake-window-s", type=float, default=None,
                   help="canary bake window before the verdict "
                        "(fleet default when omitted)")
    p.add_argument("--burn-threshold", type=float, default=None,
                   help="max canary error-budget burn rate over the "
                        "bake window (fleet default when omitted)")
    p.add_argument("--probe",
                   help="JSON file with scoring request objects POSTed "
                        "to the canary; non-finite probe scores reject "
                        "the delta")
    p.add_argument("--probe-max-abs", type=float, default=None,
                   help="reject when any canary probe |score| exceeds "
                        "this (the quality band)")
    p.add_argument("--max-iterations", type=int, default=100,
                   help="refit optimizer iterations (match training)")
    p.add_argument("--tolerance", type=float, default=1e-7)
    p.add_argument("--reg-weight", type=float, default=1.0,
                   help="L2 weight of the refit solves (match training)")
    p.add_argument("--publish-timeout-s", type=float, default=120.0,
                   help="HTTP timeout of the POST /publish call (covers "
                        "the bake window)")
    p.add_argument("--fault-plan",
                   help="JSON FaultPlan armed in this publisher "
                        "(chaos drills: kill at publish.delta_write, "
                        "corrupt at publish.delta_artifact)")
    p.add_argument("--compact-generations", metavar="GEN_ROOT",
                   help="after a successful publish, fold the committed "
                        "delta chain into the next mmap generation "
                        "under GEN_ROOT (boot/generations.py) — "
                        "replicas then restart from one mmap swap "
                        "instead of replaying the chain (docs/SERVING.md "
                        "\"Sub-second restart\"). Bootstraps gen-000001 "
                        "from --model-dir when the root is empty")
    return p


def _parse_refits(specs: list[str]) -> list[tuple[str, str]]:
    out = []
    for spec in specs:
        cid, sep, path = spec.partition("=")
        if not sep or not cid or not path:
            raise ValueError(f"--refit expects CID=TUPLES.npz, "
                             f"got {spec!r}")
        out.append((cid, path))
    return out


def cut_delta(args, ledger) -> "object":
    """Refit (or adopt) + commit one delta; returns the ModelDelta."""
    from photon_ml_tpu.game.refit import load_refit_batch, refit_rows
    from photon_ml_tpu.models import io as model_io
    from photon_ml_tpu.optim import OptimizerConfig
    from photon_ml_tpu.optim.problem import GLMOptimizationConfiguration
    from photon_ml_tpu.optim.regularization import (RegularizationContext,
                                                    RegularizationType)
    from photon_ml_tpu.serving.publish import DeltaStore, read_delta

    store = DeltaStore(args.publish_dir)
    if args.delta_dir:
        return read_delta(args.delta_dir)
    refits = _parse_refits(args.refit)
    if not refits:
        raise ValueError("nothing to publish: give --refit or "
                         "--delta-dir")
    model = model_io.load_game_model(args.model_dir, host=True)
    config = GLMOptimizationConfiguration(
        optimizer=OptimizerConfig(max_iterations=args.max_iterations,
                                  tolerance=args.tolerance),
        regularization=RegularizationContext(
            RegularizationType.L2, args.reg_weight))
    rows_by_cid = {}
    for cid, path in refits:
        batch = load_refit_batch(path)
        ids, rows, stats = refit_rows(model, cid, batch, config=config)  # pml: allow[PML012] one loop iteration IS one whole coordinate refit; its result must land on host to become the delta artifact — the sync is the product, not per-step chatter
        rows_by_cid[cid] = (ids, rows)
        ledger.record("publish", phase="refit", **stats)
    delta = store.write(rows_by_cid,
                        extra={"source": "photon-game-publish",
                               "model_dir": args.model_dir})
    ledger.record("publish", phase="delta_write", version=delta.version,
                  parent=delta.parent, entities=delta.num_rows,
                  coordinates=list(delta.coordinates))
    return delta


def compact_generations(args, ledger) -> dict:
    """Fold the committed delta chain into the next mmap generation
    (boot/generations.py): the restart path's amortization leg —
    publication cost moves INTO the artifact, so a rebooted replica has
    nothing to replay. Bootstraps the base generation from
    ``--model-dir`` when the root holds none."""
    from photon_ml_tpu.boot import GenerationStore
    from photon_ml_tpu.boot.generations import publish_generation
    from photon_ml_tpu.serving.publish import DeltaStore

    store = GenerationStore(args.compact_generations)
    if not store.versions():
        gen, _ = publish_generation(args.model_dir,
                                    args.compact_generations)
        ledger.record("publish", phase="generation_bootstrap",
                      generation=gen)
    out = store.compact(DeltaStore(args.publish_dir))
    if out is None:  # chain already folded — idempotent no-op
        return {"generation": store.current_version(),
                "compaction_skipped": True}
    gen, path = out
    ledger.record("publish", phase="compacted", generation=gen,
                  path=path)
    logger.info("delta chain compacted into generation gen-%06d (%s)",
                gen, path)
    return {"generation": gen, "generation_path": path}


def push_to_fleet(args, delta, ledger) -> dict:
    """Drive the fleet's canary ladder over HTTP; raises the publish
    taxonomy mapped back from the front door's defined statuses."""
    from photon_ml_tpu.serving.publish import (CanaryRejected,
                                               PublishError)

    payload: dict = {"path": os.path.abspath(delta.path)}
    if args.bake_window_s is not None:
        payload["bake_s"] = args.bake_window_s
    if args.burn_threshold is not None:
        payload["burn_threshold"] = args.burn_threshold
    probe: dict = {}
    if args.probe:
        with open(args.probe) as f:
            probe["requests"] = json.load(f)
    if args.probe_max_abs is not None:
        probe["max_abs_score"] = args.probe_max_abs
    if probe:
        payload["probe"] = probe
    req = urllib.request.Request(
        args.fleet_url.rstrip("/") + "/publish",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(
                req, timeout=args.publish_timeout_s) as resp:
            return json.loads(resp.read())
    except urllib.error.HTTPError as e:
        body = e.read().decode(errors="replace")
        try:
            detail = json.loads(body)
        except ValueError:
            detail = {"error": body}
        ledger.record("publish", phase="verdict", version=delta.version,
                      accepted=False, status=e.code,
                      reason=detail.get("error", ""))
        if e.code == 409:
            raise CanaryRejected(delta.version,
                                 detail.get("reason",
                                            detail.get("error", "")))
        raise PublishError(
            f"fleet refused delta v{delta.version} "
            f"(HTTP {e.code}): {detail.get('error', body)}")


def run(args) -> int:
    setup_logging()
    from photon_ml_tpu.boot import GenerationError
    from photon_ml_tpu.obs.ledger import RunLedger
    from photon_ml_tpu.serving.publish import (CanaryRejected,
                                               DeltaStore, PublishError)

    if args.fault_plan:
        from photon_ml_tpu import faults as flt

        with open(args.fault_plan) as f:
            flt.install(flt.FaultPlan.from_json(f.read()))
        logger.warning("fault plan %s ARMED in this publisher",
                       args.fault_plan)
    os.makedirs(args.publish_dir, exist_ok=True)
    # publisher-ledger, NOT ledger: the fleet process owns that one
    # (module docstring) — an append-as-produced stream has ONE writer.
    ledger = RunLedger.resume(
        os.path.join(args.publish_dir, "publisher-ledger"),
        config={"kind": "publish", "model_dir": args.model_dir})
    status = "ok"
    try:
        delta = cut_delta(args, ledger)
        summary = {"version": delta.version, "parent": delta.parent,
                   "entities": delta.num_rows,
                   "coordinates": list(delta.coordinates),
                   "path": delta.path}
        if not args.fleet_url:
            summary["published"] = False
            if args.compact_generations:
                summary.update(compact_generations(args, ledger))
            print(json.dumps(summary))
            return 0
        try:
            verdict = push_to_fleet(args, delta, ledger)
        except CanaryRejected as e:
            # Rejected deltas leave the version chain (retracted, kept
            # as rejected-v* for forensics) so the next publish reuses
            # the number and the applied chain stays gapless.
            DeltaStore(args.publish_dir).retract(delta.version)
            logger.error("%s", e)
            summary.update({"published": False, "rejected": True,
                            "reason": e.reason})
            print(json.dumps(summary))
            status = "canary_rejected"
            return 3
        except PublishError as e:
            # Swap failure (rolled back fleet-side) or an untrustworthy
            # artifact: either way it never went live — retract it.
            DeltaStore(args.publish_dir).retract(delta.version)
            logger.error("publish failed: %s", e)
            status = "error"
            return 2
        summary.update({"published": True, **verdict})
        if args.compact_generations:
            summary.update(compact_generations(args, ledger))
        print(json.dumps(summary))
        return 0
    except (PublishError, GenerationError, ValueError, OSError) as e:
        logger.error("publish failed: %s", e)
        status = "error"
        return 2
    finally:
        ledger.close(status=status)


def main(argv=None) -> None:
    sys.exit(run(build_parser().parse_args(argv)))


if __name__ == "__main__":
    main()
