"""Synthetic dataset generators for tests and benchmarks.

Reference parity: photon-test-utils ``GameTestUtils.scala`` /
``SparkTestUtils.scala`` generators (balanced binary classification draws,
per-entity GAME datasets) and the bundled integTest resources. Also stands
in for the BASELINE.json public datasets (a1a, YearPredictionMSD,
MovieLens-20M) in this zero-egress environment: same shapes/sparsity
regimes, seeded and reproducible.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np


def glm_classification(
    rng: np.random.Generator,
    n: int,
    d: int,
    *,
    intercept: bool = True,
    noise: float = 0.0,
    weight_scale: float = 1.0,
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Balanced-ish binary data from a ground-truth logistic model.

    Returns (X, y, w_true); last column of X is the intercept if requested.
    """
    X = rng.normal(size=(n, d)).astype(np.float32)
    if intercept:
        X[:, -1] = 1.0
    w_true = (rng.normal(size=d) * weight_scale).astype(np.float32)
    logits = X @ w_true + noise * rng.normal(size=n).astype(np.float32)
    p = 1.0 / (1.0 + np.exp(-logits))
    y = (rng.uniform(size=n) < p).astype(np.float32)
    return X, y, w_true


def a1a_like(rng: np.random.Generator, n: int = 1605, d: int = 123,
             density: float = 0.11) -> tuple[np.ndarray, np.ndarray]:
    """Sparse binary features in the a1a regime (123 binary features,
    ~14 set per row) with a planted logistic model."""
    X = (rng.uniform(size=(n, d)) < density).astype(np.float32)
    w_true = rng.normal(size=d).astype(np.float32) * 0.8
    logits = X @ w_true - np.mean(X @ w_true)
    y = (rng.uniform(size=n) < 1.0 / (1.0 + np.exp(-logits))).astype(np.float32)
    return X, y


@dataclasses.dataclass
class SyntheticGameData:
    """Columnar GAME dataset: global features + per-entity assignments.

    Mirrors a MovieLens-style layout: a global (fixed-effect) feature shard
    plus random-effect feature shards keyed by entity id columns.
    """

    # global shard
    X_global: np.ndarray  # (n, d_global)
    # per-RE-type: entity ids (n,) int32 and the RE feature shard (n, d_re)
    entity_ids: dict[str, np.ndarray]
    X_entity: dict[str, np.ndarray]
    num_entities: dict[str, int]
    response: np.ndarray  # (n,)
    offsets: np.ndarray
    weights: np.ndarray


def game_data(
    rng: np.random.Generator,
    n: int = 5000,
    d_global: int = 20,
    re_specs: Optional[dict[str, tuple[int, int]]] = None,  # name -> (num_entities, d_re)
    task: str = "logistic",
    entity_skew: float = 1.2,
) -> SyntheticGameData:
    """GAME data with planted fixed + random effects.

    Entity assignment is Zipf-skewed (realistic per-user activity
    distribution; exercises the bucketing path the way MovieLens does).
    """
    if re_specs is None:
        re_specs = {"userId": (200, 8), "itemId": (100, 6)}
    X_global = rng.normal(size=(n, d_global)).astype(np.float32)
    X_global[:, -1] = 1.0
    w_global = rng.normal(size=d_global).astype(np.float32) * 0.5
    logits = X_global @ w_global

    entity_ids: dict[str, np.ndarray] = {}
    X_entity: dict[str, np.ndarray] = {}
    num_entities: dict[str, int] = {}
    for name, (ne, d_re) in re_specs.items():
        # Zipf-ish skewed assignment
        p = (1.0 / np.arange(1, ne + 1) ** entity_skew)
        p /= p.sum()
        ids = rng.choice(ne, size=n, p=p).astype(np.int32)
        Xr = rng.normal(size=(n, d_re)).astype(np.float32)
        Xr[:, -1] = 1.0
        W_re = rng.normal(size=(ne, d_re)).astype(np.float32) * 0.7
        logits = logits + np.einsum("nd,nd->n", Xr, W_re[ids])
        entity_ids[name] = ids
        X_entity[name] = Xr
        num_entities[name] = ne

    if task == "logistic":
        p = 1.0 / (1.0 + np.exp(-logits))
        y = (rng.uniform(size=n) < p).astype(np.float32)
    elif task == "linear":
        y = (logits + 0.1 * rng.normal(size=n)).astype(np.float32)
    elif task == "poisson":
        y = rng.poisson(np.exp(np.clip(logits * 0.3, -5, 3))).astype(np.float32)
    else:
        raise ValueError(task)

    return SyntheticGameData(
        X_global=X_global,
        entity_ids=entity_ids,
        X_entity=X_entity,
        num_entities=num_entities,
        response=y,
        offsets=np.zeros(n, np.float32),
        weights=np.ones(n, np.float32),
    )
