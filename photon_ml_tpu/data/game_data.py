"""GAME dataset: columnar examples with feature shards and entity keys.

Reference parity: photon-api ``data/GameDatum.scala`` (response, offset,
weight, featureShards: Map[FeatureShardId, Vector], idTagToValueMap:
Map[REType, REId]) and ``data/GameConverters.scala`` (DataFrame → RDD of
GameDatum).

TPU-first design: instead of an RDD of per-example objects, ONE columnar
struct holds the whole (host or device) dataset: each feature shard is a
dense (n, d_shard) matrix, each random-effect type is an int32 id column
indexing an entity table. Examples keep a stable order (UniqueSampleId ==
row index), which turns the reference's outer-join score arithmetic
(CoordinateDataScores + / -) into plain elementwise adds on (n,) arrays.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from photon_ml_tpu.data.batch import LabeledBatch


@dataclasses.dataclass
class GameDataset:
    """Columnar GAME dataset (host-side numpy; device placement per use)."""

    response: np.ndarray  # (n,)
    offsets: np.ndarray  # (n,) base offsets from the data (prior scores)
    weights: np.ndarray  # (n,)
    feature_shards: dict[str, np.ndarray]  # shard id -> (n, d_shard)
    entity_ids: dict[str, np.ndarray]  # RE type -> (n,) int32 entity rows
    num_entities: dict[str, int]  # RE type -> entity-table size
    # Optional per-RE-type intercept column index within that shard.
    intercept_index: dict[str, Optional[int]] = dataclasses.field(
        default_factory=dict)

    @property
    def num_rows(self) -> int:
        return int(self.response.shape[0])

    def shard_dim(self, shard_id: str) -> int:
        return int(self.feature_shards[shard_id].shape[1])

    def labeled_batch(self, shard_id: str,
                      offsets: Optional[np.ndarray] = None) -> LabeledBatch:
        """A LabeledBatch view over one feature shard with given offsets."""
        return LabeledBatch.build(
            self.feature_shards[shard_id], self.response, self.weights,
            self.offsets if offsets is None else offsets)

    def subset(self, idx: np.ndarray) -> "GameDataset":
        """Row subset (host-side) — used by down-sampling and tests."""
        return GameDataset(
            response=self.response[idx],
            offsets=self.offsets[idx],
            weights=self.weights[idx],
            feature_shards={k: v[idx] for k, v in self.feature_shards.items()},
            entity_ids={k: v[idx] for k, v in self.entity_ids.items()},
            num_entities=dict(self.num_entities),
            intercept_index=dict(self.intercept_index),
        )


def from_synthetic(syn) -> GameDataset:
    """Adapter from data/synthetic.py SyntheticGameData."""
    shards = {"global": syn.X_global}
    ids = {}
    intercepts = {"global": syn.X_global.shape[1] - 1}
    for name, Xr in syn.X_entity.items():
        shards[f"re_{name}"] = Xr
        ids[name] = syn.entity_ids[name]
        intercepts[f"re_{name}"] = Xr.shape[1] - 1
    return GameDataset(
        response=syn.response,
        offsets=syn.offsets,
        weights=syn.weights,
        feature_shards=shards,
        entity_ids=ids,
        num_entities=dict(syn.num_entities),
        intercept_index=intercepts,
    )
