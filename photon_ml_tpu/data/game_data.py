"""GAME dataset: columnar examples with feature shards and entity keys.

Reference parity: photon-api ``data/GameDatum.scala`` (response, offset,
weight, featureShards: Map[FeatureShardId, Vector], idTagToValueMap:
Map[REType, REId]) and ``data/GameConverters.scala`` (DataFrame → RDD of
GameDatum).

TPU-first design: instead of an RDD of per-example objects, ONE columnar
struct holds the whole (host or device) dataset: each feature shard is a
dense (n, d_shard) matrix, each random-effect type is an int32 id column
indexing an entity table. Examples keep a stable order (UniqueSampleId ==
row index), which turns the reference's outer-join score arithmetic
(CoordinateDataScores + / -) into plain elementwise adds on (n,) arrays.
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Optional

import numpy as np

from photon_ml_tpu.data.batch import LabeledBatch


@dataclasses.dataclass
class SparseShard:
    """ELL sparse feature shard (the Criteo-scale fixed-effect regime).

    Reference parity: the reference's sparse Breeze feature vectors per
    GameDatum; here one (n, max_nnz) ELL block per shard (see
    data/sparse.py for the layout contract: padding slots carry index ==
    ``num_features`` and value 0).
    """

    indices: np.ndarray  # (n, max_nnz) int32, padding slot == num_features
    values: np.ndarray  # (n, max_nnz) float32
    num_features: int

    @property
    def shape(self) -> tuple[int, int]:
        return (int(self.indices.shape[0]), int(self.num_features))


@dataclasses.dataclass
class GameDataset:
    """Columnar GAME dataset (host-side numpy; device placement per use)."""

    response: np.ndarray  # (n,)
    offsets: np.ndarray  # (n,) base offsets from the data (prior scores)
    weights: np.ndarray  # (n,)
    # shard id -> (n, d_shard) dense matrix OR a SparseShard (ELL).
    feature_shards: dict[str, object]
    entity_ids: dict[str, np.ndarray]  # RE type -> (n,) int32 entity rows
    num_entities: dict[str, int]  # RE type -> entity-table size
    # Optional per-RE-type intercept column index within that shard.
    intercept_index: dict[str, Optional[int]] = dataclasses.field(
        default_factory=dict)
    # Optional vocabulary-provenance tokens: RE type -> (base, final) where
    # ``base`` digests the frozen vocabulary this dataset's ids extend (==
    # ``final`` when the vocabulary was built fresh) and ``final`` digests
    # the resulting vocabulary. Two datasets share entity-id meaning iff
    # one's base equals the other's final — counts alone cannot tell a true
    # extension from an unrelated same-size vocabulary (reference: shared
    # PalDB index maps make this structural; here it must be carried).
    vocab_tokens: dict[str, tuple] = dataclasses.field(default_factory=dict)
    # Optional precomputed per-entity example counts (RE type ->
    # (num_entities,) int64 bincount of entity_ids[t]). The ingestion
    # layer folds these while decoding (photon_ml_tpu/ingest), letting
    # build_bucketing skip its own bincount pass over the id column.
    # Absent for datasets assembled elsewhere — consumers must treat it
    # as a cache, not a source of truth (subset() drops it).
    entity_counts: dict[str, np.ndarray] = dataclasses.field(
        default_factory=dict)

    @property
    def num_rows(self) -> int:
        return int(self.response.shape[0])

    def shard_dim(self, shard_id: str) -> int:
        shard = self.feature_shards[shard_id]
        if isinstance(shard, SparseShard):
            return int(shard.num_features)
        return int(shard.shape[1])

    def labeled_batch(self, shard_id: str,
                      offsets: Optional[np.ndarray] = None) -> LabeledBatch:
        """A LabeledBatch view over one feature shard with given offsets."""
        return LabeledBatch.build(
            self.feature_shards[shard_id], self.response, self.weights,
            self.offsets if offsets is None else offsets)

    def subset(self, idx: np.ndarray) -> "GameDataset":
        """Row subset (host-side) — used by down-sampling and tests."""
        def _sub(shard):
            if isinstance(shard, SparseShard):
                return SparseShard(indices=shard.indices[idx],
                                   values=shard.values[idx],
                                   num_features=shard.num_features)
            return shard[idx]

        return GameDataset(
            response=self.response[idx],
            offsets=self.offsets[idx],
            weights=self.weights[idx],
            feature_shards={k: _sub(v)
                            for k, v in self.feature_shards.items()},
            entity_ids={k: v[idx] for k, v in self.entity_ids.items()},
            num_entities=dict(self.num_entities),
            intercept_index=dict(self.intercept_index),
            vocab_tokens=dict(self.vocab_tokens),
        )


def vocab_token(vocab: dict) -> str:
    """Order-independent digest of an entity vocabulary (entity -> row).

    Canonicalized by row via one numpy argsort and hashed as two big
    buffers — no per-entity Python hashing, so a 10⁶-entity vocabulary
    digests in tens of milliseconds on the ingestion path.
    """
    h = hashlib.blake2b(digest_size=16)
    n = len(vocab)
    if n:
        rows = np.fromiter(vocab.values(), np.int64, n)
        order = np.argsort(rows, kind="stable")
        keys = list(vocab)
        h.update("\x00".join(str(keys[i]) for i in order).encode())
        h.update(rows[order].tobytes())
    return h.hexdigest()


def from_sparse_batch(batch, shard_id: str = "global") -> GameDataset:
    """Adapter: one data/sparse.py SparseBatch → single-shard GameDataset
    (the Criteo fixed-effect-only configuration, BASELINE config 5)."""
    return GameDataset(
        response=np.asarray(batch.labels),
        offsets=np.asarray(batch.offsets),
        weights=np.asarray(batch.weights),
        feature_shards={shard_id: SparseShard(
            indices=np.asarray(batch.indices),
            values=np.asarray(batch.values),
            num_features=int(batch.num_features))},
        entity_ids={},
        num_entities={},
        intercept_index={},
    )


def from_synthetic(syn) -> GameDataset:
    """Adapter from data/synthetic.py SyntheticGameData."""
    shards = {"global": syn.X_global}
    ids = {}
    intercepts = {"global": syn.X_global.shape[1] - 1}
    for name, Xr in syn.X_entity.items():
        shards[f"re_{name}"] = Xr
        ids[name] = syn.entity_ids[name]
        intercepts[f"re_{name}"] = Xr.shape[1] - 1
    return GameDataset(
        response=syn.response,
        offsets=syn.offsets,
        weights=syn.weights,
        feature_shards=shards,
        entity_ids=ids,
        num_entities=dict(syn.num_entities),
        intercept_index=intercepts,
    )
