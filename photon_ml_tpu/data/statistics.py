"""Per-feature summary statistics.

Reference parity: photon-lib ``stat/FeatureDataStatistics.scala`` (a.k.a.
``BasicStatisticalSummary``; built via Spark's per-partition
``MultivariateOnlineSummarizer`` merge) — mean/variance/min/max/numNonzeros
per feature, feeding NormalizationContext and the model summary output.

TPU-first: one fused pass of weighted segment reductions over the (sharded)
feature matrix; the treeAggregate merge becomes a psum when run under
shard_map (see parallel/), but the plain jnp version auto-partitions too.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.data.batch import LabeledBatch
from photon_ml_tpu.normalization import (NormalizationContext,
                                         NormalizationType,
                                         build_normalization)

Array = jax.Array


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class FeatureDataStatistics:
    """Weighted per-feature summary (reference: FeatureDataStatistics)."""

    count: Array  # scalar: Σ weights
    mean: Array  # (d,)
    variance: Array  # (d,) population variance in weighted form
    min: Array  # (d,)
    max: Array  # (d,)
    num_nonzeros: Array  # (d,)
    max_magnitude: Array  # (d,): max |x|

    @property
    def dim(self) -> int:
        return self.mean.shape[-1]


def summarize(batch: LabeledBatch) -> FeatureDataStatistics:
    """Compute weighted feature statistics in one fused pass."""
    X = batch.features
    w = jnp.where(batch.weights > 0.0, batch.weights, 0.0)
    wsum = jnp.sum(w)
    wn = w / jnp.maximum(wsum, 1e-12)
    mean = jnp.einsum("nd,n->d", X, wn)
    ex2 = jnp.einsum("nd,n->d", X * X, wn)
    var = jnp.maximum(ex2 - mean * mean, 0.0)
    live = batch.weights > 0.0
    big = jnp.float32(np.inf)
    Xmin = jnp.min(jnp.where(live[:, None], X, big), axis=0)
    Xmax = jnp.max(jnp.where(live[:, None], X, -big), axis=0)
    nnz = jnp.sum((X != 0.0) & live[:, None], axis=0).astype(jnp.float32)
    max_mag = jnp.max(jnp.where(live[:, None], jnp.abs(X), 0.0), axis=0)
    return FeatureDataStatistics(
        count=wsum, mean=mean, variance=var, min=Xmin, max=Xmax,
        num_nonzeros=nnz, max_magnitude=max_mag)


def normalization_from_statistics(
    stats: FeatureDataStatistics,
    norm_type: NormalizationType,
    intercept_index: Optional[int],
) -> NormalizationContext:
    """Reference parity: NormalizationContext.apply(type, summary, intercept)."""
    return build_normalization(
        norm_type,
        means=np.asarray(stats.mean),
        variances=np.asarray(stats.variance),
        max_magnitudes=np.asarray(stats.max_magnitude),
        intercept_index=intercept_index,
    )
