"""Host→device prefetch pipeline for chunked datasets.

Reference parity: the executor-side record streaming of photon-client's
HDFS reads (SURVEY §0 maps it to "host-side readers feeding a
device-prefetch pipeline"). JAX device transfers are asynchronous, so
keeping ``depth`` chunks in flight overlaps the host→device copy of the
NEXT chunk with the device compute on the CURRENT one — the classic
double-buffering that hides PCIe/DCN transfer latency behind useful work.
"""

from __future__ import annotations

import collections
from typing import Iterable, Iterator, Optional

import jax


def stage_dataset(dataset):
    """Device-resident copy of a GameDataset (dense and sparse shards,
    scalars, entity ids). ``jnp.asarray`` on the result is a no-op, so
    repeated scoring/evaluation does no further host→device transfer."""
    import dataclasses

    import jax.numpy as jnp

    from photon_ml_tpu.data.game_data import SparseShard

    def _put_shard(shard):
        if isinstance(shard, SparseShard):
            return SparseShard(indices=jnp.asarray(shard.indices),
                               values=jnp.asarray(shard.values),
                               num_features=shard.num_features)
        return jnp.asarray(shard)

    staged = dataclasses.replace(
        dataset,
        response=jnp.asarray(dataset.response),
        offsets=jnp.asarray(dataset.offsets),
        weights=jnp.asarray(dataset.weights),
        feature_shards={k: _put_shard(v)
                        for k, v in dataset.feature_shards.items()},
        entity_ids={k: jnp.asarray(v)
                    for k, v in dataset.entity_ids.items()})
    if getattr(dataset, "_content_digest", None) is not None:
        staged._content_digest = dataset._content_digest
    return staged


def device_prefetch(batches: Iterable, depth: int = 2,
                    sharding: Optional[object] = None,
                    place=None) -> Iterator:
    """Yield device-placed copies of ``batches``, keeping up to ``depth``
    transfers in flight ahead of the consumer.

    ``batches`` may be any pytree of arrays (placed via
    ``jax.device_put``) or arbitrary objects when a custom ``place``
    callable is given (e.g. ``stage_dataset`` for GameDataset chunks).
    Device transfers are asynchronous: yielding only after later puts are
    enqueued means the consumer's compute on chunk k overlaps the
    transfer of chunks k+1..k+depth.
    """
    if depth < 1:
        raise ValueError(f"depth must be >= 1, got {depth}")
    it = iter(batches)

    def put(b):
        if place is not None:
            return place(b)
        return (jax.device_put(b, sharding) if sharding is not None
                else jax.device_put(b))

    q: collections.deque = collections.deque()
    exhausted = False
    while True:
        while not exhausted and len(q) < depth:
            try:
                q.append(put(next(it)))
            except StopIteration:
                exhausted = True
        if not q:
            return
        yield q.popleft()


def iter_row_chunks(dataset, batch_rows: int):
    """Split a GameDataset into contiguous row chunks.

    Chunks are sliced with basic indexing, so dense shards and scalar
    columns are numpy VIEWS — no host copy happens until the device
    transfer itself, preserving the compute/transfer overlap
    ``device_prefetch`` provides.
    """
    if batch_rows < 1:
        raise ValueError(f"batch_rows must be >= 1, got {batch_rows}")
    n = dataset.num_rows
    for lo in range(0, n, batch_rows):
        yield dataset.subset(slice(lo, min(lo + batch_rows, n)))
