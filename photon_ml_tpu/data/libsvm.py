"""LIBSVM-format reader/writer (a1a, YearPredictionMSD, ... configs).

Reference note: the reference ingests Avro (photon-client
``data/avro/AvroDataReader.scala``); LIBSVM support is this rebuild's
equivalent of the bundled-dataset path used by the BASELINE.json configs
(a1a logistic, YearPredictionMSD TRON); Avro ingestion is a separate
module.

Host-side parsing to dense or CSR numpy; the device pipeline consumes the
arrays via LabeledBatch.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np


@dataclasses.dataclass
class LibsvmData:
    """Parsed LIBSVM file: labels plus features (dense or CSR triplet)."""

    labels: np.ndarray  # (n,)
    # Dense path:
    dense: Optional[np.ndarray] = None  # (n, d)
    # Sparse path (CSR):
    indptr: Optional[np.ndarray] = None  # (n+1,)
    indices: Optional[np.ndarray] = None  # (nnz,)
    values: Optional[np.ndarray] = None  # (nnz,)
    num_features: int = 0

    @property
    def num_rows(self) -> int:
        return self.labels.shape[0]

    def to_dense(self) -> np.ndarray:
        if self.dense is not None:
            return self.dense
        out = np.zeros((self.num_rows, self.num_features), np.float32)
        for i in range(self.num_rows):
            lo, hi = self.indptr[i], self.indptr[i + 1]
            out[i, self.indices[lo:hi]] = self.values[lo:hi]
        return out


def read_libsvm(
    path: str,
    num_features: Optional[int] = None,
    zero_based: bool = False,
    dense: bool = True,
    binary_labels_to_01: bool = True,
) -> LibsvmData:
    """Parse a LIBSVM text file.

    ``binary_labels_to_01`` maps {-1,+1} labels to {0,1} (the convention of
    this framework's classification losses; a1a ships ±1).
    """
    labels: list[float] = []
    indptr = [0]
    indices: list[int] = []
    values: list[float] = []
    offset = 0 if zero_based else 1
    max_idx = -1
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            parts = line.split()
            labels.append(float(parts[0]))
            for tok in parts[1:]:
                k, v = tok.split(":")
                idx = int(k) - offset
                if idx > max_idx:
                    max_idx = idx
                indices.append(idx)
                values.append(float(v))
            indptr.append(len(indices))

    d = num_features if num_features is not None else max_idx + 1
    y = np.asarray(labels, np.float32)
    if binary_labels_to_01 and set(np.unique(y)) <= {-1.0, 1.0}:
        y = (y + 1.0) / 2.0
    data = LibsvmData(
        labels=y,
        indptr=np.asarray(indptr, np.int64),
        indices=np.asarray(indices, np.int32),
        values=np.asarray(values, np.float32),
        num_features=d,
    )
    if dense:
        data.dense = data.to_dense()
        data.indptr = data.indices = data.values = None
    return data


def write_libsvm(path: str, X: np.ndarray, y: np.ndarray,
                 zero_based: bool = False) -> None:
    """Write a dense matrix in LIBSVM format (test fixture helper)."""
    offset = 0 if zero_based else 1
    with open(path, "w") as f:
        for i in range(X.shape[0]):
            row = X[i]
            nz = np.nonzero(row)[0]
            feats = " ".join(f"{j + offset}:{row[j]:.6g}" for j in nz)
            f.write(f"{y[i]:g} {feats}\n")
