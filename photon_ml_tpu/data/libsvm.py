"""LIBSVM-format reader/writer (a1a, YearPredictionMSD, ... configs).

Reference note: the reference ingests Avro (photon-client
``data/avro/AvroDataReader.scala``); LIBSVM support is this rebuild's
equivalent of the bundled-dataset path used by the BASELINE.json configs
(a1a logistic, YearPredictionMSD TRON); Avro ingestion is a separate
module.

Host-side parsing to dense or CSR numpy; the device pipeline consumes the
arrays via LabeledBatch. The hot path is the native single-pass C++ parser
(``photon_ml_tpu/native/libsvm.cc``, the rebuild's executor-side ingestion
analog) with a pure-Python fallback when no toolchain is available. Both
enforce the same structural grammar (comments, idx:val tokens, index
bounds, strict value placement); the native parser's numeric-literal
grammar is the locale-independent ``std::from_chars`` one, while the
fallback inherits Python ``float``'s slightly larger literal set (e.g.
digit underscores) — well-formed LIBSVM files parse identically.
"""

from __future__ import annotations

import ctypes
import dataclasses
import logging
from typing import Optional

import numpy as np

_native_lib = None
_native_failed = False


def _load_native():
    """Compile/load the C++ parser once; None when unavailable."""
    global _native_lib, _native_failed
    if _native_lib is not None or _native_failed:
        return _native_lib
    try:
        from photon_ml_tpu.native import build_library

        lib = ctypes.CDLL(build_library("libsvm"))
        lib.lsvm_parse.restype = ctypes.c_void_p
        lib.lsvm_parse.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.lsvm_num_rows.restype = ctypes.c_long
        lib.lsvm_num_rows.argtypes = [ctypes.c_void_p]
        lib.lsvm_nnz.restype = ctypes.c_long
        lib.lsvm_nnz.argtypes = [ctypes.c_void_p]
        lib.lsvm_max_index.restype = ctypes.c_int
        lib.lsvm_max_index.argtypes = [ctypes.c_void_p]
        lib.lsvm_error.restype = ctypes.c_int
        lib.lsvm_error.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.c_int]
        lib.lsvm_fill.argtypes = [ctypes.c_void_p] + [
            np.ctypeslib.ndpointer(dt, flags="C_CONTIGUOUS")
            for dt in (np.float32, np.int64, np.int32, np.float32)]
        lib.lsvm_free.argtypes = [ctypes.c_void_p]
        _native_lib = lib
    except Exception:
        logging.getLogger("photon_ml_tpu.data").debug(
            "native LIBSVM parser unavailable — using the Python path",
            exc_info=True)
        _native_failed = True
    return _native_lib


def _parse_native(lib, path: str, zero_based: bool):
    handle = lib.lsvm_parse(path.encode(), int(zero_based))
    try:
        buf = ctypes.create_string_buffer(256)
        if lib.lsvm_error(handle, buf, 256):
            raise ValueError(
                f"libsvm parse error in {path}: {buf.value.decode()}")
        n = lib.lsvm_num_rows(handle)
        nnz = lib.lsvm_nnz(handle)
        labels = np.empty(n, np.float32)
        indptr = np.empty(n + 1, np.int64)
        indices = np.empty(nnz, np.int32)
        values = np.empty(nnz, np.float32)
        lib.lsvm_fill(handle, labels, indptr, indices, values)
        return labels, indptr, indices, values, lib.lsvm_max_index(handle)
    finally:
        lib.lsvm_free(handle)


@dataclasses.dataclass
class LibsvmData:
    """Parsed LIBSVM file: labels plus features (dense or CSR triplet)."""

    labels: np.ndarray  # (n,)
    # Dense path:
    dense: Optional[np.ndarray] = None  # (n, d)
    # Sparse path (CSR):
    indptr: Optional[np.ndarray] = None  # (n+1,)
    indices: Optional[np.ndarray] = None  # (nnz,)
    values: Optional[np.ndarray] = None  # (nnz,)
    num_features: int = 0

    @property
    def num_rows(self) -> int:
        return self.labels.shape[0]

    def to_dense(self) -> np.ndarray:
        if self.dense is not None:
            return self.dense
        out = np.zeros((self.num_rows, self.num_features), np.float32)
        for i in range(self.num_rows):
            lo, hi = self.indptr[i], self.indptr[i + 1]
            out[i, self.indices[lo:hi]] = self.values[lo:hi]
        return out


def read_libsvm(
    path: str,
    num_features: Optional[int] = None,
    zero_based: bool = False,
    dense: bool = True,
    binary_labels_to_01: bool = True,
) -> LibsvmData:
    """Parse a LIBSVM text file.

    ``binary_labels_to_01`` maps {-1,+1} labels to {0,1} (the convention of
    this framework's classification losses; a1a ships ±1).
    """
    import os

    if not os.path.exists(path):
        # Uniform exception type across the native and fallback paths.
        raise FileNotFoundError(path)
    lib = _load_native()
    if lib is not None:
        y, indptr_a, indices_a, values_a, max_idx = _parse_native(
            lib, path, zero_based)
    else:
        labels: list[float] = []
        indptr = [0]
        indices: list[int] = []
        values: list[float] = []
        offset = 0 if zero_based else 1
        max_idx = -1
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                parts = line.split()
                labels.append(float(parts[0]))
                for tok in parts[1:]:
                    k, v = tok.split(":")
                    idx = int(k) - offset
                    if idx < 0 or idx > 2**31 - 1:
                        raise ValueError(
                            f"feature index out of range in {path}: "
                            f"{tok!r}")
                    if idx > max_idx:
                        max_idx = idx
                    indices.append(idx)
                    values.append(float(v))
                indptr.append(len(indices))
        y = np.asarray(labels, np.float32)
        indptr_a = np.asarray(indptr, np.int64)
        indices_a = np.asarray(indices, np.int32)
        values_a = np.asarray(values, np.float32)

    if num_features is not None and max_idx >= num_features:
        # A caller-supplied feature space (e.g. "validation must share the
        # training space") makes out-of-range indices corrupt data, not
        # padding — the ELL sentinel would silently zero them out.
        raise ValueError(
            f"{path} contains feature index {max_idx} outside the declared "
            f"feature space of {num_features}")
    d = num_features if num_features is not None else max_idx + 1
    if binary_labels_to_01 and set(np.unique(y)) <= {-1.0, 1.0}:
        y = (y + 1.0) / 2.0
    data = LibsvmData(
        labels=y,
        indptr=indptr_a,
        indices=indices_a,
        values=values_a,
        num_features=d,
    )
    if dense:
        data.dense = data.to_dense()
        data.indptr = data.indices = data.values = None
    return data


def write_libsvm(path: str, X: np.ndarray, y: np.ndarray,
                 zero_based: bool = False) -> None:
    """Write a dense matrix in LIBSVM format (test fixture helper)."""
    offset = 0 if zero_based else 1
    with open(path, "w") as f:
        for i in range(X.shape[0]):
            row = X[i]
            nz = np.nonzero(row)[0]
            feats = " ".join(f"{j + offset}:{row[j]:.6g}" for j in nz)
            f.write(f"{y[i]:g} {feats}\n")
