"""Input-data sanity validation.

Reference parity: photon-client ``DataValidators.scala`` — before training,
check that the data is sane for the task: features/offsets/weights finite,
weights non-negative (zero weights are legal per-row masks, but an
all-zero weight column is a degenerate model and draws a warning), and
labels valid for the objective (binary for logistic /
smoothed-hinge, finite for linear regression, non-negative for Poisson).
The reference exposes validation levels (VALIDATE_FULL / VALIDATE_SAMPLE /
DISABLED) on the drivers; the same knob here is ``level``.

Host-side numpy checks (one vectorized pass per array) — validation runs
once per input read, not in the training hot path, and must produce loud,
actionable errors rather than NaN losses thousands of steps later.
"""

from __future__ import annotations

import enum
import logging

import numpy as np

from photon_ml_tpu.types import TaskType

logger = logging.getLogger(__name__)


class DataValidationLevel(enum.Enum):
    """Reference: DataValidationType (VALIDATE_FULL / VALIDATE_SAMPLE /
    DISABLED)."""

    VALIDATE_FULL = "VALIDATE_FULL"
    VALIDATE_SAMPLE = "VALIDATE_SAMPLE"
    DISABLED = "DISABLED"


_SAMPLE = 10_000  # rows checked under VALIDATE_SAMPLE


def _rows(n: int, level: DataValidationLevel, rng: np.random.Generator):
    """Row subset to check: None means ALL rows (checked in place, no
    gather copy). Sampling draws with replacement (rng.integers) — O(k)
    rather than the O(n) permutation rng.choice(replace=False) costs."""
    if level == DataValidationLevel.VALIDATE_SAMPLE and n > _SAMPLE:
        return np.unique(rng.integers(0, n, size=_SAMPLE))
    return None


def _take(a: np.ndarray, idx):
    return a if idx is None else a[idx]


def _orig_row(idx, i: int) -> int:
    """Map a position in the checked subset back to the dataset row."""
    return int(i) if idx is None else int(idx[i])


def _check_finite(name: str, a: np.ndarray, idx=None) -> None:
    checked = _take(a, idx)
    bad = ~np.isfinite(checked)
    if bad.any():
        flat = int(np.flatnonzero(bad.reshape(-1))[0])
        row, rest = flat // int(np.prod(checked.shape[1:], dtype=int) or 1), \
            flat % int(np.prod(checked.shape[1:], dtype=int) or 1)
        loc = f"row {_orig_row(idx, row)}"
        if checked.ndim > 1:
            loc += f", flat offset {rest} within the row"
        raise ValueError(
            f"{name} contains {int(bad.sum())} non-finite value(s) in the "
            f"checked rows; first at {loc} "
            f"({checked.reshape(-1)[flat]})")


def validate_labels(task: TaskType, labels: np.ndarray, _idx=None) -> None:
    """Per-task label validity (reference: *LabelValidator per TaskType)."""
    labels = np.asarray(labels)
    _check_finite("labels", labels, _idx)
    checked = _take(labels, _idx)
    task = TaskType(task)
    if task in (TaskType.LOGISTIC_REGRESSION,
                TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM):
        bad = ~np.isin(checked, (0.0, 1.0))
        if bad.any():
            i = int(np.flatnonzero(bad)[0])
            raise ValueError(
                f"binary classification needs labels in {{0, 1}}; "
                f"{int(bad.sum())} invalid in the checked rows (first: "
                f"labels[{_orig_row(_idx, i)}] = {checked[i]})")
    elif task == TaskType.POISSON_REGRESSION:
        bad = checked < 0.0
        if bad.any():
            i = int(np.flatnonzero(bad)[0])
            raise ValueError(
                f"Poisson regression needs non-negative labels; "
                f"{int(bad.sum())} negative in the checked rows (first: "
                f"labels[{_orig_row(_idx, i)}] = {checked[i]})")


def validate_arrays(
    task: TaskType,
    labels: np.ndarray,
    weights: np.ndarray = None,
    offsets: np.ndarray = None,
    level: DataValidationLevel = DataValidationLevel.VALIDATE_FULL,
    seed: int = 0,
) -> None:
    """Validate the scalar per-example columns."""
    level = DataValidationLevel(level)
    if level == DataValidationLevel.DISABLED:
        return
    rng = np.random.default_rng(seed)
    labels = np.asarray(labels)
    idx = _rows(labels.shape[0], level, rng)
    validate_labels(task, labels, idx)
    if weights is not None:
        w = _take(np.asarray(weights), idx)
        _check_finite("weights", np.asarray(weights), idx)
        bad = w < 0.0
        if bad.any():
            i = int(np.flatnonzero(bad)[0])
            raise ValueError(
                f"weights must be >= 0; first negative at row "
                f"{_orig_row(idx, i)} ({w[i]})")
        if w.size and not (w > 0.0).any():
            logger.warning(
                "every checked weight is zero — the objective is "
                "identically 0 and training will produce a degenerate "
                "model (did the weight column default wrong?)")
    if offsets is not None:
        _check_finite("offsets", np.asarray(offsets), idx)


def validate_features(
    name: str,
    shard,
    level: DataValidationLevel = DataValidationLevel.VALIDATE_FULL,
    seed: int = 0,
) -> None:
    """Validate a dense (n, d) matrix or an ELL SparseShard's values."""
    level = DataValidationLevel(level)
    if level == DataValidationLevel.DISABLED:
        return
    rng = np.random.default_rng(seed)
    values = shard.values if hasattr(shard, "values") else shard
    values = np.asarray(values)
    idx = _rows(values.shape[0], level, rng)
    _check_finite(f"feature shard {name!r}", values, idx)


def validate_game_dataset(
    task: TaskType,
    dataset,
    level: DataValidationLevel = DataValidationLevel.VALIDATE_FULL,
    seed: int = 0,
) -> None:
    """Validate a GameDataset end to end (reference: sanityCheckData on the
    input DataFrame before GameEstimator.fit)."""
    level = DataValidationLevel(level)
    if level == DataValidationLevel.DISABLED:
        return
    validate_arrays(task, dataset.response, dataset.weights, dataset.offsets,
                    level=level, seed=seed)
    for name, shard in dataset.feature_shards.items():
        validate_features(name, shard, level=level, seed=seed)
