"""Sparse example batches in ELL (padded-slot) layout.

The Criteo-scale path (SURVEY.md §7 step 9 / BASELINE config 5): feature
spaces of 1e6+ columns where dense (n, d) matrices are impossible. The
reference handles this with sparse Breeze vectors inside per-partition
aggregator loops; the TPU-first layout is ELL — every example gets a fixed
``max_nnz`` slots of (feature index, value) pairs:

    indices: (n, max_nnz) int32   — padding slots point at column d
    values:  (n, max_nnz) float32 — padding slots hold 0.0

Static shapes keep XLA happy; the sentinel column d lands gathers/scatters
on a zero slot of the (d+1,)-padded coefficient vector, so padding
contributes exactly nothing without any masking in the kernels. Rows with
more than ``max_nnz`` non-zeros keep their largest-magnitude entries
(callers pick ``max_nnz`` at the dataset's true max to make this lossless).

Contract: rows must be CANONICAL — no feature index may repeat within a
row (the same contract as the reference's canonical sparse Breeze
vectors). Margins and gradients are linear and would tolerate duplicates,
but the Hessian diagonal is quadratic in the per-feature value (Σx² vs
(Σx)²), so duplicates silently skew SIMPLE variances. ``from_csr``
inherits canonicality from CSR; ``synthetic_sparse`` dedupes draws.
"""

from __future__ import annotations

import dataclasses

import jax
import numpy as np

Array = jax.Array


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class SparseBatch:
    """ELL sparse batch: indices/values (n, max_nnz), labels etc. (n,)."""

    indices: Array  # int32, padding slot == num_features
    values: Array
    labels: Array
    weights: Array
    offsets: Array
    num_features: int = dataclasses.field(metadata=dict(static=True))

    @property
    def num_rows(self) -> int:
        return self.indices.shape[-2]

    @property
    def max_nnz(self) -> int:
        return self.indices.shape[-1]

    @property
    def dim(self) -> int:
        return self.num_features

    def pad_to(self, n: int) -> "SparseBatch":
        """Pad rows to ``n`` with zero-weight sentinel rows. Works on host
        numpy batches and under jit (device arrays / tracers use jnp)."""
        cur = self.num_rows
        if n == cur:
            return self
        if n < cur:
            raise ValueError(f"cannot shrink {cur} -> {n}")
        extra = n - cur
        if isinstance(self.indices, jax.Array):
            import jax.numpy as jnp

            def pad2(a, v):
                return jnp.pad(a, ((0, extra), (0, 0)), constant_values=v)

            def pad1(a):
                return jnp.pad(a, ((0, extra),))

            return SparseBatch(
                indices=pad2(self.indices, self.num_features),
                values=pad2(self.values, 0.0),
                labels=pad1(self.labels),
                weights=pad1(self.weights),
                offsets=pad1(self.offsets),
                num_features=self.num_features,
            )
        ind = np.full((extra, self.max_nnz), self.num_features, np.int32)
        zeros = np.zeros(extra, np.float32)
        return SparseBatch(
            indices=np.concatenate([np.asarray(self.indices), ind]),
            values=np.concatenate(
                [np.asarray(self.values),
                 np.zeros((extra, self.max_nnz), np.float32)]),
            labels=np.concatenate([np.asarray(self.labels), zeros]),
            weights=np.concatenate([np.asarray(self.weights), zeros]),
            offsets=np.concatenate([np.asarray(self.offsets), zeros]),
            num_features=self.num_features,
        )


def from_csr(
    indptr: np.ndarray,
    indices: np.ndarray,
    values: np.ndarray,
    labels: np.ndarray,
    num_features: int,
    weights: np.ndarray = None,
    offsets: np.ndarray = None,
    max_nnz: int = None,
) -> SparseBatch:
    """CSR triplet -> ELL. ``max_nnz`` defaults to the true row maximum;
    rows over the cap keep their largest-|value| entries."""
    n = len(indptr) - 1
    row_nnz = np.diff(indptr)
    cap = int(row_nnz.max()) if row_nnz.size else 1
    if max_nnz is None:
        max_nnz = max(cap, 1)
    ell_idx = np.full((n, max_nnz), num_features, np.int32)
    ell_val = np.zeros((n, max_nnz), np.float32)
    for i in range(n):
        lo, hi = int(indptr[i]), int(indptr[i + 1])
        k = hi - lo
        if k <= max_nnz:
            ell_idx[i, :k] = indices[lo:hi]
            ell_val[i, :k] = values[lo:hi]
        else:
            keep = np.argsort(-np.abs(values[lo:hi]))[:max_nnz]
            keep.sort()
            ell_idx[i] = indices[lo:hi][keep]
            ell_val[i] = values[lo:hi][keep]
    return SparseBatch(
        indices=ell_idx,
        values=ell_val,
        labels=np.asarray(labels, np.float32),
        weights=(np.ones(n, np.float32) if weights is None
                 else np.asarray(weights, np.float32)),
        offsets=(np.zeros(n, np.float32) if offsets is None
                 else np.asarray(offsets, np.float32)),
        num_features=num_features,
    )


def from_libsvm(data, max_nnz: int = None,
                offsets: np.ndarray = None) -> SparseBatch:
    """LibsvmData (CSR path) -> SparseBatch."""
    if data.indptr is None:
        raise ValueError("LibsvmData has no CSR arrays (dense file?)")
    return from_csr(data.indptr, data.indices, data.values, data.labels,
                    data.num_features, offsets=offsets, max_nnz=max_nnz)


def synthetic_sparse(
    n: int,
    num_features: int,
    nnz_per_row: int,
    task: str = "logistic",
    seed: int = 0,
    noise: float = 0.25,
    zipf: bool = True,
) -> tuple[SparseBatch, np.ndarray]:
    """Synthetic high-dimensional sparse GLM data (Criteo-shaped): returns
    (batch, true_weights). Feature popularity is Zipf-ish by default, like
    CTR data (``zipf=False`` gives uniform popularity, so every weight is
    identifiable — handy for recovery tests)."""
    rng = np.random.default_rng(seed)
    w_true = (rng.normal(size=num_features) *
              (rng.random(num_features) < 0.2)).astype(np.float32)
    if zipf:
        # Zipf-ish popularity: low ids much more frequent.
        raw = rng.zipf(1.3, size=(n, nnz_per_row)).astype(np.int64)
        ids = np.minimum(raw - 1, num_features - 1).astype(np.int32)
    else:
        ids = rng.integers(0, num_features,
                           size=(n, nnz_per_row)).astype(np.int32)
    vals = rng.normal(size=(n, nnz_per_row)).astype(np.float32)
    # Canonicalize rows (ELL contract): duplicate draws of the same index
    # within a row become sentinel/zero slots.
    order = np.argsort(ids, axis=1, kind="stable")
    ids = np.take_along_axis(ids, order, axis=1)
    vals = np.take_along_axis(vals, order, axis=1)
    dup = np.zeros_like(ids, dtype=bool)
    dup[:, 1:] = ids[:, 1:] == ids[:, :-1]
    ids[dup] = num_features
    vals[dup] = 0.0
    valid = ~dup
    margin = np.einsum(
        "nk,nk->n", vals,
        np.where(valid, w_true[np.minimum(ids, num_features - 1)], 0.0))
    margin += noise * rng.normal(size=n).astype(np.float32)
    if task == "logistic":
        labels = (rng.random(n) < 1.0 / (1.0 + np.exp(-margin))).astype(
            np.float32)
    else:
        labels = margin.astype(np.float32)
    batch = SparseBatch(
        indices=ids,
        values=vals,
        labels=labels,
        weights=np.ones(n, np.float32),
        offsets=np.zeros(n, np.float32),
        num_features=num_features,
    )
    return batch, w_true
