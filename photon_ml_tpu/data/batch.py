"""Columnar labeled-example batches.

Reference parity: photon-lib ``data/LabeledPoint.scala`` (label, features,
offset, weight) and photon-api ``data/LocalDataset.scala`` — but columnar:
instead of an ``Array[LabeledPoint]`` of per-example objects, a batch is a
struct-of-arrays pytree so the whole batch feeds one MXU matmul.

Padding: TPU kernels need static shapes, so batches may carry padded rows.
A padded row has ``weight == 0`` and all kernels treat zero-weight rows as
absent (masked with ``where``, not just multiplied, so non-finite garbage in
padding can never poison a sum).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class LabeledBatch:
    """A (possibly padded) batch: X (n, d), labels/weights/offsets (n,)."""

    features: Array
    labels: Array
    weights: Array
    offsets: Array

    @property
    def num_rows(self) -> int:
        return self.features.shape[-2]

    @property
    def dim(self) -> int:
        return self.features.shape[-1]

    def effective_count(self) -> Array:
        """Number of non-padded rows."""
        return jnp.sum((self.weights > 0.0).astype(jnp.int32), axis=-1)

    @staticmethod
    def build(
        features,
        labels,
        weights=None,
        offsets=None,
        dtype=jnp.float32,
        feature_dtype=None,
    ) -> "LabeledBatch":
        """``feature_dtype`` (default: ``dtype``) sets feature storage only
        — e.g. bfloat16 to halve HBM traffic; labels/weights/offsets keep
        ``dtype`` (losses and reductions stay f32)."""
        features = jnp.asarray(features, dtype=feature_dtype or dtype)
        labels = jnp.asarray(labels, dtype=dtype)
        n = features.shape[-2]
        if weights is None:
            weights = jnp.ones((n,), dtype=dtype)
        else:
            weights = jnp.asarray(weights, dtype=dtype)
        if offsets is None:
            offsets = jnp.zeros((n,), dtype=dtype)
        else:
            offsets = jnp.asarray(offsets, dtype=dtype)
        return LabeledBatch(features, labels, weights, offsets)

    def pad_to(self, n: int) -> "LabeledBatch":
        """Pad rows up to ``n`` with zero-weight rows (host-side)."""
        cur = self.num_rows
        if cur == n:
            return self
        if cur > n:
            raise ValueError(f"cannot pad {cur} rows down to {n}")
        pad = n - cur

        def _pad(a, value=0.0):
            widths = [(0, 0)] * (a.ndim - 1) + [(0, pad)]
            if a.ndim > 1:  # features: pad rows, not columns
                widths = [(0, 0)] * (a.ndim - 2) + [(0, pad), (0, 0)]
            return jnp.pad(a, widths, constant_values=value)

        return LabeledBatch(
            features=_pad(self.features),
            labels=_pad(self.labels),
            weights=_pad(self.weights),
            offsets=_pad(self.offsets),
        )


def concat_batches(batches: list[LabeledBatch]) -> LabeledBatch:
    return LabeledBatch(
        features=jnp.concatenate([b.features for b in batches], axis=-2),
        labels=jnp.concatenate([b.labels for b in batches], axis=-1),
        weights=jnp.concatenate([b.weights for b in batches], axis=-1),
        offsets=jnp.concatenate([b.offsets for b in batches], axis=-1),
    )


def batch_from_numpy(
    X: np.ndarray,
    y: np.ndarray,
    weights: Optional[np.ndarray] = None,
    offsets: Optional[np.ndarray] = None,
    add_intercept: bool = False,
) -> LabeledBatch:
    X = np.asarray(X, dtype=np.float32)
    if add_intercept:
        X = np.concatenate([X, np.ones((X.shape[0], 1), np.float32)], axis=1)
    return LabeledBatch.build(X, np.asarray(y, np.float32), weights, offsets)
