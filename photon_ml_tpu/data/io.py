"""GameDataset persistence (npz + JSON metadata).

Reference note: the reference stores training data as Avro records on HDFS
(photon-client ``data/avro/AvroDataReader.scala``); this module is the
rebuild's fast native container for the same columnar content, used by the
CLI drivers. Avro interchange lives in photon_ml_tpu/data/avro.py.
"""

from __future__ import annotations

import json
import os

import numpy as np

from photon_ml_tpu.data.game_data import GameDataset, SparseShard

_META = "dataset.json"
_ARRAYS = "arrays.npz"


def save_game_dataset(ds: GameDataset, path: str) -> None:
    os.makedirs(path, exist_ok=True)
    arrays = {
        "response": ds.response,
        "offsets": ds.offsets,
        "weights": ds.weights,
    }
    sparse_shards = {}
    for k, v in ds.feature_shards.items():
        if isinstance(v, SparseShard):
            arrays[f"shard_{k}_indices"] = v.indices
            arrays[f"shard_{k}_values"] = v.values
            sparse_shards[k] = int(v.num_features)
        else:
            arrays[f"shard_{k}"] = v
    for k, v in ds.entity_ids.items():
        arrays[f"entity_{k}"] = v
    np.savez_compressed(os.path.join(path, _ARRAYS), **arrays)
    meta = {
        "shards": list(ds.feature_shards),
        "sparse_shards": sparse_shards,  # shard id -> num_features
        "entities": {k: int(n) for k, n in ds.num_entities.items()},
        "intercept_index": {k: v for k, v in ds.intercept_index.items()},
    }
    with open(os.path.join(path, _META), "w") as f:
        json.dump(meta, f, indent=2, sort_keys=True)


def load_game_dataset(path: str) -> GameDataset:
    with open(os.path.join(path, _META)) as f:
        meta = json.load(f)
    z = np.load(os.path.join(path, _ARRAYS))
    sparse = meta.get("sparse_shards", {})

    def _shard(k):
        if k in sparse:
            return SparseShard(indices=z[f"shard_{k}_indices"],
                               values=z[f"shard_{k}_values"],
                               num_features=int(sparse[k]))
        return z[f"shard_{k}"]

    return GameDataset(
        response=z["response"],
        offsets=z["offsets"],
        weights=z["weights"],
        feature_shards={k: _shard(k) for k in meta["shards"]},
        entity_ids={k: z[f"entity_{k}"] for k in meta["entities"]},
        num_entities={k: int(v) for k, v in meta["entities"].items()},
        intercept_index={k: (None if v is None else int(v))
                         for k, v in meta["intercept_index"].items()},
    )
