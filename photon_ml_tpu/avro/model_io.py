"""GAME model <-> Avro directory layout (feature-name-keyed records).

Reference parity: photon-client ``data/avro/ModelProcessingUtils.scala`` —
``saveGameModelToHDFS`` / ``loadGameModelFromHDFS``:

    <root>/fixed-effect/<coordinate>/coefficients.avro   (1 record)
    <root>/random-effect/<coordinate>/part-00000.avro    (1 record / entity)
    <root>/id-info.json                                  (metadata [MED])

Records are ``BayesianLinearModelAvro``: coefficients keyed by feature
(name, term) so models survive feature-map changes; variances optional.
The npz fast path (no index maps needed) lives in photon_ml_tpu/models/io.py;
this module is the interoperable format.
"""

from __future__ import annotations

import json
import os
from typing import Optional

import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.avro import schemas
from photon_ml_tpu.avro.container import read_records, write_records
from photon_ml_tpu.game.models import (FixedEffectModel, GameModel,
                                       RandomEffectModel,
                                       SubspaceRandomEffectModel,
                                       sort_subspace_rows)
from photon_ml_tpu.index.indexmap import (DefaultIndexMap, IndexMap,
                                          split_key)
from photon_ml_tpu.models.coefficients import Coefficients
from photon_ml_tpu.types import TaskType

_FIXED, _RANDOM = "fixed-effect", "random-effect"
_ID_INFO = "id-info.json"


def _vector_to_ntv(vec: np.ndarray, imap: IndexMap) -> list[dict]:
    out = []
    for j in np.nonzero(vec)[0]:
        key = imap.get_feature_name(int(j))
        if key is None:
            raise KeyError(f"index map has no feature for column {j}")
        name, term = split_key(key)
        out.append({"name": name, "term": term, "value": float(vec[j])})
    return out


def _ntv_to_vector(ntv: list[dict], imap: IndexMap, dim: int) -> np.ndarray:
    vec = np.zeros(dim, np.float32)
    from photon_ml_tpu.index.indexmap import feature_key
    for rec in ntv:
        j = imap.get_index(feature_key(rec["name"], rec.get("term", "")))
        if j >= 0:
            vec[j] = rec["value"]
    return vec


def _active_to_ntv(cols_row: np.ndarray, vals_row: np.ndarray,
                   imap: IndexMap) -> list[dict]:
    """Entries for ALL active columns (zero coefficients included): the
    active set IS the entity's subspace and must survive a round trip."""
    out = []
    for a in np.flatnonzero(cols_row >= 0):
        j = int(cols_row[a])
        key = imap.get_feature_name(j)
        if key is None:
            raise KeyError(f"index map has no feature for column {j}")
        name, term = split_key(key)
        out.append({"name": name, "term": term, "value": float(vals_row[a])})
    return out


def _is_factored(m) -> bool:
    from photon_ml_tpu.game.factored import FactoredRandomEffectModel

    return isinstance(m, FactoredRandomEffectModel)


def save_game_model_avro(
    model: GameModel,
    path: str,
    index_maps: dict[str, IndexMap],
    entity_vocabs: Optional[dict[str, dict[str, int]]] = None,
    codec: str = "deflate",
) -> None:
    """Write the reference's Avro model directory layout."""
    entity_vocabs = entity_vocabs or {}
    os.makedirs(path, exist_ok=True)
    meta = {"task": TaskType(model.task).value, "coordinates": {}}
    for cid, m in model.models.items():
        imap = index_maps[m.shard_id]
        if isinstance(m, FixedEffectModel):
            sub = os.path.join(path, _FIXED, cid)
            rec = {
                "modelId": cid,
                "modelClass": "FixedEffectModel",
                "means": _vector_to_ntv(
                    np.asarray(m.coefficients.means), imap),
            }
            if m.coefficients.variances is not None:
                rec["variances"] = _vector_to_ntv(
                    np.asarray(m.coefficients.variances), imap)
            write_records(os.path.join(sub, "coefficients.avro"),
                          schemas.BAYESIAN_LINEAR_MODEL_AVRO, [rec],
                          codec=codec)
            meta["coordinates"][cid] = {"type": "fixed",
                                        "shard": m.shard_id}
        elif _is_factored(m):
            # Reference layout: LatentFactorAvro records — per-entity latent
            # factors plus the shared projection matrix (one record per
            # feature row, effectId = the feature's name␁term key).
            sub = os.path.join(path, _RANDOM, cid)
            vocab = entity_vocabs.get(m.re_type)
            if vocab is None:
                vocab = {str(i): i for i in range(m.num_entities)}
            Z = np.asarray(m.factors)
            A = np.asarray(m.projection)
            # A vocabulary extended via allow_unseen_entities maps entities
            # to rows past the trained table; those have no coefficients
            # (they score zero) and the load path already tolerates
            # oversized vocabularies — skip them instead of IndexError.
            recs = [{"effectId": ent,
                     "factors": [float(v) for v in Z[row]]}
                    for ent, row in sorted(vocab.items(),
                                           key=lambda kv: kv[1])
                    if row < Z.shape[0]]
            write_records(os.path.join(sub, "latent-factors.avro"),
                          schemas.LATENT_FACTOR_AVRO, recs, codec=codec)
            proj_recs = []
            for j in range(A.shape[0]):
                key = imap.get_feature_name(j)
                if key is None:
                    raise KeyError(
                        f"index map for shard {m.shard_id!r} has no feature "
                        f"for projection row {j} (map covers {len(imap)} of "
                        f"{A.shape[0]} columns)")
                proj_recs.append({"effectId": key,
                                  "factors": [float(v) for v in A[j]]})
            write_records(os.path.join(sub, "projection-matrix.avro"),
                          schemas.LATENT_FACTOR_AVRO, proj_recs, codec=codec)
            meta["coordinates"][cid] = {
                "type": "factored", "shard": m.shard_id,
                "re_type": m.re_type, "num_entities": m.num_entities,
                "rank": int(m.rank),
            }
        elif isinstance(m, SubspaceRandomEffectModel):
            # Reference: RandomEffectModelInProjectedSpace — per-entity
            # records carry exactly the active-column coefficients (the
            # BayesianLinearModelAvro name/term/value layout is naturally
            # sparse), so the (E, d) dense table never exists on disk
            # either.
            sub = os.path.join(path, _RANDOM, cid)
            vocab = entity_vocabs.get(m.re_type)
            if vocab is None:
                vocab = {str(i): i for i in range(m.num_entities)}
            cols = np.asarray(m.cols)
            means = np.asarray(m.means)
            variances = (None if m.variances is None
                         else np.asarray(m.variances))

            def sub_records(vocab=vocab, cols=cols, means=means,
                            variances=variances, imap=imap):
                # Generator: at the 10⁶-entity scale this branch exists
                # for, materializing every record dict first would cost
                # gigabytes of host RAM — stream one entity at a time.
                for ent, row in sorted(vocab.items(),
                                       key=lambda kv: kv[1]):
                    if row >= cols.shape[0]:
                        continue  # extended vocab: untrained, scores zero
                    rec = {
                        "modelId": ent,
                        "modelClass": "RandomEffectModel",
                        "means": _active_to_ntv(cols[row], means[row],
                                                imap),
                    }
                    if variances is not None:
                        rec["variances"] = _active_to_ntv(
                            cols[row], variances[row], imap)
                    yield rec

            write_records(os.path.join(sub, "part-00000.avro"),
                          schemas.BAYESIAN_LINEAR_MODEL_AVRO,
                          sub_records(), codec=codec)
            meta["coordinates"][cid] = {
                "type": "random-subspace", "shard": m.shard_id,
                "re_type": m.re_type, "num_entities": m.num_entities,
                "subspace_dim": int(m.subspace_dim),
            }
        else:
            sub = os.path.join(path, _RANDOM, cid)
            vocab = entity_vocabs.get(m.re_type)
            if vocab is None:
                vocab = {str(i): i for i in range(m.num_entities)}
            means = np.asarray(m.means)
            variances = (None if m.variances is None
                         else np.asarray(m.variances))
            recs = []
            for ent, row in sorted(vocab.items(), key=lambda kv: kv[1]):
                if row >= means.shape[0]:
                    # Extended vocabulary (allow_unseen_entities): no
                    # trained row — scores zero; load tolerates the gap.
                    continue
                rec = {
                    "modelId": ent,
                    "modelClass": "RandomEffectModel",
                    "means": _vector_to_ntv(means[row], imap),
                }
                if variances is not None:
                    rec["variances"] = _vector_to_ntv(variances[row], imap)
                recs.append(rec)
            write_records(os.path.join(sub, "part-00000.avro"),
                          schemas.BAYESIAN_LINEAR_MODEL_AVRO, recs,
                          codec=codec)
            meta["coordinates"][cid] = {
                "type": "random", "shard": m.shard_id,
                "re_type": m.re_type, "num_entities": m.num_entities,
            }
    with open(os.path.join(path, _ID_INFO), "w") as fh:
        json.dump(meta, fh, indent=2)


def load_game_model_avro(
    path: str,
    index_maps: dict[str, IndexMap],
    entity_vocabs: Optional[dict[str, dict[str, int]]] = None,
) -> GameModel:
    """Load the Avro model directory written by :func:`save_game_model_avro`
    (or by the reference's ModelProcessingUtils, same layout)."""
    entity_vocabs = entity_vocabs or {}
    with open(os.path.join(path, _ID_INFO)) as fh:
        meta = json.load(fh)
    models = {}
    for cid, info in meta["coordinates"].items():
        imap = index_maps[info["shard"]]
        dim = len(imap)
        if info["type"] == "fixed":
            recs = read_records(os.path.join(path, _FIXED, cid))
            rec = recs[0]
            means = _ntv_to_vector(rec["means"], imap, dim)
            var = rec.get("variances")
            models[cid] = FixedEffectModel(
                shard_id=info["shard"],
                coefficients=Coefficients(
                    means=jnp.asarray(means),
                    variances=(None if var is None
                               else jnp.asarray(_ntv_to_vector(
                                   var, imap, dim)))))
        elif info["type"] == "factored":
            from photon_ml_tpu.game.factored import FactoredRandomEffectModel

            sub = os.path.join(path, _RANDOM, cid)
            z_recs = read_records(os.path.join(sub, "latent-factors.avro"))
            a_recs = read_records(os.path.join(sub,
                                               "projection-matrix.avro"))
            rank = int(info["rank"])
            vocab = entity_vocabs.get(info["re_type"]) or {
                r["effectId"]: i for i, r in enumerate(z_recs)}
            # Size by the CALLER's vocabulary too: scoring-time vocabs may
            # map saved entities to rows beyond the save-time entity count
            # (new entities get zero factors — the passive-data contract).
            n_ent = max(info.get("num_entities", 0), len(vocab),
                        max(vocab.values(), default=-1) + 1)
            Z = np.zeros((n_ent, rank), np.float32)
            for rec in z_recs:
                row = vocab.get(rec["effectId"])
                if row is not None:
                    Z[row] = rec["factors"]
            A = np.zeros((dim, rank), np.float32)
            for rec in a_recs:
                j = imap.get_index(rec["effectId"])
                if j >= 0:
                    A[j] = rec["factors"]
            models[cid] = FactoredRandomEffectModel(
                re_type=info["re_type"], shard_id=info["shard"],
                projection=jnp.asarray(A), factors=jnp.asarray(Z))
        elif info["type"] == "random-subspace":
            from photon_ml_tpu.index.indexmap import feature_key

            recs = read_records(os.path.join(path, _RANDOM, cid))
            vocab = entity_vocabs.get(info["re_type"]) or {
                r["modelId"]: i for i, r in enumerate(recs)}
            n_ent = max(info.get("num_entities", 0), len(vocab),
                        max(vocab.values(), default=-1) + 1)
            A = max(int(info.get("subspace_dim", 1)), 1)
            cols = np.full((n_ent, A), -1, np.int32)
            means = np.zeros((n_ent, A), np.float32)
            variances = None
            for rec in recs:
                row = vocab.get(rec["modelId"])
                if row is None:
                    continue
                for a, e in enumerate(rec["means"][:A]):
                    j = imap.get_index(feature_key(e["name"],
                                                   e.get("term", "")))
                    if j >= 0:
                        cols[row, a] = j
                        means[row, a] = e["value"]
                if rec.get("variances") is not None:
                    if variances is None:
                        variances = np.zeros((n_ent, A), np.float32)
                    for a, e in enumerate(rec["variances"][:A]):
                        if cols[row, a] >= 0:
                            variances[row, a] = e["value"]
            # Re-sort each row by column id (padding last): the caller's
            # index map may reorder columns (or drop some, leaving -1
            # holes mid-row), and score() requires sorted cols rows.
            cols, _, means, variances = sort_subspace_rows(
                cols, means, variances)
            models[cid] = SubspaceRandomEffectModel(
                re_type=info["re_type"], shard_id=info["shard"],
                num_features=dim, cols=jnp.asarray(cols),
                means=jnp.asarray(means),
                variances=(None if variances is None
                           else jnp.asarray(variances)))
        else:
            recs = read_records(os.path.join(path, _RANDOM, cid))
            vocab = entity_vocabs.get(info["re_type"]) or {
                r["modelId"]: i for i, r in enumerate(recs)}
            # Same sizing rule as the factored branch: honor scoring-time
            # vocabularies whose rows exceed the save-time entity count.
            n_ent = max(info.get("num_entities", 0), len(vocab),
                        max(vocab.values(), default=-1) + 1)
            means = np.zeros((n_ent, dim), np.float32)
            variances = None
            for rec in recs:
                row = vocab.get(rec["modelId"])
                if row is None:
                    continue
                means[row] = _ntv_to_vector(rec["means"], imap, dim)
                if rec.get("variances") is not None:
                    if variances is None:
                        variances = np.zeros((n_ent, dim), np.float32)
                    variances[row] = _ntv_to_vector(rec["variances"], imap,
                                                    dim)
            models[cid] = RandomEffectModel(
                re_type=info["re_type"], shard_id=info["shard"],
                means=jnp.asarray(means),
                variances=(None if variances is None
                           else jnp.asarray(variances)))
    return GameModel(task=TaskType(meta["task"]), models=models)


def save_index_maps(index_maps: dict[str, IndexMap], path: str) -> None:
    os.makedirs(path, exist_ok=True)
    for shard, imap in index_maps.items():
        if not isinstance(imap, DefaultIndexMap):
            imap = DefaultIndexMap(
                {imap.get_feature_name(i): i for i in range(len(imap))})
        imap.save(os.path.join(path, f"{shard}.json"))


def load_index_maps(path: str) -> dict[str, IndexMap]:
    from photon_ml_tpu.index.indexmap import load_index_map
    out = {}
    for name in sorted(os.listdir(path)):
        if name.endswith((".json", ".pidx")):
            out[name.rsplit(".", 1)[0]] = load_index_map(
                os.path.join(path, name))
    return out
