"""Avro training-data reader: container files -> columnar GameDataset.

Reference parity: photon-client ``data/avro/AvroDataReader.scala`` (+
``AvroFieldNames.scala`` field-name presets,
``data/FeatureShardConfiguration.scala``). The reference assembles one
sparse-vector DataFrame column per feature shard; the TPU-first equivalent
assembles one dense (n, d_shard) host matrix per shard (sparse CSR shards
for huge feature spaces live in the Criteo path, ``data/sparse.py``), plus
int32 entity-id columns mapped through per-RE-type vocabularies.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Union

import numpy as np

from photon_ml_tpu.avro.container import read_records
from photon_ml_tpu.data.game_data import GameDataset, SparseShard
from photon_ml_tpu.index.indexmap import (DefaultIndexMap, INTERCEPT_KEY,
                                          IndexMap, feature_key)


@dataclasses.dataclass(frozen=True)
class FieldNames:
    """Record field-name preset (AvroFieldNames parity)."""

    response: str = "label"
    offset: str = "offset"
    weight: str = "weight"
    uid: str = "uid"
    metadata: str = "metadataMap"


TRAINING_EXAMPLE_FIELDS = FieldNames()  # TrainingExampleFieldNames parity
RESPONSE_PREDICTION_FIELDS = FieldNames(response="response")


@dataclasses.dataclass(frozen=True)
class FeatureShardConfig:
    """A feature shard = named union of feature bags + intercept flag
    (FeatureShardConfiguration parity).

    ``sparse=True`` materializes the shard as ELL (data/game_data.py
    SparseShard) instead of a dense (n, d) matrix — the Criteo regime,
    where d reaches millions and densifying is impossible. Repeated
    features within a record accumulate (same as the dense path), keeping
    the ELL rows canonical."""

    feature_bags: tuple[str, ...] = ("features",)
    has_intercept: bool = True
    sparse: bool = False


def _record_features(record: dict, bags: Sequence[str]):
    for bag in bags:
        for f in record.get(bag) or ():
            yield feature_key(f["name"], f.get("term", ""))


def _entity_value(record: dict, re_type: str,
                  meta_field: str) -> Optional[str]:
    v = record.get(re_type)
    if v is None:
        meta = record.get(meta_field) or {}
        v = meta.get(re_type)
    return None if v is None else str(v)


class AvroDataReader:
    """Read Avro container files into a GameDataset.

    ``read`` makes one pass if index maps (and entity vocabularies) are
    supplied, otherwise a scan pass builds DefaultIndexMaps per shard —
    mirroring the reference's choice between PalDB-backed maps and
    from-data map generation.
    """

    def __init__(self, field_names: FieldNames = TRAINING_EXAMPLE_FIELDS):
        self.fields = field_names

    def read(
        self,
        paths: Union[str, Sequence[str]],
        feature_shard_configs: dict[str, FeatureShardConfig],
        random_effect_types: Sequence[str] = (),
        index_maps: Optional[dict[str, IndexMap]] = None,
        entity_vocabs: Optional[dict[str, dict[str, int]]] = None,
        use_native: bool = True,
        allow_unseen_entities: bool = False,
    ):
        """Returns (GameDataset, ReadMeta).

        ``use_native=True`` (default) decodes supported schemas through the
        C++ block decoder (native/avro_decode.cc) with vectorized columnar
        assembly — identical results to the pure-Python path, which remains
        the fallback for exotic schemas or when no toolchain is available.

        ``allow_unseen_entities=True`` makes a frozen ``entity_vocabs``
        EXTENSIBLE: ids absent from it get fresh rows appended after the
        frozen range instead of raising. Scoring-time semantics match the
        reference — a random-effect model has no row for those ids, and
        model scoring contributes exactly zero for them (fixed effect
        only).
        """
        if isinstance(paths, str):
            paths = [paths]
        if use_native:
            out = self._read_native(paths, feature_shard_configs,
                                    random_effect_types, index_maps,
                                    entity_vocabs, allow_unseen_entities)
            if out is not None:
                return out
        records: list[dict] = []
        for p in paths:
            records.extend(read_records(p))
        if not records:
            raise ValueError(f"no records under {paths}")

        if index_maps is None:
            index_maps = {
                shard: DefaultIndexMap.from_keys(
                    (k for r in records
                     for k in _record_features(r, cfg.feature_bags)),
                    add_intercept=cfg.has_intercept)
                for shard, cfg in feature_shard_configs.items()
            }

        frozen_vocab = entity_vocabs is not None
        vocabs: dict[str, dict[str, int]] = (
            {t: dict(v) for t, v in entity_vocabs.items()} if frozen_vocab
            else {t: {} for t in random_effect_types})

        n = len(records)
        fields = self.fields
        response = np.zeros(n, np.float32)
        offsets = np.zeros(n, np.float32)
        weights = np.ones(n, np.float32)
        uids = np.empty(n, object)
        shard_mats = {
            shard: np.zeros((n, len(index_maps[shard])), np.float32)
            for shard, cfg in feature_shard_configs.items() if not cfg.sparse
        }
        # Sparse shards: one {col: val} accumulator per record, ELL-ified
        # after the pass (repeated features accumulate like the dense path).
        sparse_rows: dict[str, list[dict]] = {
            shard: [dict() for _ in range(n)]
            for shard, cfg in feature_shard_configs.items() if cfg.sparse
        }
        id_cols = {t: np.zeros(n, np.int32) for t in random_effect_types}

        for i, rec in enumerate(records):
            # Reference AvroDataReader fails fast on a missing response
            # column; defaulting would silently train on all-zero labels.
            if rec.get(fields.response) is None:
                raise ValueError(
                    f"record {i} is missing required response field "
                    f"{fields.response!r}")
            response[i] = rec[fields.response]
            off = rec.get(fields.offset)
            offsets[i] = 0.0 if off is None else off
            w = rec.get(fields.weight)
            weights[i] = 1.0 if w is None else w
            uid = rec.get(fields.uid)
            uids[i] = i if uid is None else uid
            for shard, cfg in feature_shard_configs.items():
                imap = index_maps[shard]
                if cfg.sparse:
                    row = sparse_rows[shard][i]
                    for bag in cfg.feature_bags:
                        for f in rec.get(bag) or ():
                            j = imap.get_index(feature_key(f["name"],
                                                           f.get("term", "")))
                            if j >= 0:
                                row[j] = row.get(j, 0.0) + f["value"]
                    if cfg.has_intercept:
                        j = imap.get_index(INTERCEPT_KEY)
                        if j >= 0:
                            row[j] = 1.0
                    continue
                mat = shard_mats[shard]
                for bag in cfg.feature_bags:
                    for f in rec.get(bag) or ():
                        j = imap.get_index(feature_key(f["name"],
                                                       f.get("term", "")))
                        if j >= 0:
                            mat[i, j] += f["value"]
                if cfg.has_intercept:
                    j = imap.get_index(INTERCEPT_KEY)
                    if j >= 0:
                        mat[i, j] = 1.0
            for t in random_effect_types:
                raw = _entity_value(rec, t, fields.metadata)
                if raw is None:
                    raise ValueError(
                        f"record {i} missing random-effect id {t!r}")
                vocab = vocabs[t]
                if raw not in vocab:
                    if frozen_vocab and not allow_unseen_entities:
                        raise KeyError(
                            f"unseen entity {raw!r} for {t!r} under a frozen "
                            f"vocabulary (scoring with unseen entities must "
                            f"map them explicitly, or pass "
                            f"allow_unseen_entities=True)")
                    vocab[raw] = len(vocab)
                id_cols[t][i] = vocab[raw]

        feature_shards: dict = dict(shard_mats)
        for shard, rows in sparse_rows.items():
            # CSR triplets → data/sparse.py from_csr, the ONE owner of the
            # ELL layout contract (padding sentinel, max_nnz policy).
            from photon_ml_tpu.data.sparse import from_csr

            d = len(index_maps[shard])
            indptr = np.zeros(n + 1, np.int64)
            cols: list[int] = []
            vals: list[float] = []
            for i, row in enumerate(rows):
                for j, v in sorted(row.items()):
                    cols.append(j)
                    vals.append(v)
                indptr[i + 1] = len(cols)
            ell = from_csr(indptr, np.asarray(cols, np.int32),
                           np.asarray(vals, np.float32), labels=response,
                           num_features=d)
            feature_shards[shard] = SparseShard(
                indices=ell.indices, values=ell.values, num_features=d)

        ds = GameDataset(
            response=response,
            offsets=offsets,
            weights=weights,
            feature_shards=feature_shards,
            entity_ids=id_cols,
            num_entities={t: len(v) for t, v in vocabs.items()},
            intercept_index={
                shard: (index_maps[shard].get_index(INTERCEPT_KEY)
                        if cfg.has_intercept else None)
                for shard, cfg in feature_shard_configs.items()
            },
        )
        return ds, ReadMeta(index_maps=index_maps, entity_vocabs=vocabs,
                            uids=uids)


    # -- native fast path --------------------------------------------------

    def _read_native(self, paths, feature_shard_configs,
                     random_effect_types, index_maps, entity_vocabs,
                     allow_unseen_entities=False):
        """Vectorized read over native/avro_decode.cc columns; None →
        caller falls back to the per-record Python loop. Semantics are
        kept IDENTICAL to that loop: encounter-order index maps,
        first-occurrence entity vocabularies, accumulate-then-set-intercept
        feature assembly, and the same error conditions."""
        import os

        from photon_ml_tpu.avro import native_decode as nd

        if not nd.native_available():
            return None
        files: list[str] = []
        for p in paths:
            if os.path.isdir(p):
                files.extend(os.path.join(p, name)
                             for name in sorted(os.listdir(p))
                             if name.endswith(".avro"))
            elif os.path.exists(p):
                files.append(p)
            else:
                return None  # let the Python path raise its own error
        if not files:
            raise ValueError(f"no records under {list(paths)}")

        fields = self.fields
        bag_names = list(dict.fromkeys(
            b for cfg in feature_shard_configs.values()
            for b in cfg.feature_bags))
        captures = {
            fields.response: (nd.CAP_RESPONSE, 0),
            fields.offset: (nd.CAP_OFFSET, 0),
            fields.weight: (nd.CAP_WEIGHT, 0),
            fields.uid: (nd.CAP_UID, 0),
            fields.metadata: (nd.CAP_META, 0),
        }
        if len(captures) != 5:
            return None  # colliding field-name preset: fall back
        for k, b in enumerate(bag_names):
            if b in captures:
                return None
            captures[b] = (nd.CAP_BAG, k)
        decoded = []
        for f in files:
            d = nd.decode_file(f, captures, n_bags=len(bag_names),
                               forbidden_fields=frozenset(
                                   random_effect_types))
            if d is None:
                return None
            decoded.append(d)
        n = sum(d.num_records for d in decoded)
        if n == 0:
            raise ValueError(f"no records under {list(paths)}")
        bag_pos = {b: k for k, b in enumerate(bag_names)}

        # Index maps: DefaultIndexMap.from_keys SORTS its keys, so the
        # union of each shard's bag key tables is all that matters (the
        # tables already deduplicate per bag per file).
        if index_maps is None:
            index_maps = {}
            for shard, cfg in feature_shard_configs.items():
                keys: set[str] = set()
                for d in decoded:
                    for b in cfg.feature_bags:
                        keys.update(d.bags[bag_pos[b]].key_strings)
                index_maps[shard] = DefaultIndexMap.from_keys(
                    keys, add_intercept=cfg.has_intercept)

        # Scalars + uids.
        response = np.concatenate(
            [d.response for d in decoded]).astype(np.float32)
        offsets = np.concatenate(
            [d.offsets for d in decoded]).astype(np.float32)
        weights = np.concatenate(
            [d.weights for d in decoded]).astype(np.float32)
        # uids: default to the GLOBAL record index; overwrite only where a
        # record carried one (vectorized fancy-index assignment).
        uids = np.arange(n).astype(object)
        base = 0
        for d in decoded:
            present = d.uid_kind != 0
            if present.any():
                seg = uids[base: base + d.num_records]
                seg[present] = d.uids[present]
            base += d.num_records

        # Feature shards.
        feature_shards: dict = {}
        for shard, cfg in feature_shard_configs.items():
            imap = index_maps[shard]
            dcols = len(imap)
            ji = imap.get_index(INTERCEPT_KEY) if cfg.has_intercept else -1
            rows_l, cols_l, vals_l = [], [], []
            base = 0
            for d in decoded:
                for b in cfg.feature_bags:
                    bag = d.bags[bag_pos[b]]
                    if not len(bag.rows):
                        continue
                    lut = np.asarray([imap.get_index(s)
                                      for s in bag.key_strings], np.int64)
                    cols = lut[bag.keys]
                    keep = cols >= 0
                    rows_l.append(bag.rows[keep] + base)
                    cols_l.append(cols[keep])
                    vals_l.append(bag.values[keep])
                base += d.num_records
            rows = (np.concatenate(rows_l) if rows_l
                    else np.zeros(0, np.int64))
            cols = (np.concatenate(cols_l) if cols_l
                    else np.zeros(0, np.int64))
            vals = (np.concatenate(vals_l) if vals_l
                    else np.zeros(0, np.float64))
            if not cfg.sparse:
                mat = np.zeros((n, dcols), np.float32)
                np.add.at(mat, (rows, cols), vals.astype(np.float32))
                if ji >= 0:
                    mat[:, ji] = 1.0
                feature_shards[shard] = mat
                continue
            # Sparse (ELL via CSR): accumulate duplicates, then SET the
            # intercept (the per-record dict semantics of the slow path).
            if ji >= 0:
                keep = cols != ji
                rows, cols, vals = rows[keep], cols[keep], vals[keep]
            pair = rows * dcols + cols
            uniq, inv = np.unique(pair, return_inverse=True)
            sums = np.bincount(inv, weights=vals,
                               minlength=len(uniq)).astype(np.float32)
            urows, ucols = uniq // dcols, uniq % dcols
            if ji >= 0:
                urows = np.concatenate([urows, np.arange(n)])
                ucols = np.concatenate(
                    [ucols, np.full(n, ji, np.int64)])
                sums = np.concatenate([sums, np.ones(n, np.float32)])
                order = np.lexsort((ucols, urows))
                urows, ucols, sums = (urows[order], ucols[order],
                                      sums[order])
            from photon_ml_tpu.data.sparse import from_csr

            indptr = np.zeros(n + 1, np.int64)
            np.cumsum(np.bincount(urows, minlength=n), out=indptr[1:])
            ell = from_csr(indptr, ucols.astype(np.int32), sums,
                           labels=response, num_features=dcols)
            feature_shards[shard] = SparseShard(
                indices=ell.indices, values=ell.values,
                num_features=dcols)

        # Entity ids (from metadataMap; direct-field layouts fell back).
        frozen = entity_vocabs is not None
        vocabs: dict[str, dict[str, int]] = (
            {t: dict(v) for t, v in entity_vocabs.items()} if frozen
            else {t: {} for t in random_effect_types})
        id_cols = {}
        for t in random_effect_types:
            col = np.zeros(n, np.int64)
            base = 0
            for d in decoded:
                try:
                    key_id = d.meta_key_strings.index(t)
                    sel = d.meta_keys == key_id
                except ValueError:
                    sel = np.zeros(len(d.meta_keys), bool)
                rows_t = d.meta_rows[sel]
                val_ids = d.meta_vals[sel]
                if (len(rows_t) != d.num_records
                        or not np.array_equal(
                            rows_t, np.arange(d.num_records))):
                    present = np.zeros(d.num_records, bool)
                    present[rows_t] = True
                    missing = np.flatnonzero(~present)
                    if len(missing):
                        raise ValueError(
                            f"record {base + int(missing[0])} missing "
                            f"random-effect id {t!r}")
                    # Wire-level duplicate map keys: keep the LAST value
                    # per record, the Python dict-decode semantics.
                    last = np.full(d.num_records, -1, np.int64)
                    last[rows_t] = np.arange(len(rows_t))
                    val_ids = val_ids[last]
                lut = np.full(len(d.meta_val_strings), -1, np.int64)
                uniq_vids, first = np.unique(val_ids, return_index=True)
                for vid in uniq_vids[np.argsort(first)]:
                    raw = d.meta_val_strings[vid]
                    if raw not in vocabs[t]:
                        if frozen and not allow_unseen_entities:
                            raise KeyError(
                                f"unseen entity {raw!r} for {t!r} under a "
                                f"frozen vocabulary (scoring with unseen "
                                f"entities must map them explicitly, or "
                                f"pass allow_unseen_entities=True)")
                        vocabs[t][raw] = len(vocabs[t])
                    lut[vid] = vocabs[t][raw]
                col[base: base + d.num_records] = lut[val_ids]
                base += d.num_records
            id_cols[t] = col.astype(np.int32)

        ds = GameDataset(
            response=response,
            offsets=offsets,
            weights=weights,
            feature_shards=feature_shards,
            entity_ids=id_cols,
            num_entities={t: len(v) for t, v in vocabs.items()},
            intercept_index={
                shard: (index_maps[shard].get_index(INTERCEPT_KEY)
                        if cfg.has_intercept else None)
                for shard, cfg in feature_shard_configs.items()
            },
        )
        return ds, ReadMeta(index_maps=index_maps, entity_vocabs=vocabs,
                            uids=uids)


@dataclasses.dataclass
class ReadMeta:
    """Side products of a read: feature maps, entity vocabularies, uids."""

    index_maps: dict[str, IndexMap]
    entity_vocabs: dict[str, dict[str, int]]
    uids: np.ndarray
