"""Avro training-data reader: container files -> columnar GameDataset.

Reference parity: photon-client ``data/avro/AvroDataReader.scala`` (+
``AvroFieldNames.scala`` field-name presets,
``data/FeatureShardConfiguration.scala``). The reference assembles one
sparse-vector DataFrame column per feature shard; the TPU-first equivalent
assembles one dense (n, d_shard) host matrix per shard (sparse CSR shards
for huge feature spaces live in the Criteo path, ``data/sparse.py``), plus
int32 entity-id columns mapped through per-RE-type vocabularies.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Union

import numpy as np

from photon_ml_tpu.avro.container import read_records
from photon_ml_tpu.data.game_data import GameDataset, SparseShard
from photon_ml_tpu.index.indexmap import (DefaultIndexMap, INTERCEPT_KEY,
                                          IndexMap, feature_key)


@dataclasses.dataclass(frozen=True)
class FieldNames:
    """Record field-name preset (AvroFieldNames parity)."""

    response: str = "label"
    offset: str = "offset"
    weight: str = "weight"
    uid: str = "uid"
    metadata: str = "metadataMap"


TRAINING_EXAMPLE_FIELDS = FieldNames()  # TrainingExampleFieldNames parity
RESPONSE_PREDICTION_FIELDS = FieldNames(response="response")


@dataclasses.dataclass(frozen=True)
class FeatureShardConfig:
    """A feature shard = named union of feature bags + intercept flag
    (FeatureShardConfiguration parity).

    ``sparse=True`` materializes the shard as ELL (data/game_data.py
    SparseShard) instead of a dense (n, d) matrix — the Criteo regime,
    where d reaches millions and densifying is impossible. Repeated
    features within a record accumulate (same as the dense path), keeping
    the ELL rows canonical."""

    feature_bags: tuple[str, ...] = ("features",)
    has_intercept: bool = True
    sparse: bool = False


def _record_features(record: dict, bags: Sequence[str]):
    for bag in bags:
        for f in record.get(bag) or ():
            yield feature_key(f["name"], f.get("term", ""))


def _entity_value(record: dict, re_type: str,
                  meta_field: str) -> Optional[str]:
    v = record.get(re_type)
    if v is None:
        meta = record.get(meta_field) or {}
        v = meta.get(re_type)
    return None if v is None else str(v)


class AvroDataReader:
    """Read Avro container files into a GameDataset.

    ``read`` makes one pass if index maps (and entity vocabularies) are
    supplied, otherwise a scan pass builds DefaultIndexMaps per shard —
    mirroring the reference's choice between PalDB-backed maps and
    from-data map generation.
    """

    def __init__(self, field_names: FieldNames = TRAINING_EXAMPLE_FIELDS):
        self.fields = field_names

    def read(
        self,
        paths: Union[str, Sequence[str]],
        feature_shard_configs: dict[str, FeatureShardConfig],
        random_effect_types: Sequence[str] = (),
        index_maps: Optional[dict[str, IndexMap]] = None,
        entity_vocabs: Optional[dict[str, dict[str, int]]] = None,
    ):
        """Returns (GameDataset, ReadMeta)."""
        if isinstance(paths, str):
            paths = [paths]
        records: list[dict] = []
        for p in paths:
            records.extend(read_records(p))
        if not records:
            raise ValueError(f"no records under {paths}")

        if index_maps is None:
            index_maps = {
                shard: DefaultIndexMap.from_keys(
                    (k for r in records
                     for k in _record_features(r, cfg.feature_bags)),
                    add_intercept=cfg.has_intercept)
                for shard, cfg in feature_shard_configs.items()
            }

        frozen_vocab = entity_vocabs is not None
        vocabs: dict[str, dict[str, int]] = (
            {t: dict(v) for t, v in entity_vocabs.items()} if frozen_vocab
            else {t: {} for t in random_effect_types})

        n = len(records)
        fields = self.fields
        response = np.zeros(n, np.float32)
        offsets = np.zeros(n, np.float32)
        weights = np.ones(n, np.float32)
        uids = np.empty(n, object)
        shard_mats = {
            shard: np.zeros((n, len(index_maps[shard])), np.float32)
            for shard, cfg in feature_shard_configs.items() if not cfg.sparse
        }
        # Sparse shards: one {col: val} accumulator per record, ELL-ified
        # after the pass (repeated features accumulate like the dense path).
        sparse_rows: dict[str, list[dict]] = {
            shard: [dict() for _ in range(n)]
            for shard, cfg in feature_shard_configs.items() if cfg.sparse
        }
        id_cols = {t: np.zeros(n, np.int32) for t in random_effect_types}

        for i, rec in enumerate(records):
            # Reference AvroDataReader fails fast on a missing response
            # column; defaulting would silently train on all-zero labels.
            if rec.get(fields.response) is None:
                raise ValueError(
                    f"record {i} is missing required response field "
                    f"{fields.response!r}")
            response[i] = rec[fields.response]
            off = rec.get(fields.offset)
            offsets[i] = 0.0 if off is None else off
            w = rec.get(fields.weight)
            weights[i] = 1.0 if w is None else w
            uid = rec.get(fields.uid)
            uids[i] = i if uid is None else uid
            for shard, cfg in feature_shard_configs.items():
                imap = index_maps[shard]
                if cfg.sparse:
                    row = sparse_rows[shard][i]
                    for bag in cfg.feature_bags:
                        for f in rec.get(bag) or ():
                            j = imap.get_index(feature_key(f["name"],
                                                           f.get("term", "")))
                            if j >= 0:
                                row[j] = row.get(j, 0.0) + f["value"]
                    if cfg.has_intercept:
                        j = imap.get_index(INTERCEPT_KEY)
                        if j >= 0:
                            row[j] = 1.0
                    continue
                mat = shard_mats[shard]
                for bag in cfg.feature_bags:
                    for f in rec.get(bag) or ():
                        j = imap.get_index(feature_key(f["name"],
                                                       f.get("term", "")))
                        if j >= 0:
                            mat[i, j] += f["value"]
                if cfg.has_intercept:
                    j = imap.get_index(INTERCEPT_KEY)
                    if j >= 0:
                        mat[i, j] = 1.0
            for t in random_effect_types:
                raw = _entity_value(rec, t, fields.metadata)
                if raw is None:
                    raise ValueError(
                        f"record {i} missing random-effect id {t!r}")
                vocab = vocabs[t]
                if raw not in vocab:
                    if frozen_vocab:
                        raise KeyError(
                            f"unseen entity {raw!r} for {t!r} under a frozen "
                            f"vocabulary (scoring with unseen entities must "
                            f"map them explicitly)")
                    vocab[raw] = len(vocab)
                id_cols[t][i] = vocab[raw]

        feature_shards: dict = dict(shard_mats)
        for shard, rows in sparse_rows.items():
            # CSR triplets → data/sparse.py from_csr, the ONE owner of the
            # ELL layout contract (padding sentinel, max_nnz policy).
            from photon_ml_tpu.data.sparse import from_csr

            d = len(index_maps[shard])
            indptr = np.zeros(n + 1, np.int64)
            cols: list[int] = []
            vals: list[float] = []
            for i, row in enumerate(rows):
                for j, v in sorted(row.items()):
                    cols.append(j)
                    vals.append(v)
                indptr[i + 1] = len(cols)
            ell = from_csr(indptr, np.asarray(cols, np.int32),
                           np.asarray(vals, np.float32), labels=response,
                           num_features=d)
            feature_shards[shard] = SparseShard(
                indices=ell.indices, values=ell.values, num_features=d)

        ds = GameDataset(
            response=response,
            offsets=offsets,
            weights=weights,
            feature_shards=feature_shards,
            entity_ids=id_cols,
            num_entities={t: len(v) for t, v in vocabs.items()},
            intercept_index={
                shard: (index_maps[shard].get_index(INTERCEPT_KEY)
                        if cfg.has_intercept else None)
                for shard, cfg in feature_shard_configs.items()
            },
        )
        return ds, ReadMeta(index_maps=index_maps, entity_vocabs=vocabs,
                            uids=uids)


@dataclasses.dataclass
class ReadMeta:
    """Side products of a read: feature maps, entity vocabularies, uids."""

    index_maps: dict[str, IndexMap]
    entity_vocabs: dict[str, dict[str, int]]
    uids: np.ndarray
