"""Avro training-data reader: container files -> columnar GameDataset.

Reference parity: photon-client ``data/avro/AvroDataReader.scala`` (+
``AvroFieldNames.scala`` field-name presets,
``data/FeatureShardConfiguration.scala``). The reference assembles one
sparse-vector DataFrame column per feature shard; the TPU-first equivalent
assembles one dense (n, d_shard) host matrix per shard (sparse CSR shards
for huge feature spaces live in the Criteo path, ``data/sparse.py``), plus
int32 entity-id columns mapped through per-RE-type vocabularies.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Optional, Sequence, Union

import numpy as np

from photon_ml_tpu.avro.container import read_records
from photon_ml_tpu.data.game_data import (GameDataset, SparseShard,
                                          vocab_token)
from photon_ml_tpu.index.indexmap import (DefaultIndexMap, INTERCEPT_KEY,
                                          IndexMap, feature_key)
from photon_ml_tpu.utils import events as ev_mod

logger = logging.getLogger("photon_ml_tpu.avro")

# The committed BENCH_r05 rates the fallback warning quotes: the native
# block decoder measured ~123k records/s against ~6k records/s for the
# pure-Python codec on the same file (bench.py, bench_avro_ingest).
_FALLBACK_RATE_GAP = "~20x slower (BENCH_r05: ~123k vs ~6k records/s)"


@dataclasses.dataclass(frozen=True)
class FieldNames:
    """Record field-name preset (AvroFieldNames parity)."""

    response: str = "label"
    offset: str = "offset"
    weight: str = "weight"
    uid: str = "uid"
    metadata: str = "metadataMap"


TRAINING_EXAMPLE_FIELDS = FieldNames()  # TrainingExampleFieldNames parity
RESPONSE_PREDICTION_FIELDS = FieldNames(response="response")


@dataclasses.dataclass(frozen=True)
class FeatureShardConfig:
    """A feature shard = named union of feature bags + intercept flag
    (FeatureShardConfiguration parity).

    ``sparse=True`` materializes the shard as ELL (data/game_data.py
    SparseShard) instead of a dense (n, d) matrix — the Criteo regime,
    where d reaches millions and densifying is impossible. Repeated
    features within a record accumulate (same as the dense path), keeping
    the ELL rows canonical."""

    feature_bags: tuple[str, ...] = ("features",)
    has_intercept: bool = True
    sparse: bool = False


def _record_features(record: dict, bags: Sequence[str]):
    for bag in bags:
        for f in record.get(bag) or ():
            yield feature_key(f["name"], f.get("term", ""))


def _entity_value(record: dict, re_type: str,
                  meta_field: str) -> Optional[str]:
    v = record.get(re_type)
    if v is None:
        meta = record.get(meta_field) or {}
        v = meta.get(re_type)
    return None if v is None else str(v)


class AvroDataReader:
    """Read Avro container files into a GameDataset.

    ``read`` makes one pass if index maps (and entity vocabularies) are
    supplied, otherwise a scan pass builds DefaultIndexMaps per shard —
    mirroring the reference's choice between PalDB-backed maps and
    from-data map generation.
    """

    def __init__(self, field_names: FieldNames = TRAINING_EXAMPLE_FIELDS):
        self.fields = field_names

    def read(
        self,
        paths: Union[str, Sequence[str]],
        feature_shard_configs: dict[str, FeatureShardConfig],
        random_effect_types: Sequence[str] = (),
        index_maps: Optional[dict[str, IndexMap]] = None,
        entity_vocabs: Optional[dict[str, dict[str, int]]] = None,
        use_native: bool = True,
        allow_unseen_entities: bool = False,
        chunk_rows: int = 65536,
        ingest=None,
    ):
        """Returns (GameDataset, ReadMeta).

        ``use_native=True`` (default) decodes supported schemas through the
        C++ block decoder (native/avro_decode.cc), block-parallel and
        pipelined (photon_ml_tpu/ingest, knobs via ``ingest=
        IngestConfig(...)`` including the columnar warm-restart cache) with
        vectorized columnar assembly — identical results to the pure-Python
        path, which remains the fallback for exotic schemas or when no
        toolchain is available. The fallback is LOUD: it logs the measured
        rate gap and emits an ``IngestFallback`` event, because silently
        degrading ~20x on the cold-fit input layer cost a round of
        benchmarking to notice (docs/INGEST.md).

        ``allow_unseen_entities=True`` makes a frozen ``entity_vocabs``
        EXTENSIBLE: ids absent from it get fresh rows appended after the
        frozen range instead of raising. Scoring-time semantics match the
        reference — a random-effect model has no row for those ids, and
        model scoring contributes exactly zero for them (fixed effect
        only).

        Bounded-memory streaming (reference: executors stream HDFS
        partitions through ``AvroDataReader.scala``; SURVEY §0 "host-side
        readers feeding a device-prefetch pipeline"): the Python path
        decodes at most ``chunk_rows`` record dicts at a time (decoded
        records cost ~50× their columnar size, so this bounds the
        dominant transient); the native path frees each file's decoded
        columns as soon as they are folded in whenever ``index_maps`` is
        given — the production flow (frozen feature space over daily
        partitions) never holds more than one partition's columns beyond
        the output arrays. Without ``index_maps`` the feature space is
        discovered in a separate streaming pass first, trading one extra
        read of the input for flat memory.
        """
        if isinstance(paths, str):
            paths = [paths]
        if use_native:
            out, fallback = self._read_native(
                paths, feature_shard_configs, random_effect_types,
                index_maps, entity_vocabs, allow_unseen_entities,
                ingest=ingest)
            if out is not None:
                return out
            if fallback:
                logger.warning(
                    "avro ingest is falling back to the pure-Python "
                    "codec — %s — reason: %s (docs/INGEST.md)",
                    _FALLBACK_RATE_GAP, fallback)
                ev_mod.default_emitter.emit(
                    ev_mod.IngestFallback(reason=fallback))

        def stream():
            for p in paths:
                yield from read_records(p)

        if index_maps is None:
            # Discovery pass: ONE extra stream over the input collects
            # every shard's key set simultaneously (bounded by vocabulary
            # size, not input size), then assembly streams again.
            keys_by_shard: dict[str, dict] = {
                s: {} for s in feature_shard_configs}
            for r in stream():
                for shard, cfg in feature_shard_configs.items():
                    sk = keys_by_shard[shard]
                    for k in _record_features(r, cfg.feature_bags):
                        sk[k] = None
            index_maps = {
                shard: DefaultIndexMap.from_keys(
                    keys_by_shard[shard],
                    add_intercept=cfg.has_intercept)
                for shard, cfg in feature_shard_configs.items()
            }

        frozen_vocab = entity_vocabs is not None
        vocabs: dict[str, dict[str, int]] = (
            {t: dict(v) for t, v in entity_vocabs.items()} if frozen_vocab
            else {t: {} for t in random_effect_types})

        acc = _ChunkAccumulator(self.fields, feature_shard_configs,
                                index_maps, random_effect_types, vocabs,
                                frozen_vocab, allow_unseen_entities)
        chunk: list[dict] = []
        for rec in stream():
            chunk.append(rec)
            if len(chunk) >= max(1, chunk_rows):
                acc.add_chunk(chunk)
                chunk = []
        if chunk:
            acc.add_chunk(chunk)
        if acc.num_rows == 0:
            raise ValueError(f"no records under {paths}")
        ds, uids = acc.finalize()
        ds.vocab_tokens = _make_vocab_tokens(entity_vocabs, vocabs)
        return ds, ReadMeta(index_maps=index_maps, entity_vocabs=vocabs,
                            uids=uids)


    # -- native fast path --------------------------------------------------

    def _read_native(self, paths, feature_shard_configs,
                     random_effect_types, index_maps, entity_vocabs,
                     allow_unseen_entities=False, ingest=None):
        """Vectorized read over native/avro_decode.cc columns, block-
        parallel and pipelined (photon_ml_tpu/ingest): the inputs split
        at sync-marker boundaries, decode workers fan over the chunks,
        and this thread folds each chunk's columns in plan order as it
        arrives — so decode and fold overlap, and warm restarts
        memory-map the columnar ingest cache instead of decoding.

        Returns ``(result, fallback_reason)``; ``result is None`` means
        the caller falls back to the per-record Python loop (loudly when
        ``fallback_reason`` is set; a None reason means the Python path
        is about to raise its own error). Semantics are kept IDENTICAL
        to that loop: encounter-order index maps, first-occurrence
        entity vocabularies, accumulate-then-set-intercept feature
        assembly, and the same error conditions."""
        import os

        from photon_ml_tpu import ingest as ing
        from photon_ml_tpu.avro import native_decode as nd

        if not nd.native_available():
            return None, ("the native Avro decoder is unavailable (no "
                          "C++ toolchain, or PHOTON_TPU_NO_NATIVE_AVRO=1)")
        files: list[str] = []
        for p in paths:
            if os.path.isdir(p):
                files.extend(os.path.join(p, name)
                             for name in sorted(os.listdir(p))
                             if name.endswith(".avro"))
            elif os.path.exists(p):
                files.append(p)
            else:
                # Let the Python path raise its own error (not a silent
                # degradation — the read fails either way).
                return None, None
        if not files:
            raise ValueError(f"no records under {list(paths)}")

        fields = self.fields
        bag_names = list(dict.fromkeys(
            b for cfg in feature_shard_configs.values()
            for b in cfg.feature_bags))
        captures = {
            fields.response: (nd.CAP_RESPONSE, 0),
            fields.offset: (nd.CAP_OFFSET, 0),
            fields.weight: (nd.CAP_WEIGHT, 0),
            fields.uid: (nd.CAP_UID, 0),
            fields.metadata: (nd.CAP_META, 0),
        }
        if len(captures) != 5:
            return None, "colliding field-name preset"
        for k, b in enumerate(bag_names):
            if b in captures:
                return None, (f"feature bag {b!r} collides with a "
                              f"scalar field name")
            captures[b] = (nd.CAP_BAG, k)
        bag_pos = {b: k for k, b in enumerate(bag_names)}

        # Block scan + per-file decode plans. Any file whose writer
        # schema the native plan compiler cannot express sends the WHOLE
        # read down the Python path (one feature space, one code path).
        forbidden = frozenset(random_effect_types)
        fbs: list[ing.FileBlocks] = []
        plans: list[np.ndarray] = []
        for f in files:
            fb = ing.scan_file(f)
            schema = fb.schema
            if isinstance(schema, dict) and any(
                    fld.get("name") in forbidden
                    for fld in schema.get("fields", ())):
                return None, (f"{f}: an entity id is a top-level record "
                              f"field (metadataMap layout required)")
            plan = nd.compile_plan(schema, captures)
            if plan is None:
                return None, f"{f}: schema outside the native family"
            fbs.append(fb)
            plans.append(plan)
        if not sum(fb.num_records for fb in fbs):
            raise ValueError(f"no records under {list(paths)}")

        config = ingest or ing.IngestConfig()
        chunks = ing.plan_chunks(fbs, config.chunk_records)
        cache_key = None
        if config.cache_dir:
            cache_key = ing.ingest_key(fbs, captures, len(bag_names),
                                       config.chunk_records)
        pipe = ing.IngestPipeline(chunks, plans, n_bags=len(bag_names),
                                  config=config, cache_key=cache_key)

        # Fold. With ``index_maps`` given (the production frozen-feature-
        # space flow), each chunk's decoded columns are folded into compact
        # accumulators and FREED before the next chunk is folded — peak
        # memory is the output arrays plus the pipeline's depth bound.
        # Without maps the feature space must be known before columns can
        # be mapped, so all chunks stay decoded until the union key tables
        # are built (the one-pass trade; pass index_maps to bound memory).
        incremental = index_maps is not None
        decoded: list = []
        scal_chunks: list[tuple] = []  # (response, offsets, weights, uids)
        coo_chunks: dict[str, list[tuple]] = {
            s: [] for s in feature_shard_configs}
        n = 0

        def fold_scalars(d, base):
            uid_seg = np.arange(base, base + d.num_records).astype(object)
            present = d.uid_kind != 0
            if present.any():
                uid_seg[present] = d.uids[present]
            scal_chunks.append((d.response.astype(np.float32),
                                d.offsets.astype(np.float32),
                                d.weights.astype(np.float32), uid_seg))

        def fold_features(d, base):
            for shard, cfg in feature_shard_configs.items():
                imap = index_maps[shard]
                for b in cfg.feature_bags:
                    bag = d.bags[bag_pos[b]]
                    if not len(bag.rows):
                        continue
                    lut = np.asarray([imap.get_index(s)
                                      for s in bag.key_strings], np.int64)
                    cols = lut[np.asarray(bag.keys)]
                    keep = cols >= 0
                    coo_chunks[shard].append(
                        (np.asarray(bag.rows)[keep] + base, cols[keep],
                         np.asarray(bag.values)[keep]))

        for d in pipe.chunks():
            if incremental:
                fold_scalars(d, n)
                fold_features(d, n)
                # Entity ids still need the string tables; keep only those
                # and DROP the bag/scalar columns before the next fold
                # (otherwise chunks peak-coexist beyond the depth bound).
                decoded.append(_MetaOnly(d))
                n += d.num_records
                del d
            else:
                decoded.append(d)
                n += d.num_records
        if n == 0:
            raise ValueError(f"no records under {list(paths)}")

        # Index maps: DefaultIndexMap.from_keys SORTS its keys, so the
        # union of each shard's bag key tables is all that matters (the
        # tables already deduplicate per bag per file).
        if index_maps is None:
            index_maps = {}
            for shard, cfg in feature_shard_configs.items():
                keys: set[str] = set()
                for d in decoded:
                    for b in cfg.feature_bags:
                        keys.update(d.bags[bag_pos[b]].key_strings)
                index_maps[shard] = DefaultIndexMap.from_keys(
                    keys, add_intercept=cfg.has_intercept)
            base = 0
            for d in decoded:
                fold_scalars(d, base)
                fold_features(d, base)
                base += d.num_records

        response = np.concatenate([c[0] for c in scal_chunks])
        offsets = np.concatenate([c[1] for c in scal_chunks])
        weights = np.concatenate([c[2] for c in scal_chunks])
        uids = np.concatenate([c[3] for c in scal_chunks])

        # Feature shards.
        feature_shards: dict = {}
        for shard, cfg in feature_shard_configs.items():
            imap = index_maps[shard]
            dcols = len(imap)
            ji = imap.get_index(INTERCEPT_KEY) if cfg.has_intercept else -1
            pieces = coo_chunks[shard]
            rows = (np.concatenate([p[0] for p in pieces]) if pieces
                    else np.zeros(0, np.int64))
            cols = (np.concatenate([p[1] for p in pieces]) if pieces
                    else np.zeros(0, np.int64))
            vals = (np.concatenate([p[2] for p in pieces]) if pieces
                    else np.zeros(0, np.float64))
            if not cfg.sparse:
                mat = np.zeros((n, dcols), np.float32)
                np.add.at(mat, (rows, cols), vals.astype(np.float32))
                if ji >= 0:
                    mat[:, ji] = 1.0
                feature_shards[shard] = mat
                continue
            # Sparse (ELL via CSR): accumulate duplicates, then SET the
            # intercept (the per-record dict semantics of the slow path).
            if ji >= 0:
                keep = cols != ji
                rows, cols, vals = rows[keep], cols[keep], vals[keep]
            pair = rows * dcols + cols
            uniq, inv = np.unique(pair, return_inverse=True)
            sums = np.bincount(inv, weights=vals,
                               minlength=len(uniq)).astype(np.float32)
            urows, ucols = uniq // dcols, uniq % dcols
            if ji >= 0:
                urows = np.concatenate([urows, np.arange(n)])
                ucols = np.concatenate(
                    [ucols, np.full(n, ji, np.int64)])
                sums = np.concatenate([sums, np.ones(n, np.float32)])
                order = np.lexsort((ucols, urows))
                urows, ucols, sums = (urows[order], ucols[order],
                                      sums[order])
            from photon_ml_tpu.data.sparse import from_csr

            indptr = np.zeros(n + 1, np.int64)
            np.cumsum(np.bincount(urows, minlength=n), out=indptr[1:])
            ell = from_csr(indptr, ucols.astype(np.int32), sums,
                           labels=response, num_features=dcols)
            feature_shards[shard] = SparseShard(
                indices=ell.indices, values=ell.values,
                num_features=dcols)

        # Entity ids (from metadataMap; direct-field layouts fell back).
        frozen = entity_vocabs is not None
        vocabs: dict[str, dict[str, int]] = (
            {t: dict(v) for t, v in entity_vocabs.items()} if frozen
            else {t: {} for t in random_effect_types})
        id_cols = {}
        for t in random_effect_types:
            col = np.zeros(n, np.int64)
            base = 0
            for d in decoded:
                try:
                    key_id = d.meta_key_strings.index(t)
                    sel = d.meta_keys == key_id
                except ValueError:
                    sel = np.zeros(len(d.meta_keys), bool)
                rows_t = d.meta_rows[sel]
                val_ids = d.meta_vals[sel]
                if (len(rows_t) != d.num_records
                        or not np.array_equal(
                            rows_t, np.arange(d.num_records))):
                    present = np.zeros(d.num_records, bool)
                    present[rows_t] = True
                    missing = np.flatnonzero(~present)
                    if len(missing):
                        raise ValueError(
                            f"record {base + int(missing[0])} missing "
                            f"random-effect id {t!r}")
                    # Wire-level duplicate map keys: keep the LAST value
                    # per record, the Python dict-decode semantics.
                    last = np.full(d.num_records, -1, np.int64)
                    last[rows_t] = np.arange(len(rows_t))
                    val_ids = val_ids[last]
                lut = np.full(len(d.meta_val_strings), -1, np.int64)
                uniq_vids, first = np.unique(val_ids, return_index=True)
                for vid in uniq_vids[np.argsort(first)]:
                    raw = d.meta_val_strings[vid]
                    if raw not in vocabs[t]:
                        if frozen and not allow_unseen_entities:
                            raise KeyError(
                                f"unseen entity {raw!r} for {t!r} under a "
                                f"frozen vocabulary (scoring with unseen "
                                f"entities must map them explicitly, or "
                                f"pass allow_unseen_entities=True)")
                        vocabs[t][raw] = len(vocabs[t])
                    lut[vid] = vocabs[t][raw]
                col[base: base + d.num_records] = lut[val_ids]
                base += d.num_records
            id_cols[t] = col.astype(np.int32)

        ds = GameDataset(
            response=response,
            offsets=offsets,
            weights=weights,
            feature_shards=feature_shards,
            entity_ids=id_cols,
            num_entities={t: len(v) for t, v in vocabs.items()},
            intercept_index={
                shard: (index_maps[shard].get_index(INTERCEPT_KEY)
                        if cfg.has_intercept else None)
                for shard, cfg in feature_shard_configs.items()
            },
            vocab_tokens=_make_vocab_tokens(entity_vocabs, vocabs),
            entity_counts={
                t: np.bincount(col, minlength=len(vocabs[t]))
                for t, col in id_cols.items()},
        )
        return (ds, ReadMeta(index_maps=index_maps, entity_vocabs=vocabs,
                             uids=uids)), None


class _MetaOnly:
    """Retains only a DecodedFile's metadataMap columns (what entity-id
    assembly still needs) so the much larger bag/scalar columns can be
    freed file-by-file in the incremental native path."""

    __slots__ = ("num_records", "meta_key_strings", "meta_keys",
                 "meta_rows", "meta_vals", "meta_val_strings")

    def __init__(self, d):
        self.num_records = d.num_records
        self.meta_key_strings = d.meta_key_strings
        self.meta_keys = d.meta_keys
        self.meta_rows = d.meta_rows
        self.meta_vals = d.meta_vals
        self.meta_val_strings = d.meta_val_strings


class _ChunkAccumulator:
    """Bounded-memory columnar assembly for the Python decode path.

    Per chunk it runs exactly the historical per-record loop (missing-
    response errors with GLOBAL record indices, accumulate-then-set-
    intercept feature assembly, encounter-order entity vocabularies) but
    emits compact columnar pieces and lets the record dicts go; peak
    transient memory is one chunk of dicts, independent of input size.
    """

    def __init__(self, fields, feature_shard_configs, index_maps,
                 random_effect_types, vocabs, frozen_vocab,
                 allow_unseen_entities):
        self.fields = fields
        self.cfgs = feature_shard_configs
        self.index_maps = index_maps
        self.re_types = list(random_effect_types)
        self.vocabs = vocabs
        self.frozen_vocab = frozen_vocab
        self.allow_unseen = allow_unseen_entities
        self.num_rows = 0
        self._response: list[np.ndarray] = []
        self._offsets: list[np.ndarray] = []
        self._weights: list[np.ndarray] = []
        self._uids: list[np.ndarray] = []
        self._dense: dict[str, list[np.ndarray]] = {
            s: [] for s, c in feature_shard_configs.items() if not c.sparse}
        # Sparse shards accumulate CSR pieces: (row_nnz, cols, vals).
        self._sparse: dict[str, list[tuple]] = {
            s: [] for s, c in feature_shard_configs.items() if c.sparse}
        self._ids: dict[str, list[np.ndarray]] = {
            t: [] for t in random_effect_types}

    def add_chunk(self, records: list[dict]) -> None:
        fields = self.fields
        base = self.num_rows
        n = len(records)
        response = np.zeros(n, np.float32)
        offsets = np.zeros(n, np.float32)
        weights = np.ones(n, np.float32)
        uids = np.empty(n, object)
        mats = {s: np.zeros((n, len(self.index_maps[s])), np.float32)
                for s in self._dense}
        sp_rows = {s: [dict() for _ in range(n)] for s in self._sparse}
        ids = {t: np.zeros(n, np.int32) for t in self.re_types}

        for i, rec in enumerate(records):
            # Reference AvroDataReader fails fast on a missing response
            # column; defaulting would silently train on all-zero labels.
            if rec.get(fields.response) is None:
                raise ValueError(
                    f"record {base + i} is missing required response field "
                    f"{fields.response!r}")
            response[i] = rec[fields.response]
            off = rec.get(fields.offset)
            offsets[i] = 0.0 if off is None else off
            w = rec.get(fields.weight)
            weights[i] = 1.0 if w is None else w
            uid = rec.get(fields.uid)
            uids[i] = base + i if uid is None else uid
            for shard, cfg in self.cfgs.items():
                imap = self.index_maps[shard]
                if cfg.sparse:
                    row = sp_rows[shard][i]
                    for bag in cfg.feature_bags:
                        for f in rec.get(bag) or ():
                            j = imap.get_index(feature_key(
                                f["name"], f.get("term", "")))
                            if j >= 0:
                                row[j] = row.get(j, 0.0) + f["value"]
                    if cfg.has_intercept:
                        j = imap.get_index(INTERCEPT_KEY)
                        if j >= 0:
                            row[j] = 1.0
                    continue
                mat = mats[shard]
                for bag in cfg.feature_bags:
                    for f in rec.get(bag) or ():
                        j = imap.get_index(feature_key(
                            f["name"], f.get("term", "")))
                        if j >= 0:
                            mat[i, j] += f["value"]
                if cfg.has_intercept:
                    j = imap.get_index(INTERCEPT_KEY)
                    if j >= 0:
                        mat[i, j] = 1.0
            for t in self.re_types:
                raw = _entity_value(rec, t, fields.metadata)
                if raw is None:
                    raise ValueError(
                        f"record {base + i} missing random-effect id {t!r}")
                vocab = self.vocabs[t]
                if raw not in vocab:
                    if self.frozen_vocab and not self.allow_unseen:
                        raise KeyError(
                            f"unseen entity {raw!r} for {t!r} under a "
                            f"frozen vocabulary (scoring with unseen "
                            f"entities must map them explicitly, or pass "
                            f"allow_unseen_entities=True)")
                    vocab[raw] = len(vocab)
                ids[t][i] = vocab[raw]

        self._response.append(response)
        self._offsets.append(offsets)
        self._weights.append(weights)
        self._uids.append(uids)
        for s, m in mats.items():
            self._dense[s].append(m)
        for s, rows in sp_rows.items():
            row_nnz = np.asarray([len(r) for r in rows], np.int64)
            by_row = [sorted(r.items()) for r in rows]
            cols = np.asarray([j for r in by_row for j, _ in r], np.int32)
            vals = np.asarray([v for r in by_row for _, v in r],
                              np.float32)
            self._sparse[s].append((row_nnz, cols, vals))
        for t, col in ids.items():
            self._ids[t].append(col)
        self.num_rows += n

    def finalize(self):
        from photon_ml_tpu.data.sparse import from_csr

        n = self.num_rows
        response = np.concatenate(self._response)
        feature_shards: dict = {
            s: np.concatenate(chunks) for s, chunks in self._dense.items()}
        for s, pieces in self._sparse.items():
            d = len(self.index_maps[s])
            indptr = np.zeros(n + 1, np.int64)
            np.cumsum(np.concatenate([p[0] for p in pieces]),
                      out=indptr[1:])
            ell = from_csr(indptr,
                           np.concatenate([p[1] for p in pieces]),
                           np.concatenate([p[2] for p in pieces]),
                           labels=response, num_features=d)
            feature_shards[s] = SparseShard(
                indices=ell.indices, values=ell.values, num_features=d)
        id_cols = {t: np.concatenate(chunks)
                   for t, chunks in self._ids.items()}
        ds = GameDataset(
            response=response,
            offsets=np.concatenate(self._offsets),
            weights=np.concatenate(self._weights),
            feature_shards=feature_shards,
            entity_ids=id_cols,
            num_entities={t: len(v) for t, v in self.vocabs.items()},
            intercept_index={
                s: (self.index_maps[s].get_index(INTERCEPT_KEY)
                    if c.has_intercept else None)
                for s, c in self.cfgs.items()
            },
            entity_counts={
                t: np.bincount(col, minlength=len(self.vocabs[t]))
                for t, col in id_cols.items()},
        )
        return ds, np.concatenate(self._uids)


def _make_vocab_tokens(frozen_vocabs, final_vocabs):
    """(base, final) provenance digests per RE type: ``base`` identifies
    the frozen vocabulary the ids extend (the final vocabulary itself when
    built fresh), ``final`` the resulting one. Lets a consumer distinguish
    a true vocabulary extension from an unrelated same-size vocabulary —
    counts cannot (GameEstimator.fit checks validation.base ==
    training.final)."""
    tokens = {}
    for t, v in final_vocabs.items():
        final = vocab_token(v)
        if frozen_vocabs is not None and t in frozen_vocabs:
            base = (final if len(frozen_vocabs[t]) == len(v)
                    else vocab_token(frozen_vocabs[t]))
        else:
            base = final
        tokens[t] = (base, final)
    return tokens


@dataclasses.dataclass
class ReadMeta:
    """Side products of a read: feature maps, entity vocabularies, uids."""

    index_maps: dict[str, IndexMap]
    entity_vocabs: dict[str, dict[str, int]]
    uids: np.ndarray
