"""Avro layer (L0) — wire/storage records.

Reference parity: ``photon-avro-schemas/`` (Avro schema definitions compiled
to Java) plus the Avro container-file I/O used by photon-client. No Avro
library ships in this image, so ``codec``/``container`` implement the Avro
1.x binary encoding and Object Container File format from the spec.
"""

from photon_ml_tpu.avro.codec import BinaryDecoder, BinaryEncoder, parse_schema
from photon_ml_tpu.avro.container import DataFileReader, DataFileWriter
from photon_ml_tpu.avro.data_writer import AvroDataWriter
from photon_ml_tpu.avro import schemas

__all__ = [
    "AvroDataWriter",
    "BinaryDecoder",
    "BinaryEncoder",
    "parse_schema",
    "DataFileReader",
    "DataFileWriter",
    "schemas",
]
