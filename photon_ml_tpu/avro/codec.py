"""Avro binary encoding: schema-driven encoder/decoder.

Implements the Avro 1.x binary encoding (zigzag varints, length-prefixed
bytes/strings, block-encoded arrays/maps, index-prefixed unions) for the
schema subset the photon record types need: null, boolean, int, long, float,
double, bytes, string, record, enum, array, map, union, fixed.

Values map to plain Python: records <-> dict, arrays <-> list, maps <-> dict,
enums <-> str, unions <-> the branch value (encoder picks the first matching
branch; ``None`` always matches the ``null`` branch).

Reference parity: stands in for the generated-Java Avro runtime used by
``photon-avro-schemas/`` (exact upstream files unavailable — reference mount
empty; see SURVEY.md header).
"""

from __future__ import annotations

import io
import json
import struct
from typing import Any, Union

PRIMITIVES = {
    "null", "boolean", "int", "long", "float", "double", "bytes", "string",
}

SchemaT = Union[str, dict, list]


def parse_schema(schema: Union[str, SchemaT]) -> SchemaT:
    """Accept a JSON string or an already-parsed schema structure.

    Bare strings that aren't JSON documents are primitive names or named-type
    references and pass through unchanged.
    """
    if isinstance(schema, str) and schema[:1] in "{[\"":
        return json.loads(schema)
    return schema


def _schema_type(schema: SchemaT) -> str:
    if isinstance(schema, str):
        return schema
    if isinstance(schema, list):
        return "union"
    return schema["type"]


class _NamedSchemas:
    """Registry so named types (records/enums/fixed) can self-reference.

    Filled eagerly by pre-walking the schema at construction — registration
    during traversal alone would miss definitions skipped in data order
    (e.g. a by-name reference whose defining occurrence sits in an empty
    array).
    """

    def __init__(self, root: SchemaT = None):
        self.by_name: dict[str, SchemaT] = {}
        if root is not None:
            self._walk(root)

    def _walk(self, schema: SchemaT) -> None:
        if isinstance(schema, list):
            for branch in schema:
                self._walk(branch)
            return
        if not isinstance(schema, dict):
            return
        t = schema.get("type")
        if t in ("record", "enum", "fixed"):
            self.register(schema)
        if t == "record":
            for field in schema.get("fields", ()):
                self._walk(parse_schema(field["type"]))
        elif t == "array":
            self._walk(parse_schema(schema["items"]))
        elif t == "map":
            self._walk(parse_schema(schema["values"]))

    def register(self, schema: dict) -> None:
        name = schema.get("name")
        if name:
            self.by_name[name] = schema
            ns = schema.get("namespace")
            if ns:
                self.by_name[f"{ns}.{name}"] = schema

    def resolve(self, schema: SchemaT) -> SchemaT:
        if isinstance(schema, str) and schema not in PRIMITIVES:
            if schema in self.by_name:
                return self.by_name[schema]
            raise ValueError(f"unknown named type: {schema}")
        return schema


class BinaryEncoder:
    """Encode Python values against a schema into an Avro byte stream."""

    def __init__(self, schema: SchemaT):
        self.schema = parse_schema(schema)
        self.names = _NamedSchemas(self.schema)

    def encode(self, value: Any) -> bytes:
        buf = io.BytesIO()
        self.write(buf, value)
        return buf.getvalue()

    def write(self, buf: io.BytesIO, value: Any,
              schema: SchemaT = None) -> None:
        schema = self.schema if schema is None else schema
        schema = self.names.resolve(parse_schema(schema))
        t = _schema_type(schema)
        if t == "null":
            return
        if t == "boolean":
            buf.write(b"\x01" if value else b"\x00")
        elif t in ("int", "long"):
            _write_long(buf, int(value))
        elif t == "float":
            buf.write(struct.pack("<f", float(value)))
        elif t == "double":
            buf.write(struct.pack("<d", float(value)))
        elif t == "bytes":
            _write_long(buf, len(value))
            buf.write(value)
        elif t == "string":
            raw = value.encode("utf-8")
            _write_long(buf, len(raw))
            buf.write(raw)
        elif t == "fixed":
            self.names.register(schema)
            if len(value) != schema["size"]:
                raise ValueError("fixed size mismatch")
            buf.write(value)
        elif t == "enum":
            self.names.register(schema)
            _write_long(buf, schema["symbols"].index(value))
        elif t == "array":
            if value:
                _write_long(buf, len(value))
                for item in value:
                    self.write(buf, item, schema["items"])
            _write_long(buf, 0)
        elif t == "map":
            if value:
                _write_long(buf, len(value))
                for k, v in value.items():
                    self.write(buf, k, "string")
                    self.write(buf, v, schema["values"])
            _write_long(buf, 0)
        elif t == "union":
            idx = _pick_union_branch(self.names, schema, value)
            _write_long(buf, idx)
            self.write(buf, value, schema[idx])
        elif t == "record":
            self.names.register(schema)
            for field in schema["fields"]:
                name = field["name"]
                if name in value:
                    fv = value[name]
                elif "default" in field:
                    fv = field["default"]
                else:
                    raise ValueError(f"missing field {name} with no default")
                self.write(buf, fv, field["type"])
        else:
            raise ValueError(f"unsupported schema type: {t}")


class BinaryDecoder:
    """Decode an Avro byte stream against a schema into Python values."""

    def __init__(self, schema: SchemaT):
        self.schema = parse_schema(schema)
        self.names = _NamedSchemas(self.schema)

    def decode(self, data: bytes) -> Any:
        return self.read(io.BytesIO(data))

    def read(self, buf: io.BytesIO, schema: SchemaT = None) -> Any:
        schema = self.schema if schema is None else schema
        schema = self.names.resolve(parse_schema(schema))
        t = _schema_type(schema)
        if t == "null":
            return None
        if t == "boolean":
            b = buf.read(1)
            if not b:
                raise EOFError("truncated avro stream reading boolean")
            return b != b"\x00"
        if t in ("int", "long"):
            return _read_long(buf)
        if t == "float":
            return struct.unpack("<f", buf.read(4))[0]
        if t == "double":
            return struct.unpack("<d", buf.read(8))[0]
        if t == "bytes":
            return buf.read(_read_long(buf))
        if t == "string":
            return buf.read(_read_long(buf)).decode("utf-8")
        if t == "fixed":
            self.names.register(schema)
            return buf.read(schema["size"])
        if t == "enum":
            self.names.register(schema)
            return schema["symbols"][_read_long(buf)]
        if t == "array":
            out = []
            while True:
                count = _read_long(buf)
                if count == 0:
                    return out
                if count < 0:  # block with byte-size prefix
                    count = -count
                    _read_long(buf)
                for _ in range(count):
                    out.append(self.read(buf, schema["items"]))
        if t == "map":
            out = {}
            while True:
                count = _read_long(buf)
                if count == 0:
                    return out
                if count < 0:
                    count = -count
                    _read_long(buf)
                for _ in range(count):
                    k = self.read(buf, "string")
                    out[k] = self.read(buf, schema["values"])
        if t == "union":
            return self.read(buf, schema[_read_long(buf)])
        if t == "record":
            self.names.register(schema)
            return {f["name"]: self.read(buf, f["type"])
                    for f in schema["fields"]}
        raise ValueError(f"unsupported schema type: {t}")


def _write_long(buf: io.BytesIO, n: int) -> None:
    n = (n << 1) ^ (n >> 63) if n < 0 else n << 1  # zigzag
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            buf.write(bytes((b | 0x80,)))
        else:
            buf.write(bytes((b,)))
            return


def _read_long(buf: io.BytesIO) -> int:
    shift, acc = 0, 0
    while True:
        byte = buf.read(1)
        if not byte:
            raise EOFError("truncated varint")
        b = byte[0]
        acc |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
    return (acc >> 1) ^ -(acc & 1)  # un-zigzag


def _matches(names: _NamedSchemas, schema: SchemaT, value: Any) -> bool:
    schema = names.resolve(parse_schema(schema))
    t = _schema_type(schema)
    if t == "null":
        return value is None
    if t == "boolean":
        return isinstance(value, bool)
    if t in ("int", "long"):
        return isinstance(value, int) and not isinstance(value, bool)
    if t in ("float", "double"):
        return isinstance(value, (int, float)) and not isinstance(value, bool)
    if t in ("bytes", "fixed"):
        return isinstance(value, (bytes, bytearray))
    if t in ("string", "enum"):
        return isinstance(value, str)
    if t == "array":
        return isinstance(value, list)
    if t in ("map", "record"):
        return isinstance(value, dict)
    return False


def _pick_union_branch(names: _NamedSchemas, union: list, value: Any) -> int:
    for i, branch in enumerate(union):
        if _matches(names, branch, value):
            return i
    raise ValueError(f"no union branch matches {type(value)}")
