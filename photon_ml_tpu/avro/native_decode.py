"""Native Avro container decoding: plan compiler + ctypes wrapper.

Reference parity: the reference ingests Avro through JVM-generated record
classes inside Spark executors (photon-client
``data/avro/AvroDataReader.scala``); this module is the rebuild's native
data-loader for the Avro path. The file's WRITER SCHEMA is compiled into a
flat int32 plan that ``native/avro_decode.cc`` interprets per record; any
schema outside the supported family (TrainingExample-shaped records:
primitive scalars, unions of them, ``map<string>`` metadata, feature bags
as ``array<{name, term?, value: double}>``) yields ``None`` and callers
fall back to the pure-Python codec, whose semantics the native decoder
mirrors exactly.
"""

from __future__ import annotations

import ctypes
import dataclasses
import logging
from typing import Optional

import numpy as np

_PRIMS = {"null": 0, "boolean": 1, "int": 2, "long": 3, "float": 4,
          "double": 5, "string": 6, "bytes": 7}
CAP_SKIP, CAP_RESPONSE, CAP_OFFSET, CAP_WEIGHT, CAP_UID, CAP_META, \
    CAP_BAG = range(7)
_T_MAP_STRING = 8
_T_NTV = 9

_lib = None
_lib_failed = False


def _load():
    global _lib, _lib_failed
    if _lib is not None or _lib_failed:
        return _lib
    import os

    if os.environ.get("PHOTON_TPU_NO_NATIVE_AVRO") == "1":
        _lib_failed = True
        return None
    try:
        from photon_ml_tpu.native import build_library

        lib = ctypes.CDLL(build_library("avro_decode", link=("-lz",)))
        lib.pavro_open.restype = ctypes.c_void_p
        lib.pavro_open.argtypes = [ctypes.c_char_p]
        lib.pavro_open_range.restype = ctypes.c_void_p
        lib.pavro_open_range.argtypes = [ctypes.c_char_p, ctypes.c_long,
                                         ctypes.c_long, ctypes.c_long]
        lib.pavro_error.restype = ctypes.c_int
        lib.pavro_error.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                    ctypes.c_int]
        lib.pavro_schema_len.restype = ctypes.c_long
        lib.pavro_schema_len.argtypes = [ctypes.c_void_p]
        lib.pavro_schema.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.pavro_decode.restype = ctypes.c_long
        lib.pavro_decode.argtypes = [
            ctypes.c_void_p,
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            ctypes.c_long, ctypes.c_int]
        lib.pavro_num_records.restype = ctypes.c_long
        lib.pavro_num_records.argtypes = [ctypes.c_void_p]
        lib.pavro_fill_scalars.argtypes = [
            ctypes.c_void_p,
            np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")]
        lib.pavro_uid_strs_len.restype = ctypes.c_long
        lib.pavro_uid_strs_len.argtypes = [ctypes.c_void_p]
        lib.pavro_fill_uid_strs.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p,
            np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")]
        for fn in ("pavro_bag_nnz", "pavro_bag_nkeys",
                   "pavro_bag_keys_len"):
            getattr(lib, fn).restype = ctypes.c_long
            getattr(lib, fn).argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.pavro_fill_bag.argtypes = [
            ctypes.c_void_p, ctypes.c_int,
            np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")]
        lib.pavro_fill_bag_keys.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p,
            np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")]
        lib.pavro_meta_count.restype = ctypes.c_long
        lib.pavro_meta_count.argtypes = [ctypes.c_void_p]
        lib.pavro_fill_meta.argtypes = [
            ctypes.c_void_p,
            np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")]
        for fn in ("pavro_meta_table_nkeys", "pavro_meta_table_len"):
            getattr(lib, fn).restype = ctypes.c_long
            getattr(lib, fn).argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.pavro_fill_meta_table.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p,
            np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")]
        lib.pavro_free.argtypes = [ctypes.c_void_p]
        _lib = lib
    except Exception:
        logging.getLogger("photon_ml_tpu.avro").debug(
            "native Avro decoder unavailable — using the Python path",
            exc_info=True)
        _lib_failed = True
    return _lib


def native_available() -> bool:
    return _load() is not None


# ----------------------------------------------------------------- plan


def _resolve(schema, names: dict):
    """Resolve a schema node: register/lookup named types, normalize
    {"type": "x"} wrappers."""
    if isinstance(schema, str):
        if schema in _PRIMS:
            return schema
        return names.get(schema)
    if isinstance(schema, dict):
        t = schema.get("type")
        if t in ("record", "enum", "fixed") and "name" in schema:
            names[schema["name"]] = schema
        if isinstance(t, str) and t in _PRIMS and len(schema) == 1:
            return t
        return schema
    return schema  # unions stay lists


def _ntv_arg(items, names) -> Optional[int]:
    """If ``items`` is a {name, term?, value: double} record, return the
    plan arg (bit0 = has term), else None."""
    items = _resolve(items, names)
    if not isinstance(items, dict) or items.get("type") != "record":
        return None
    fields = items.get("fields", [])
    fnames = [f["name"] for f in fields]
    if fnames == ["name", "term", "value"]:
        has_term = True
    elif fnames == ["name", "value"]:
        has_term = False
    else:
        return None
    for f in fields:
        want = "double" if f["name"] == "value" else "string"
        ft = _resolve(f["type"], names)
        if ft != want:
            return None
    return 1 if has_term else 0


def _branch(schema, capture: int, arg: int, names) -> Optional[tuple]:
    """(type, capture, arg) for one non-union schema node, or None."""
    schema = _resolve(schema, names)
    if isinstance(schema, str) and schema in _PRIMS:
        t = _PRIMS[schema]
        if capture in (CAP_RESPONSE, CAP_OFFSET, CAP_WEIGHT):
            if t not in (0, 1, 2, 3, 4, 5):
                return None  # numeric captures need numeric branches
        if capture == CAP_UID and t not in (0, 2, 3, 6):
            return None
        if capture == CAP_META and t != 0:
            return None
        if capture == CAP_BAG and t != 0:
            return None
        return (t, CAP_SKIP if t == 0 and capture == CAP_UID else capture,
                arg)
    if isinstance(schema, dict):
        t = schema.get("type")
        if t == "map":
            if _resolve(schema.get("values"), names) != "string":
                return None
            if capture not in (CAP_SKIP, CAP_META):
                return None
            return (_T_MAP_STRING, capture, 0)
        if t == "array":
            ntv = _ntv_arg(schema.get("items"), names)
            if ntv is None:
                return None
            if capture not in (CAP_SKIP, CAP_BAG):
                return None
            return (_T_NTV, capture,
                    (arg << 1) | ntv if capture == CAP_BAG else ntv)
    return None


def compile_plan(schema, captures: dict[str, tuple[int, int]]
                 ) -> Optional[np.ndarray]:
    """Compile a writer schema into the int32 plan.

    ``captures`` maps field name → (capture, arg). Returns None when any
    field cannot be expressed (callers fall back to the Python codec).
    """
    names: dict = {}
    schema = _resolve(schema, names)
    if not isinstance(schema, dict) or schema.get("type") != "record":
        return None
    plan: list[int] = []
    for field in schema.get("fields", []):
        cap, arg = captures.get(field["name"], (CAP_SKIP, 0))
        ftype = field["type"]
        branches = ftype if isinstance(ftype, list) else [ftype]
        entries = []
        for b in branches:
            e = _branch(b, cap, arg, names)
            if e is None:
                return None
            entries.append(e)
        plan.append(len(entries))
        for e in entries:
            plan.extend(e)
    return np.asarray(plan, np.int32)


# ----------------------------------------------------------------- decode


@dataclasses.dataclass
class BagColumns:
    rows: np.ndarray  # (nnz,) int64 record rows
    keys: np.ndarray  # (nnz,) int32 ids into key_strings
    values: np.ndarray  # (nnz,) float64
    key_strings: list[str]  # "name\x01term" (or bare name without term)


@dataclasses.dataclass
class DecodedFile:
    num_records: int
    response: np.ndarray  # float64
    offsets: np.ndarray
    weights: np.ndarray
    uids: np.ndarray  # object: int (row or long uid) or str
    uid_kind: np.ndarray  # uint8: 0 absent/null (uids[i] = LOCAL row i)
    bags: list[BagColumns]
    # metadataMap entries
    meta_rows: np.ndarray
    meta_keys: np.ndarray
    meta_vals: np.ndarray
    meta_key_strings: list[str]
    meta_val_strings: list[str]


def _strings(n_keys: int, total: int, fill) -> list[str]:
    buf = ctypes.create_string_buffer(max(1, total))
    offsets = np.zeros(max(1, n_keys), np.int64)
    if n_keys:
        fill(buf, offsets)
    out = []
    prev = 0
    raw = buf.raw
    for i in range(n_keys):
        end = int(offsets[i])
        out.append(raw[prev:end].decode("utf-8"))
        prev = end
    return out


def decode_file(path: str, captures: dict[str, tuple[int, int]],
                n_bags: int,
                forbidden_fields: frozenset = frozenset(),
                ) -> Optional[DecodedFile]:
    """Decode one container file natively; None → caller must fall back
    (unsupported schema / no toolchain / a ``forbidden_fields`` name is a
    top-level record field — e.g. an entity id read directly rather than
    from the metadata map). Raises ValueError on corrupt or semantically
    invalid data (same failure mode as the Python reader)."""
    lib = _load()
    if lib is None:
        return None
    h = lib.pavro_open(path.encode())
    try:
        err = ctypes.create_string_buffer(512)
        if lib.pavro_error(h, err, 512):
            raise ValueError(f"{path}: {err.value.decode()}")
        slen = lib.pavro_schema_len(h)
        sbuf = ctypes.create_string_buffer(slen + 1)
        lib.pavro_schema(h, sbuf)
        import json

        schema = json.loads(sbuf.raw[:slen].decode("utf-8"))
        if isinstance(schema, dict) and any(
                f.get("name") in forbidden_fields
                for f in schema.get("fields", ())):
            return None
        plan = compile_plan(schema, captures)
        if plan is None:
            return None
        return _decode_open_handle(lib, h, path, plan, n_bags)
    finally:
        lib.pavro_free(h)


def decode_span(path: str, header_len: int, start: int, end: int,
                plan: np.ndarray, n_bags: int) -> DecodedFile:
    """Decode one sync-aligned byte range of a container file with a
    precompiled plan — the block-parallel ingestion path
    (photon_ml_tpu/ingest): workers each decode a disjoint run of whole
    blocks and the pipeline merges them in plan order, bit-identical to a
    whole-file decode. No schema fallback here: the caller compiled the
    plan from the scanned writer schema already. Raises ValueError on
    corrupt data (the same failure mode as the whole-file decode) and
    RuntimeError when the native toolchain is unavailable."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native Avro decoder unavailable")
    h = lib.pavro_open_range(path.encode(), header_len, start, end)
    try:
        err = ctypes.create_string_buffer(512)
        if lib.pavro_error(h, err, 512):
            raise ValueError(f"{path}: {err.value.decode()}")
        return _decode_open_handle(lib, h, path, plan, n_bags)
    finally:
        lib.pavro_free(h)


def _decode_open_handle(lib, h, path: str, plan: np.ndarray,
                        n_bags: int) -> DecodedFile:
    """Run the plan over an open handle and pull the columnar outputs
    into numpy (shared by the whole-file and block-range entry points)."""
    err = ctypes.create_string_buffer(512)
    n = lib.pavro_decode(h, plan, len(plan), n_bags)
    if n < 0:
        lib.pavro_error(h, err, 512)
        raise ValueError(f"{path}: {err.value.decode()}")
    n = int(n)
    response = np.zeros(max(1, n), np.float64)
    offsets = np.zeros(max(1, n), np.float64)
    weights = np.zeros(max(1, n), np.float64)
    uid_kind = np.zeros(max(1, n), np.uint8)
    uid_long = np.zeros(max(1, n), np.int64)
    if n:
        lib.pavro_fill_scalars(h, response, offsets, weights, uid_kind,
                               uid_long)
    # uids: local row index by default; vectorized fancy-index
    # assignment for the records that carried one (no per-record
    # interpreter loop on the hot ingestion path).
    uids = np.arange(n).astype(object)
    has_long = uid_kind[:n] == 2
    if has_long.any():
        uids[has_long] = uid_long[:n][has_long].tolist()
    has_str = uid_kind[:n] == 1
    if has_str.any():
        uid_strs = _strings(
            n, int(lib.pavro_uid_strs_len(h)),
            lambda b, o: lib.pavro_fill_uid_strs(h, b, o))
        uids[has_str] = np.asarray(uid_strs, object)[has_str]
    bags = []
    for b in range(n_bags):
        nnz = int(lib.pavro_bag_nnz(h, b))
        rows = np.zeros(max(1, nnz), np.int64)
        keys = np.zeros(max(1, nnz), np.int32)
        values = np.zeros(max(1, nnz), np.float64)
        if nnz:
            lib.pavro_fill_bag(h, b, rows, keys, values)
        key_strings = _strings(
            int(lib.pavro_bag_nkeys(h, b)),
            int(lib.pavro_bag_keys_len(h, b)),
            lambda bb, oo, _b=b: lib.pavro_fill_bag_keys(h, _b, bb, oo))
        bags.append(BagColumns(rows[:nnz], keys[:nnz], values[:nnz],
                               key_strings))
    mcount = int(lib.pavro_meta_count(h))
    meta_rows = np.zeros(max(1, mcount), np.int64)
    meta_keys = np.zeros(max(1, mcount), np.int32)
    meta_vals = np.zeros(max(1, mcount), np.int32)
    if mcount:
        lib.pavro_fill_meta(h, meta_rows, meta_keys, meta_vals)
    meta_key_strings = _strings(
        int(lib.pavro_meta_table_nkeys(h, 0)),
        int(lib.pavro_meta_table_len(h, 0)),
        lambda b, o: lib.pavro_fill_meta_table(h, 0, b, o))
    meta_val_strings = _strings(
        int(lib.pavro_meta_table_nkeys(h, 1)),
        int(lib.pavro_meta_table_len(h, 1)),
        lambda b, o: lib.pavro_fill_meta_table(h, 1, b, o))
    return DecodedFile(
        num_records=n,
        response=response[:n], offsets=offsets[:n], weights=weights[:n],
        uids=uids, uid_kind=uid_kind[:n].copy(),
        bags=bags,
        meta_rows=meta_rows[:mcount], meta_keys=meta_keys[:mcount],
        meta_vals=meta_vals[:mcount],
        meta_key_strings=meta_key_strings,
        meta_val_strings=meta_val_strings)
