"""Photon Avro record schemas (L0).

Reference parity: ``photon-avro-schemas/src/main/avro/*.avsc`` —
``TrainingExampleAvro`` (label/weight/offset + features as name/term/value
triples), ``BayesianLinearModelAvro`` (coefficient means + variances),
``ScoringResultAvro``, ``FeatureSummarizationResultAvro``,
``LatentFactorAvro``. The reference mount was empty (SURVEY.md header), so
field sets follow upstream linkedin/photon-ml [MED]; the codec round-trips
whatever schema a file declares, so drift in optional fields is tolerated at
read time.
"""

NAMESPACE = "com.linkedin.photon.avro.generated"

FEATURE_AVRO = {
    "type": "record",
    "name": "FeatureAvro",
    "namespace": NAMESPACE,
    "fields": [
        {"name": "name", "type": "string"},
        {"name": "term", "type": "string", "default": ""},
        {"name": "value", "type": "double"},
    ],
}

TRAINING_EXAMPLE_AVRO = {
    "type": "record",
    "name": "TrainingExampleAvro",
    "namespace": NAMESPACE,
    "fields": [
        {"name": "uid", "type": ["null", "string", "long"], "default": None},
        {"name": "label", "type": "double"},
        {"name": "weight", "type": ["null", "double"], "default": None},
        {"name": "offset", "type": ["null", "double"], "default": None},
        {"name": "features", "type": {"type": "array",
                                      "items": FEATURE_AVRO}},
        # Random-effect ids and other passthrough columns (e.g. userId).
        {"name": "metadataMap",
         "type": ["null", {"type": "map", "values": "string"}],
         "default": None},
    ],
}

NAME_TERM_VALUE_AVRO = {
    "type": "record",
    "name": "NameTermValueAvro",
    "namespace": NAMESPACE,
    "fields": [
        {"name": "name", "type": "string"},
        {"name": "term", "type": "string", "default": ""},
        {"name": "value", "type": "double"},
    ],
}

BAYESIAN_LINEAR_MODEL_AVRO = {
    "type": "record",
    "name": "BayesianLinearModelAvro",
    "namespace": NAMESPACE,
    "fields": [
        {"name": "modelId", "type": "string"},
        {"name": "modelClass", "type": ["null", "string"], "default": None},
        {"name": "means", "type": {"type": "array",
                                   "items": NAME_TERM_VALUE_AVRO}},
        {"name": "variances",
         "type": ["null", {"type": "array", "items": "NameTermValueAvro"}],
         "default": None},
        {"name": "lossFunction", "type": ["null", "string"],
         "default": None},
    ],
}

SCORING_RESULT_AVRO = {
    "type": "record",
    "name": "ScoringResultAvro",
    "namespace": NAMESPACE,
    "fields": [
        {"name": "uid", "type": ["null", "string", "long"], "default": None},
        {"name": "predictionScore", "type": "double"},
        {"name": "label", "type": ["null", "double"], "default": None},
        {"name": "weight", "type": ["null", "double"], "default": None},
        {"name": "offset", "type": ["null", "double"], "default": None},
        {"name": "metadataMap",
         "type": ["null", {"type": "map", "values": "string"}],
         "default": None},
    ],
}

FEATURE_SUMMARIZATION_RESULT_AVRO = {
    "type": "record",
    "name": "FeatureSummarizationResultAvro",
    "namespace": NAMESPACE,
    "fields": [
        {"name": "name", "type": "string"},
        {"name": "term", "type": "string", "default": ""},
        {"name": "max", "type": "double"},
        {"name": "min", "type": "double"},
        {"name": "mean", "type": "double"},
        {"name": "variance", "type": "double"},
        {"name": "numNonzeros", "type": "double"},
        {"name": "count", "type": "long"},
    ],
}

LATENT_FACTOR_AVRO = {
    "type": "record",
    "name": "LatentFactorAvro",
    "namespace": NAMESPACE,
    "fields": [
        {"name": "effectId", "type": "string"},
        {"name": "factors", "type": {"type": "array", "items": "double"}},
    ],
}
