"""Avro training-data writer: columnar GameDataset -> container files.

Reference parity: photon-client ``data/avro/AvroDataWriter.scala`` — the
inverse of AvroDataReader: write examples back out as
``TrainingExampleAvro`` records (label/weight/offset/uid, features as
name/term/value triples, random-effect ids in ``metadataMap``), so a
prepared dataset can be persisted and re-read (or handed to the reference
toolchain) without the original source files.

Conventions mirroring the reader (avro/data_reader.py):
- the intercept column is NOT written — it is implicit
  (``FeatureShardConfig.has_intercept`` re-adds it on read);
- zero-valued features are not written (dense matrices round-trip through
  their nonzero support, exactly the reference's sparse-vector semantics);
- entity ids are written as ``metadataMap`` entries keyed by RE type, the
  reader's fallback location (``_entity_value``).
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import numpy as np

from photon_ml_tpu.avro.container import write_records
from photon_ml_tpu.avro.data_reader import (FieldNames,
                                            TRAINING_EXAMPLE_FIELDS)
from photon_ml_tpu.avro.schemas import TRAINING_EXAMPLE_AVRO
from photon_ml_tpu.data.game_data import GameDataset, SparseShard
from photon_ml_tpu.index.indexmap import INTERCEPT_KEY, IndexMap, split_key


class AvroDataWriter:
    """Write a GameDataset as TrainingExampleAvro container files."""

    def __init__(self, field_names: FieldNames = TRAINING_EXAMPLE_FIELDS):
        self.fields = field_names

    def write(
        self,
        path: str,
        dataset: GameDataset,
        index_maps: dict[str, IndexMap],
        entity_vocabs: Optional[dict[str, dict[str, int]]] = None,
        uids: Optional[Union[Sequence, np.ndarray]] = None,
        shards: Optional[Sequence[str]] = None,
        bag_by_shard: Optional[dict[str, str]] = None,
        codec: str = "deflate",
    ) -> int:
        """Write ``dataset`` to one Avro OCF at ``path``; returns #records.

        ``index_maps`` supplies the column→(name, term) reverse mapping per
        shard (the maps a read produced or a feature-indexing job built).
        ``entity_vocabs`` maps RE type → {raw id: row}; entity rows are
        written back as their raw string ids. When omitted, rows are written
        as their decimal string (a valid vocabulary for re-reading).
        ``bag_by_shard`` routes each shard's features into a named bag field
        (default: every shard into ``"features"``); distinct bags let a
        multi-shard dataset round-trip through ``FeatureShardConfig``s with
        disjoint ``feature_bags``.
        """
        shards = list(dataset.feature_shards if shards is None else shards)
        for s in shards:
            if s not in index_maps:
                raise ValueError(f"no index map for shard {s!r}")
        if bag_by_shard is None:
            bag_by_shard = {s: "features" for s in shards}
        else:
            unknown = set(bag_by_shard) - set(shards)
            if unknown:
                raise ValueError(
                    f"bag_by_shard names unknown shards {sorted(unknown)}; "
                    f"dataset shards are {sorted(shards)}")
        bags = []  # distinct bag fields, schema order
        for s in shards:
            b = bag_by_shard.get(s, "features")
            if b not in bags:
                bags.append(b)
        n = dataset.num_rows
        fields = self.fields
        schema = _schema_with_bags(bags, fields)

        # Reverse vocabularies: entity row -> raw id string.
        rev_vocab: dict[str, dict[int, str]] = {}
        for t in dataset.entity_ids:
            if entity_vocabs is not None and t in entity_vocabs:
                rev_vocab[t] = {row: raw
                                for raw, row in entity_vocabs[t].items()}
            else:
                rev_vocab[t] = {}

        # Per-shard (name, term) tuple per column; None marks the intercept
        # (skipped on write — implicit on read).
        name_term: dict[str, list] = {}
        for s in shards:
            imap = index_maps[s]
            d = dataset.shard_dim(s)
            cols = []
            for j in range(d):
                key = imap.get_feature_name(j)
                if key is None:
                    raise ValueError(
                        f"shard {s!r}: index map has no feature for "
                        f"column {j}")
                cols.append(None if key == INTERCEPT_KEY else split_key(key))
            name_term[s] = cols

        def record(i: int) -> dict:
            feats: dict[str, list] = {b: [] for b in bags}
            for s in shards:
                shard = dataset.feature_shards[s]
                cols = name_term[s]
                out = feats[bag_by_shard.get(s, "features")]
                if isinstance(shard, SparseShard):
                    for j, v in zip(shard.indices[i], shard.values[i]):
                        j = int(j)
                        if j >= shard.num_features or v == 0.0:
                            continue  # ELL padding slot
                        nt = cols[j]
                        if nt is None:
                            continue
                        out.append({"name": nt[0], "term": nt[1],
                                    "value": float(v)})
                else:
                    for j in np.flatnonzero(shard[i]):
                        nt = cols[int(j)]
                        if nt is None:
                            continue
                        out.append({"name": nt[0], "term": nt[1],
                                    "value": float(shard[i, int(j)])})
            meta = {}
            for t, ids in dataset.entity_ids.items():
                row = int(ids[i])
                meta[t] = rev_vocab[t].get(row, str(row))
            uid = None
            if uids is not None:
                uid = uids[i]
                # The union encoder picks branches by native Python type.
                if uid is not None and not isinstance(uid, str):
                    uid = int(uid)
            rec = {
                fields.uid: uid,
                fields.response: float(dataset.response[i]),
                fields.weight: float(dataset.weights[i]),
                fields.offset: float(dataset.offsets[i]),
                fields.metadata: meta if meta else None,
            }
            rec.update(feats)
            return rec

        write_records(path, schema, (record(i) for i in range(n)),
                      codec=codec)
        return n


def _schema_with_bags(bags: Sequence[str], fields: FieldNames) -> dict:
    """TrainingExampleAvro with one feature-array field per bag, its scalar
    fields renamed per the ``FieldNames`` preset (a non-default preset —
    e.g. RESPONSE_PREDICTION_FIELDS — must rename the schema too, or the
    codec rejects records keyed by the preset's names).

    With the default preset and single ``"features"`` bag this is exactly
    TRAINING_EXAMPLE_AVRO; extra bags replace the features field in place
    (the reference writes generic records with one array field per bag).
    """
    rename = {"label": fields.response, "offset": fields.offset,
              "weight": fields.weight, "uid": fields.uid,
              "metadataMap": fields.metadata}
    if list(bags) == ["features"] and all(k == v for k, v in rename.items()):
        return TRAINING_EXAMPLE_AVRO
    schema = dict(TRAINING_EXAMPLE_AVRO)
    out = []
    for f in TRAINING_EXAMPLE_AVRO["fields"]:
        if f["name"] != "features":
            out.append({**f, "name": rename.get(f["name"], f["name"])})
            continue
        items = f["type"]["items"]
        for k, b in enumerate(bags):
            # Avro named types must be defined once, then referenced.
            out.append({
                "name": b,
                "type": {"type": "array",
                         "items": items if k == 0 else items["name"]},
            })
    schema["fields"] = out
    return schema
