"""Scoring-result Avro output.

Reference parity: the ``ScoringResultAvro`` write in photon-client
``cli/game/scoring/GameScoringDriver.scala`` (uid, score, label/offset/weight
passthrough).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from photon_ml_tpu.avro import schemas
from photon_ml_tpu.avro.container import read_records, write_records


def write_scoring_results(
    path: str,
    scores: np.ndarray,
    uids: Optional[np.ndarray] = None,
    labels: Optional[np.ndarray] = None,
    weights: Optional[np.ndarray] = None,
    offsets: Optional[np.ndarray] = None,
    codec: str = "deflate",
) -> None:
    n = len(scores)

    def _uid(i):
        if uids is None:
            return int(i)
        u = uids[i]
        return int(u) if isinstance(u, (int, np.integer)) else str(u)

    records = []
    for i in range(n):
        rec = {"uid": _uid(i), "predictionScore": float(scores[i])}
        if labels is not None:
            rec["label"] = float(labels[i])
        if weights is not None:
            rec["weight"] = float(weights[i])
        if offsets is not None:
            rec["offset"] = float(offsets[i])
        records.append(rec)
    write_records(path, schemas.SCORING_RESULT_AVRO, records, codec=codec)


def read_scoring_results(path: str) -> list[dict]:
    return read_records(path)
