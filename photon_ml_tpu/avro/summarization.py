"""Feature-summary Avro output.

Reference parity: photon-client writes per-feature
``FeatureSummarizationResultAvro`` records (name/term, min/max/mean/
variance/numNonzeros/count) beside the model when feature summarization
runs (``Driver`` INIT stage / GameTrainingDriver summarization output) —
the human-auditable record of the statistics that fed normalization.
"""

from __future__ import annotations

import numpy as np

from photon_ml_tpu.avro import schemas
from photon_ml_tpu.avro.container import read_records, write_records
from photon_ml_tpu.data.statistics import FeatureDataStatistics
from photon_ml_tpu.index.indexmap import IndexMap, split_key


def write_feature_summaries(
    path: str,
    stats: FeatureDataStatistics,
    index_map: IndexMap,
    codec: str = "deflate",
) -> int:
    """Write one FeatureSummarizationResultAvro record per feature column;
    returns the record count."""
    mean = np.asarray(stats.mean)
    var = np.asarray(stats.variance)
    mn = np.asarray(stats.min)
    mx = np.asarray(stats.max)
    nnz = np.asarray(stats.num_nonzeros)
    count = int(np.asarray(stats.count))
    recs = []
    for j in range(stats.dim):
        key = index_map.get_feature_name(j)
        if key is None:
            raise KeyError(
                f"index map has no feature for column {j} "
                f"(map covers {len(index_map)} of {stats.dim} columns)")
        name, term = split_key(key)
        recs.append({
            "name": name, "term": term,
            "max": float(mx[j]), "min": float(mn[j]),
            "mean": float(mean[j]), "variance": float(var[j]),
            "numNonzeros": float(nnz[j]), "count": count,
        })
    write_records(path, schemas.FEATURE_SUMMARIZATION_RESULT_AVRO, recs,
                  codec=codec)
    return len(recs)


def read_feature_summaries(path: str) -> list[dict]:
    """Read back the records written by :func:`write_feature_summaries`."""
    return read_records(path)
