"""Avro Object Container File format: reader/writer.

Layout per the Avro spec: 4-byte magic ``Obj\\x01``; file-metadata map with
``avro.schema`` (JSON) and ``avro.codec`` (``null`` or ``deflate``); a random
16-byte sync marker; then data blocks of (object count, serialized byte size,
payload, sync marker). Deflate payloads are raw DEFLATE streams (no zlib
header), matching the spec.

Reference parity: the HDFS Avro read/write path of photon-client
(``data/avro/AvroUtils.scala``) — here plain local files.
"""

from __future__ import annotations

import io
import json
import os
import zlib
from typing import Any, Iterator, Optional

from photon_ml_tpu.avro.codec import (BinaryDecoder, BinaryEncoder,
                                      _read_long, _write_long, parse_schema)

MAGIC = b"Obj\x01"
_META_SCHEMA = {"type": "map", "values": "bytes"}


class DataFileWriter:
    """Write an Avro container file; append records, flush in blocks."""

    def __init__(self, path: str, schema, codec: str = "null",
                 block_records: int = 4096, sync_marker: bytes = None):
        if codec not in ("null", "deflate"):
            raise ValueError(f"unsupported codec {codec}")
        self.schema = parse_schema(schema)
        self.codec = codec
        self.block_records = block_records
        self._encoder = BinaryEncoder(self.schema)
        # Deterministic-by-content marker keeps golden-file tests stable.
        self._sync = sync_marker or os.urandom(16)
        self._buf = io.BytesIO()
        self._count = 0
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self._fh = open(path, "wb")
        self._write_header()

    def _write_header(self) -> None:
        self._fh.write(MAGIC)
        meta = {
            "avro.schema": json.dumps(self.schema).encode("utf-8"),
            "avro.codec": self.codec.encode("utf-8"),
        }
        enc = BinaryEncoder(_META_SCHEMA)
        self._fh.write(enc.encode(meta))
        self._fh.write(self._sync)

    def append(self, record: Any) -> None:
        # Encode to a scratch buffer first: a mid-encode failure (bad record)
        # must not leave partial bytes in the block.
        scratch = io.BytesIO()
        self._encoder.write(scratch, record)
        self._buf.write(scratch.getvalue())
        self._count += 1
        if self._count >= self.block_records:
            self._flush_block()

    def _flush_block(self) -> None:
        if not self._count:
            return
        payload = self._buf.getvalue()
        if self.codec == "deflate":
            payload = zlib.compress(payload)[2:-4]  # strip zlib header+adler
        head = io.BytesIO()
        _write_long(head, self._count)
        _write_long(head, len(payload))
        self._fh.write(head.getvalue())
        self._fh.write(payload)
        self._fh.write(self._sync)
        self._buf = io.BytesIO()
        self._count = 0

    def close(self) -> None:
        self._flush_block()
        self._fh.close()

    def __enter__(self) -> "DataFileWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class DataFileReader:
    """Iterate records of an Avro container file."""

    def __init__(self, path: str, reader_schema=None):
        self._fh = open(path, "rb")
        if self._fh.read(4) != MAGIC:
            raise ValueError(f"{path}: not an Avro container file")
        meta = BinaryDecoder(_META_SCHEMA).read(self._fh)
        self.schema = json.loads(meta["avro.schema"].decode("utf-8"))
        self.codec = meta.get("avro.codec", b"null").decode("utf-8")
        if self.codec not in ("null", "deflate"):
            raise ValueError(f"unsupported codec {self.codec}")
        self._sync = self._fh.read(16)
        # Schema-resolution subset: the reader decodes with the writer schema;
        # a caller-supplied reader_schema only filters record fields.
        self._decoder = BinaryDecoder(self.schema)
        self._reader_schema = parse_schema(reader_schema) if reader_schema \
            else None

    def __iter__(self) -> Iterator[Any]:
        while True:
            head = self._fh.read(1)
            if not head:
                return
            buf = io.BytesIO(head + self._fh.read(9))
            count = _read_long(buf)
            rest = buf.read()
            self._fh.seek(-len(rest), io.SEEK_CUR)
            size = _read_long(self._fh)
            payload = self._fh.read(size)
            if self.codec == "deflate":
                payload = zlib.decompress(payload, wbits=-15)
            if self._fh.read(16) != self._sync:
                raise ValueError("sync marker mismatch (corrupt block)")
            block = io.BytesIO(payload)
            for _ in range(count):
                yield self._filter(self._decoder.read(block))

    def _filter(self, record: Any) -> Any:
        if self._reader_schema is None or not isinstance(record, dict):
            return record
        wanted = {f["name"] for f in self._reader_schema.get("fields", [])}
        return {k: v for k, v in record.items() if k in wanted} \
            if wanted else record

    def close(self) -> None:
        self._fh.close()

    def __enter__(self) -> "DataFileReader":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def read_records(path: str) -> list:
    """All records of one container file (or of every ``*.avro`` in a dir)."""
    if os.path.isdir(path):
        out = []
        for name in sorted(os.listdir(path)):
            if name.endswith(".avro"):
                out.extend(read_records(os.path.join(path, name)))
        return out
    with DataFileReader(path) as r:
        return list(r)


def write_records(path: str, schema, records, codec: str = "deflate",
                  sync_marker: Optional[bytes] = None) -> None:
    with DataFileWriter(path, schema, codec=codec,
                        sync_marker=sync_marker) as w:
        for rec in records:
            w.append(rec)
