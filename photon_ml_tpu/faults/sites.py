"""The fault-site registry: every injection point, as a named constant.

A fault site only exists at the moment a string at a ``fire()`` /
``poison_scalar()`` / ``corrupt_file()`` call matches a string in a
``FaultSpec`` — there is no registration step, so a typo on either side
does not fail, it silently never fires, and the chaos drill that
"passed" exercised nothing. This module is the fix: production call
sites import these constants instead of repeating literals, and lint
rule **PML014** (docs/ANALYSIS.md) checks every dotted site literal in
the tree — test fault plans included — against this registry.
``photon-lint --catalog`` emits the same registry as JSON for docs/CI.

Grouped by the subsystem that owns the instrumentation point; the
failure-ladder semantics of each site live in docs/ROBUSTNESS.md.
Sites addressed by ``corrupt_file`` keep their own name even when they
share a code path with a ``fire`` site: the two hooks count occurrences
independently, and sharing a name would interleave their occurrence
spaces (the ``stream.checkpoint_write`` / ``stream.checkpoint_artifact``
lesson, game/checkpoint.py).
"""

from __future__ import annotations

# -- random-effect staging (game/staging.py, game/staging_cache.py) ----------
STAGING_PHASE_A = "staging.phase_a"
STAGING_PHASE_B = "staging.phase_b"
STAGING_CACHE_SAVE_SHARD = "staging_cache.save_shard"
STAGING_CACHE_LOAD_SHARD = "staging_cache.load_shard"
STAGING_CACHE_SHARD_FILE = "staging_cache.shard_file"  # corrupt_file

# -- descent checkpoints (game/checkpoint.py) --------------------------------
CHECKPOINT_SAVE = "checkpoint.save"
CHECKPOINT_LOAD = "checkpoint.load"
CHECKPOINT_ARTIFACT = "checkpoint.artifact"  # corrupt_file
# SWEEP_GATE_STATE fires BEFORE the gated descent's dirty-set state
# (``sweep/<cid>.npz``: offsets-at-last-fit + per-entity grad norms)
# is written into a checkpoint commit (kill seam: a SIGKILL here must
# leave the previous committed generation loadable, and a gated resume
# from it must be bit-identical to an unkilled gated run).
SWEEP_GATE_STATE = "sweep.gate_state"

# -- streamed fixed-effect path (ops/streaming_sparse.py, optim/streaming.py,
#    game/checkpoint.py StreamingStateStore) ---------------------------------
STREAM_CHUNK_TRANSFER = "stream.chunk_transfer"
STREAM_QUANTIZE = "stream.quantize"  # corrupt_file (staged-chunk store)
STREAM_OBJECTIVE = "stream.objective"  # poison_scalar (nan kind)
STREAM_CHECKPOINT_WRITE = "stream.checkpoint_write"
STREAM_CHECKPOINT_LOAD = "stream.checkpoint_load"
STREAM_CHECKPOINT_ARTIFACT = "stream.checkpoint_artifact"  # corrupt_file

# -- stochastic streamed solvers (optim/stochastic.py) -----------------------
# OPT_DUAL_UPDATE fires BEFORE each chunk's stochastic update (kill seam:
# a SIGKILL mid-epoch must resume from the last epoch-boundary (w, α)
# snapshot to bit-identical coefficients); OPT_GAP_CHECK poisons the
# epoch's assembled duality gap (nan seam: the watchdog gap gate must
# turn a sick certificate into a loud, defined error).
OPT_DUAL_UPDATE = "opt.dual_update"
OPT_GAP_CHECK = "opt.gap_check"  # poison_scalar (nan kind)

# -- Avro ingestion (ingest/pipeline.py, ingest/cache.py) --------------------
INGEST_DECODE_BLOCK = "ingest.decode_block"
INGEST_CACHE_WRITE = "ingest.cache_write"
INGEST_CACHE_FILE = "ingest.cache_file"  # corrupt_file

# -- single-process serving (serving/service.py, serving/model_store.py) -----
SERVING_FLUSH = "serving.flush"
SERVING_FETCH = "serving.fetch"

# -- replicated fleet (serving/router.py, serving/supervisor.py,
#    serving/service.py) -----------------------------------------------------
FLEET_ROUTE = "fleet.route"
FLEET_PROBE = "fleet.probe"
FLEET_REPLICA_FLUSH = "fleet.replica_flush"

# -- elastic fleet (serving/elastic.py; docs/SERVING.md "Elastic fleet") -----
# Each fires BEFORE its map/fleet mutation, so a fault leaves the shard
# map at exactly the old version — and the mutation itself is one
# version bump under the map lock, so a fault after it leaves exactly
# the new version: never torn (the mid-split kill contract).
FLEET_SPLIT = "fleet.split"
FLEET_MIGRATE = "fleet.migrate"
FLEET_SCALE = "fleet.scale"

# -- boot: mmap model publication (boot/mapfmt.py, boot/generations.py) ------
BOOT_MAP_WRITE = "boot.map_write"
BOOT_MAP_OPEN = "boot.map_open"  # corrupt_file (post-CRC bit rot in a blob)
BOOT_COMPACT = "boot.compact"

# -- fused-kernel registry (ops/kernels/registry.py) -------------------------
# Fires at the moment the registry commits to the Pallas backend for a
# kernel — BEFORE any program is built — so a fault here exercises the
# degradation contract: the resolve falls back to the XLA closure, emits
# a KernelFallback event + photon_kernel_fallbacks_total, and the caller
# never sees the failure (docs/KERNELS.md "Failure ladder").
KERNEL_LAUNCH = "kernel.launch"

# -- continuous publication (serving/publish.py, serving/fleet.py,
#    serving/model_store.py) -------------------------------------------------
PUBLISH_DELTA_WRITE = "publish.delta_write"
PUBLISH_DELTA_ARTIFACT = "publish.delta_artifact"  # corrupt_file
PUBLISH_CANARY_APPLY = "publish.canary_apply"
PUBLISH_SWAP = "publish.swap"
PUBLISH_ROLLBACK = "publish.rollback"

# -- multi-host fabric (fabric/collective.py, fabric/transport.py,
#    serving/publish.py fetch_delta; docs/ROBUSTNESS.md "Fabric") ------------
# FABRIC_DCN_ALLREDUCE fires once per cross-host allreduce ATTEMPT
# (index = the round's sequence number), inside the retry ladder — a
# `partition` spec here models the DCN edge dropping a round;
# FABRIC_HEARTBEAT fires before each machine-agent liveness query (the
# remote analogue of FLEET_PROBE: a `delay` spec models a slow agent,
# which must NOT be declared a death); FABRIC_ADOPT fires at the moment
# a RemoteTransport adopts an already-running remote replica instead of
# respawning; FABRIC_DELTA_FETCH fires once per artifact file pulled
# over HTTP by a remote replica (a `partition`/`corrupt` spec models a
# torn fetch, which must leave the previous model version servable).
FABRIC_DCN_ALLREDUCE = "fabric.dcn_allreduce"
FABRIC_ADOPT = "fabric.adopt"
FABRIC_HEARTBEAT = "fabric.heartbeat"
FABRIC_DELTA_FETCH = "fabric.delta_fetch"

# Every registered site. Computed from the module's own constants so the
# registry cannot drift from itself; PML014 reads the CONSTANTS above
# via AST (this comprehension never runs under the linter).
ALL_SITES = frozenset(
    v for k, v in dict(globals()).items()
    if not k.startswith("_") and isinstance(v, str) and k.isupper())
