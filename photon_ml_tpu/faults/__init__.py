"""photon-fault: deterministic, seeded fault injection
(docs/ROBUSTNESS.md).

Pure stdlib — importable from process-pool workers and the lint-adjacent
tooling without dragging JAX in.
"""

from photon_ml_tpu.faults import sites
from photon_ml_tpu.faults.injector import (FaultInjector, FaultPlan,
                                           FaultSpec, InjectedFault,
                                           InjectedIOError,
                                           InjectedPartition,
                                           InjectedThreadDeath, active,
                                           corrupt_file, current_plan,
                                           fire, install, installed,
                                           poison_scalar)

__all__ = [
    "sites",
    "FaultInjector",
    "FaultPlan",
    "FaultSpec",
    "InjectedFault",
    "InjectedIOError",
    "InjectedPartition",
    "InjectedThreadDeath",
    "active",
    "corrupt_file",
    "current_plan",
    "fire",
    "install",
    "installed",
    "poison_scalar",
]
