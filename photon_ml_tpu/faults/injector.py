"""Deterministic fault injection: the testable-failure seam.

Reference parity: none in photon-ml itself — the Spark lineage got fault
coverage "for free" from the cluster manager, and Snap ML (PAPERS.md)
treats executor failure and stragglers as first-class events of the
hierarchical training loop. XLA gives us neither, so resilience here has
to be engineered explicitly — and engineered resilience that cannot be
exercised on demand is dead code. This module is the on-demand part.

Model
-----
Production code is instrumented with **fault sites**: named points where
a failure can physically happen (a staging worker body, a cache write, a
batcher flush). Each call to a site is an **occurrence**, counted per
site; many sites also pass a stable **index** (the shard number, say).
A ``FaultSpec`` addresses ``(site, occurrence index and/or call index)``
and says what happens there:

- ``raise``        — raise an exception (worker crash, transient I/O);
- ``sleep``        — delay ``seconds`` (slow shard / straggler);
- ``delay``        — delay ``seconds``, the NETWORK-latency class: same
                     mechanics as ``sleep``, but named for what it
                     models — injected link/RPC latency at a send site
                     (a router forward, a chunk transfer, a probe), the
                     fault hedged sends and heartbeat deadlines exist
                     for. Usable at any site, training ones included;
- ``partition``    — raise ``InjectedPartition`` (a ConnectionError):
                     the site's traffic is DROPPED, as if the network
                     between the caller and its peer went away —
                     distinct from ``raise`` because callers that
                     retry/fail over catch connection errors
                     specifically. Usable at any site;
- ``kill``         — SIGKILL the calling process (worker/driver death);
- ``replica_kill`` — SIGKILL the calling process, the replica-death
                     class: same mechanics as ``kill``, named so fleet
                     fault plans read as what they drill (a scoring
                     replica dying mid-flush; aim it at
                     ``fleet.replica_flush`` with index = replica id);
- ``corrupt``      — garble the bytes of the file a save-site just wrote
                     (corrupted cache shard / checkpoint artifact);
- ``thread_death`` — raise ``InjectedThreadDeath`` (a BaseException, so
                     it sails past ``except Exception`` and kills the
                     thread — the scoring-worker-death fault class);
- ``nan``          — poison a scalar flowing through a ``poison_scalar``
                     site with NaN (a numerically sick objective — the
                     convergence-watchdog fault class, obs/watchdog.py).

Everything is deterministic: specs address exact occurrences, corruption
bytes come from ``random.Random(plan.seed)``, and the injector records
every firing so tests can assert the fault actually happened. A
``FaultPlan`` is plain picklable data — it crosses the spawn boundary to
process-pool staging workers and serializes to JSON for the
``game_train --fault-plan`` flag (docs/ROBUSTNESS.md).

When no plan is installed every hook is a no-op behind one ``is None``
check — the production hot paths pay nothing.
"""

from __future__ import annotations

import dataclasses
import json
import os
import random
import signal
import threading
import time
from typing import Optional


class InjectedFault(RuntimeError):
    """An injector-raised failure (the generic worker-crash class)."""


class InjectedIOError(OSError):
    """An injector-raised transient I/O failure."""


class InjectedPartition(ConnectionError):
    """An injector-dropped network edge: the peer is (simulated) on the
    other side of a partition. A ConnectionError, because that is what
    routers and supervisors catch to fail over — a partition drill that
    raised a generic error would test the wrong handler."""


class InjectedThreadDeath(BaseException):
    """Deliberately NOT an Exception: escapes ``except Exception``
    handlers the way a real interpreter-level thread death (MemoryError,
    SystemExit in a callback) does, killing the worker thread it fires
    on. Supervisors must recover from exactly this."""


_EXC_TYPES = {
    "InjectedFault": InjectedFault,
    "InjectedIOError": InjectedIOError,
    "InjectedPartition": InjectedPartition,
    "RuntimeError": RuntimeError,
    "OSError": OSError,
    "IOError": OSError,
    "ConnectionError": ConnectionError,
    "ValueError": ValueError,
}


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One addressed fault.

    ``site``: the instrumentation point's name (docs/ROBUSTNESS.md lists
    them). ``occurrences``: 0-based per-site call numbers to fire at
    (empty = every call). ``indices``: site-supplied stable indices (e.g.
    shard numbers) to fire at (empty = any). Both filters must match.
    ``kind``: raise | sleep | kill | corrupt | thread_death. ``exc``:
    exception type name for ``raise`` (picklable as a string).
    ``seconds``: sleep duration. ``max_fires``: stop firing after this
    many hits (None = unlimited) — a once-only transient fault is
    ``max_fires=1`` with no occurrence filter. ``scope``: "any" (default)
    fires wherever the site is hit; "worker" only inside pool worker
    processes; "driver" only in the main process — a worker-kill spec
    must not also kill the driver when the quarantined work re-runs
    serially there.
    """

    site: str
    kind: str = "raise"
    occurrences: tuple[int, ...] = ()
    indices: tuple[int, ...] = ()
    exc: str = "InjectedFault"
    message: str = "injected fault"
    seconds: float = 0.0
    max_fires: Optional[int] = None
    scope: str = "any"

    def __post_init__(self):
        if self.kind not in ("raise", "sleep", "delay", "kill",
                             "replica_kill", "corrupt", "thread_death",
                             "nan", "partition"):
            raise ValueError(f"unknown fault kind {self.kind!r}")
        if self.scope not in ("any", "worker", "driver"):
            raise ValueError(f"unknown fault scope {self.scope!r}")
        if self.kind == "raise" and self.exc not in _EXC_TYPES:
            raise ValueError(
                f"unknown exception type {self.exc!r} "
                f"(known: {sorted(_EXC_TYPES)})")


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """A deterministic set of faults + the seed for corruption bytes."""

    specs: tuple[FaultSpec, ...] = ()
    seed: int = 0

    def to_json(self) -> str:
        return json.dumps({
            "seed": self.seed,
            "specs": [dataclasses.asdict(s) for s in self.specs],
        }, indent=2)

    @classmethod
    def from_json(cls, text: str) -> "FaultPlan":
        obj = json.loads(text)
        specs = []
        for s in obj.get("specs", []):
            s = dict(s)
            s["occurrences"] = tuple(s.get("occurrences", ()))
            s["indices"] = tuple(s.get("indices", ()))
            specs.append(FaultSpec(**s))
        return cls(specs=tuple(specs), seed=int(obj.get("seed", 0)))


class FaultInjector:
    """Counts site occurrences and fires matching specs (thread-safe).

    ``worker=True`` marks an injector living inside a pool worker
    process (installed by the pool initializer) — it arms "worker"-scoped
    specs and disarms "driver"-scoped ones.
    """

    def __init__(self, plan: FaultPlan, worker: bool = False):
        self.plan = plan
        self.is_worker = bool(worker)
        self._lock = threading.Lock()
        self._counts: dict[str, int] = {}
        self._spec_fires: dict[int, int] = {}
        self.fired: list[tuple[str, int, Optional[int], str]] = []

    # -- bookkeeping -------------------------------------------------------

    def occurrences(self, site: str) -> int:
        with self._lock:
            return self._counts.get(site, 0)

    def fires(self, site: Optional[str] = None) -> int:
        with self._lock:
            return len([f for f in self.fired
                        if site is None or f[0] == site])

    def _match(self, site: str, index: Optional[int],
               kinds: tuple[str, ...]) -> Optional[FaultSpec]:
        """Count one occurrence of ``site`` and return the firing spec,
        if any, recording the hit."""
        with self._lock:
            occ = self._counts.get(site, 0)
            self._counts[site] = occ + 1
            my_scope = "worker" if self.is_worker else "driver"
            for si, spec in enumerate(self.plan.specs):
                if spec.site != site or spec.kind not in kinds:
                    continue
                if spec.scope not in ("any", my_scope):
                    continue
                if spec.occurrences and occ not in spec.occurrences:
                    continue
                if spec.indices and (index is None
                                     or index not in spec.indices):
                    continue
                hits = self._spec_fires.get(si, 0)
                if spec.max_fires is not None and hits >= spec.max_fires:
                    continue
                self._spec_fires[si] = hits + 1
                self.fired.append((site, occ, index, spec.kind))
                return spec
        return None

    # -- the hooks production code calls -----------------------------------

    def fire(self, site: str, index: Optional[int] = None) -> None:
        """Crash/delay/kill hook: every instrumented execution point
        calls this once per occurrence."""
        spec = self._match(site, index,
                           ("raise", "sleep", "delay", "kill",
                            "replica_kill", "thread_death", "partition"))
        if spec is None:
            return
        if spec.kind in ("sleep", "delay"):
            time.sleep(spec.seconds)
        elif spec.kind in ("kill", "replica_kill"):
            os.kill(os.getpid(), signal.SIGKILL)
        elif spec.kind == "thread_death":
            raise InjectedThreadDeath(f"{spec.message} [site={site}]")
        elif spec.kind == "partition":
            raise InjectedPartition(f"{spec.message} [site={site}]")
        else:
            raise _EXC_TYPES[spec.exc](f"{spec.message} [site={site}]")

    def poison_scalar(self, site: str, value: float,
                      index: Optional[int] = None) -> float:
        """Value-poisoning hook for numeric sites: returns NaN when a
        ``nan`` spec matches, else ``value`` unchanged — the injected
        form of a numerically sick objective (watchdog chaos drills)."""
        spec = self._match(site, index, ("nan",))
        return float("nan") if spec is not None else value

    def corrupt_file(self, site: str, path: str,
                     index: Optional[int] = None) -> bool:
        """Corruption hook for save-sites: garble ``path`` in place when
        a ``corrupt`` spec matches. Deterministic: the overwritten bytes
        come from ``Random(seed, site, occurrence)``. Returns True when
        the file was corrupted."""
        spec = self._match(site, index, ("corrupt",))
        if spec is None:
            return False
        size = os.path.getsize(path)
        rng = random.Random(
            f"{self.plan.seed}|{site}|{self._counts.get(site, 0)}")
        n = max(1, min(64, size))
        blob = bytes(rng.randrange(256) for _ in range(n))
        with open(path, "r+b") as f:
            f.seek(max(0, size // 2 - n // 2))
            f.write(blob)
        return True


# -- process-global seam -----------------------------------------------------
#
# One injector per process, installed explicitly (tests, --fault-plan) or
# shipped to pool workers through their initializer ctx. Reads are a single
# None check when no faults are active.

_ACTIVE: Optional[FaultInjector] = None


def install(plan: Optional[FaultPlan],
            worker: bool = False) -> Optional[FaultInjector]:
    """Install ``plan`` process-wide (None uninstalls); returns the
    injector so tests can assert on its firing record. ``worker=True``
    is set by pool-worker initializers (arms "worker"-scoped specs)."""
    global _ACTIVE
    _ACTIVE = (FaultInjector(plan, worker=worker)
               if plan is not None else None)
    return _ACTIVE


def active() -> Optional[FaultInjector]:
    return _ACTIVE


def current_plan() -> Optional[FaultPlan]:
    """The installed plan, if any — picklable, for shipping to workers."""
    return _ACTIVE.plan if _ACTIVE is not None else None


class installed:
    """Context-manager install: ``with faults.installed(plan) as inj:``
    — uninstalls on exit even when the body raises."""

    def __init__(self, plan: FaultPlan):
        self._plan = plan
        self.injector: Optional[FaultInjector] = None

    def __enter__(self) -> FaultInjector:
        self.injector = install(self._plan)
        return self.injector

    def __exit__(self, *exc):
        install(None)


def fire(site: str, index: Optional[int] = None) -> None:
    """Module-level hook: no-op unless a plan is installed."""
    if _ACTIVE is not None:
        _ACTIVE.fire(site, index)


def corrupt_file(site: str, path: str, index: Optional[int] = None) -> bool:
    if _ACTIVE is not None:
        return _ACTIVE.corrupt_file(site, path, index)
    return False


def poison_scalar(site: str, value: float,
                  index: Optional[int] = None) -> float:
    """Module-level poisoning hook: identity unless a plan is installed."""
    if _ACTIVE is not None:
        return _ACTIVE.poison_scalar(site, value, index)
    return value
