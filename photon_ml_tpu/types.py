"""Core type aliases, enums and constants.

Reference parity: photon-lib ``TaskType.scala``, ``Types.scala``,
``Constants.scala``.
"""

from __future__ import annotations

import enum

import jax.numpy as jnp

# Type aliases (reference: photon-lib Types.scala)
REId = int  # random-effect entity id (row index into an entity table)
REType = str  # random-effect type name, e.g. "userId"
FeatureShardId = str  # named feature shard, e.g. "globalFeatures"
CoordinateId = str  # GAME coordinate name, e.g. "per-user"
UniqueSampleId = int  # stable example index within a dataset

# Canonical intercept feature name (reference: Constants.scala INTERCEPT_KEY:
# name = "(INTERCEPT)", term = "").
INTERCEPT_NAME = "(INTERCEPT)"
INTERCEPT_TERM = ""
INTERCEPT_KEY = (INTERCEPT_NAME, INTERCEPT_TERM)

# Default compute dtype. TPU MXU prefers bf16 inputs / f32 accumulation;
# GLM coefficient math is small, so f32 everywhere is the safe default.
DEFAULT_DTYPE = jnp.float32


class TaskType(enum.Enum):
    """Supported training tasks (reference: photon-lib TaskType.scala)."""

    LOGISTIC_REGRESSION = "LOGISTIC_REGRESSION"
    LINEAR_REGRESSION = "LINEAR_REGRESSION"
    POISSON_REGRESSION = "POISSON_REGRESSION"
    SMOOTHED_HINGE_LOSS_LINEAR_SVM = "SMOOTHED_HINGE_LOSS_LINEAR_SVM"

    @property
    def is_classification(self) -> bool:
        return self in (
            TaskType.LOGISTIC_REGRESSION,
            TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM,
        )
