"""ModuleContext: one parsed file, shared by every rule."""

from __future__ import annotations

import ast
import dataclasses

from photon_ml_tpu.analysis.findings import Finding


@dataclasses.dataclass
class ModuleContext:
    path: str  # repo-relative posix path
    source: str
    lines: list[str]
    tree: ast.Module

    @classmethod
    def parse(cls, path: str, source: str) -> "ModuleContext":
        return cls(path=path, source=source,
                   lines=source.splitlines(),
                   tree=ast.parse(source, filename=path))

    def snippet(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        line = getattr(node, "lineno", 0)
        col = getattr(node, "col_offset", 0)
        return Finding(rule=rule, path=self.path, line=line, col=col,
                       message=message, snippet=self.snippet(line))
