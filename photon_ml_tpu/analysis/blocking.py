"""The ONE blocking-call predicate shared by PML011 and PML019.

PML011 (per-file: blocking network call without a timeout) and PML019
(whole-program: blocking call reached while a lock is held) care about
the same call shapes — HTTP/socket primitives, ``Future.result()``,
``Popen.wait()``, ``queue.get()``, ``time.sleep`` — but from different
angles: PML011 asks "is the hang bounded?", PML019 asks "does a lock
holder pay for it?". Keeping two copies of the shape/timeout tables was
exactly the drift PML014 exists to prevent, so both rules classify
through :func:`classify_call` and share :data:`NET_CALLS`.

Timeout semantics (``TimeoutState``): a call site reports its
``timeout=`` keyword as ``"finite"`` (present, not the literal
``None``), ``"none"`` (the literal ``None`` — explicitly unbounded) or
``""`` (absent). Positional timeouts are recognized per call shape
(``Future.result(5)``, ``queue.get(True, 5)``, ``Popen.wait(5)``, and
the network table's per-callee positions).
"""

from __future__ import annotations

from typing import Optional

# call leaf → (dotted-suffix requirements, positional index of timeout).
# A call matches when its dotted name ends with one of the suffixes;
# bare leaves like ``get`` never match the NETWORK table without their
# module base (or ``dict.get`` would light up the repo).
NET_CALLS = {
    "urlopen": (("urllib.request.urlopen", "request.urlopen",
                 "urlopen"), 2),
    "create_connection": (("socket.create_connection",), 1),
    "HTTPConnection": (("http.client.HTTPConnection",
                        "client.HTTPConnection"), 2),
    "HTTPSConnection": (("http.client.HTTPSConnection",
                         "client.HTTPSConnection"), 2),
    "get": (("requests.get",), None),
    "post": (("requests.post",), None),
    "put": (("requests.put",), None),
    "delete": (("requests.delete",), None),
    "head": (("requests.head",), None),
    "request": (("requests.request",), None),
}

# Waiting primitives: leaf → positional index of their timeout argument.
# ``result`` is Future.result(timeout=None); ``wait`` is Popen/Event/
# Condition/Thread-shaped (a Condition.wait on the HELD lock releases it
# — PML019 exempts that case by receiver, see locks.py); ``get`` is
# queue.Queue.get(block=True, timeout=None) — matched only with ZERO
# positional args so ``dict.get(key)`` never trips it.
WAIT_CALLS = {"result": 0, "wait": 0, "get": 1, "join": 0}

# time.sleep: bounded by construction but still a deliberate stall —
# PML019 flags it under a lock regardless (every waiter inherits the
# nap); PML011 does not care about it.
SLEEP_SUFFIXES = ("time.sleep", "sleep")

# Device-sync leafs that block the host on the accelerator (the flush
# path's np.asarray(...)-style casts are caught by taint in project.py;
# these names block by NAME regardless of taint).
SYNC_LEAFS = {"block_until_ready", "device_get"}


def net_spec(name: str):
    """(suffixes, timeout_pos) when ``name`` is a known blocking network
    callable, else None."""
    leaf = name.rsplit(".", 1)[-1]
    spec = NET_CALLS.get(leaf)
    if spec is None:
        return None
    suffixes, pos = spec
    if not any(name == s or name.endswith("." + s) for s in suffixes):
        return None
    return suffixes, pos


def classify_call(name: str, arg_count: int, kwarg_names: list,
                  timeout_state: str
                  ) -> Optional[tuple[str, bool]]:
    """(kind, bounded) for a blocking-shaped call, else None.

    kinds: ``net`` (HTTP/socket), ``sleep``, ``result``
    (Future.result), ``wait`` (Popen/Event/Condition/Thread),
    ``queue_get``, ``sync`` (device sync by name). ``bounded`` means a
    finite timeout rode along (positionally or by keyword) — the shared
    exemption predicate PML019's "timeout-carrying call" rule and
    PML011's timeout detection both apply.
    """
    leaf = name.rsplit(".", 1)[-1]
    spec = net_spec(name)
    if spec is not None:
        _suffixes, pos = spec
        bounded = timeout_state == "finite" \
            or (pos is not None and arg_count > pos)
        return "net", bounded
    if name in SLEEP_SUFFIXES or any(
            name.endswith("." + s) for s in ("time.sleep",)):
        return "sleep", True  # bounded, but a stall every waiter inherits
    if leaf in SYNC_LEAFS and "." in name:
        return "sync", False
    if leaf == "result" and "." in name:
        bounded = timeout_state == "finite" \
            or (arg_count > WAIT_CALLS["result"])
        return "result", bounded
    if leaf in ("wait", "join") and "." in name:
        bounded = timeout_state == "finite" \
            or (arg_count > WAIT_CALLS["wait"])
        return "wait", bounded
    if leaf == "get" and "." in name and arg_count == 0:
        # queue.Queue.get() only ever takes (block, timeout); a
        # positional arg means dict/mapping .get — not blocking.
        bounded = timeout_state == "finite"
        return "queue_get", bounded
    return None


def kind_label(kind: str) -> str:
    return {
        "net": "network call",
        "sleep": "sleep",
        "result": "Future.result()",
        "wait": "wait()",
        "queue_get": "queue.get()",
        "sync": "host-device sync",
    }.get(kind, kind)
