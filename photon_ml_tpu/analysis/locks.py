"""PML018/PML019 — the static half of photon-lockdep.

The serving/publish stack is hand-rolled threads (MicroBatcher flush
loops, ReplicaSupervisor monitors, the fleet's publish ladder), which
means the two deadlock shapes Spark gave the reference for free are
ours to prove absent: lock-ORDER cycles (thread 1 takes A then B,
thread 2 takes B then A) and blocking-UNDER-lock (a lock held across
HTTP, ``Future.result()``, a sleep, or a host-device sync turns one
slow call into a convoy).

Both are whole-program properties. A single file shows ``with
self._lock:`` around an innocuous-looking ``self._post(...)``; only the
project graph knows ``_post`` is an HTTP round trip three modules away.
So this module builds a **global lock graph** over the FileSummary pass
PR 11 pays for anyway:

- **nodes** are lock objects the summaries can name: ``self.X``
  attributes whose constructor is ``threading.Lock/RLock/Condition``
  (node id ``{module}.{Class}.{X}``) and module-level ``NAME =
  threading.Lock()`` constants (``{module}.{NAME}``).
- **edges** A→B mean "some thread acquires B while holding A": either
  lexically (nested ``with``), or through the call graph (a call made
  under A reaches a function that acquires B — closed over
  ``may_acquire`` by bounded fixpoint, witness chains kept), or through
  a **callback handoff** (``Supervisor(on_death=self._m)``: the
  supervisor's monitor invokes the stored attr under its own lock, so
  the edge starts at the supervisor's lock and lands on whatever ``_m``
  acquires — the same constructor-param plumbing PML015 uses).

**PML018** reports every non-trivial strongly-connected component (a
cycle = an interleaving away from deadlock) with the witness chain of
each participating edge, plus re-entrant self-acquisition of a
non-reentrant lock type. **PML019** reports a blocking call reached —
via the graph — while any lock is held, one finding per
(function, lock, kind), with the exemptions and the hot-path severity
split below. The blocking-call *shapes* live in
:mod:`photon_ml_tpu.analysis.blocking`, shared with PML011 so the two
rules can never drift on what "has a timeout" means.

Exemptions (conservative: silence over a wrong edge, PR 11 doctrine):

- ``result``/``wait``/``queue.get`` carrying a finite timeout are
  bounded stalls — exempt.
- ``cond.wait()`` while HOLDING ``cond`` releases the lock for the
  duration — exempt for that lock (the MicroBatcher idiom). The wait
  still blocks any *other* lock held above it; that case is only
  reached through a caller edge and is deliberately not modeled.
- network calls are flagged even with a timeout (every waiter inherits
  the round trip); the message says which case you're in.

The runtime half (:mod:`photon_ml_tpu.utils.lockdep`) observes the real
acquisition DAG under tests; :func:`reconcile` diffs the two —
runtime-only edges are resolver gaps, static-only edges are coverage
debt.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from photon_ml_tpu.analysis.blocking import classify_call, kind_label
from photon_ml_tpu.analysis.findings import Finding
from photon_ml_tpu.analysis.project import (FileSummary, FunctionSummary,
                                            ProjectGraph)

# Locks on the per-request scoring path: a blocking call under one of
# these stalls every scorer fleet-wide, not just a background thread.
# Matched by node-id suffix so the split survives module moves.
HOT_LOCK_SUFFIXES = ("ScoringService._lock", "ResidentModelStore._lock")

# Lock types whose self-edges are legal re-entry. threading.Condition's
# default inner lock is an RLock, so ``with c: ... with c:`` is safe.
_REENTRANT_TYPES = {"RLock", "Condition"}

_MAX_CHAIN = 8       # witness chains deeper than this stop growing
_FIXPOINT_ROUNDS = 6  # call-graph depth bound (PML012 precedent)


@dataclasses.dataclass
class LockEdge:
    """One ordered acquisition A→B with its first-found witness."""

    src: str
    dst: str
    path: str      # file of the witnessing acquire/call site
    line: int
    witness: list  # call-chain strings, outermost frame first


@dataclasses.dataclass
class LockAnalysis:
    nodes: dict    # lock id -> type leaf ("Lock"/"RLock"/"Condition")
    edges: dict    # (src, dst) -> LockEdge
    blocked: list  # [(fs, qname, lock_id, kind, bounded, chain, line)]


# ------------------------------------------------------------ resolution


def _lock_id(fs: FileSummary, qname: str, cand: str) -> Optional[str]:
    """A held-candidate name ("self.X" / bare NAME) resolved to a lock
    node id in the defining scope, or None when it isn't a lock."""
    if cand.startswith("self."):
        if "." not in qname:
            return None
        cls_name = qname.split(".", 1)[0]
        attr = cand.split(".", 1)[1]
        cls = fs.classes.get(cls_name)
        if cls is not None and attr in cls.lock_attrs:
            return f"{fs.module}.{cls_name}.{attr}"
        return None
    if cand in fs.module_locks:
        return f"{fs.module}.{cand}"
    return None


def _callback_map(graph: ProjectGraph, files: list) -> dict:
    """(path, class, attr) -> [(callee_path, callee_qname)] for every
    ``Target(param=self.m)`` constructor handoff where Target stores
    ``param`` on ``attr`` — so Target's own ``self.attr(...)`` sites
    resolve to the caller's bound method (PML015's seam, reused here so
    a lock held around the invocation flows into the callback)."""
    out: dict = {}
    for fs in files:
        for qname, fn in fs.functions.items():
            if "." not in qname:
                continue
            caller_cls = qname.split(".", 1)[0]
            for c in fn.calls:
                if not c.selfattr_args and not c.selfattr_kwargs:
                    continue
                rc = graph.resolve_class(fs, c.name)
                if rc is None:
                    continue
                tfs, tcls = rc
                param_attr: dict = {}
                for m in tcls.methods.values():
                    for p, attr in m.stores_params.items():
                        param_attr[p] = attr
                hooked = []
                for kw, cattr in c.selfattr_kwargs.items():
                    if kw in param_attr \
                            and f"{caller_cls}.{cattr}" in fs.functions:
                        hooked.append((param_attr[kw], cattr))
                for pos_s, cattr in c.selfattr_args.items():
                    pos = int(pos_s)
                    if pos < len(tcls.init_params):
                        p = tcls.init_params[pos]
                        if p in param_attr \
                                and f"{caller_cls}.{cattr}" in fs.functions:
                            hooked.append((param_attr[p], cattr))
                for tattr, cattr in hooked:
                    out.setdefault((tfs.path, tcls.name, tattr), []) \
                        .append((fs.path, f"{caller_cls}.{cattr}"))
    return out


# Generic verbs that exist on file handles, threads, futures, sockets
# and half the stdlib: the unique-method-leaf fallback must never guess
# an edge from one (``self._fh.flush()`` landing on RunLedger.flush()
# would fabricate a deadlock). Lock analysis prefers a missed edge —
# the runtime validator exists to catch those — over a fabricated one.
_GENERIC_LEAFS = {"flush", "close", "join", "wait", "get", "put",
                  "result", "acquire", "release", "start", "stop",
                  "run", "send", "recv", "read", "write", "open",
                  "item", "clear", "pop", "append", "update", "copy",
                  "shutdown", "submit", "cancel", "set"}


def _attr_types(graph: ProjectGraph, files: list) -> dict:
    """(path, class, attr) -> (path, class) for every ``self.attr =
    SomeProjectClass(...)`` constructor assignment — the receiver-type
    facts that let ``self.attr.method()`` resolve precisely instead of
    by leaf-name guessing."""
    out: dict = {}
    for fs in files:
        for qname, fn in fs.functions.items():
            if "." not in qname:
                continue
            cls = qname.split(".", 1)[0]
            for c in fn.calls:
                if not c.binding.startswith("self:"):
                    continue
                rc = graph.resolve_class(fs, c.name)
                if rc is None:
                    continue
                tfs, tcls = rc
                attr = c.binding.split(":", 1)[1]
                out[(fs.path, cls, attr)] = (tfs.path, tcls.name)
    return out


def _call_targets(graph: ProjectGraph, fs: FileSummary, c, qname: str,
                  callbacks: dict, attr_types: dict) -> list:
    """Every (path, qname) a call site may land on. Stricter than
    ``ProjectGraph.resolve_call``: its unique-method-leaf fallback is
    fine when a missed edge merely silences a finding (PML012), but
    here a WRONG edge fabricates a deadlock — so external-alias
    receivers never fall back, ``self.attr.method()`` resolves only
    through a known constructor assignment, and generic verb leafs
    never resolve by uniqueness."""
    parts = c.name.split(".")
    if parts[0] == "self":
        if len(parts) == 2:
            r = graph.resolve_call(fs, c, caller=qname)
            if r is not None:
                return [(r[0].path, r[1].name)]
            if "." in qname:
                cls = qname.split(".", 1)[0]
                return list(callbacks.get((fs.path, cls, parts[1]), ()))
            return []
        if len(parts) == 3 and "." in qname:
            cls = qname.split(".", 1)[0]
            t = attr_types.get((fs.path, cls, parts[1]))
            if t is not None:
                tpath, tcls = t
                q = f"{tcls}.{parts[2]}"
                tfs = graph.files.get(tpath)
                if tfs is not None and q in tfs.functions:
                    return [(tpath, q)]
        return []
    if len(parts) == 1:
        r = graph.resolve_call(fs, c, caller=qname)
        return [(r[0].path, r[1].name)] if r is not None else []
    if parts[0] in fs.imports:
        target = fs.imports[parts[0]]
        roots = {m.split(".", 1)[0] for m in graph.modules}
        if target.split(".", 1)[0] not in roots:
            return []  # external library: never guess an edge
        # Imported-Class.method resolves precisely through the class.
        if len(parts) == 2:
            rc = graph.resolve_class(fs, parts[0])
            if rc is not None:
                tfs, tcls = rc
                q = f"{tcls.name}.{parts[1]}"
                if q in tfs.functions:
                    return [(tfs.path, q)]
        r = graph.resolve_call(fs, c, caller=qname)
        if r is not None:
            return [(r[0].path, r[1].name)]
        return []
    # Local-variable receiver: allow the unique-leaf fallback, but
    # never for generic verbs.
    if parts[-1] in _GENERIC_LEAFS:
        return []
    r = graph.resolve_call(fs, c, caller=qname)
    return [(r[0].path, r[1].name)] if r is not None else []


# ------------------------------------------------------------- the build


def _classify_site(c) -> Optional[tuple]:
    """(kind, bounded) when this call site blocks, else None — device
    syncs by taint (marked during summarization) or by shared-predicate
    shape, with the timeout/cond-wait exemptions applied."""
    if c.blocking_kind == "sync":
        return "sync", False
    b = classify_call(c.name, c.arg_count, list(c.kwarg_names),
                      c.timeout_state)
    if b is None:
        return None
    kind, bounded = b
    if kind in ("result", "wait", "queue_get") and bounded:
        return None  # a finite timeout bounds the stall
    if kind == "wait":
        receiver = c.name.rsplit(".", 1)[0]
        if receiver in c.held:
            return None  # cond.wait() RELEASES the held condition
    return kind, bounded


def _build(graph: ProjectGraph) -> LockAnalysis:
    files = sorted(graph.package_files(), key=lambda fs: fs.path)

    nodes: dict = {}
    for fs in files:
        for cname in sorted(fs.classes):
            cls = fs.classes[cname]
            for attr in sorted(cls.lock_types):
                nodes[f"{fs.module}.{cname}.{attr}"] = \
                    cls.lock_types[attr]
        for name in sorted(fs.module_locks):
            nodes[f"{fs.module}.{name}"] = fs.module_locks[name]

    callbacks = _callback_map(graph, files)
    attr_types = _attr_types(graph, files)

    fkeys: dict = {}
    for fs in files:
        for qname, fn in fs.functions.items():
            fkeys[(fs.path, qname)] = (fs, fn)

    calls = []  # (fs, qname, fn, call, [target keys])
    for fs in files:
        for qname, fn in fs.functions.items():
            for c in fn.calls:
                tkeys = [t for t in _call_targets(graph, fs, c, qname,
                                                  callbacks, attr_types)
                         if t in fkeys]
                calls.append((fs, qname, fn, c, tkeys))

    edges: dict = {}

    def add_edge(src: str, dst: str, path: str, line: int,
                 witness: list) -> None:
        if (src, dst) not in edges:
            edges[(src, dst)] = LockEdge(src, dst, path, line,
                                         list(witness))

    # Direct acquisitions: may_acquire seeds + lexical nesting edges.
    may_acquire: dict = {k: {} for k in fkeys}
    for key in sorted(fkeys):
        fs, fn = fkeys[key]
        path, qname = key
        for name, line, held in fn.acquires:
            lock = _lock_id(fs, qname, name)
            if lock is None:
                continue
            ma = may_acquire[key]
            if lock not in ma:
                ma[lock] = [f"{path}:{line} {qname}() acquires {lock}"]
            for h in held:
                hid = _lock_id(fs, qname, h)
                if hid is None:
                    continue
                add_edge(hid, lock, path, line,
                         [f"{path}:{line} {qname}() acquires {lock} "
                          f"while holding {hid}"])

    # Close may_acquire over the call graph (witness chains ride along).
    for _ in range(_FIXPOINT_ROUNDS):
        changed = False
        for fs, qname, fn, c, tkeys in calls:
            k = (fs.path, qname)
            for tkey in tkeys:
                for lock, chain in list(may_acquire[tkey].items()):
                    if lock not in may_acquire[k] \
                            and len(chain) < _MAX_CHAIN:
                        may_acquire[k][lock] = \
                            [f"{fs.path}:{c.line} {qname}() -> "
                             f"{tkey[1]}()"] + chain
                        changed = True
        if not changed:
            break

    # Cross-function edges: a call made under H reaching an acquire of L.
    for fs, qname, fn, c, tkeys in calls:
        held_ids = [hid for h in c.held
                    if (hid := _lock_id(fs, qname, h)) is not None]
        if not held_ids:
            continue
        for tkey in tkeys:
            for lock, chain in may_acquire[tkey].items():
                for hid in held_ids:
                    add_edge(hid, lock, fs.path, c.line,
                             [f"{fs.path}:{c.line} {qname}() holds "
                              f"{hid}, calls {tkey[1]}()"] + chain)

    # may_block: which blocking behaviors a call into f can reach.
    may_block: dict = {k: {} for k in fkeys}
    for key in sorted(fkeys):
        fs, fn = fkeys[key]
        path, qname = key
        for c in fn.calls:
            b = _classify_site(c)
            if b is None:
                continue
            kind, bounded = b
            if kind not in may_block[key]:
                may_block[key][kind] = (
                    bounded,
                    [f"{path}:{c.line} {qname}() — "
                     f"{kind_label(kind)} ({c.name})"],
                    c.line)
    for _ in range(_FIXPOINT_ROUNDS):
        changed = False
        for fs, qname, fn, c, tkeys in calls:
            k = (fs.path, qname)
            for tkey in tkeys:
                for kind, (bounded, chain, line) in \
                        list(may_block[tkey].items()):
                    if kind not in may_block[k] \
                            and len(chain) < _MAX_CHAIN:
                        may_block[k][kind] = (
                            bounded,
                            [f"{fs.path}:{c.line} {qname}() -> "
                             f"{tkey[1]}()"] + chain,
                            line)
                        changed = True
        if not changed:
            break

    # Blocking-under-lock sites, deduped to (function, lock, kind).
    blocked = []
    seen: set = set()
    for fs, qname, fn, c, tkeys in calls:
        held_ids = [hid for h in c.held
                    if (hid := _lock_id(fs, qname, h)) is not None]
        if not held_ids:
            continue
        events = []
        direct = _classify_site(c)
        if direct is not None:
            events.append((direct[0], direct[1], [], c.line))
        for tkey in tkeys:
            for kind, (bounded, chain, line) in \
                    may_block[tkey].items():
                events.append((kind, bounded, chain, c.line))
        for kind, bounded, chain, line in events:
            for hid in held_ids:
                dkey = (fs.path, qname, hid, kind)
                if dkey in seen:
                    continue
                seen.add(dkey)
                blocked.append((fs, qname, hid, kind, bounded,
                                list(chain), c.line))

    return LockAnalysis(nodes=nodes, edges=edges, blocked=blocked)


def _analysis(graph: ProjectGraph) -> LockAnalysis:
    cached = graph.__dict__.get("_lockdep")
    if cached is None:
        cached = graph.__dict__["_lockdep"] = _build(graph)
    return cached


# ----------------------------------------------------------------- PML018


def _sccs(nodes, edge_keys) -> list:
    """Tarjan over the lock graph (tiny: recursion is fine)."""
    adj: dict = {n: [] for n in nodes}
    for s, d in edge_keys:
        adj.setdefault(s, []).append(d)
        adj.setdefault(d, [])
    index: dict = {}
    low: dict = {}
    stack: list = []
    on: set = set()
    out: list = []
    counter = [0]

    def strong(v):
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on.add(v)
        for w in sorted(adj.get(v, ())):
            if w not in index:
                strong(w)
                low[v] = min(low[v], low[w])
            elif w in on:
                low[v] = min(low[v], index[w])
        if low[v] == index[v]:
            comp = []
            while True:
                w = stack.pop()
                on.discard(w)
                comp.append(w)
                if w == v:
                    break
            out.append(sorted(comp))

    for v in sorted(adj):
        if v not in index:
            strong(v)
    return out


def check_lock_order(graph: ProjectGraph) -> list[Finding]:
    a = _analysis(graph)
    out: list[Finding] = []
    for comp in _sccs(a.nodes, a.edges.keys()):
        if len(comp) < 2:
            continue
        internal = sorted(
            (e for (s, d), e in a.edges.items()
             if s in comp and d in comp),
            key=lambda e: (e.path, e.line, e.src, e.dst))
        anchor = internal[0]
        legs = "; ".join(
            f"{e.src} -> {e.dst} (witness: "
            f"{' | '.join(e.witness[:3])})" for e in internal[:4])
        out.append(Finding(
            rule="PML018", path=anchor.path, line=anchor.line, col=0,
            message=(
                f"lock-order cycle among "
                f"{{{', '.join(comp)}}} — two threads walking opposite "
                f"legs deadlock: {legs}")))
    for (s, d), e in sorted(a.edges.items()):
        if s == d and a.nodes.get(s) not in _REENTRANT_TYPES:
            out.append(Finding(
                rule="PML018", path=e.path, line=e.line, col=0,
                message=(
                    f"re-entrant acquisition of non-reentrant lock "
                    f"{s} ({a.nodes.get(s, 'Lock')}) — "
                    f"{' | '.join(e.witness[:3])} — the second acquire "
                    f"deadlocks the holding thread")))
    out.sort(key=lambda f: (f.path, f.line, f.message))
    return out


# ----------------------------------------------------------------- PML019


def check_blocking_under_lock(graph: ProjectGraph) -> list[Finding]:
    a = _analysis(graph)
    out: list[Finding] = []
    for fs, qname, lock, kind, bounded, chain, line in a.blocked:
        hot = any(lock.endswith(s) for s in HOT_LOCK_SUFFIXES)
        label = kind_label(kind)
        if chain:
            body = (f"{qname}() holds {lock} across a call that "
                    f"reaches a {label} "
                    f"({' | '.join(chain[:4])})")
        else:
            body = (f"{qname}() makes a {label} while holding {lock}")
        if kind == "net":
            body += (" — the timeout bounds the stall but every waiter "
                     "still pays the round trip" if bounded
                     else " — with NO timeout: one hung peer wedges "
                          "every thread behind this lock")
        elif kind in ("result", "wait", "queue_get"):
            body += " — unbounded"
        if hot:
            body += (" [hot-path lock: the scoring fleet serializes "
                     "behind it]")
        out.append(Finding(rule="PML019", path=fs.path, line=line,
                           col=0, message=body))
    out.sort(key=lambda f: (f.path, f.line, f.message))
    return out


# ------------------------------------------------- artifact + reconcile


def lock_graph_json(graph: ProjectGraph) -> dict:
    """The ``photon-lint --locks`` payload: deterministic node/edge
    dump, diffable in review and consumed by :func:`reconcile`."""
    a = _analysis(graph)
    return {
        "version": 1,
        "nodes": [{"id": n, "type": a.nodes[n]}
                  for n in sorted(a.nodes)],
        "edges": [{"src": e.src, "dst": e.dst, "path": e.path,
                   "line": e.line, "witness": e.witness}
                  for (s, d), e in sorted(a.edges.items())],
    }


def reconcile(static_doc: dict, runtime_doc: dict,
              allow_gaps: tuple = ()) -> dict:
    """Diff the static lock graph against a runtime ``.photon-lockdep
    .json`` dump. Runtime-only edges = the resolver missed a real
    acquisition path (fix the analysis, or list the edge in
    ``allow_gaps`` as "src -> dst" with a tracked reason); static-only
    edges = paths no test exercises (coverage debt, reported not
    failed)."""

    def norm(g: str) -> tuple:
        s, _, d = g.partition("->")
        return s.strip(), d.strip()

    allowed = {norm(g) for g in allow_gaps}
    s_edges = {(e["src"], e["dst"])
               for e in static_doc.get("edges", [])}
    r_edges = {(e["src"], e["dst"])
               for e in runtime_doc.get("edges", [])}
    runtime_only = sorted(r_edges - s_edges)
    gaps = [e for e in runtime_only if e not in allowed]
    inversions = runtime_doc.get("inversions", [])
    return {
        "runtime_only": [f"{s} -> {d}" for s, d in runtime_only],
        "resolver_gaps": [f"{s} -> {d}" for s, d in gaps],
        "allowed_gaps": sorted(
            f"{s} -> {d}"
            for s, d in set(runtime_only) & allowed),
        "unexercised": sorted(
            f"{s} -> {d}" for s, d in s_edges - r_edges),
        "inversions": len(inversions),
        "ok": not gaps and not inversions,
    }
