"""The project graph: a repo-wide symbol table + call graph for the
interprocedural lint rules (PML012-PML016), with an mtime/CRC-keyed
on-disk cache so repo-wide lint stays inside the seconds photon-lint
promises.

Per-file rules (PML001-PML011) see one AST at a time; the bug classes
this PR mechanizes cross module boundaries — a helper in ``ops/`` that
syncs inside a caller's loop in ``optim/streaming.py``, a raw write into
a ledger directory from a helper two files away, a callback handed
across a class boundary onto another object's monitor thread. For
those, every file is distilled ONCE (sharing the parse with the
per-file rules) into a :class:`FileSummary` — functions with their call
sites, sync/write/resource behavior, classes with their lock/entrypoint
topology, plus the raw material of the string-keyed catalogs (fault
sites, events, ``photon_*`` metrics, span names). The summaries are
plain JSON-able data: the :class:`ProjectCache` persists them (keyed by
file size + mtime_ns + CRC32, fenced by a signature over the analysis
package's own sources), so a warm repo-wide run re-parses only changed
files.

Resolution is intra-package and deliberately conservative: import
aliases and ``from``-imports resolve exactly; a bare ``obj.method()``
attribute call falls back to a method-name lookup only when the name is
UNIQUE across the whole project (two candidates = no edge — an
interprocedural lint rule must prefer silence to a wrong edge).
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
import zlib
from typing import Iterable, Optional

from photon_ml_tpu.analysis.astwalk import (scope_statements,
                                            self_attribute,
                                            statement_exprs)
from photon_ml_tpu.analysis.taint import (TaintScope, call_func_name,
                                          dotted_name)

# Leaf names whose call acquires an OS resource the caller must release
# (PML016's seed set; intra-package factory functions that RETURN one of
# these propagate resource-ness through the call graph).
RESOURCE_LEAFS = {"Popen", "create_connection", "create_server",
                  "HTTPServer", "ThreadingHTTPServer", "TCPServer",
                  "UDPServer", "ThreadPoolExecutor",
                  "ProcessPoolExecutor", "make_pool"}
RESOURCE_NAMES = {"socket.socket", "mmap.mmap", "multiprocessing.Pool"}
# Method leafs that release a resource.
CLOSER_LEAFS = {"close", "server_close", "terminate", "kill", "shutdown",
                "stop", "release", "closed", "join"}
# Release-ish methods a class may use to free resources it stores.
RELEASE_METHODS = {"close", "stop", "shutdown", "server_close",
                   "terminate", "__exit__", "__del__", "join"}

_SYNC_CASTS = {"float", "int", "bool"}
_SYNC_NP = {"np.asarray", "np.array", "numpy.asarray", "numpy.array"}
_WRITE_MODES = set("wax+")
_LOCK_TYPES = {"Lock", "RLock", "Condition"}
_FAULT_HOOKS = {"fire": 0, "poison_scalar": 0, "corrupt_file": 0}
_METRIC_FACTORIES = {"counter", "gauge", "histogram"}


# --------------------------------------------------------------- summaries


@dataclasses.dataclass
class CallSite:
    """One call expression inside a function body."""

    name: str           # the callee as written ("flt.fire", "self._m")
    line: int
    depth: int          # enclosing loop depth within the function
    device_args: list   # positional indices whose expr is device-tainted
    device_kwargs: list  # kwarg names whose expr is device-tainted
    param_args: dict    # positional index (as str) -> caller param index
    param_kwargs: dict  # kwarg name -> caller param index
    selfattr_args: dict = dataclasses.field(default_factory=dict)
    # ^ positional index (as str) -> "attr" for ``self.attr`` arguments
    selfattr_kwargs: dict = dataclasses.field(default_factory=dict)
    # ^ kwarg name -> "attr" for ``kw=self.attr`` arguments
    arg_count: int = 0
    kwarg_names: list = dataclasses.field(default_factory=list)
    # Lock context (PML018/PML019): the candidate lock names held when
    # this call runs ("self.attr" / bare module-level NAME — resolved
    # against class lock_attrs / module_locks by analysis/locks.py),
    # the call's ``timeout=`` keyword state ("finite"/"none"/"" absent),
    # and "sync" when this site host-syncs a device value (taint-aware,
    # computed where the sync subject is known).
    held: list = dataclasses.field(default_factory=list)
    timeout_state: str = ""
    blocking_kind: str = ""
    # Result binding (PML016): how the call's value is held.
    binding: str = "bare"   # "local:<n>" | "self:<attr>" | "other" | "bare"
    with_item: bool = False
    is_returned: bool = False
    bound_closed: bool = False
    bound_closed_finally: bool = False
    bound_returned: bool = False
    bound_escapes: bool = False

    @property
    def leaf(self) -> str:
        return self.name.rsplit(".", 1)[-1]


@dataclasses.dataclass
class WriteSite:
    """One raw write primitive (open-for-write / np.save* / json.dump)."""

    line: int
    kind: str
    param_paths: list   # caller param indices the target path derives from
    in_atomic: bool     # lexically inside an atomic_write(...) argument


@dataclasses.dataclass
class FunctionSummary:
    name: str          # "func" or "Class.method"
    line: int
    params: list
    calls: list        # [CallSite]
    sync_params: list  # param indices this function host-syncs directly
    device_sync: bool  # syncs a device-tainted local of its own
    sync_witness: str  # "line:<n> <desc>" of one direct sync
    writes: list       # [WriteSite]
    write_params: list  # param indices raw-written (derived from writes)
    returns_resource: bool = False
    # Lock acquisitions (PML018): [[lock_name, line, [held...]]] — every
    # ``with self.X:`` / ``with NAME:`` / bare ``X.acquire()`` statement,
    # with the candidate lock names already held at that point (the
    # intra-function nesting edges fall straight out of this).
    acquires: list = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class MethodInfo:
    name: str
    writes: list        # [[attr, line, locked]]
    touched: list       # self attrs referenced at all
    self_calls: list    # self.m() callees
    stores_params: dict  # param name -> self attr it is stored to
    invokes_attrs: list  # self.<attr>(...) invocations
    closes_attrs: list   # attrs X with a self.X.<closer>() call


@dataclasses.dataclass
class ClassSummary:
    name: str
    line: int
    methods: dict       # name -> MethodInfo
    lock_attrs: list
    entrypoints: list   # PML005-style worker entrypoints
    init_params: list   # __init__ params, self excluded, in order
    lock_types: dict = dataclasses.field(default_factory=dict)
    # ^ lock attr -> constructor leaf ("Lock"/"RLock"/"Condition") —
    #   PML018 exempts re-entrant self-edges only for RLock.


@dataclasses.dataclass
class FileSummary:
    path: str           # repo-relative posix path
    module: str         # dotted module name derived from the path
    imports: dict       # alias -> dotted target (module or module.symbol)
    functions: dict     # qname ("f" / "C.m") -> FunctionSummary
    classes: dict       # name -> ClassSummary
    crash_module: bool  # participates in the .ok-marker/CRC protocol
    site_literals: list  # [[site, line, context]]
    metric_defs: list    # [[name, line, exact]]
    metric_refs: list    # [[name, line]]
    span_defs: list      # [[name, line]]
    event_classes: list  # Event subclasses defined here
    event_maps: list     # [[key, line]] dict keys mapping to photon_* values
    event_compares: list  # [[literal, line, func_qname]] CamelCase == lits
    registry_constants: dict  # NAME -> value (module-level str constants)
    module_locks: dict = dataclasses.field(default_factory=dict)
    # ^ NAME -> lock type leaf, for module-level ``_LOCK =
    #   threading.Lock()`` constants (lock-graph nodes like class attrs)


def _module_name(path: str) -> str:
    p = path.replace(os.sep, "/")
    if p.endswith(".py"):
        p = p[:-3]
    if p.endswith("/__init__"):
        p = p[: -len("/__init__")]
    return p.replace("/", ".").lstrip(".")


# ------------------------------------------------------ summary extraction


def _names_in(node: ast.AST) -> set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _param_derived(body: list[ast.stmt], params: list[str]) -> set[str]:
    """Names derived (transitively, two passes) from the parameters —
    the local taint that lets ``tmp = path + '.tmp'`` carry ``path``'s
    param-ness into a write site."""
    derived = set(params)
    for _ in range(2):
        for stmt, _d in scope_statements(body):
            if isinstance(stmt, ast.Assign) and stmt.value is not None:
                if _names_in(stmt.value) & derived:
                    for t in stmt.targets:
                        derived |= {n.id for n in ast.walk(t)
                                    if isinstance(n, ast.Name)}
    return derived


def _atomic_arg_ids(fn_body: list[ast.stmt]) -> set[int]:
    """ids of every node inside an argument of an atomic_write(...) call
    (writes there are the SANCTIONED path, not raw writes)."""
    out: set[int] = set()
    for stmt, _d in scope_statements(fn_body):
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call):
                leaf = (call_func_name(node) or "").rsplit(".", 1)[-1]
                if leaf in ("atomic_write", "_atomic_write"):
                    for a in list(node.args) + [k.value
                                                for k in node.keywords]:
                        for sub in ast.walk(a):
                            out.add(id(sub))
    return out


def _open_write_mode(call: ast.Call) -> Optional[str]:
    """The literal mode of an ``open`` call when it writes; None for
    reads, dynamic modes, or non-open calls."""
    name = call_func_name(call)
    if name is None or name.rsplit(".", 1)[-1] != "open" \
            or name not in ("open", "io.open", "os.fdopen"):
        return None
    mode = None
    if len(call.args) >= 2:
        mode = call.args[1]
    for k in call.keywords:
        if k.arg == "mode":
            mode = k.value
    if mode is None:
        return None  # default "r"
    if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
        return mode.value if set(mode.value) & _WRITE_MODES else None
    return None  # dynamic mode: benefit of the doubt (PML010 precedent)


def _extract_writes(body: list[ast.stmt], params: list[str]
                    ) -> tuple[list[WriteSite], list[int]]:
    derived = _param_derived(body, params)
    atomic_ids = _atomic_arg_ids(body)
    pidx = {p: i for i, p in enumerate(params)}
    writes: list[WriteSite] = []
    wparams: set[int] = set()

    def param_hits(expr: Optional[ast.AST]) -> list[int]:
        if expr is None:
            return []
        names = _names_in(expr)
        hit = [pidx[p] for p in params if p in names]
        if not hit and names & derived:
            # Derived local: attribute the write to EVERY param that
            # could have fed it (conservative; rules only need "any").
            hit = [pidx[p] for p in params]
        return hit

    seen: set[int] = set()
    for stmt, _d in scope_statements(body):
        for node in ast.walk(stmt):
            if not isinstance(node, ast.Call) or id(node) in seen:
                continue
            seen.add(id(node))
            in_atomic = id(node) in atomic_ids
            mode = _open_write_mode(node)
            if mode is not None:
                subject = node.args[0] if node.args else None
                writes.append(WriteSite(
                    line=node.lineno, kind=f"open(mode={mode!r})",
                    param_paths=param_hits(subject),
                    in_atomic=in_atomic))
                continue
            name = call_func_name(node) or ""
            leaf = name.rsplit(".", 1)[-1]
            if leaf in ("save", "savez", "savez_compressed") \
                    and name.split(".", 1)[0] in ("np", "numpy"):
                subject = node.args[0] if node.args else None
                writes.append(WriteSite(
                    line=node.lineno, kind=name,
                    param_paths=param_hits(subject),
                    in_atomic=in_atomic))
            elif name == "json.dump":
                subject = node.args[1] if len(node.args) > 1 else None
                writes.append(WriteSite(
                    line=node.lineno, kind=name,
                    param_paths=param_hits(subject),
                    in_atomic=in_atomic))
    for w in writes:
        if not w.in_atomic:
            wparams.update(w.param_paths)
    return writes, sorted(wparams)


def _sync_subject(call: ast.Call) -> Optional[ast.AST]:
    """The expression a sync-shaped call materializes on the host, or
    None when the call is not sync-shaped."""
    name = call_func_name(call)
    if name in _SYNC_CASTS or name in _SYNC_NP:
        return call.args[0] if call.args else None
    if name is not None and name.rsplit(".", 1)[-1] == "device_get":
        return call.args[0] if call.args else ast.Constant(value=True)
    if isinstance(call.func, ast.Attribute) and call.func.attr == "item" \
            and not call.args:
        return call.func.value
    return None


def _binding_annotations(body: list[ast.stmt]):
    """Per-local-name usage facts for PML016's ownership analysis:
    which names get a ``.closer()`` call (and whether inside a
    ``finally``), get returned, or escape into another object."""
    finally_ids: set[int] = set()
    for stmt, _d in scope_statements(body):
        if isinstance(stmt, ast.Try):
            for s in stmt.finalbody:
                for sub in ast.walk(s):
                    finally_ids.add(id(sub))
    closed: dict[str, bool] = {}          # name -> closed anywhere
    closed_fin: dict[str, bool] = {}      # name -> closed under finally
    returned: set[str] = set()
    escapes: set[str] = set()
    for stmt, _d in scope_statements(body):
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call):
                func = node.func
                if isinstance(func, ast.Attribute) \
                        and func.attr in CLOSER_LEAFS \
                        and isinstance(func.value, ast.Name):
                    n = func.value.id
                    closed[n] = True
                    if id(node) in finally_ids:
                        closed_fin[n] = True
                else:
                    for a in list(node.args) + [k.value
                                                for k in node.keywords]:
                        if isinstance(a, ast.Name):
                            escapes.add(a.id)
            elif isinstance(node, ast.Return) and node.value is not None:
                returned |= _names_in(node.value)
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, (ast.Attribute, ast.Subscript)):
                        if isinstance(node.value, ast.Name):
                            escapes.add(node.value.id)
                        else:
                            escapes |= _names_in(node.value)
            elif isinstance(node, (ast.Tuple, ast.List, ast.Dict)):
                escapes |= _names_in(node)
    return closed, closed_fin, returned, escapes


def _lock_expr_name(expr: ast.AST) -> Optional[str]:
    """The candidate lock name of a with-item / acquire receiver:
    ``self.X`` (one level) or a bare module-level NAME. Non-lock names
    are filtered later against class ``lock_attrs`` / file
    ``module_locks`` — recording here is deliberately over-broad."""
    attr = self_attribute(expr)
    if attr is not None:
        return f"self.{attr}"
    if isinstance(expr, ast.Name):
        return expr.id
    return None


def _held_lock_map(body: list[ast.stmt]
                   ) -> tuple[dict[int, tuple], list[list]]:
    """(statement-id -> candidate locks held when it runs, acquire
    sites). Tracks ``with`` nesting plus statement-level bare
    ``X.acquire()`` / ``X.release()`` pairs within a block (the
    try/finally idiom); nested def/class bodies are separate scopes and
    start lock-free (their code runs when CALLED, not here)."""
    held_map: dict[int, tuple] = {}
    acquires: list[list] = []

    def walk(stmts: list[ast.stmt], held: tuple) -> None:
        for stmt in stmts:
            held_map[id(stmt)] = held
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                inner = held
                for item in stmt.items:
                    name = _lock_expr_name(item.context_expr)
                    if name is not None:
                        acquires.append([name, stmt.lineno,
                                         sorted(inner)])
                        if name not in inner:
                            inner = inner + (name,)
                walk(stmt.body, inner)
            elif isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                walk(stmt.body, held)
                walk(stmt.orelse, held)
            elif isinstance(stmt, ast.If):
                walk(stmt.body, held)
                walk(stmt.orelse, held)
            elif isinstance(stmt, ast.Try):
                walk(stmt.body, held)
                for h in stmt.handlers:
                    walk(h.body, held)
                walk(stmt.orelse, held)
                walk(stmt.finalbody, held)
            elif isinstance(stmt, ast.Expr) \
                    and isinstance(stmt.value, ast.Call):
                name = call_func_name(stmt.value) or ""
                if name.endswith(".acquire") and not stmt.value.args \
                        and not stmt.value.keywords:
                    base = name[: -len(".acquire")]
                    base = _normalize_lock_base(base)
                    if base is not None:
                        acquires.append([base, stmt.lineno,
                                         sorted(held)])
                        if base not in held:
                            held = held + (base,)
                elif name.endswith(".release"):
                    base = _normalize_lock_base(name[: -len(".release")])
                    if base is not None:
                        held = tuple(h for h in held if h != base)

    walk(body, ())
    return held_map, acquires


def _normalize_lock_base(base: str) -> Optional[str]:
    """'self.X' or bare NAME; anything deeper is not a trackable lock."""
    if base.startswith("self.") and base.count(".") == 1:
        return base
    if base and "." not in base:
        return base
    return None


def _timeout_state(node: ast.Call) -> str:
    for kw in node.keywords:
        if kw.arg == "timeout":
            if isinstance(kw.value, ast.Constant) \
                    and kw.value.value is None:
                return "none"
            return "finite"
    return ""


def _summarize_function(owner: Optional[str], fn, path: str
                        ) -> FunctionSummary:
    params = [a.arg for a in (fn.args.posonlyargs + fn.args.args
                              + fn.args.kwonlyargs)]
    if params and params[0] == "self":
        params = params[1:]
    pidx = {p: i for i, p in enumerate(params)}
    body = fn.body
    scope = TaintScope(body)
    closed, closed_fin, returned, escapes = _binding_annotations(body)

    calls: list[CallSite] = []
    sync_params: set[int] = set()
    device_sync = False
    witness = ""
    derived = _param_derived(body, params)
    returns_resource = False
    held_map, acquires = _held_lock_map(body)

    def record_call(node: ast.Call, depth: int, binding: str,
                    with_item: bool, is_returned: bool,
                    held: tuple = ()) -> Optional[CallSite]:
        name = call_func_name(node)
        if name is None:
            return None
        device_args = [i for i, a in enumerate(node.args)
                       if scope.is_device(a)]
        device_kwargs = [k.arg for k in node.keywords
                         if k.arg and scope.is_device(k.value)]
        param_args = {str(i): pidx[a.id] for i, a in enumerate(node.args)
                      if isinstance(a, ast.Name) and a.id in pidx}
        param_kwargs = {k.arg: pidx[k.value.id] for k in node.keywords
                        if k.arg and isinstance(k.value, ast.Name)
                        and k.value.id in pidx}
        selfattr_args = {str(i): a for i, arg in enumerate(node.args)
                         if (a := self_attribute(arg)) is not None}
        selfattr_kwargs = {k.arg: a for k in node.keywords
                           if k.arg
                           and (a := self_attribute(k.value)) is not None}
        cs = CallSite(
            name=name, line=node.lineno, depth=depth,
            device_args=device_args, device_kwargs=device_kwargs,
            param_args=param_args, param_kwargs=param_kwargs,
            selfattr_args=selfattr_args, selfattr_kwargs=selfattr_kwargs,
            arg_count=len(node.args),
            kwarg_names=[k.arg for k in node.keywords if k.arg],
            held=list(held), timeout_state=_timeout_state(node),
            binding=binding, with_item=with_item, is_returned=is_returned)
        if binding.startswith("local:"):
            n = binding.split(":", 1)[1]
            cs.bound_closed = closed.get(n, False)
            cs.bound_closed_finally = closed_fin.get(n, False)
            cs.bound_returned = n in returned
            cs.bound_escapes = n in escapes
        calls.append(cs)
        return cs

    for stmt, depth in scope_statements(body):
        # How does this statement bind call results?
        bindings: dict[int, tuple[str, bool, bool]] = {}
        if isinstance(stmt, ast.Assign) and isinstance(stmt.value, ast.Call):
            t = stmt.targets[0]
            if isinstance(t, ast.Name):
                bindings[id(stmt.value)] = (f"local:{t.id}", False, False)
            elif self_attribute(t) is not None:
                bindings[id(stmt.value)] = \
                    (f"self:{self_attribute(t)}", False, False)
            else:
                bindings[id(stmt.value)] = ("other", False, False)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                ce = item.context_expr
                if isinstance(ce, ast.Call):
                    bindings[id(ce)] = ("other", True, False)
        elif isinstance(stmt, ast.Return) and isinstance(stmt.value,
                                                         ast.Call):
            bindings[id(stmt.value)] = ("other", False, True)

        stmt_held = held_map.get(id(stmt), ())
        for node in statement_exprs(stmt):
            if not isinstance(node, ast.Call):
                continue
            binding, with_item, is_ret = bindings.get(
                id(node), ("bare" if isinstance(stmt, ast.Expr)
                           and stmt.value is node else "other",
                           False, False))
            cs = record_call(node, depth, binding, with_item, is_ret,
                             held=stmt_held)
            subject = _sync_subject(node)
            if subject is not None:
                names = _names_in(subject)
                hit = {pidx[p] for p in pidx if p in names}
                if not hit and names & derived:
                    hit = set(pidx.values())
                if hit:
                    sync_params |= hit
                    if not witness:
                        witness = f"{path}:{node.lineno}"
                if scope.is_device(subject):
                    device_sync = True
                    witness = witness or f"{path}:{node.lineno}"
                    if cs is not None:
                        cs.blocking_kind = "sync"
        if isinstance(stmt, ast.Return) and stmt.value is not None:
            if isinstance(stmt.value, ast.Call):
                rn = call_func_name(stmt.value) or ""
                if rn in RESOURCE_NAMES \
                        or rn.rsplit(".", 1)[-1] in RESOURCE_LEAFS:
                    returns_resource = True

    writes, write_params = _extract_writes(body, params)
    name = fn.name if owner is None else f"{owner}.{fn.name}"
    return FunctionSummary(
        name=name, line=fn.lineno, params=params, calls=calls,
        sync_params=sorted(sync_params), device_sync=device_sync,
        sync_witness=witness, writes=writes, write_params=write_params,
        returns_resource=returns_resource, acquires=acquires)


def _summarize_class(cls: ast.ClassDef) -> ClassSummary:
    methods = {n.name: n for n in cls.body
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
    lock_attrs: set[str] = set()
    lock_types: dict[str, str] = {}
    for fn in methods.values():
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call):
                leaf = (call_func_name(node.value) or "").rsplit(".", 1)[-1]
                if leaf in _LOCK_TYPES:
                    for t in node.targets:
                        attr = self_attribute(t)
                        if attr:
                            lock_attrs.add(attr)
                            lock_types[attr] = leaf
    # Worker entrypoints, PML005-style (target=, submit, callbacks, a
    # bound method escaping into a constructor).
    eps: set[str] = set()
    for node in ast.walk(cls):
        if not isinstance(node, ast.Call):
            continue
        for kw in node.keywords:
            if kw.arg == "target":
                eps |= {a for n in ast.walk(kw.value)
                        if (a := self_attribute(n)) is not None}
        name = call_func_name(node) or ""
        leaf = name.rsplit(".", 1)[-1]
        if leaf in ("submit", "map", "apply_async",
                    "add_done_callback") and node.args:
            eps |= {a for n in ast.walk(node.args[0])
                    if (a := self_attribute(n)) is not None}
    eps &= set(methods)

    infos: dict[str, MethodInfo] = {}
    for mname, fn in methods.items():
        writes: list[list] = []

        def visit(node: ast.AST, locked: bool) -> None:
            if isinstance(node, (ast.With, ast.AsyncWith)):
                now = locked or any(
                    self_attribute(i.context_expr) in lock_attrs
                    for i in node.items)
                for c in node.body:
                    visit(c, now)
                return
            if isinstance(node, (ast.Assign, ast.AugAssign,
                                 ast.AnnAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    attr = self_attribute(t)
                    if attr is None and isinstance(t, ast.Subscript):
                        attr = self_attribute(t.value)
                    if attr is not None:
                        writes.append([attr, node.lineno, locked])
            for child in ast.iter_child_nodes(node):
                if not isinstance(child, ast.expr) \
                        or isinstance(child, (ast.With, ast.AsyncWith)):
                    visit(child, locked)

        for stmt in fn.body:
            visit(stmt, False)
        touched = sorted({a for n in ast.walk(fn)
                          if (a := self_attribute(n)) is not None})
        self_calls = sorted({a for n in ast.walk(fn)
                             if isinstance(n, ast.Call)
                             and (a := self_attribute(n.func)) is not None
                             and a in methods})
        params = [a.arg for a in (fn.args.posonlyargs + fn.args.args
                                  + fn.args.kwonlyargs)
                  if a.arg != "self"]
        stores = {}
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id in params:
                for t in node.targets:
                    attr = self_attribute(t)
                    if attr:
                        stores[node.value.id] = attr
        invokes = sorted({a for n in ast.walk(fn)
                          if isinstance(n, ast.Call)
                          and (a := self_attribute(n.func)) is not None})
        closes = sorted({
            self_attribute(n.func.value)
            for n in ast.walk(fn)
            if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)
            and n.func.attr in CLOSER_LEAFS
            and self_attribute(n.func.value) is not None})
        infos[mname] = MethodInfo(
            name=mname, writes=writes, touched=touched,
            self_calls=self_calls, stores_params=stores,
            invokes_attrs=invokes, closes_attrs=closes)
    init = methods.get("__init__")
    init_params = []
    if init is not None:
        init_params = [a.arg for a in (init.args.posonlyargs
                                       + init.args.args
                                       + init.args.kwonlyargs)
                       if a.arg != "self"]
    return ClassSummary(name=cls.name, line=cls.lineno, methods=infos,
                        lock_attrs=sorted(lock_attrs),
                        entrypoints=sorted(eps), init_params=init_params,
                        lock_types=lock_types)


def _extract_imports(tree: ast.Module, module: str) -> dict[str, str]:
    out: dict[str, str] = {}
    pkg_parts = module.split(".")[:-1] if module else []
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                name = alias.asname or alias.name.split(".", 1)[0]
                out[name] = alias.name if alias.asname else \
                    alias.name.split(".", 1)[0]
                if alias.asname:
                    out[alias.asname] = alias.name
        elif isinstance(node, ast.ImportFrom):
            base = node.module or ""
            if node.level:
                up = pkg_parts[: len(pkg_parts) - (node.level - 1)]
                base = ".".join(up + ([base] if base else []))
            for alias in node.names:
                if alias.name == "*":
                    continue
                out[alias.asname or alias.name] = \
                    f"{base}.{alias.name}" if base else alias.name
    return out


def _fstring_leading(node: ast.JoinedStr) -> tuple[str, bool]:
    """(leading constant text, fully_static) of an f-string."""
    if not node.values:
        return "", True
    first = node.values[0]
    if not (isinstance(first, ast.Constant)
            and isinstance(first.value, str)):
        return "", False
    return first.value, len(node.values) == 1


_METRIC_RE = re.compile(r"^photon_[a-z0-9_]*[a-z0-9]")
_METRIC_FULL_RE = re.compile(r"^photon_[a-z0-9_]*[a-z0-9]$")
_CAMEL_RE = re.compile(r"^[A-Z][a-z]+(?:[A-Z][a-z]+)+$")
_DOTTED_RE = re.compile(r"^[a-z][a-z0-9_]*(?:\.[a-z0-9_]+)+$")


def _extract_string_facts(tree: ast.Module, summary: "FileSummary",
                          func_of: dict[int, str]) -> None:
    """Fault-site / metric / span / event string usage, for PML014 and
    the ``--catalog`` emission."""

    dyn_span_fns: set[str] = set()
    dotted_by_fn: dict[str, list] = {}

    def add_metric_def(text: str, line: int, fully_static: bool) -> None:
        m = _METRIC_RE.match(text)
        if not m:
            return
        name = m.group(0)
        rest = text[len(name):]
        if fully_static or (rest and rest[0] in " {"):
            # The name ends at a render boundary: exact.
            summary.metric_defs.append([name, line, True])
        else:
            # The leading constant runs straight into a dynamic part
            # (f"photon_serving_{name}_..."): a prefix family.
            summary.metric_defs.append([text, line, False])

    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            name = call_func_name(node) or ""
            leaf = name.rsplit(".", 1)[-1]
            if leaf in _FAULT_HOOKS and node.args:
                a0 = node.args[0]
                if isinstance(a0, ast.Constant) \
                        and isinstance(a0.value, str):
                    summary.site_literals.append(
                        [a0.value, node.lineno, leaf])
            if leaf == "FaultSpec":
                site = node.args[0] if node.args else None
                for k in node.keywords:
                    if k.arg == "site":
                        site = k.value
                if isinstance(site, ast.Constant) \
                        and isinstance(site.value, str):
                    summary.site_literals.append(
                        [site.value, node.lineno, "FaultSpec"])
            if leaf in _METRIC_FACTORIES and node.args \
                    and isinstance(node.func, ast.Attribute):
                a0 = node.args[0]
                if isinstance(a0, ast.Constant) \
                        and isinstance(a0.value, str):
                    add_metric_def(a0.value, node.lineno, True)
                elif isinstance(a0, ast.JoinedStr):
                    text, full = _fstring_leading(a0)
                    add_metric_def(text, node.lineno, full)
            if leaf in ("span", "record_complete") and node.args \
                    and isinstance(node.func, ast.Attribute):
                a0 = node.args[0]
                if isinstance(a0, ast.Constant) \
                        and isinstance(a0.value, str):
                    summary.span_defs.append([a0.value, node.lineno])
                elif isinstance(a0, ast.Name):
                    # Span name fed from a variable: the function's
                    # dotted literals (a stage-name tuple, say) are the
                    # candidate names — collected below.
                    dyn_span_fns.add(func_of.get(id(node), ""))
        elif isinstance(node, ast.Dict):
            # {"site": "..."} literals (fault plans built as dicts) and
            # event-name -> photon_* counter maps (the bridge).
            vals = [v for v in node.values
                    if isinstance(v, ast.Constant)
                    and isinstance(v.value, str)]
            str_vals = [v.value for v in vals]
            for k, v in zip(node.keys, node.values):
                if isinstance(k, ast.Constant) and k.value == "site" \
                        and isinstance(v, ast.Constant) \
                        and isinstance(v.value, str):
                    summary.site_literals.append(
                        [v.value, v.lineno, "dict"])
            if str_vals and len(str_vals) == len(node.values) \
                    and all(_METRIC_FULL_RE.match(v) for v in str_vals):
                keys = [k for k in node.keys
                        if isinstance(k, ast.Constant)
                        and isinstance(k.value, str)]
                # Only the bridge shape (CamelCase event-class keys) is
                # held to the event catalog; a bench-name -> metric map
                # is a different, legitimate dict.
                if keys and all(_CAMEL_RE.match(k.value) for k in keys):
                    for k in keys:
                        summary.event_maps.append([k.value, k.lineno])
                for v in vals:
                    add_metric_def(v.value, v.lineno, True)
        elif isinstance(node, ast.Compare):
            for cmp_ in node.comparators:
                if isinstance(cmp_, ast.Constant) \
                        and isinstance(cmp_.value, str) \
                        and _CAMEL_RE.match(cmp_.value):
                    summary.event_compares.append(
                        [cmp_.value, cmp_.lineno,
                         func_of.get(id(node), "")])
        elif isinstance(node, ast.Constant) and isinstance(node.value, str):
            if _METRIC_FULL_RE.match(node.value):
                summary.metric_refs.append([node.value, node.lineno])
            if _DOTTED_RE.match(node.value):
                dotted_by_fn.setdefault(
                    func_of.get(id(node), ""), []).append(
                        [node.value, node.lineno])
        elif isinstance(node, ast.JoinedStr):
            text, full = _fstring_leading(node)
            if _METRIC_RE.match(text):
                add_metric_def(text, node.lineno, full)

    for fn in dyn_span_fns:
        summary.span_defs.extend(dotted_by_fn.get(fn, []))


def summarize_file(path: str, tree: ast.Module,
                   source: str = "") -> FileSummary:
    module = _module_name(path)
    summary = FileSummary(
        path=path, module=module,
        imports=_extract_imports(tree, module),
        functions={}, classes={}, crash_module=False,
        site_literals=[], metric_defs=[], metric_refs=[],
        span_defs=[], event_classes=[], event_maps=[],
        event_compares=[], registry_constants={}, module_locks={})

    # Map expression nodes to the function that owns them (for the
    # event-compare heuristic's per-function grouping).
    func_of: dict[int, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for sub in ast.walk(node):
                func_of.setdefault(id(sub), node.name)

    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fs = _summarize_function(None, node, path)
            summary.functions[fs.name] = fs
        elif isinstance(node, ast.ClassDef):
            summary.classes[node.name] = _summarize_class(node)
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                    fs = _summarize_function(node.name, sub, path)
                    summary.functions[fs.name] = fs
            if any((dotted_name(b) or "").rsplit(".", 1)[-1] == "Event"
                   for b in node.bases):
                summary.event_classes.append(node.name)
        elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, str) \
                and node.targets[0].id.isupper():
            summary.registry_constants[node.targets[0].id] = \
                node.value.value
        elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Call):
            leaf = (call_func_name(node.value) or "").rsplit(".", 1)[-1]
            if leaf in _LOCK_TYPES:
                summary.module_locks[node.targets[0].id] = leaf

    imported = set(summary.imports.values())
    # Importing the atomic-write module IS the marker-protocol opt-in:
    # a module that writes through utils/diskio participates in the
    # .ok/CRC commit discipline, so PML013 holds it to it everywhere.
    summary.crash_module = any(
        t.endswith(".diskio") or ".diskio." in t for t in imported)
    _extract_string_facts(tree, summary, func_of)
    return summary


# ----------------------------------------------------------------- graph


class ProjectGraph:
    """Resolved view over one :class:`FileSummary` per file."""

    def __init__(self, files: dict[str, FileSummary],
                 package_prefix: str = "photon_ml_tpu"):
        self.files = files
        if os.path.isabs(package_prefix):
            # Summaries carry cwd-relative paths; match the prefix in
            # the same coordinate system.
            package_prefix = os.path.relpath(package_prefix)
        self.package_prefix = \
            package_prefix.replace(os.sep, "/").rstrip("/")
        self.modules: dict[str, FileSummary] = {}
        for fs in files.values():
            self.modules[fs.module] = fs
        # Unique-method fallback index: leaf name -> [(file, qname)].
        self._method_index: dict[str, list[tuple[str, str]]] = {}
        self._class_index: dict[str, list[tuple[str, str]]] = {}
        for fs in files.values():
            for qname in fs.functions:
                leaf = qname.rsplit(".", 1)[-1]
                self._method_index.setdefault(leaf, []).append(
                    (fs.path, qname))
            for cname in fs.classes:
                self._class_index.setdefault(cname, []).append(
                    (fs.path, cname))

    # -- membership --------------------------------------------------------

    def is_package_file(self, path: str) -> bool:
        return path.replace(os.sep, "/").startswith(
            self.package_prefix + "/") or path == self.package_prefix

    def package_files(self) -> list[FileSummary]:
        return [fs for fs in self.files.values()
                if self.is_package_file(fs.path)]

    # -- resolution --------------------------------------------------------

    def _module_for(self, dotted: str) -> Optional[FileSummary]:
        return self.modules.get(dotted)

    def _lookup_symbol(self, fs: FileSummary, symbol: str
                      ) -> Optional[tuple[FileSummary, str]]:
        """symbol inside module fs: function, class (-> __init__), or a
        re-exported import."""
        if symbol in fs.functions:
            return fs, symbol
        if symbol in fs.classes:
            init = f"{symbol}.__init__"
            return fs, init if init in fs.functions else symbol
        target = fs.imports.get(symbol)
        if target:
            mod, _, sym = target.rpartition(".")
            m = self._module_for(target)
            if m is not None:  # a submodule re-export
                return None
            m = self._module_for(mod)
            if m is not None and sym:
                return self._lookup_symbol(m, sym)
        return None

    def resolve_call(self, fs: FileSummary, call: CallSite,
                     caller: Optional[str] = None
                     ) -> Optional[tuple[FileSummary, FunctionSummary]]:
        """The FunctionSummary a call lands on, or None. ``caller`` is
        the calling function's qname (for ``self.m`` resolution)."""
        name = call.name
        parts = name.split(".")
        if parts[0] == "self" and caller and "." in caller:
            cls = caller.split(".", 1)[0]
            q = f"{cls}.{parts[1]}" if len(parts) == 2 else None
            if q and q in fs.functions:
                return fs, fs.functions[q]
            return None
        if len(parts) == 1:
            hit = self._lookup_symbol(fs, parts[0])
            if hit and hit[1] in hit[0].functions:
                return hit[0], hit[0].functions[hit[1]]
            return None
        # alias.attr... : resolve the longest module prefix.
        target = fs.imports.get(parts[0])
        if target is not None:
            rest = parts[1:]
            # try alias->module, then alias.sub->module, deepest first
            cands = []
            for i in range(len(rest), -1, -1):
                mod = ".".join([target] + rest[:i])
                m = self._module_for(mod)
                if m is not None and i < len(rest):
                    cands.append((m, rest[i:]))
                    break
            for m, tail in cands:
                if len(tail) == 1:
                    hit = self._lookup_symbol(m, tail[0])
                    if hit and hit[1] in hit[0].functions:
                        return hit[0], hit[0].functions[hit[1]]
                elif len(tail) == 2 and tail[0] in m.classes:
                    q = ".".join(tail)
                    if q in m.functions:
                        return m, m.functions[q]
            if cands:
                return None
        # Conservative fallback: obj.method() with a UNIQUE method name
        # across the project (two candidates = no edge).
        leaf = parts[-1]
        cands = [(p, q) for p, q in self._method_index.get(leaf, ())
                 if "." in q]  # methods only — free functions need imports
        if len(cands) == 1:
            p, q = cands[0]
            m = self.files[p]
            return m, m.functions[q]
        return None

    def resolve_class(self, fs: FileSummary, name: str
                      ) -> Optional[tuple[FileSummary, ClassSummary]]:
        parts = name.split(".")
        if len(parts) == 1:
            if parts[0] in fs.classes:
                return fs, fs.classes[parts[0]]
            target = fs.imports.get(parts[0])
            if target:
                mod, _, sym = target.rpartition(".")
                m = self._module_for(mod)
                if m is not None and sym in m.classes:
                    return m, m.classes[sym]
            return None
        target = fs.imports.get(parts[0])
        if target is not None:
            for i in range(len(parts) - 1, 0, -1):
                mod = ".".join([target] + parts[1:i])
                m = self._module_for(mod)
                if m is not None and parts[i] in m.classes \
                        and i == len(parts) - 1:
                    return m, m.classes[parts[i]]
        return None

    # -- catalogs ----------------------------------------------------------

    def fault_site_registry(self) -> dict[str, str]:
        """site string -> constant name, from faults/sites.py-shaped
        registry modules (empty when the graph has none)."""
        out: dict[str, str] = {}
        for fs in self.files.values():
            if fs.path.replace(os.sep, "/").endswith("faults/sites.py"):
                for k, v in fs.registry_constants.items():
                    out[v] = k
        return out

    def event_catalog(self) -> set[str]:
        out: set[str] = set()
        for fs in self.files.values():
            if fs.path.replace(os.sep, "/").endswith("events.py"):
                out |= set(fs.event_classes)
        return out

    def metric_catalog(self) -> tuple[set[str], set[str]]:
        """(exact names, dynamic prefixes) defined by package files."""
        exact: set[str] = set()
        prefixes: set[str] = set()
        for fs in self.package_files():
            for name, _line, is_exact in fs.metric_defs:
                (exact if is_exact else prefixes).add(name)
        return exact, prefixes

    def span_catalog(self) -> set[str]:
        out: set[str] = set()
        for fs in self.package_files():
            out |= {name for name, _line in fs.span_defs}
        return out


def build_catalog(graph: ProjectGraph) -> dict:
    """The ``photon-lint --catalog`` payload: every string-keyed seam's
    registry, as JSON for docs and CI to consume."""
    registry = graph.fault_site_registry()
    exact, prefixes = graph.metric_catalog()
    return {
        "fault_sites": {site: registry[site] for site in sorted(registry)},
        "events": sorted(graph.event_catalog()),
        "metrics": {"exact": sorted(exact),
                    "prefixes": sorted(prefixes)},
        "spans": sorted(graph.span_catalog()),
    }


# ----------------------------------------------------------------- cache


CACHE_VERSION = 4  # v4: lock-context summary fields (PML018/PML019)
DEFAULT_CACHE = ".photon-lint-cache.json"


def _file_key(path: str) -> Optional[list]:
    try:
        st = os.stat(path)
        with open(path, "rb") as f:
            crc = zlib.crc32(f.read()) & 0xFFFFFFFF
        return [st.st_size, st.st_mtime_ns, crc]
    except OSError:
        return None


def analysis_signature() -> str:
    """CRC over the analysis package's own sources — a rule edit must
    invalidate every cached summary and finding."""
    root = os.path.dirname(os.path.abspath(__file__))
    crc = 0
    for sub, _dirs, names in sorted(os.walk(root)):
        for n in sorted(names):
            if n.endswith(".py"):
                with open(os.path.join(sub, n), "rb") as f:
                    crc = zlib.crc32(f.read(), crc)
    return f"{CACHE_VERSION}:{crc & 0xFFFFFFFF:08x}"


class ProjectCache:
    """mtime/CRC-keyed store of per-file summaries + per-file-rule
    findings, fenced by :func:`analysis_signature`."""

    def __init__(self, path: str = DEFAULT_CACHE):
        self.path = path
        self.signature = analysis_signature()
        self._entries: dict[str, dict] = {}
        self.hits = 0
        self.misses = 0
        self._load()

    def _load(self) -> None:
        try:
            with open(self.path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return
        if doc.get("signature") != self.signature:
            return  # rules changed: every entry is stale
        self._entries = doc.get("files", {})

    def lookup(self, path: str) -> Optional[dict]:
        entry = self._entries.get(path)
        if entry is None:
            self.misses += 1
            return None
        if entry.get("key") != _file_key(path):
            self.misses += 1
            return None
        self.hits += 1
        return entry

    def store(self, path: str, summary: Optional[FileSummary],
              findings: list, unused: list, suppressions: list) -> None:
        self._entries[path] = {
            "key": _file_key(path),
            "summary": (summary_to_dict(summary)
                        if summary is not None else None),
            "findings": findings,
            "unused": unused,
            "suppressions": suppressions,
        }

    def save(self, live_paths: Iterable[str]) -> None:
        live = set(live_paths)
        doc = {"signature": self.signature,
               "files": {p: e for p, e in self._entries.items()
                         if p in live}}
        tmp = self.path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(doc, f)
            os.replace(tmp, self.path)
        except OSError:
            pass  # a read-only checkout still lints, just never warm


# -------------------------------------------------- summary (de)serialize


def summary_to_dict(s: FileSummary) -> dict:
    return dataclasses.asdict(s)


def summary_from_dict(d: dict) -> FileSummary:
    fns = {}
    for q, f in d.get("functions", {}).items():
        f = dict(f)
        f["calls"] = [CallSite(**c) for c in f.get("calls", [])]
        f["writes"] = [WriteSite(**w) for w in f.get("writes", [])]
        fns[q] = FunctionSummary(**f)
    classes = {}
    for n, c in d.get("classes", {}).items():
        c = dict(c)
        c["methods"] = {m: MethodInfo(**mi)
                        for m, mi in c.get("methods", {}).items()}
        classes[n] = ClassSummary(**c)
    d = dict(d)
    d["functions"] = fns
    d["classes"] = classes
    return FileSummary(**d)
