"""PML005 — a lightweight intra-class race detector.

The bug class: a class starts worker threads (``threading.Thread``,
executor ``submit``/``map``, future callbacks) and some attribute write
reachable from a worker entrypoint happens OUTSIDE the class's lock while
the same attribute is read from the caller side — the staging/serving
threading seams PR 1/PR 2 debugged dynamically, made a lint query:

1. find the class's lock attributes (``self._lock = threading.Lock()`` /
   ``RLock`` / ``Condition``);
2. find its thread/worker ENTRYPOINTS (``target=self.m``,
   ``pool.submit(self.m, …)``, ``fut.add_done_callback(self.m)``,
   ``Executor.map(self.m, …)``, ``self.m`` handed to a constructor);
3. close the ``self.m()`` call graph over the entrypoints (nested
   callback defs count as part of their enclosing method);
4. flag every ``self.attr = …`` (or ``self.attr[i] = …``) in the
   reachable set that is not dominated by ``with self.<lock>:`` — when
   the attribute is also touched by a method OUTSIDE the reachable set,
   i.e. actually shared with the caller thread.

Single-writer seams published through ``threading.Event`` are real and
safe — that is what inline suppressions with reasons are for; the lint's
job is to make the invariant visible, not to forbid the pattern.
"""

from __future__ import annotations

import ast
from typing import Optional

from photon_ml_tpu.analysis.context import ModuleContext
from photon_ml_tpu.analysis.findings import Finding
from photon_ml_tpu.analysis.rules._walk import self_attribute
from photon_ml_tpu.analysis.taint import call_func_name

_LOCK_TYPES = {"Lock", "RLock", "Condition"}


def _method_map(cls: ast.ClassDef) -> dict[str, ast.FunctionDef]:
    return {n.name: n for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}


def _lock_attrs(methods: dict[str, ast.FunctionDef]) -> set[str]:
    out = set()
    for fn in methods.values():
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call):
                leaf = (call_func_name(node.value) or "").rsplit(".", 1)[-1]
                if leaf in _LOCK_TYPES:
                    for t in node.targets:
                        attr = self_attribute(t)
                        if attr:
                            out.add(attr)
    return out


def _self_methods_in(node: ast.AST) -> set[str]:
    """Method names referenced as ``self.m`` anywhere under ``node``
    (unwraps functools.partial by just walking everything)."""
    return {attr for n in ast.walk(node)
            if (attr := self_attribute(n)) is not None}


def _entrypoints(cls: ast.ClassDef,
                 methods: dict[str, ast.FunctionDef]) -> set[str]:
    eps: set[str] = set()
    for node in ast.walk(cls):
        if not isinstance(node, ast.Call):
            continue
        # target=self.m (Thread/Timer/anything with a worker target)
        for kw in node.keywords:
            if kw.arg == "target":
                eps |= _self_methods_in(kw.value)
        name = call_func_name(node) or ""
        leaf = name.rsplit(".", 1)[-1]
        if leaf in ("submit", "map", "apply_async") and node.args:
            eps |= _self_methods_in(node.args[0])
        if leaf == "add_done_callback" and node.args:
            eps |= _self_methods_in(node.args[0])
        # self.m handed to a constructor (e.g. MicroBatcher(self._flush)):
        # conservatively treat a bound method escaping into another
        # object as a worker entrypoint.
        if leaf[:1].isupper():
            for a in node.args:
                attr = self_attribute(a)
                if attr:
                    eps.add(attr)
    return {e for e in eps if e in methods}


def _reachable(methods: dict[str, ast.FunctionDef],
               roots: set[str]) -> set[str]:
    seen = set()
    frontier = list(roots)
    while frontier:
        m = frontier.pop()
        if m in seen or m not in methods:
            continue
        seen.add(m)
        for node in ast.walk(methods[m]):
            if isinstance(node, ast.Call):
                attr = self_attribute(node.func)
                if attr and attr in methods and attr not in seen:
                    frontier.append(attr)
    return seen


def _written_attr(target: ast.AST) -> Optional[str]:
    """self.X = …  or  self.X[i] = …  → 'X'."""
    attr = self_attribute(target)
    if attr is not None:
        return attr
    if isinstance(target, ast.Subscript):
        return self_attribute(target.value)
    return None


def _touched_attrs(fn: ast.FunctionDef) -> set[str]:
    return {attr for n in ast.walk(fn)
            if (attr := self_attribute(n)) is not None}


def _collect_writes(fn: ast.FunctionDef, lock_attrs: set[str]
                    ) -> list[tuple[str, ast.stmt, bool]]:
    """(attr, node, dominated_by_lock) for every self-attribute write in
    ``fn``, nested defs included (callbacks run on worker threads too)."""
    out: list[tuple[str, ast.stmt, bool]] = []

    def visit(node: ast.AST, locked: bool) -> None:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            now_locked = locked or any(
                self_attribute(item.context_expr) in lock_attrs
                for item in node.items)
            for child in node.body:
                visit(child, now_locked)
            return
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                attr = _written_attr(t)
                if attr is not None:
                    out.append((attr, node, locked))
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.With, ast.AsyncWith)):
                visit(child, locked)
            elif not isinstance(child, ast.expr):
                visit(child, locked)

    for stmt in fn.body:
        visit(stmt, False)
    return out


def check_unguarded_shared_state(ctx: ModuleContext) -> list[Finding]:
    out = []
    for cls in ast.walk(ctx.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        methods = _method_map(cls)
        eps = _entrypoints(cls, methods)
        if not eps:
            continue
        locks = _lock_attrs(methods)
        reachable = _reachable(methods, eps)
        outside = {name: fn for name, fn in methods.items()
                   if name not in reachable and name != "__init__"}
        shared_attrs = set()
        for fn in outside.values():
            shared_attrs |= _touched_attrs(fn)
        for name in sorted(reachable):
            if name == "__init__":
                continue  # runs before any worker thread exists
            for attr, node, locked in _collect_writes(methods[name],
                                                      locks):
                if locked or attr in locks or attr not in shared_attrs:
                    continue
                why = (f"held lock (class locks: "
                       f"{', '.join(sorted('self.' + a for a in locks))})"
                       if locks else
                       "any lock (the class defines none)")
                out.append(ctx.finding(
                    "PML005", node,
                    f"{cls.name}.{name}() runs on a worker thread "
                    f"(entrypoints: {', '.join(sorted(eps))}) and writes "
                    f"self.{attr} — also used from caller-side methods — "
                    f"without {why}"))
    return out
