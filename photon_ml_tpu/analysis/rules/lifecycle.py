"""PML007 — unbalanced lifecycle events.

The events module's contract (utils/events.py) is balanced scopes: every
``*Start`` emit eventually gets its ``*Finish``, or listeners tracking
open scopes (progress reporting, audit logs) leak one forever. The bug
shape is an emit pair in one function with an exception path between
them: the Start fires, the body raises, the Finish never does. The rule:

- a ``*Start`` emit whose matching ``*Finish`` is emitted in the SAME
  function must have that Finish inside a ``finally`` block that covers
  the region after the Start — otherwise any raise in between leaks the
  scope;
- a ``*Start`` with no matching ``*Finish`` anywhere in the module is
  flagged outright (object-lifetime pairs that span methods — Start in
  ``__init__``, Finish in ``close()`` — match at module scope and are
  fine).
"""

from __future__ import annotations

import ast
import re
from typing import Optional

from photon_ml_tpu.analysis.context import ModuleContext
from photon_ml_tpu.analysis.findings import Finding
from photon_ml_tpu.analysis.taint import dotted_name, function_bodies

_START_RE = re.compile(r"(\w+)Start$")
_FINISH_RE = re.compile(r"(\w+)Finish$")


def _emitted_event(node: ast.AST) -> Optional[str]:
    """'StagingStart' when node is ``<anything>.emit(StagingStart(...))``
    or ``emit(ev.StagingStart(...))``; else None."""
    if not isinstance(node, ast.Call):
        return None
    func = node.func
    leaf = (dotted_name(func) or "").rsplit(".", 1)[-1]
    if leaf != "emit" or not node.args:
        return None
    arg = node.args[0]
    if not isinstance(arg, ast.Call):
        return None
    return (dotted_name(arg.func) or "").rsplit(".", 1)[-1]


def _scan_emits(root: ast.AST) -> list[tuple[str, ast.Call]]:
    return [(name, node) for node in ast.walk(root)
            if (name := _emitted_event(node)) is not None]


def _finally_protected(fn_body: list[ast.stmt], start: ast.Call,
                       finish: ast.Call) -> bool:
    """True when ``finish`` sits in the finalbody of a Try and ``start``
    is not lexically after that Try (so every path from the Start's
    region runs the Finish)."""
    for node in ast.walk(ast.Module(body=fn_body, type_ignores=[])):
        if not isinstance(node, ast.Try) or not node.finalbody:
            continue
        in_final = any(finish is n for s in node.finalbody
                       for n in ast.walk(s))
        if in_final and start.lineno <= node.end_lineno:
            return True
    return False


def check_unbalanced_lifecycle(ctx: ModuleContext) -> list[Finding]:
    module_finishes = {m.group(1) for name, _ in _scan_emits(ctx.tree)
                       if (m := _FINISH_RE.match(name))}
    out = []
    for owner, body in function_bodies(ctx.tree):
        if isinstance(owner, ast.Module):
            continue
        emits = _scan_emits(owner)
        starts = [(m.group(1), node) for name, node in emits
                  if (m := _START_RE.match(name))]
        finishes = {m.group(1): node for name, node in emits
                    if (m := _FINISH_RE.match(name))}
        for prefix, snode in starts:
            fnode = finishes.get(prefix)
            if fnode is not None:
                if not _finally_protected(owner.body, snode, fnode):
                    out.append(ctx.finding(
                        "PML007", snode,
                        f"{prefix}Start is emitted here but the matching "
                        f"{prefix}Finish in {owner.name}() is not "
                        f"finally-guaranteed — a raise in between leaks "
                        f"the scope; move the Finish emit into a "
                        f"finally block"))
            elif prefix not in module_finishes:
                out.append(ctx.finding(
                    "PML007", snode,
                    f"{prefix}Start is emitted but no {prefix}Finish "
                    f"emit exists in this module — every lifecycle "
                    f"scope needs a guaranteed close"))
    return out
