"""PML011 — blocking network call without an explicit timeout.

The fleet (serving/router.py, serving/supervisor.py) made the repo a
distributed system: routers forward over HTTP, supervisors probe
replicas, and every one of those calls BLOCKS a thread. A blocking
socket/HTTP call without a timeout turns a dead peer into a hung
thread — the exact failure mode the heartbeat-deadline machinery exists
to prevent, reintroduced one layer down. The degradation ladder
(docs/ROBUSTNESS.md) demands "never hang"; this rule mechanizes it the
way PML004 mechanized wall-clock durations:

- ``urllib.request.urlopen(...)`` must pass ``timeout=`` (or the third
  positional argument);
- ``socket.create_connection(...)`` must pass ``timeout=`` (or the
  second positional);
- ``http.client.HTTPConnection(...)`` / ``HTTPSConnection(...)`` must
  pass ``timeout=`` (or the third positional);
- ``requests.get/post/...`` must pass ``timeout=`` (requests never
  times out by default — the classic production hang);
- ``sock.settimeout(None)`` / an explicit ``timeout=None`` literal is
  ALSO a finding — including on the waiting primitives
  ``Future.result(timeout=None)``, ``x.wait(timeout=None)`` and
  ``queue.get(timeout=None)``: deliberately unbounded blocking needs a
  ``# pml: allow[PML011] <reason>`` stating why a hang is acceptable.

The call *shapes* and timeout positions live in
:mod:`photon_ml_tpu.analysis.blocking`, shared with PML019
(blocking-under-lock) so the two rules agree forever on what counts as
bounded; when a site is both lockless-unbounded AND under a lock, the
engine keeps only the PML019 finding (one finding per site).

Sites with a genuinely unbounded contract (an interactive REPL, a
drain-forever worker) carry the inline allow like every other rule.
"""

from __future__ import annotations

import ast

from photon_ml_tpu.analysis.blocking import WAIT_CALLS, net_spec
from photon_ml_tpu.analysis.context import ModuleContext
from photon_ml_tpu.analysis.findings import Finding
from photon_ml_tpu.analysis.taint import dotted_name


def _timeout_kwarg(node: ast.Call):
    for kw in node.keywords:
        if kw.arg == "timeout":
            return kw
    return None


def _is_none(expr) -> bool:
    return isinstance(expr, ast.Constant) and expr.value is None


def check_blocking_network_timeout(ctx: ModuleContext) -> list[Finding]:
    out = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func) or ""
        leaf = name.rsplit(".", 1)[-1]
        spec = net_spec(name)
        if spec is not None:
            _suffixes, pos = spec
            kw = _timeout_kwarg(node)
            if kw is not None:
                if _is_none(kw.value):
                    out.append(ctx.finding(
                        "PML011", node,
                        f"{name}(timeout=None) blocks unboundedly — a "
                        f"dead peer hangs this thread forever; pass a "
                        f"finite timeout or allow with a reason"))
                continue
            if pos is not None and len(node.args) > pos:
                continue  # timeout rode in positionally
            out.append(ctx.finding(
                "PML011", node,
                f"blocking network call {name}() without an explicit "
                f"timeout — a dead peer hangs this thread forever "
                f"(the never-hang contract, docs/ROBUSTNESS.md); pass "
                f"timeout=..."))
        elif leaf == "settimeout" and node.args \
                and _is_none(node.args[0]):
            out.append(ctx.finding(
                "PML011", node,
                "settimeout(None) puts the socket in unbounded "
                "blocking mode — a dead peer hangs this thread "
                "forever; use a finite timeout or allow with a reason"))
        elif leaf in WAIT_CALLS and "." in name:
            # Waiting primitives only flag the EXPLICIT timeout=None
            # form (a bare .result()/.get() is often join-at-shutdown;
            # under a lock PML019 owns the bare form).
            kw = _timeout_kwarg(node)
            if kw is not None and _is_none(kw.value):
                out.append(ctx.finding(
                    "PML011", node,
                    f"{name}(timeout=None) waits unboundedly — a "
                    f"wedged producer hangs this thread forever; pass "
                    f"a finite timeout or allow with a reason"))
    return out
