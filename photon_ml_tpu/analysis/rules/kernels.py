"""PML017 — the fused-kernel seam (docs/KERNELS.md).

Every Pallas program in this repo lives in ``ops/kernels/`` behind the
:class:`~photon_ml_tpu.ops.kernels.registry.KernelRegistry`: a per-kernel
flag, an XLA reference closure, an interpret-mode CPU path, and the loud
degradation ladder. A ``pl.pallas_call`` anywhere else bypasses all four
— no flag to turn it off when the sweep stops justifying it, no
reference for parity tests, no CPU smoke coverage, and a silent crash
instead of a KernelFallback when the backend can't run it.
"""

from __future__ import annotations

import ast

from photon_ml_tpu.analysis.context import ModuleContext
from photon_ml_tpu.analysis.findings import Finding
from photon_ml_tpu.analysis.taint import call_func_name

_KERNEL_HOME = "photon_ml_tpu/ops/kernels/"


def check_kernel_seam(ctx: ModuleContext) -> list[Finding]:
    """A direct ``pallas_call`` outside ``ops/kernels/`` dodges the
    registry's flag/fallback/parity/interpret contract."""
    if ctx.path.startswith(_KERNEL_HOME):
        return []
    out = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = call_func_name(node)
        if name is not None and name.rsplit(".", 1)[-1] == "pallas_call":
            out.append(ctx.finding(
                "PML017", node,
                f"direct {name}(...) outside {_KERNEL_HOME}: fused "
                f"programs must register in ops/kernels/__init__.py "
                f"(flag + XLA reference + interpret path + loud "
                f"fallback) and call sites must resolve through the "
                f"registry (docs/KERNELS.md)"))
    return out
