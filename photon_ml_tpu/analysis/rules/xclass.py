"""PML015 — PML005's race detector lifted across class boundaries.

PML005 finds a class's OWN worker seams (``target=self.m``, pool
submits) and flags unlocked writes reachable from them. The fleet era
added a topology it cannot see: a bound method handed ACROSS a class
boundary — ``ReplicaSupervisor(..., on_death=self._on_death)`` — runs
on the *other* object's monitor thread, so every write it makes back
into its own object's state is a cross-thread write, with no
``Thread(...)`` anywhere near the caller's class to tip PML005 off.

The project graph closes the loop: a class summary knows which of its
constructor parameters are stored and later INVOKED from a method
reachable from its own worker entrypoints ("worker-invoked callback
params"). Any ``self.m`` passed into such a parameter makes ``m`` a
worker entrypoint of the *calling* class, and the PML005 write
discipline applies to everything reachable from it: writes to state
shared with caller-side methods must hold the class lock or carry a
reasoned ``# pml: allow[PML015]``.
"""

from __future__ import annotations

from photon_ml_tpu.analysis.findings import Finding
from photon_ml_tpu.analysis.project import ClassSummary, ProjectGraph


def _closure(cls: ClassSummary, roots: set[str]) -> set[str]:
    seen: set[str] = set()
    frontier = [r for r in roots if r in cls.methods]
    while frontier:
        m = frontier.pop()
        if m in seen:
            continue
        seen.add(m)
        for callee in cls.methods[m].self_calls:
            if callee not in seen and callee in cls.methods:
                frontier.append(callee)
    return seen


def _worker_invoked_params(cls: ClassSummary) -> set[str]:
    """Constructor params stored on self and invoked from a method
    reachable from the class's own worker entrypoints."""
    reach = _closure(cls, set(cls.entrypoints))
    if not reach:
        return set()
    param_attr: dict[str, str] = {}
    for m in cls.methods.values():
        for p, attr in m.stores_params.items():
            param_attr[attr] = p
    invoked: set[str] = set()
    for mname in reach:
        invoked |= set(cls.methods[mname].invokes_attrs)
    return {param_attr[a] for a in invoked if a in param_attr} \
        & set(cls.init_params)


def check_cross_class_locks(graph: ProjectGraph) -> list[Finding]:
    # Pass 1: which classes invoke which constructor params from
    # worker context.
    cb_params: dict[tuple[str, str], set[str]] = {}
    for fs in graph.files.values():
        for cname, cls in fs.classes.items():
            cbs = _worker_invoked_params(cls)
            if cbs:
                cb_params[(fs.path, cname)] = cbs

    # Pass 2: find self.m handed into such a parameter; collect cross
    # entrypoints per calling class.
    cross: dict[tuple[str, str], dict[str, str]] = {}  # -> {method: seam}
    for fs in graph.files.values():
        for qname, fn in fs.functions.items():
            if "." not in qname:
                continue
            caller_cls = qname.split(".", 1)[0]
            for c in fn.calls:
                if not c.selfattr_args and not c.selfattr_kwargs:
                    continue
                rc = graph.resolve_class(fs, c.name)
                if rc is None:
                    continue
                tfs, tcls = rc
                cbs = cb_params.get((tfs.path, tcls.name))
                if not cbs:
                    continue
                hooked: list[tuple[str, str]] = []
                for kw, attr in c.selfattr_kwargs.items():
                    if kw in cbs:
                        hooked.append((attr, kw))
                for pos_s, attr in c.selfattr_args.items():
                    pos = int(pos_s)
                    if pos < len(tcls.init_params) \
                            and tcls.init_params[pos] in cbs:
                        hooked.append((attr, tcls.init_params[pos]))
                for attr, param in hooked:
                    cross.setdefault((fs.path, caller_cls), {})[attr] = \
                        f"{tcls.name}({param}=...)"

    # Pass 3: PML005's write discipline over the cross entrypoints.
    out: list[Finding] = []
    for (path, cname), eps in sorted(cross.items()):
        fs = graph.files[path]
        cls = fs.classes.get(cname)
        if cls is None:
            continue
        own_reach = _closure(cls, set(cls.entrypoints))
        reach = _closure(cls, set(eps))
        outside = {m for m in cls.methods
                   if m not in reach and m != "__init__"}
        shared: set[str] = set()
        for m in outside:
            shared |= set(cls.methods[m].touched)
        locks = set(cls.lock_attrs)
        for mname in sorted(reach):
            if mname == "__init__" or mname in own_reach:
                continue  # own-seam writes are PML005's findings
            seam_root = next((eps[r] for r in eps
                              if mname in _closure(cls, {r})), "?")
            for attr, line, locked in cls.methods[mname].writes:
                if locked or attr in locks or attr not in shared:
                    continue
                why = (f"the class lock ("
                       f"{', '.join(sorted('self.' + a for a in locks))})"
                       if locks else
                       "any lock (the class defines none)")
                out.append(Finding(
                    rule="PML015", path=path, line=line, col=0,
                    message=(
                        f"{cname}.{mname}() runs on another object's "
                        f"worker thread (handed across the class "
                        f"boundary via {seam_root}) and writes "
                        f"self.{attr} — also used by caller-side "
                        f"methods — without {why}")))
    return out
