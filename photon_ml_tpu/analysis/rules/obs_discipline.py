"""PML009/PML010 — observability-discipline rules.

PML009 — raw tracer span opened outside a ``with``/``finally``.

The obs tracing API (photon_ml_tpu/obs) is finally-safe by construction
through its context manager: ``with tracer.span("name"): ...``. The raw
pair — ``tracer.start()`` returning a Span closed by ``.end()`` — exists
for bridge-style code whose open and close live in different callbacks.
Anywhere else it reintroduces exactly the leak PML007 mechanizes for
events: the span opens, the body raises, ``end()`` never runs, and the
exported trace carries a phantom "unfinished" span covering everything
after the crash (or, worse, the contextvar parent is never restored and
every LATER span nests under a dead scope).

The rule (the PML007 pairing discipline extended to the span API):

- a ``<tracer>.start(...)`` call used directly as a ``with`` item is
  fine (the context manager owns the close);
- otherwise, if a ``.end(...)`` call exists in the SAME function, it
  must sit in a ``finally`` block covering the region after the start;
- a start with no ``.end(...)`` anywhere in the module is flagged
  outright (cross-method open/close — start held on self, end in a
  different method — matches at module scope and is fine).

"Tracer-ish" receivers are names whose last segment contains ``tracer``
(``tracer``, ``self._tracer``, ``worker_tracer``) — the repo's naming
convention for obs.Tracer handles, asserted by the obs module itself.

PML010 — raw telemetry/artifact I/O inside a loop.

The run ledger (obs/ledger.py) exists so per-iteration telemetry costs
one buffered ``led.record(...)`` per row — the PML001 host-sync
discipline applied to I/O: a raw ``open(..., "w")``/``json.dump``/
``np.save`` inside an optimizer or descent loop re-opens a file (or
rewrites a whole JSON document) once per iteration, serializes the loop
on the filesystem, and — unlike the ledger — leaves no CRC'd
crash-consistent prefix. The rule flags, at loop depth >= 1:

- ``open(...)`` whose mode contains ``w``/``a``/``x``/``+``;
- ``json.dump(...)`` (the file-writing form; ``dumps`` is fine);
- ``np.save``/``np.savez``/``np.savez_compressed``.

Reads in loops are untouched; writes at loop depth 0 (per-call
artifacts like checkpoint commits) are untouched; the ledger API and
``atomic_write`` helpers don't match the patterns by construction.
"""

from __future__ import annotations

import ast
from typing import Optional

from photon_ml_tpu.analysis.context import ModuleContext
from photon_ml_tpu.analysis.findings import Finding
from photon_ml_tpu.analysis.rules._walk import (scope_statements,
                                                statement_exprs)
from photon_ml_tpu.analysis.taint import (call_func_name, dotted_name,
                                          function_bodies)


def _tracer_start(node: ast.AST) -> bool:
    """True for ``<tracer-ish>.start(...)``."""
    if not (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "start"):
        return False
    recv = dotted_name(node.func.value)
    if recv is None:
        # Chained receivers (``obs.tracer().start(...)``): the callee
        # name decides.
        if isinstance(node.func.value, ast.Call):
            callee = dotted_name(node.func.value.func) or ""
            return "tracer" in callee.rsplit(".", 1)[-1].lower()
        return False
    return "tracer" in recv.rsplit(".", 1)[-1].lower()


def _span_end(node: ast.AST) -> bool:
    """True for any ``<x>.end(...)`` call (the loose half of the pair —
    existence and finally-placement are what the rule checks, exactly
    like PML007's module-scope Finish matching)."""
    return (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "end")


def _with_item_calls(root: ast.AST) -> set:
    """ids of calls used directly as ``with`` context expressions."""
    out = set()
    for node in ast.walk(root):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                out.add(id(item.context_expr))
    return out


def _finally_covers(fn_body: list[ast.stmt], start: ast.Call,
                    end: ast.Call) -> bool:
    """True when ``end`` sits in the finalbody of a Try and ``start`` is
    not lexically after that Try (the PML007 geometry)."""
    for node in ast.walk(ast.Module(body=fn_body, type_ignores=[])):
        if not isinstance(node, ast.Try) or not node.finalbody:
            continue
        in_final = any(end is n for s in node.finalbody
                       for n in ast.walk(s))
        if in_final and start.lineno <= node.end_lineno:
            return True
    return False


def check_raw_span_discipline(ctx: ModuleContext) -> list[Finding]:
    module_has_end = any(_span_end(n) for n in ast.walk(ctx.tree))
    out: list[Finding] = []
    for owner, body in function_bodies(ctx.tree):
        if isinstance(owner, ast.Module):
            continue
        with_items = _with_item_calls(owner)
        starts = [n for n in ast.walk(owner)
                  if _tracer_start(n) and id(n) not in with_items]
        if not starts:
            continue
        ends = [n for n in ast.walk(owner) if _span_end(n)]
        for snode in starts:
            if ends:
                if not any(_finally_covers(owner.body, snode, e)
                           for e in ends):
                    out.append(ctx.finding(
                        "PML009", snode,
                        f"raw tracer.start() in {owner.name}() whose "
                        f".end() is not finally-guaranteed — a raise in "
                        f"between leaks the span (and its contextvar "
                        f"parent); use `with tracer.span(...)` or move "
                        f"the end() into a finally block"))
            elif not module_has_end:
                out.append(ctx.finding(
                    "PML009", snode,
                    f"raw tracer.start() in {owner.name}() with no "
                    f".end() anywhere in this module — every span needs "
                    f"a guaranteed close; use `with tracer.span(...)`"))
    return out


# ---------------------------------------------------------------- PML010


_NP_SAVERS = {"save", "savez", "savez_compressed"}
_WRITE_MODE_CHARS = set("wax+")


def _open_write_mode(call: ast.Call) -> Optional[str]:
    """The write-ish mode string of an ``open(...)`` call, or None when
    it is a read (default mode, explicit 'r'/'rb', or a dynamic mode —
    dynamic modes are given the benefit of the doubt)."""
    if call_func_name(call) not in ("open", "io.open", "os.fdopen",
                                    "gzip.open"):
        return None
    mode_node = None
    if len(call.args) >= 2:
        mode_node = call.args[1]
    for kw in call.keywords:
        if kw.arg == "mode":
            mode_node = kw.value
    if not (isinstance(mode_node, ast.Constant)
            and isinstance(mode_node.value, str)):
        return None
    mode = mode_node.value
    return mode if set(mode) & _WRITE_MODE_CHARS else None


def _telemetry_write_message(call: ast.Call) -> Optional[str]:
    mode = _open_write_mode(call)
    if mode is not None:
        return (f"open(..., {mode!r}) inside a loop re-opens a file "
                f"every iteration; per-iteration telemetry goes through "
                f"the buffered run-ledger API "
                f"(obs/ledger.RunLedger.record) — or hoist the open out "
                f"of the loop")
    name = call_func_name(call)
    if name in ("json.dump",):
        return ("json.dump inside a loop rewrites a document every "
                "iteration; per-iteration telemetry goes through the "
                "buffered run-ledger API (obs/ledger.RunLedger.record, "
                "one CRC'd JSONL row per record)")
    if name is not None:
        head, _, tail = name.rpartition(".")
        if head in ("np", "numpy") and tail in _NP_SAVERS:
            return (f"{name} inside a loop writes an artifact every "
                    f"iteration; batch the save outside the loop or "
                    f"route telemetry through the run ledger "
                    f"(obs/ledger.py)")
    return None


def check_ledger_io_discipline(ctx: ModuleContext) -> list[Finding]:
    """PML010: raw telemetry/artifact writes inside loops must go
    through the buffered ledger API (the PML001 host-sync discipline
    applied to telemetry I/O)."""
    out: list[Finding] = []
    for _owner, body in function_bodies(ctx.tree):
        for stmt, depth in scope_statements(body):
            if depth == 0:
                continue
            for node in statement_exprs(stmt):
                if not isinstance(node, ast.Call):
                    continue
                msg = _telemetry_write_message(node)
                if msg:
                    out.append(ctx.finding("PML010", node, msg))
    return out
