"""PML009 — raw tracer span opened outside a ``with``/``finally``.

The obs tracing API (photon_ml_tpu/obs) is finally-safe by construction
through its context manager: ``with tracer.span("name"): ...``. The raw
pair — ``tracer.start()`` returning a Span closed by ``.end()`` — exists
for bridge-style code whose open and close live in different callbacks.
Anywhere else it reintroduces exactly the leak PML007 mechanizes for
events: the span opens, the body raises, ``end()`` never runs, and the
exported trace carries a phantom "unfinished" span covering everything
after the crash (or, worse, the contextvar parent is never restored and
every LATER span nests under a dead scope).

The rule (the PML007 pairing discipline extended to the span API):

- a ``<tracer>.start(...)`` call used directly as a ``with`` item is
  fine (the context manager owns the close);
- otherwise, if a ``.end(...)`` call exists in the SAME function, it
  must sit in a ``finally`` block covering the region after the start;
- a start with no ``.end(...)`` anywhere in the module is flagged
  outright (cross-method open/close — start held on self, end in a
  different method — matches at module scope and is fine).

"Tracer-ish" receivers are names whose last segment contains ``tracer``
(``tracer``, ``self._tracer``, ``worker_tracer``) — the repo's naming
convention for obs.Tracer handles, asserted by the obs module itself.
"""

from __future__ import annotations

import ast
from typing import Optional

from photon_ml_tpu.analysis.context import ModuleContext
from photon_ml_tpu.analysis.findings import Finding
from photon_ml_tpu.analysis.taint import dotted_name, function_bodies


def _tracer_start(node: ast.AST) -> bool:
    """True for ``<tracer-ish>.start(...)``."""
    if not (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "start"):
        return False
    recv = dotted_name(node.func.value)
    if recv is None:
        # Chained receivers (``obs.tracer().start(...)``): the callee
        # name decides.
        if isinstance(node.func.value, ast.Call):
            callee = dotted_name(node.func.value.func) or ""
            return "tracer" in callee.rsplit(".", 1)[-1].lower()
        return False
    return "tracer" in recv.rsplit(".", 1)[-1].lower()


def _span_end(node: ast.AST) -> bool:
    """True for any ``<x>.end(...)`` call (the loose half of the pair —
    existence and finally-placement are what the rule checks, exactly
    like PML007's module-scope Finish matching)."""
    return (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "end")


def _with_item_calls(root: ast.AST) -> set:
    """ids of calls used directly as ``with`` context expressions."""
    out = set()
    for node in ast.walk(root):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                out.add(id(item.context_expr))
    return out


def _finally_covers(fn_body: list[ast.stmt], start: ast.Call,
                    end: ast.Call) -> bool:
    """True when ``end`` sits in the finalbody of a Try and ``start`` is
    not lexically after that Try (the PML007 geometry)."""
    for node in ast.walk(ast.Module(body=fn_body, type_ignores=[])):
        if not isinstance(node, ast.Try) or not node.finalbody:
            continue
        in_final = any(end is n for s in node.finalbody
                       for n in ast.walk(s))
        if in_final and start.lineno <= node.end_lineno:
            return True
    return False


def check_raw_span_discipline(ctx: ModuleContext) -> list[Finding]:
    module_has_end = any(_span_end(n) for n in ast.walk(ctx.tree))
    out: list[Finding] = []
    for owner, body in function_bodies(ctx.tree):
        if isinstance(owner, ast.Module):
            continue
        with_items = _with_item_calls(owner)
        starts = [n for n in ast.walk(owner)
                  if _tracer_start(n) and id(n) not in with_items]
        if not starts:
            continue
        ends = [n for n in ast.walk(owner) if _span_end(n)]
        for snode in starts:
            if ends:
                if not any(_finally_covers(owner.body, snode, e)
                           for e in ends):
                    out.append(ctx.finding(
                        "PML009", snode,
                        f"raw tracer.start() in {owner.name}() whose "
                        f".end() is not finally-guaranteed — a raise in "
                        f"between leaks the span (and its contextvar "
                        f"parent); use `with tracer.span(...)` or move "
                        f"the end() into a finally block"))
            elif not module_has_end:
                out.append(ctx.finding(
                    "PML009", snode,
                    f"raw tracer.start() in {owner.name}() with no "
                    f".end() anywhere in this module — every span needs "
                    f"a guaranteed close; use `with tracer.span(...)`"))
    return out
