"""PML012/PML013 — interprocedural rules over the project graph.

**PML012** is PML001's sync-in-loop analysis propagated through the call
graph: PML001 sees one file, so a helper in ``ops/`` that hides a
``float()``/``.item()``/``np.asarray()`` behind a function boundary goes
dark the moment its caller's loop lives in another module. Here the
helper's summary carries "syncs parameter i" / "syncs a device value of
its own", those facts close over the call graph, and a CROSS-MODULE call
inside a loop that reaches one is the finding — at the caller's line,
naming the witness sync.

**PML013** mechanizes the ``.ok``-marker crash-consistency discipline
(docs/ROBUSTNESS.md): inside a module that participates in the
marker/CRC protocol (it imports ``utils/diskio``), every artifact write
must flow through ``diskio.atomic_write`` so the commit marker stays
LAST — a raw ``open(.., "w")``/``np.save`` there (or a call handing a
protected path to a helper module that raw-writes it) can leave a
half-written artifact that the marker already vouches for.
"""

from __future__ import annotations

from typing import Optional

from photon_ml_tpu.analysis.findings import Finding
from photon_ml_tpu.analysis.project import ProjectGraph


def _qkey(path: str, qname: str) -> tuple[str, str]:
    return (path, qname)


def _resolved_calls(graph: ProjectGraph):
    """[(file, qname, fn, call, target_key or None)] for every call in
    the graph — resolved once, shared by the fixpoint and the report."""
    out = []
    targets: dict[tuple[str, str], object] = {}
    for fs in graph.files.values():
        for qname, fn in fs.functions.items():
            for c in fn.calls:
                r = graph.resolve_call(fs, c, caller=qname)
                tkey = None
                if r is not None:
                    tfs, tfn = r
                    tkey = _qkey(tfs.path, tfn.name)
                    targets[tkey] = (tfs, tfn)
                out.append((fs, qname, fn, c, tkey))
    return out, targets


# ---------------------------------------------------------------- PML012


def check_cross_module_sync(graph: ProjectGraph) -> list[Finding]:
    calls, targets = _resolved_calls(graph)
    sync_params: dict[tuple, set[int]] = {}
    trans: dict[tuple, bool] = {}
    witness: dict[tuple, str] = {}
    for fs in graph.files.values():
        for qname, fn in fs.functions.items():
            k = _qkey(fs.path, qname)
            sync_params[k] = set(fn.sync_params)
            trans[k] = fn.device_sync
            witness[k] = fn.sync_witness

    def kw_position(tfn, kw: str) -> Optional[int]:
        try:
            return tfn.params.index(kw)
        except ValueError:
            return None

    for _ in range(6):  # bounded fixpoint over call-graph depth
        changed = False
        for fs, qname, fn, c, tkey in calls:
            if tkey is None or tkey not in targets:
                continue
            k = _qkey(fs.path, qname)
            tfs, tfn = targets[tkey]
            # Param passthrough: my param p flows into a synced param.
            for pos_s, pi in c.param_args.items():
                if int(pos_s) in sync_params[tkey] \
                        and pi not in sync_params[k]:
                    sync_params[k].add(pi)
                    witness[k] = witness[k] or witness.get(tkey, "")
                    changed = True
            for kw, pi in c.param_kwargs.items():
                tp = kw_position(tfn, kw)
                if tp is not None and tp in sync_params[tkey] \
                        and pi not in sync_params[k]:
                    sync_params[k].add(pi)
                    witness[k] = witness[k] or witness.get(tkey, "")
                    changed = True
            # A call that ALWAYS syncs (callee syncs its own device
            # value, or I feed a device value into a synced param)
            # makes me transitively syncing.
            hits_sync = trans.get(tkey, False) or any(
                pos in sync_params[tkey] for pos in c.device_args) or any(
                (tp := kw_position(tfn, kw)) is not None
                and tp in sync_params[tkey] for kw in c.device_kwargs)
            if hits_sync and not trans[k]:
                trans[k] = True
                witness[k] = witness[k] or witness.get(tkey, "")
                changed = True
        if not changed:
            break

    out: list[Finding] = []
    seen: set[tuple] = set()
    for fs, qname, fn, c, tkey in calls:
        if c.depth < 1 or tkey is None or tkey not in targets:
            continue
        if not graph.is_package_file(fs.path):
            continue  # a test looping whole driver runs is the norm,
            # not the hot-path bug class this rule targets
        tfs, tfn = targets[tkey]
        if tfs.path == fs.path:
            continue  # same-file chains are PML001's jurisdiction
        wit = witness.get(tkey, "") or f"{tfs.path}:{tfn.line}"
        msg = None
        if trans.get(tkey):
            msg = (f"{qname}() calls {tfn.name}() ({tfs.path}) inside a "
                   f"loop, and that call reaches a host-device sync "
                   f"({wit}) — every iteration blocks the host on the "
                   f"device stream; hoist the call or batch the "
                   f"transfer")
        else:
            synced_pos = sync_params.get(tkey, set())
            feeds = [p for p in c.device_args if p in synced_pos]
            feeds_kw = [kw for kw in c.device_kwargs
                        if (tp := kw_position(tfn, kw)) is not None
                        and tp in synced_pos]
            if feeds or feeds_kw:
                which = ", ".join(
                    [tfn.params[p] if p < len(tfn.params) else str(p)
                     for p in feeds] + feeds_kw)
                msg = (f"{qname}() passes a device value into "
                       f"{tfn.name}({which}) ({tfs.path}) inside a loop "
                       f"— the callee host-syncs that argument ({wit}); "
                       f"sync once outside the loop instead")
        if msg is not None:
            key = (fs.path, c.line)
            if key not in seen:
                seen.add(key)
                out.append(Finding(rule="PML012", path=fs.path,
                                   line=c.line, col=0, message=msg))
    return out


# ---------------------------------------------------------------- PML013


def check_crash_consistency(graph: ProjectGraph) -> list[Finding]:
    out: list[Finding] = []
    for fs in graph.files.values():
        if not fs.crash_module:
            continue
        path = fs.path.replace("\\", "/")
        if path.endswith("utils/diskio.py"):
            continue  # the sanctioned writer itself
        for qname, fn in fs.functions.items():
            for w in fn.writes:
                if w.in_atomic:
                    continue
                out.append(Finding(
                    rule="PML013", path=fs.path, line=w.line, col=0,
                    message=(
                        f"raw {w.kind} in {qname}() — this module "
                        f"participates in the .ok-marker/CRC commit "
                        f"protocol; route artifact writes through "
                        f"utils/diskio.atomic_write so a crash can "
                        f"never leave bytes the marker vouches for")))
            for c in fn.calls:
                r = graph.resolve_call(fs, c, caller=qname)
                if r is None:
                    continue
                tfs, tfn = r
                if tfs.path == fs.path or tfs.crash_module:
                    continue  # the callee owns its own discipline
                provided = [
                    p for p in tfn.write_params
                    if p < c.arg_count
                    or (p < len(tfn.params)
                        and tfn.params[p] in c.kwarg_names)]
                if not provided:
                    continue
                which = ", ".join(tfn.params[p] for p in provided
                                  if p < len(tfn.params))
                out.append(Finding(
                    rule="PML013", path=fs.path, line=c.line, col=0,
                    message=(
                        f"{qname}() hands a path to {tfn.name}() "
                        f"({tfs.path}), which raw-writes its "
                        f"argument ({which}) outside "
                        f"utils/diskio.atomic_write — a crash "
                        f"mid-write leaves a torn artifact inside this "
                        f"module's marker-committed tree")))
    return out
