"""PML008 — swallowed broad exceptions.

The robustness pass (docs/ROBUSTNESS.md) hardened three layers against
faults, and the recurring anti-pattern it had to undo was the silent
swallow: ``except: pass`` / ``except Exception: <no raise, no log>``.
A handler like that converts a real fault (a dead worker, a corrupt
file, a failed flush) into nothing — the run continues wrong, and the
chaos suite cannot even observe that the fault happened. The rule:

- a handler that catches EVERYTHING (bare ``except``, ``Exception``,
  ``BaseException``, or a tuple containing one of those) must visibly
  handle the error: re-raise (bare ``raise`` or raising a new error),
  log it (``logger.*`` / ``logging.*`` / ``warnings.warn`` /
  ``traceback.print_exc``), hand it to a waiter
  (``future.set_exception``), or at minimum REFERENCE the bound
  exception (``except Exception as e: queue.put(e)`` routes the error
  somewhere; ``except Exception: pass`` routes it nowhere);
- narrow handlers (``except OSError: pass``) are out of scope — catching
  a SPECIFIC exception and deciding it is benign is a legitimate,
  reviewable decision; catching everything and ignoring it is not.

Deliberate broad-swallow contracts (a cache whose misses are silent by
design) carry ``# pml: allow[PML008] <reason>`` like every other rule.
"""

from __future__ import annotations

import ast

from photon_ml_tpu.analysis.context import ModuleContext
from photon_ml_tpu.analysis.findings import Finding
from photon_ml_tpu.analysis.taint import dotted_name

_BROAD = {"Exception", "BaseException"}

_LOG_LEAVES = {"debug", "info", "warning", "warn", "error", "exception",
               "critical", "log", "print_exc"}


def _is_broad(type_node) -> bool:
    """True for bare ``except``, Exception/BaseException (any dotting),
    or a tuple containing one of those."""
    if type_node is None:
        return True
    if isinstance(type_node, ast.Tuple):
        return any(_is_broad(el) for el in type_node.elts)
    leaf = (dotted_name(type_node) or "").rsplit(".", 1)[-1]
    return leaf in _BROAD


def _call_handles(node: ast.Call) -> bool:
    """Calls that count as visible handling: logging-ish calls, and
    handing the error to a waiter via ``set_exception``."""
    if isinstance(node.func, ast.Attribute):
        leaf = node.func.attr
        base = ast.unparse(node.func.value)
    else:
        name = dotted_name(node.func) or ""
        leaf = name.rsplit(".", 1)[-1]
        base = name.rsplit(".", 1)[0] if "." in name else ""
    if leaf == "set_exception":
        return True
    if leaf not in _LOG_LEAVES:
        return False
    # ``logger.warning`` / ``logging.error`` / ``self._log.debug`` /
    # ``logging.getLogger(...).debug`` / ``warnings.warn`` — anything
    # whose base smells like a logging seam. A bare ``warn()``/``log()``
    # call counts too.
    return (base == "" or "log" in base.lower()
            or base.rsplit(".", 1)[-1] in ("warnings", "traceback"))


def _handler_handles(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(ast.Module(body=handler.body, type_ignores=[])):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call) and _call_handles(node):
            return True
        if (handler.name is not None and isinstance(node, ast.Name)
                and node.id == handler.name
                and isinstance(node.ctx, ast.Load)):
            return True  # the error is read/routed, not dropped
    return False


def check_swallowed_exception(ctx: ModuleContext) -> list[Finding]:
    out = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Try):
            continue
        for handler in node.handlers:
            if not _is_broad(handler.type):
                continue
            if _handler_handles(handler):
                continue
            caught = ("bare except" if handler.type is None else
                      f"except {ast.unparse(handler.type)}")
            out.append(ctx.finding(
                "PML008", handler,
                f"{caught} swallows the error without re-raise, "
                f"logging, or set_exception — a real fault (dead "
                f"worker, corrupt file) vanishes here; log it, narrow "
                f"the type, or re-raise"))
    return out
