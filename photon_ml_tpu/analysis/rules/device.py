"""PML001/002/003 — the JAX host/device seam.

These mechanize the bug classes PR 1/PR 2 paid for by hand: a stray
``float()`` in a descent loop serializes the device pipeline once per
iteration; a Python scalar that varies per call re-specializes a jitted
program every iteration; a tracer stored on ``self`` from inside a traced
function escapes its trace and detonates at the next use.
"""

from __future__ import annotations

import ast
from typing import Optional

from photon_ml_tpu.analysis.context import ModuleContext
from photon_ml_tpu.analysis.findings import Finding
from photon_ml_tpu.analysis.rules._walk import (assigned_names,
                                                scope_statements,
                                                self_attribute,
                                                statement_exprs)
from photon_ml_tpu.analysis.taint import (TRANSFORM_FACTORIES, TaintScope,
                                          call_func_name, dotted_name,
                                          function_bodies)

_SYNC_CASTS = {"float", "int", "bool"}
_SYNC_NP = {"np.asarray", "np.array", "numpy.asarray", "numpy.array"}
_STATIC_KW = {"static_argnames", "static_argnums"}


# ---------------------------------------------------------------- PML001


def check_host_sync(ctx: ModuleContext) -> list[Finding]:
    """``float()``/``.item()``/``np.asarray()`` on a device value inside a
    loop: each call blocks the host on the device stream — the dispatch
    pipelining that makes the descent/serving hot paths fast dies there."""
    out = []
    for _owner, body in function_bodies(ctx.tree):
        scope = TaintScope(body)
        for stmt, depth in scope_statements(body):
            if depth == 0:
                continue
            for node in statement_exprs(stmt):
                if not isinstance(node, ast.Call):
                    continue
                msg = _sync_call_message(node, scope)
                if msg:
                    out.append(ctx.finding("PML001", node, msg))
    return out


def _sync_call_message(call: ast.Call, scope: TaintScope) -> Optional[str]:
    name = call_func_name(call)
    arg0 = call.args[0] if call.args else None
    if name in _SYNC_CASTS and arg0 is not None \
            and scope.is_device(arg0):
        return (f"{name}() on a device value inside a loop forces a "
                f"host-device sync every iteration; hoist it out of the "
                f"loop or keep the reduction on device")
    if name in _SYNC_NP and arg0 is not None and scope.is_device(arg0):
        return (f"{name}() on a device value inside a loop copies "
                f"device->host every iteration; batch the transfer "
                f"outside the loop")
    if name is not None and name.rsplit(".", 1)[-1] == "device_get":
        return ("jax.device_get inside a loop syncs every iteration; "
                "batch the transfer outside the loop")
    if isinstance(call.func, ast.Attribute) and call.func.attr == "item" \
            and not call.args and scope.is_device(call.func.value):
        return (".item() on a device value inside a loop forces a "
                "host-device sync every iteration")
    return None


# ---------------------------------------------------------------- PML002


def _jit_call_parts(node: ast.AST) -> Optional[ast.Call]:
    """The ``jax.jit(...)`` call inside ``node``, unwrapping
    ``partial(jax.jit, ...)``; None when node isn't a jit application."""
    if not isinstance(node, ast.Call):
        return None
    name = call_func_name(node)
    if name in ("jax.jit", "jit"):
        return node
    if name in ("partial", "functools.partial") and node.args:
        inner = dotted_name(node.args[0])
        if inner in ("jax.jit", "jit"):
            return node
    return None


def _has_static_args(jit_call: ast.Call) -> bool:
    return any(k.arg in _STATIC_KW for k in jit_call.keywords)


def _jitted_registry(tree: ast.Module) -> dict[str, bool]:
    """Callable name (possibly dotted, e.g. ``self._insert``) → whether
    its jit application declares static_argnames/argnums."""
    reg: dict[str, bool] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            jit = _jit_call_parts(node.value)
            if jit is not None:
                static = _has_static_args(jit)
                for t in node.targets:
                    name = dotted_name(t)
                    if name:
                        reg[name] = static
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if dotted_name(dec) in ("jax.jit", "jit"):
                    reg[node.name] = False
                else:
                    jit = _jit_call_parts(dec)
                    if jit is not None:
                        reg[node.name] = _has_static_args(jit)
    return reg


class _LoopVariance:
    """Names that change per iteration of the enclosing loop(s), split by
    whether they are provably Python-scalar-ish (range/enumerate targets,
    len()/shape-derived)."""

    def __init__(self):
        self.variant: set[str] = set()
        self.scalarish: set[str] = set()

    def enter_loop(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            targets = _names_of_target(stmt.target)
            self.variant |= targets
            itn = call_func_name(stmt.iter) \
                if isinstance(stmt.iter, ast.Call) else None
            if itn == "range":
                self.scalarish |= targets
            elif itn == "enumerate" and isinstance(stmt.target, ast.Tuple) \
                    and stmt.target.elts:
                self.scalarish |= _names_of_target(stmt.target.elts[0])

    def absorb_assignment(self, stmt: ast.stmt) -> None:
        names = assigned_names(stmt)
        if not names:
            return
        self.variant |= names
        value = getattr(stmt, "value", None)
        if value is not None and self.is_scalarish(value):
            self.scalarish |= names

    def is_scalarish(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.scalarish
        if isinstance(node, ast.Call):
            name = call_func_name(node)
            return name in ("len", "int")
        if isinstance(node, ast.Attribute):
            return node.attr in ("shape", "size", "ndim")
        if isinstance(node, ast.Subscript):
            return self.is_scalarish(node.value)
        if isinstance(node, ast.BinOp):
            return self.is_scalarish(node.left) \
                or self.is_scalarish(node.right)
        return False

    def is_variant(self, node: ast.AST) -> bool:
        return any(isinstance(n, ast.Name) and n.id in self.variant
                   for n in ast.walk(node))


def _names_of_target(t: ast.AST) -> set[str]:
    out: set[str] = set()
    if isinstance(t, ast.Name):
        out.add(t.id)
    elif isinstance(t, (ast.Tuple, ast.List)):
        for e in t.elts:
            out |= _names_of_target(e)
    elif isinstance(t, ast.Starred):
        out |= _names_of_target(t.value)
    return out


def check_recompile_hazard(ctx: ModuleContext) -> list[Finding]:
    """Calls to jitted functions, inside loops, fed a loop-varying Python
    scalar (or a slice whose bound varies): every distinct value/shape
    builds a fresh XLA program. Declaring static_argnames is the opt-in
    that makes the specialization intentional."""
    reg = _jitted_registry(ctx.tree)
    out = []
    for _owner, body in function_bodies(ctx.tree):
        out.extend(_scan_scope_for_recompiles(ctx, body, reg))
    return out


def _scan_scope_for_recompiles(ctx, body, reg) -> list[Finding]:
    out = []

    def scan(stmts, var: Optional[_LoopVariance]):
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            if var is not None:
                var.absorb_assignment(stmt)
                for node in statement_exprs(stmt):
                    if isinstance(node, ast.Call):
                        f = _flag_call(node, var)
                        if f is not None:
                            out.append(f)
            if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                inner = _LoopVariance()
                if var is not None:
                    inner.variant |= var.variant
                    inner.scalarish |= var.scalarish
                inner.enter_loop(stmt)
                # Pre-pass: names assigned anywhere in the body vary.
                for s, _ in scope_statements(stmt.body):
                    inner.variant |= assigned_names(s)
                scan(stmt.body, inner)
                scan(stmt.orelse, var)
            elif isinstance(stmt, ast.If):
                scan(stmt.body, var)
                scan(stmt.orelse, var)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                scan(stmt.body, var)
            elif isinstance(stmt, ast.Try):
                scan(stmt.body, var)
                for h in stmt.handlers:
                    scan(h.body, var)
                scan(stmt.orelse, var)
                scan(stmt.finalbody, var)

    def _flag_call(call: ast.Call, var: _LoopVariance
                   ) -> Optional[Finding]:
        if _jit_call_parts(call) is not None:
            return ctx.finding(
                "PML002", call,
                "jax.jit applied inside a loop builds a new wrapper "
                "(and cache entry) per iteration; hoist the jit out")
        name = call_func_name(call)
        if name is None or name not in reg or reg[name]:
            return None
        for arg in list(call.args) + [k.value for k in call.keywords]:
            if var.is_variant(arg) and var.is_scalarish(arg):
                return ctx.finding(
                    "PML002", call,
                    f"jitted {name}() receives a loop-varying Python "
                    f"scalar — every distinct value compiles a new "
                    f"program; mark it in static_argnames (intentional "
                    f"specialization) or pass it as a device array")
            if isinstance(arg, ast.Subscript) \
                    and isinstance(arg.slice, ast.Slice) \
                    and any(b is not None and var.is_variant(b)
                            for b in (arg.slice.lower, arg.slice.upper)):
                return ctx.finding(
                    "PML002", call,
                    f"jitted {name}() receives a slice whose bound varies "
                    f"per iteration — a new SHAPE (and program) every "
                    f"call; pad to a bucketed size instead")
        return None

    scan(body, None)
    return out


# ---------------------------------------------------------------- PML003


def _traced_functions(tree: ast.Module) -> list[ast.FunctionDef]:
    """Functions whose body runs under a JAX trace: decorated with a
    transform, or passed by name to one anywhere in the module."""
    traced_names: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            name = call_func_name(node)
            leaf = name.rsplit(".", 1)[-1] if name else ""
            if leaf in TRANSFORM_FACTORIES:
                args = list(node.args)
                if name in ("partial", "functools.partial"):
                    args = args[1:]
                for a in args:
                    if isinstance(a, ast.Name):
                        traced_names.add(a.id)
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if node.name in traced_names:
            out.append(node)
            continue
        for dec in node.decorator_list:
            leaf = (dotted_name(dec) or "").rsplit(".", 1)[-1]
            if leaf in TRANSFORM_FACTORIES \
                    or _jit_call_parts(dec) is not None:
                out.append(node)
                break
            if isinstance(dec, ast.Call):
                dleaf = (call_func_name(dec) or "").rsplit(".", 1)[-1]
                if dleaf in TRANSFORM_FACTORIES:
                    out.append(node)
                    break
    return out


def check_tracer_leak(ctx: ModuleContext) -> list[Finding]:
    """Inside a traced function, a tracer assigned to ``self.*`` or a
    ``global`` outlives its trace — the stored object is an abstract
    tracer, not an array, and the NEXT trace (or plain host code) that
    touches it fails far from here."""
    out = []
    for fn in _traced_functions(ctx.tree):
        params = {a.arg for a in (fn.args.args + fn.args.posonlyargs
                                  + fn.args.kwonlyargs)}
        params.discard("self")
        scope = TaintScope(fn.body, pre_tainted=params)
        globals_declared: set[str] = {
            n for node in ast.walk(fn) if isinstance(node, ast.Global)
            for n in node.names}
        for node in ast.walk(fn):
            if not isinstance(node, (ast.Assign, ast.AugAssign)):
                continue
            value = node.value
            if not (scope.is_device(value) or _mentions(value, params)):
                continue
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                if self_attribute(t) is not None:
                    out.append(ctx.finding(
                        "PML003", node,
                        f"traced function {fn.name}() stores a traced "
                        f"value on self.{self_attribute(t)} — the tracer "
                        f"escapes its trace; return it instead"))
                elif isinstance(t, ast.Name) and t.id in globals_declared:
                    out.append(ctx.finding(
                        "PML003", node,
                        f"traced function {fn.name}() stores a traced "
                        f"value in global {t.id} — the tracer escapes "
                        f"its trace; return it instead"))
    return out


def _mentions(node: ast.AST, names: set[str]) -> bool:
    return any(isinstance(n, ast.Name) and n.id in names
               for n in ast.walk(node))
