"""PML006 — numeric accumulation whose order isn't pinned.

f32 addition is not associative: the same values summed in a different
order produce a different last bit, and PR 1's checkpoint-resume parity
broke exactly this way (a re-summation regrouped an f32 accumulation and
drifted ~1e-3 through the factored alternation). Statically visible
shapes of the hazard:

- Python ``sum()`` (or ``functools.reduce`` over ``+``) where the terms
  are arrays/device values: the grouping is whatever the iterable
  happens to be — stack the terms and use one pinned ``np.sum``/
  ``jnp.sum`` reduction instead;
- any reduction or ``+=`` accumulation driven by an UNORDERED container
  (``set``/``frozenset`` literals and calls, set algebra results,
  ``os.listdir``/``glob.glob`` filesystem order): iteration order — and
  therefore the float result — varies run to run; sort first.
"""

from __future__ import annotations

import ast

from photon_ml_tpu.analysis.context import ModuleContext
from photon_ml_tpu.analysis.findings import Finding
from photon_ml_tpu.analysis.rules._walk import scope_statements, \
    statement_exprs
from photon_ml_tpu.analysis.taint import TaintScope, call_func_name, \
    function_bodies

_SET_CALLS = {"set", "frozenset"}
_SET_METHODS = {"union", "intersection", "difference",
                "symmetric_difference"}
_FS_ORDER_CALLS = {"os.listdir", "listdir", "glob.glob", "glob.iglob"}


def _is_unordered(node: ast.AST) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        name = call_func_name(node)
        if name in _SET_CALLS or name in _FS_ORDER_CALLS:
            return True
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in _SET_METHODS:
            return True
    if isinstance(node, ast.Name):
        return False  # aliasing is out of scope for a one-pass lint
    return False


def _comprehension_sources(node: ast.AST) -> list[ast.AST]:
    if isinstance(node, (ast.GeneratorExp, ast.ListComp)):
        return [g.iter for g in node.generators]
    return [node]


def check_nondeterministic_accumulation(ctx: ModuleContext
                                        ) -> list[Finding]:
    out = []
    for _owner, body in function_bodies(ctx.tree):
        scope = TaintScope(body)
        for stmt, _depth in scope_statements(body):
            for node in statement_exprs(stmt):
                if isinstance(node, ast.Call):
                    f = _flag_reduction(ctx, node, scope)
                    if f is not None:
                        out.append(f)
            # acc += … inside `for x in <unordered>`
            if isinstance(stmt, (ast.For, ast.AsyncFor)) \
                    and _is_unordered(stmt.iter):
                for s, _ in scope_statements(stmt.body):
                    if isinstance(s, ast.AugAssign) \
                            and isinstance(s.op, ast.Add):
                        out.append(ctx.finding(
                            "PML006", s,
                            "accumulation over an unordered container — "
                            "iteration order (and the f32 result) varies "
                            "run to run; iterate sorted(...) instead"))
    return out


def _flag_reduction(ctx: ModuleContext, call: ast.Call,
                    scope: TaintScope):
    name = call_func_name(call)
    is_sum = name == "sum"
    is_reduce = name in ("reduce", "functools.reduce")
    if not (is_sum or is_reduce):
        return None
    arg = call.args[1] if is_reduce and len(call.args) > 1 \
        else (call.args[0] if call.args else None)
    if arg is None:
        return None
    for src in _comprehension_sources(arg):
        if _is_unordered(src):
            return ctx.finding(
                "PML006", call,
                "reduction over an unordered container — iteration "
                "order (and the f32 result) varies run to run; sort "
                "the terms before reducing")
    element = arg.elt if isinstance(arg, (ast.GeneratorExp, ast.ListComp)) \
        else arg
    if is_sum and (scope.is_device(element) or _elements_device(
            arg, scope)):
        return ctx.finding(
            "PML006", call,
            "Python sum() over array terms accumulates left-to-right "
            "in f32 with whatever grouping the iterable has — "
            "checkpoint-resume bit-parity dies here; stack the terms "
            "and use one np.sum/jnp.sum reduction with a pinned order")
    return None


def _elements_device(arg: ast.AST, scope: TaintScope) -> bool:
    if isinstance(arg, (ast.List, ast.Tuple)):
        return any(scope.is_device(e) for e in arg.elts)
    return False
