"""Rule registry. Per-file rules are callables ``check(ctx) ->
list[Finding]`` over one :class:`ModuleContext`; project rules (PML012+)
are callables ``check(graph) -> list[Finding]`` over the whole
:class:`~photon_ml_tpu.analysis.project.ProjectGraph`. The engine runs
every registered rule unless the CLI selects/ignores a subset."""

from __future__ import annotations

from photon_ml_tpu.analysis import locks
from photon_ml_tpu.analysis.rules import (concurrency, device, drift,
                                          interproc, kernels, lifecycle,
                                          network, numeric,
                                          obs_discipline, resources,
                                          robustness, timeclock, xclass)

# id → (check, one-line summary). Order is report order.
ALL_RULES = {
    "PML001": (device.check_host_sync,
               "host-device sync inside a loop or jit-adjacent hot path"),
    "PML002": (device.check_recompile_hazard,
               "shape-/scalar-varying argument reaching a jitted callee"),
    "PML003": (device.check_tracer_leak,
               "tracer stored on self/global from inside a traced function"),
    "PML004": (timeclock.check_wall_clock_duration,
               "duration or deadline computed from the wall clock"),
    "PML005": (concurrency.check_unguarded_shared_state,
               "thread-reachable write to shared state outside the lock"),
    "PML006": (numeric.check_nondeterministic_accumulation,
               "numeric accumulation with unpinned order"),
    "PML007": (lifecycle.check_unbalanced_lifecycle,
               "*Start event without a guaranteed matching *Finish"),
    "PML008": (robustness.check_swallowed_exception,
               "broad except that swallows the error silently"),
    "PML009": (obs_discipline.check_raw_span_discipline,
               "raw tracer span begin/end without a with/finally "
               "guarantee"),
    "PML010": (obs_discipline.check_ledger_io_discipline,
               "raw telemetry/artifact write inside a loop (use the "
               "buffered run-ledger API)"),
    "PML011": (network.check_blocking_network_timeout,
               "blocking socket/HTTP call without an explicit timeout"),
    "PML017": (kernels.check_kernel_seam,
               "direct pallas_call outside ops/kernels/ (bypasses the "
               "kernel registry's flag/fallback/parity contract)"),
}

# Whole-program rules over the project graph (analysis/project.py):
# id → (check(graph), one-line summary). Same report order contract.
PROJECT_RULES = {
    "PML012": (interproc.check_cross_module_sync,
               "cross-module call chain syncing host-device inside a "
               "loop"),
    "PML013": (interproc.check_crash_consistency,
               "raw write inside (or handed out of) a .ok-marker "
               "crash-consistency module"),
    "PML014": (drift.check_registry_drift,
               "string-registry drift: unknown fault site / metric / "
               "span / event name"),
    "PML015": (xclass.check_cross_class_locks,
               "cross-class callback writing shared state off-thread "
               "without the lock"),
    "PML016": (resources.check_resource_lifecycle,
               "subprocess/socket/server/pool acquired without a "
               "guaranteed release"),
    "PML018": (locks.check_lock_order,
               "lock-order cycle (or non-reentrant re-entry) in the "
               "global lock graph"),
    "PML019": (locks.check_blocking_under_lock,
               "blocking call (network/result/wait/sleep/device sync) "
               "reached while a lock is held"),
}
