"""Rule registry. Each rule is a callable ``check(ctx) -> list[Finding]``
registered under its PML id; the engine runs every registered rule unless
the CLI selects/ignores a subset."""

from __future__ import annotations

from photon_ml_tpu.analysis.rules import (concurrency, device, lifecycle,
                                          network, numeric,
                                          obs_discipline, robustness,
                                          timeclock)

# id → (check, one-line summary). Order is report order.
ALL_RULES = {
    "PML001": (device.check_host_sync,
               "host-device sync inside a loop or jit-adjacent hot path"),
    "PML002": (device.check_recompile_hazard,
               "shape-/scalar-varying argument reaching a jitted callee"),
    "PML003": (device.check_tracer_leak,
               "tracer stored on self/global from inside a traced function"),
    "PML004": (timeclock.check_wall_clock_duration,
               "duration or deadline computed from the wall clock"),
    "PML005": (concurrency.check_unguarded_shared_state,
               "thread-reachable write to shared state outside the lock"),
    "PML006": (numeric.check_nondeterministic_accumulation,
               "numeric accumulation with unpinned order"),
    "PML007": (lifecycle.check_unbalanced_lifecycle,
               "*Start event without a guaranteed matching *Finish"),
    "PML008": (robustness.check_swallowed_exception,
               "broad except that swallows the error silently"),
    "PML009": (obs_discipline.check_raw_span_discipline,
               "raw tracer span begin/end without a with/finally "
               "guarantee"),
    "PML010": (obs_discipline.check_ledger_io_discipline,
               "raw telemetry/artifact write inside a loop (use the "
               "buffered run-ledger API)"),
    "PML011": (network.check_blocking_network_timeout,
               "blocking socket/HTTP call without an explicit timeout"),
}
