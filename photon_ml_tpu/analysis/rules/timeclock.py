"""PML004 — durations and deadlines measured with the wall clock.

``time.time()`` is a TIMESTAMP source: it steps when NTP corrects the
clock, jumps across suspend, and can run backwards. A duration computed
as a difference of wall-clock reads (or a deadline compared against one)
silently absorbs those steps — the serving batcher's flush window, uptime
counters, and bench numbers all drifted this way before the clocks were
split. Durations belong to ``time.perf_counter()`` / ``time.monotonic()``;
wall time is for timestamps only.
"""

from __future__ import annotations

import ast

from photon_ml_tpu.analysis.context import ModuleContext
from photon_ml_tpu.analysis.findings import Finding
from photon_ml_tpu.analysis.rules._walk import statement_exprs
from photon_ml_tpu.analysis.taint import call_func_name, function_bodies

_WALL_CALLS = {"time.time", "datetime.now", "datetime.datetime.now",
               "datetime.utcnow", "datetime.datetime.utcnow"}


def _is_wall_call(node: ast.AST, wall_aliases: set[str]) -> bool:
    if not isinstance(node, ast.Call):
        return False
    name = call_func_name(node)
    return name in _WALL_CALLS or name in wall_aliases


def _module_wall_aliases(tree: ast.Module) -> set[str]:
    """Bare names bound to the wall clock by imports:
    ``from time import time`` / ``from time import time as now``."""
    out: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "time":
            for alias in node.names:
                if alias.name == "time":
                    out.add(alias.asname or alias.name)
    return out


def check_wall_clock_duration(ctx: ModuleContext) -> list[Finding]:
    aliases = _module_wall_aliases(ctx.tree)
    out = []
    for _owner, body in function_bodies(ctx.tree):
        # Names assigned from a wall-clock read in this scope.
        wall_names: set[str] = set()
        for node in ast.walk(ast.Module(body=body, type_ignores=[])):
            if isinstance(node, ast.Assign) \
                    and _is_wall_call(node.value, aliases):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        wall_names.add(t.id)

        def wallish(node: ast.AST) -> bool:
            return _is_wall_call(node, aliases) or (
                isinstance(node, ast.Name) and node.id in wall_names)

        for stmt in body:
            for node in _all_exprs(stmt):
                if isinstance(node, ast.BinOp) \
                        and isinstance(node.op, ast.Sub) \
                        and (wallish(node.left) or wallish(node.right)):
                    out.append(ctx.finding(
                        "PML004",
                        node,
                        "duration computed from the wall clock — an NTP "
                        "step or suspend skews it; use "
                        "time.perf_counter()/time.monotonic() for "
                        "durations and deadlines, keep time.time() for "
                        "timestamps"))
    return out


def _all_exprs(stmt: ast.stmt):
    """statement_exprs plus recursion into nested blocks of this stmt
    (but still not into nested function/class bodies)."""
    yield from statement_exprs(stmt)
    blocks = []
    if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While, ast.If)):
        blocks = [stmt.body, stmt.orelse]
    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
        blocks = [stmt.body]
    elif isinstance(stmt, ast.Try):
        blocks = [stmt.body, stmt.orelse, stmt.finalbody] \
            + [h.body for h in stmt.handlers]
    for b in blocks:
        for s in b:
            yield from _all_exprs(s)
