"""PML014 — string-registry drift across the string-keyed seams.

The fault injector, the metrics registry, the span tracer, and the
event bridge are all STRING-keyed: a site/metric/span/event only exists
at the moment two strings match. Nothing fails on a typo — the fault
plan silently never fires (the chaos drill "passes" while exercising
nothing), the metric lookup silently reads zero, the bridge counter
silently never increments. This rule resolves every such literal
against the generated registries:

- **fault sites** — dotted literals at ``fire()`` / ``poison_scalar()``
  / ``corrupt_file()`` calls, ``FaultSpec(site=...)``, and ``"site"``
  keys in fault-plan dict literals must be members of the
  ``faults/sites.py`` registry (undotted names are the injector unit
  tests' synthetic sites and are exempt by convention);
- **metrics** — ``photon_*`` literals OUTSIDE the package (tests,
  dev-scripts: `metric_value` lookups, assertion needles, bench↔metric
  maps) must resolve against the names the package actually emits
  (exact registrations, render-time f-string names, known
  ``_peak``/quantile suffixes, dynamic-prefix families);
- **spans** — dotted span names started outside the package must be
  names the package starts somewhere;
- **events** — dict literals mapping event-class names to ``photon_*``
  counters (the bridge shape), and CamelCase equality switches in
  functions that demonstrably switch on event names, must use class
  names that exist in ``utils/events.py``.

``photon-lint --catalog`` emits the same registries as JSON.
"""

from __future__ import annotations

from photon_ml_tpu.analysis.findings import Finding
from photon_ml_tpu.analysis.project import ProjectGraph

_METRIC_SUFFIXES = ("_peak", "_count", "_sum", "_p50", "_p95", "_p99")


def check_registry_drift(graph: ProjectGraph) -> list[Finding]:
    out: list[Finding] = []
    out += _check_fault_sites(graph)
    out += _check_metric_refs(graph)
    out += _check_span_refs(graph)
    out += _check_event_names(graph)
    return out


def _check_fault_sites(graph: ProjectGraph) -> list[Finding]:
    registry = graph.fault_site_registry()
    if not registry:
        return []  # no sites module in this graph: nothing to drift from
    out = []
    for fs in graph.files.values():
        if fs.path.replace("\\", "/").endswith("faults/sites.py"):
            continue
        for site, line, ctx in fs.site_literals:
            if "." not in site:
                continue  # undotted = injector-unit-test synthetic site
            if site not in registry:
                out.append(Finding(
                    rule="PML014", path=fs.path, line=line, col=0,
                    message=(
                        f"unknown fault site {site!r} at a {ctx} call "
                        f"— not in the faults/sites.py registry, so "
                        f"this fault silently NEVER fires; fix the "
                        f"typo or register the site")))
    return out


def _check_metric_refs(graph: ProjectGraph) -> list[Finding]:
    exact, prefixes = graph.metric_catalog()
    if not exact and not prefixes:
        return []
    out = []
    pkg_name = graph.package_prefix.replace("/", ".").split(".")[-1]
    for fs in graph.files.values():
        if graph.is_package_file(fs.path):
            continue
        local_defs = {name for name, _l, _e in fs.metric_defs}
        for name, line in fs.metric_refs:
            if name == pkg_name:
                continue  # the package's own name, not a metric
            if _metric_resolves(name, exact | local_defs, prefixes):
                continue
            out.append(Finding(
                rule="PML014", path=fs.path, line=line, col=0,
                message=(
                    f"metric {name!r} is not a name the package "
                    f"emits — a lookup on it silently reads nothing; "
                    f"check against `photon-lint --catalog`")))
    return out


def _metric_resolves(name: str, exact: set[str],
                     prefixes: set[str]) -> bool:
    if name in exact:
        return True
    for suf in _METRIC_SUFFIXES:
        if name.endswith(suf) and name[: -len(suf)] in exact:
            return True
    return any(name.startswith(p) for p in prefixes)


def _check_span_refs(graph: ProjectGraph) -> list[Finding]:
    spans = graph.span_catalog()
    if not spans:
        return []
    # Only names in a namespace the PACKAGE owns are checked: a
    # dev-script inventing its own "flagship.*" spans is defining, not
    # referencing; "serving.quue_wait" is a typo'd reference.
    namespaces = {s.split(".", 1)[0] for s in spans if "." in s}
    out = []
    for fs in graph.files.values():
        if graph.is_package_file(fs.path):
            continue
        for name, line in fs.span_defs:
            if "." not in name or name in spans \
                    or name.split(".", 1)[0] not in namespaces:
                continue
            out.append(Finding(
                rule="PML014", path=fs.path, line=line, col=0,
                message=(
                    f"span name {name!r} is not one the package "
                    f"starts — an assertion or summary keyed on it "
                    f"silently matches nothing; check against "
                    f"`photon-lint --catalog`")))
    return out


def _check_event_names(graph: ProjectGraph) -> list[Finding]:
    events = graph.event_catalog()
    if not events:
        return []
    out = []
    for fs in graph.files.values():
        for key, line in fs.event_maps:
            if key not in events:
                out.append(Finding(
                    rule="PML014", path=fs.path, line=line, col=0,
                    message=(
                        f"{key!r} maps to a photon_* counter but is "
                        f"not an event class in utils/events.py — "
                        f"the bridge would silently never count it")))
        # Equality switches: only functions that PROVABLY switch on
        # event names (at least one literal resolves) are checked.
        by_fn: dict[str, list[tuple[str, int]]] = {}
        for lit, line, fn in fs.event_compares:
            by_fn.setdefault(fn, []).append((lit, line))
        for fn, lits in by_fn.items():
            if not any(lit in events for lit, _l in lits):
                continue
            for lit, line in lits:
                if lit not in events:
                    out.append(Finding(
                        rule="PML014", path=fs.path, line=line, col=0,
                        message=(
                            f"{fn}() switches on event-class names "
                            f"but {lit!r} is not one — that branch "
                            f"silently never runs")))
    return out
