"""PML016 — resource lifecycle: acquire implies a guaranteed release.

The fleet layer's bug class: a subprocess, socket, HTTP server, mmap,
or worker pool acquired on a path where an exception between acquire
and release leaks it — a leaked replica subprocess keeps serving stale
shards, a leaked server socket blocks the next bind, a leaked pool
leaks OS threads for the process lifetime. The discipline this rule
mechanizes:

- acquire as a ``with`` item, or release in a ``finally``;
- or hand the resource off: return it, store it on another object,
  pass it to an owner — ownership transfer is fine, the new owner is
  then on the hook;
- or store it on ``self`` — then the CLASS must have a release method
  (``close``/``stop``/``shutdown``/``__exit__``/...) that closes that
  attribute.

Resource-ness propagates through the call graph: an intra-package
factory that returns ``ThreadingHTTPServer(...)`` makes its callers'
bindings resources too (``make_fleet_http_server`` is the repo's own
example). A release that exists but sits in straight-line code is
still flagged — it is not on the exception paths.
"""

from __future__ import annotations

from photon_ml_tpu.analysis.findings import Finding
from photon_ml_tpu.analysis.project import (RESOURCE_LEAFS, RESOURCE_NAMES,
                                            ProjectGraph)


def _is_resource_call(c) -> bool:
    return c.name in RESOURCE_NAMES or c.leaf in RESOURCE_LEAFS


def check_resource_lifecycle(graph: ProjectGraph) -> list[Finding]:
    # Resource-ness fixpoint: a function returning a resource makes its
    # call sites acquisitions too.
    rr: dict[tuple[str, str], bool] = {}
    resolved: dict[tuple[str, str, int], tuple] = {}
    items = []
    for fs in graph.files.values():
        for qname, fn in fs.functions.items():
            rr[(fs.path, qname)] = fn.returns_resource
            for c in fn.calls:
                r = graph.resolve_call(fs, c, caller=qname)
                if r is not None:
                    resolved[(fs.path, qname, id(c))] = \
                        (r[0].path, r[1].name)
                items.append((fs, qname, fn, c))
    for _ in range(4):
        changed = False
        for fs, qname, fn, c in items:
            if not (c.is_returned or c.bound_returned):
                continue
            tkey = resolved.get((fs.path, qname, id(c)))
            if _is_resource_call(c) or (tkey and rr.get(tkey)):
                if not rr[(fs.path, qname)]:
                    rr[(fs.path, qname)] = True
                    changed = True
        if not changed:
            break

    out: list[Finding] = []
    for fs, qname, fn, c in items:
        tkey = resolved.get((fs.path, qname, id(c)))
        if not (_is_resource_call(c) or (tkey and rr.get(tkey))):
            continue
        if c.with_item or c.is_returned:
            continue
        what = c.leaf if _is_resource_call(c) else c.name
        if c.binding == "bare":
            out.append(Finding(
                rule="PML016", path=fs.path, line=c.line, col=0,
                message=(
                    f"{qname}() acquires {what}(...) and discards the "
                    f"handle — nothing can ever release it; bind it "
                    f"and close in a finally, or use `with`")))
        elif c.binding.startswith("local:"):
            if c.bound_returned or c.bound_escapes \
                    or c.bound_closed_finally:
                continue
            if c.bound_closed:
                out.append(Finding(
                    rule="PML016", path=fs.path, line=c.line, col=0,
                    message=(
                        f"{qname}() closes its {what}(...) in "
                        f"straight-line code — a raise between acquire "
                        f"and close leaks it; move the close into a "
                        f"finally or use `with`")))
            else:
                out.append(Finding(
                    rule="PML016", path=fs.path, line=c.line, col=0,
                    message=(
                        f"{qname}() acquires {what}(...) into a local "
                        f"and never closes it on any path; close in a "
                        f"finally, use `with`, or hand it to an owner")))
        elif c.binding.startswith("self:"):
            attr = c.binding.split(":", 1)[1]
            cls_name = qname.split(".", 1)[0] if "." in qname else None
            cls = fs.classes.get(cls_name) if cls_name else None
            released = cls is not None and any(
                attr in m.closes_attrs for m in cls.methods.values())
            if not released:
                out.append(Finding(
                    rule="PML016", path=fs.path, line=c.line, col=0,
                    message=(
                        f"{qname}() stores {what}(...) on self.{attr} "
                        f"but no method of "
                        f"{cls_name or 'the class'} ever closes it — "
                        f"add a close()/stop() that releases it")))
    return out
