"""Shim: the shared AST walkers moved to ``analysis/astwalk.py`` so the
project graph (analysis/project.py) can use them without importing the
rules package (which imports the project-rule modules, which import the
project graph — a cycle). Rule modules keep importing from here."""

from photon_ml_tpu.analysis.astwalk import (assigned_names,  # noqa: F401
                                            scope_statements,
                                            self_attribute,
                                            statement_exprs)

__all__ = ["assigned_names", "scope_statements", "self_attribute",
           "statement_exprs"]
