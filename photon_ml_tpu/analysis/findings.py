"""Finding: one lint diagnostic, with a drift-stable fingerprint.

A finding is located by (path, line, col) for humans, but the BASELINE
matches findings by fingerprint: rule + path + the normalized source
snippet + the occurrence index of that snippet within the file. Line
numbers are deliberately excluded — inserting a docstring above a
grandfathered finding must not invalidate the whole baseline (the lesson
of every lint rollout that tried to pin line numbers).
"""

from __future__ import annotations

import dataclasses
import hashlib


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str  # "PML001" … "PML007" ("PML000" = meta: broken suppression)
    path: str  # repo-relative posix path
    line: int  # 1-based
    col: int  # 0-based
    message: str
    snippet: str = ""  # stripped source line at ``line``

    def location(self) -> str:
        return f"{self.path}:{self.line}:{self.col + 1}"

    def render(self) -> str:
        tail = f"  [{self.snippet}]" if self.snippet else ""
        return f"{self.location()}: {self.rule} {self.message}{tail}"

    def to_json(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message,
                "snippet": self.snippet}


def normalize_snippet(snippet: str) -> str:
    """Whitespace-insensitive snippet form (re-indenting a block must not
    rotate its fingerprint)."""
    return " ".join(snippet.split())


def fingerprint(rule: str, path: str, snippet: str, occurrence: int) -> str:
    key = f"{rule}|{path}|{normalize_snippet(snippet)}|{occurrence}"
    return hashlib.sha256(key.encode()).hexdigest()[:16]


def fingerprint_findings(findings: list[Finding]) -> list[tuple[str, Finding]]:
    """(fingerprint, finding) pairs; occurrence indices disambiguate
    repeated identical snippets within one file."""
    seen: dict[tuple[str, str, str], int] = {}
    out = []
    for f in findings:
        key = (f.rule, f.path, normalize_snippet(f.snippet))
        occ = seen.get(key, 0)
        seen[key] = occ + 1
        out.append((fingerprint(f.rule, f.path, f.snippet, occ), f))
    return out
