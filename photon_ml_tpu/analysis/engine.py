"""The photon-lint engine: walk files, run per-file rules, build the
project graph, run whole-program rules, apply suppressions and the
baseline, report.

Pure stdlib + AST — importing this package must NEVER import JAX (the
lint gate runs before/without a working accelerator stack and finishes in
seconds on the whole repo; tests assert the no-JAX property).

Two rule tiers share one parse per file:

- per-file rules (PML001-PML011) see a :class:`ModuleContext`;
- project rules (PML012-PML016) see a
  :class:`~photon_ml_tpu.analysis.project.ProjectGraph` built from
  per-file summaries extracted in the same pass.

The summaries and per-file findings are cached on disk keyed by file
size/mtime/CRC32 (``.photon-lint-cache.json``, fenced by a signature
over the analysis package's own sources), so a warm repo-wide run
re-parses only changed files and stays inside the CI wall-clock budget
(cold ≤ 15 s, warm ≤ 3 s — enforced by dev-scripts/run_tier1.sh).
"""

from __future__ import annotations

import ast
import dataclasses
import logging
import os
from typing import Iterable, Optional

from photon_ml_tpu.analysis import baseline as bl
from photon_ml_tpu.analysis import locks as lk
from photon_ml_tpu.analysis import project as pj
from photon_ml_tpu.analysis.context import ModuleContext
from photon_ml_tpu.analysis.findings import Finding
from photon_ml_tpu.analysis.rules import ALL_RULES, PROJECT_RULES
from photon_ml_tpu.analysis.suppressions import (Suppression,
                                                 apply_suppressions,
                                                 next_code_lines,
                                                 parse_suppressions)

_SKIP_DIRS = {"__pycache__", ".git", ".ruff_cache", ".pytest_cache",
              "node_modules"}


@dataclasses.dataclass
class LintResult:
    findings: list[Finding]  # gating findings (not suppressed/baselined)
    files: int
    baselined: int = 0
    stale_baseline: list[bl.BaselineEntry] = \
        dataclasses.field(default_factory=list)
    unused_suppressions: list[tuple[str, int]] = \
        dataclasses.field(default_factory=list)  # (path, line)
    graph_files: int = 0     # files summarized into the project graph
    cache_hits: int = 0
    cache_misses: int = 0
    catalog: Optional[dict] = None  # built on demand (CLI --catalog)
    lock_graph: Optional[dict] = None  # built on demand (CLI --locks)

    @property
    def exit_code(self) -> int:
        return 1 if self.findings else 0


def iter_python_files(paths: Iterable[str]) -> list[str]:
    out = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = sorted(d for d in dirs
                             if d not in _SKIP_DIRS
                             and not d.startswith("."))
            for f in sorted(files):
                if f.endswith(".py"):
                    out.append(os.path.join(root, f))
    return sorted(dict.fromkeys(os.path.normpath(p) for p in out))


def _rule_items(select: Optional[set[str]], ignore: Optional[set[str]],
                registry=None):
    items = []
    for rid, (check, _doc) in (registry or ALL_RULES).items():
        if select and rid not in select:
            continue
        if ignore and rid in ignore:
            continue
        items.append((rid, check))
    return items


def lint_file(path: str, select: Optional[set[str]] = None,
              ignore: Optional[set[str]] = None
              ) -> tuple[list[Finding], list[tuple[str, int]]]:
    """(findings, unused-suppression sites) for one file, per-file rules
    only. Findings include PML000 meta-diagnostics (reasonless allows,
    parse errors). Project rules need :func:`lint_paths`."""
    kept, unused, _sups, _summary = _lint_file_full(path)
    if select or ignore:
        keep_ids = {rid for rid, _ in _rule_items(select, ignore)}
        keep_ids.add("PML000")
        kept = [f for f in kept if f.rule in keep_ids]
    return kept, unused


def _lint_file_full(path: str):
    """One parse of ``path`` → (kept findings for ALL per-file rules,
    unused suppression sites, suppression records, project summary)."""
    rel = os.path.relpath(path).replace(os.sep, "/")
    with open(path, encoding="utf-8") as fh:
        source = fh.read()
    lines = source.splitlines()
    sups, meta = parse_suppressions(rel, source)
    try:
        ctx = ModuleContext.parse(rel, source)
    except SyntaxError as exc:
        meta.append(Finding(
            rule="PML000", path=rel, line=exc.lineno or 0, col=0,
            message=f"file does not parse: {exc.msg}"))
        return meta, [], [], None
    findings = [f for rid, check in _rule_items(None, None)
                for f in _check_safely(rid, check, ctx)]
    code_after = next_code_lines(lines)
    kept = apply_suppressions(findings, sups, code_after)
    unused = [(rel, s.line) for s in sups if not s.used]
    kept.extend(meta)  # meta-diagnostics are never suppressible
    try:
        summary = pj.summarize_file(rel, ctx.tree, source)
    except Exception as exc:
        # A summary crash must not break per-file lint, but it silently
        # removes this file from the project graph — say so.
        logging.getLogger("photon_ml_tpu.analysis").warning(
            "project summary failed for %s: %s: %s", rel,
            type(exc).__name__, exc)
        summary = None
    sup_records = [[s.line, list(s.rules), s.reason, s.standalone,
                    code_after.get(s.line, 0), s.used] for s in sups]
    return kept, unused, sup_records, summary


def _check_safely(rid: str, check, ctx: ModuleContext) -> list[Finding]:
    try:
        return check(ctx)
    except Exception as exc:
        return [Finding(
            rule="PML000", path=ctx.path, line=0, col=0,
            message=f"rule {rid} crashed on this file: "
                    f"{type(exc).__name__}: {exc}")]


def _findings_to_json(findings: list[Finding]) -> list[dict]:
    return [f.to_json() for f in findings]


def _findings_from_json(rows: list[dict]) -> list[Finding]:
    return [Finding(**row) for row in rows]


class _SnippetCache:
    def __init__(self):
        self._lines: dict[str, list[str]] = {}

    def get(self, path: str, line: int) -> str:
        if path not in self._lines:
            try:
                with open(path, encoding="utf-8") as fh:
                    self._lines[path] = fh.read().splitlines()
            except OSError:
                self._lines[path] = []
        lines = self._lines[path]
        if 1 <= line <= len(lines):
            return lines[line - 1].strip()
        return ""


def lint_paths(paths: Iterable[str],
               select: Optional[set[str]] = None,
               ignore: Optional[set[str]] = None,
               baseline_path: Optional[str] = None,
               project: bool = True,
               cache_path: Optional[str] = None,
               package_prefix: str = "photon_ml_tpu",
               want_catalog: bool = False,
               want_locks: bool = False) -> LintResult:
    requested = iter_python_files(paths)
    graph_files = list(requested)
    if project and os.path.isdir(package_prefix):
        # The registries PML014 resolves against live in the package;
        # linting tests/ or dev-scripts/ alone must still see them.
        graph_files = sorted(set(requested)
                             | set(iter_python_files([package_prefix])))

    cache = pj.ProjectCache(cache_path) if cache_path else None
    requested_set = set(requested)
    findings: list[Finding] = []
    unused_candidates: list[tuple[str, int]] = []
    summaries: dict[str, pj.FileSummary] = {}
    sups_by_path: dict[str, list[Suppression]] = {}
    nextcode_by_path: dict[str, dict[int, int]] = {}

    for path in graph_files:
        rel = os.path.relpath(path).replace(os.sep, "/")
        entry = cache.lookup(path) if cache else None
        if entry is not None:
            kept = _findings_from_json(entry["findings"])
            unused = [tuple(u) for u in entry["unused"]]
            sup_records = entry["suppressions"]
            summary = (pj.summary_from_dict(entry["summary"])
                       if entry["summary"] is not None else None)
        else:
            kept, unused, sup_records, summary = _lint_file_full(path)
            if cache:
                cache.store(path, summary, _findings_to_json(kept),
                            [list(u) for u in unused], sup_records)
        sups = []
        nextcode = {}
        for line, rules, reason, standalone, next_code, used in \
                sup_records:
            s = Suppression(line=line, rules=tuple(rules), reason=reason,
                            standalone=standalone, used=used)
            sups.append(s)
            nextcode[line] = next_code
        sups_by_path[rel] = sups
        nextcode_by_path[rel] = nextcode
        if summary is not None:
            summaries[rel] = summary
        if path in requested_set:
            findings.extend(kept)
            unused_candidates.extend(unused)

    graph = pj.ProjectGraph(summaries, package_prefix=package_prefix) \
        if (project or want_catalog or want_locks) else None

    project_findings: list[Finding] = []
    if project and graph is not None:
        for rid, check in _rule_items(select, ignore, PROJECT_RULES):
            try:
                project_findings.extend(check(graph))
            except Exception as exc:
                project_findings.append(Finding(
                    rule="PML000", path="<project>", line=0, col=0,
                    message=f"project rule {rid} crashed: "
                            f"{type(exc).__name__}: {exc}"))
        # Fill snippets (project rules only know line numbers) and
        # apply the owning file's inline suppressions.
        snip = _SnippetCache()
        requested_rel = {os.path.relpath(p).replace(os.sep, "/")
                         for p in requested_set}
        kept_project = []
        for f in project_findings:
            f = dataclasses.replace(f, snippet=snip.get(f.path, f.line))
            covered = False
            for s in sups_by_path.get(f.path, ()):
                nxt = nextcode_by_path.get(f.path, {}).get(s.line, 0)
                if s.covers(f.rule, f.line, nxt):
                    s.used = True
                    covered = True
                    break
            if not covered and (f.path in requested_rel
                                or f.path == "<project>"):
                kept_project.append(f)
        findings.extend(kept_project)
        # One finding per site: when PML019 (blocking under a lock) and
        # PML011 (blocking without a timeout) land on the same line, the
        # lock finding subsumes the timeout one — same call, and the
        # lock context is the sharper diagnosis.
        lock_sites = {(f.path, f.line) for f in findings
                      if f.rule == "PML019"}
        if lock_sites:
            findings = [f for f in findings
                        if f.rule != "PML011"
                        or (f.path, f.line) not in lock_sites]
        # A suppression the per-file pass left unused may have just been
        # consumed by a project finding.
        unused_candidates = [
            (p, line) for p, line in unused_candidates
            if not any(s.line == line and s.used
                       for s in sups_by_path.get(p, ()))]

    if select or ignore:
        keep_ids = {rid for rid, _ in _rule_items(select, ignore)}
        keep_ids |= {rid for rid, _ in _rule_items(select, ignore,
                                                   PROJECT_RULES)}
        keep_ids.add("PML000")
        findings = [f for f in findings if f.rule in keep_ids]

    if cache:
        cache.save(graph_files)

    result = LintResult(findings=findings, files=len(requested),
                        unused_suppressions=unused_candidates,
                        graph_files=len(graph_files),
                        cache_hits=cache.hits if cache else 0,
                        cache_misses=cache.misses if cache else 0)
    if want_catalog and graph is not None:
        result.catalog = pj.build_catalog(graph)
    if want_locks and graph is not None:
        result.lock_graph = lk.lock_graph_json(graph)
    if baseline_path and os.path.exists(baseline_path):
        entries = bl.load_baseline(baseline_path)
        res = bl.apply_baseline(result.findings, entries, baseline_path)
        result.findings = res.kept + res.meta
        result.baselined = res.matched
        result.stale_baseline = res.stale
    result.findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return result


def parse_ok(source: str) -> bool:
    """Cheap helper for tests: does this fixture even parse?"""
    try:
        ast.parse(source)
        return True
    except SyntaxError:
        return False
