"""The photon-lint engine: walk files, run rules, apply suppressions and
the baseline, report.

Pure stdlib + AST — importing this package must NEVER import JAX (the
lint gate runs before/without a working accelerator stack and finishes in
seconds on the whole repo; tests assert the no-JAX property).
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Iterable, Optional

from photon_ml_tpu.analysis import baseline as bl
from photon_ml_tpu.analysis.context import ModuleContext
from photon_ml_tpu.analysis.findings import Finding
from photon_ml_tpu.analysis.rules import ALL_RULES
from photon_ml_tpu.analysis.suppressions import (apply_suppressions,
                                                 next_code_lines,
                                                 parse_suppressions)

_SKIP_DIRS = {"__pycache__", ".git", ".ruff_cache", ".pytest_cache",
              "node_modules"}


@dataclasses.dataclass
class LintResult:
    findings: list[Finding]  # gating findings (not suppressed/baselined)
    files: int
    baselined: int = 0
    stale_baseline: list[bl.BaselineEntry] = \
        dataclasses.field(default_factory=list)
    unused_suppressions: list[tuple[str, int]] = \
        dataclasses.field(default_factory=list)  # (path, line)

    @property
    def exit_code(self) -> int:
        return 1 if self.findings else 0


def iter_python_files(paths: Iterable[str]) -> list[str]:
    out = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = sorted(d for d in dirs
                             if d not in _SKIP_DIRS
                             and not d.startswith("."))
            for f in sorted(files):
                if f.endswith(".py"):
                    out.append(os.path.join(root, f))
    return sorted(dict.fromkeys(os.path.normpath(p) for p in out))


def _rule_items(select: Optional[set[str]], ignore: Optional[set[str]]):
    items = []
    for rid, (check, _doc) in ALL_RULES.items():
        if select and rid not in select:
            continue
        if ignore and rid in ignore:
            continue
        items.append((rid, check))
    return items


def lint_file(path: str, select: Optional[set[str]] = None,
              ignore: Optional[set[str]] = None
              ) -> tuple[list[Finding], list[tuple[str, int]]]:
    """(findings, unused-suppression sites) for one file. Findings
    include PML000 meta-diagnostics (reasonless allows, parse errors)."""
    rel = os.path.relpath(path).replace(os.sep, "/")
    with open(path, encoding="utf-8") as fh:
        source = fh.read()
    lines = source.splitlines()
    sups, meta = parse_suppressions(rel, source)
    try:
        ctx = ModuleContext.parse(rel, source)
    except SyntaxError as exc:
        meta.append(Finding(
            rule="PML000", path=rel, line=exc.lineno or 0, col=0,
            message=f"file does not parse: {exc.msg}"))
        return meta, []
    findings: list[Finding] = []
    for rid, check in _rule_items(select, ignore):
        try:
            findings.extend(check(ctx))
        except Exception as exc:  # a broken rule must fail loud, not pass
            findings.append(Finding(
                rule="PML000", path=rel, line=0, col=0,
                message=f"rule {rid} crashed on this file: "
                        f"{type(exc).__name__}: {exc}"))
    code_after = next_code_lines(lines)
    kept = apply_suppressions(findings, sups, code_after)
    unused = [(rel, s.line) for s in sups if not s.used]
    kept.extend(meta)  # meta-diagnostics are never suppressible
    return kept, unused


def lint_paths(paths: Iterable[str],
               select: Optional[set[str]] = None,
               ignore: Optional[set[str]] = None,
               baseline_path: Optional[str] = None) -> LintResult:
    files = iter_python_files(paths)
    findings: list[Finding] = []
    unused: list[tuple[str, int]] = []
    for path in files:
        f, u = lint_file(path, select=select, ignore=ignore)
        findings.extend(f)
        unused.extend(u)
    result = LintResult(findings=findings, files=len(files),
                        unused_suppressions=unused)
    if baseline_path and os.path.exists(baseline_path):
        entries = bl.load_baseline(baseline_path)
        res = bl.apply_baseline(findings, entries, baseline_path)
        result.findings = res.kept + res.meta
        result.baselined = res.matched
        result.stale_baseline = res.stale
    result.findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return result


def parse_ok(source: str) -> bool:
    """Cheap helper for tests: does this fixture even parse?"""
    try:
        ast.parse(source)
        return True
    except SyntaxError:
        return False
