"""Committed baseline: grandfather existing findings so the gate starts
green on day one, then ratchets — new findings fail, fixed findings turn
their baseline entry STALE (reported so the entry gets deleted, keeping
the debt ledger honest).

Format (JSON, committed at the repo root as ``.photon-lint-baseline.json``):

    {"version": 1,
     "entries": [{"fingerprint": "…", "rule": "PML006", "path": "…",
                  "snippet": "…", "reason": "why this is grandfathered"}]}

Every entry carries a reason, same contract as inline suppressions; an
entry without one is reported as PML000 and fails the gate.
"""

from __future__ import annotations

import dataclasses
import json
import os

from photon_ml_tpu.analysis.findings import Finding, fingerprint_findings

DEFAULT_BASELINE = ".photon-lint-baseline.json"
_VERSION = 1


@dataclasses.dataclass
class BaselineEntry:
    fingerprint: str
    rule: str
    path: str
    snippet: str
    reason: str

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class BaselineResult:
    kept: list[Finding]  # findings NOT in the baseline (these gate)
    matched: int  # findings absorbed by the baseline
    stale: list[BaselineEntry]  # entries whose finding no longer exists
    meta: list[Finding]  # PML000 for reasonless entries


def load_baseline(path: str) -> list[BaselineEntry]:
    with open(path) as fh:
        doc = json.load(fh)
    if doc.get("version") != _VERSION:
        raise ValueError(f"unsupported baseline version "
                         f"{doc.get('version')!r} in {path}")
    return [BaselineEntry(
        fingerprint=e["fingerprint"], rule=e["rule"], path=e["path"],
        snippet=e.get("snippet", ""), reason=e.get("reason", ""))
        for e in doc.get("entries", [])]


def save_baseline(path: str, entries: list[BaselineEntry]) -> None:
    doc = {"version": _VERSION,
           "entries": [e.to_json() for e in
                       sorted(entries, key=lambda e: (e.path, e.rule,
                                                      e.fingerprint))]}
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(doc, fh, indent=2, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, path)


def entries_from_findings(findings: list[Finding],
                          reason: str) -> list[BaselineEntry]:
    return [BaselineEntry(fingerprint=fp, rule=f.rule, path=f.path,
                          snippet=f.snippet, reason=reason)
            for fp, f in fingerprint_findings(findings)]


def apply_baseline(findings: list[Finding],
                   entries: list[BaselineEntry],
                   baseline_path: str) -> BaselineResult:
    by_fp = {fp: f for fp, f in fingerprint_findings(findings)}
    matched_fps = set()
    stale = []
    meta = []
    for e in entries:
        if not e.reason.strip():
            meta.append(Finding(
                rule="PML000", path=baseline_path, line=0, col=0,
                message=f"baseline entry {e.fingerprint} ({e.rule} in "
                        f"{e.path}) carries no reason",
                snippet=e.snippet))
            continue
        if e.fingerprint in by_fp:
            matched_fps.add(e.fingerprint)
        else:
            stale.append(e)
    kept = [f for fp, f in fingerprint_findings(findings)
            if fp not in matched_fps]
    return BaselineResult(kept=kept, matched=len(matched_fps),
                          stale=stale, meta=meta)
