"""Shared walkers: per-scope statement iteration with loop depth, and
expression iteration that respects deferred-execution boundaries."""

from __future__ import annotations

import ast
from typing import Iterator

_SCOPE_STMTS = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)


def scope_statements(body: list[ast.stmt], depth: int = 0
                     ) -> Iterator[tuple[ast.stmt, int]]:
    """Yield (statement, loop_depth) for one scope, NOT descending into
    nested function/class bodies (those are separate scopes — their code
    runs when called, not where it is written)."""
    for stmt in body:
        yield stmt, depth
        if isinstance(stmt, _SCOPE_STMTS):
            continue
        if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
            yield from scope_statements(stmt.body, depth + 1)
            yield from scope_statements(stmt.orelse, depth)
        elif isinstance(stmt, ast.If):
            yield from scope_statements(stmt.body, depth)
            yield from scope_statements(stmt.orelse, depth)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            yield from scope_statements(stmt.body, depth)
        elif isinstance(stmt, ast.Try):
            yield from scope_statements(stmt.body, depth)
            for h in stmt.handlers:
                yield from scope_statements(h.body, depth)
            yield from scope_statements(stmt.orelse, depth)
            yield from scope_statements(stmt.finalbody, depth)


def statement_exprs(stmt: ast.stmt) -> Iterator[ast.AST]:
    """Every expression node executed AS PART of this statement: skips
    nested def/class/lambda bodies (deferred) and the statement's own
    nested block statements (yielded separately by scope_statements)."""
    blocks = []
    if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While, ast.If)):
        blocks = [stmt.body, stmt.orelse]
    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
        blocks = [stmt.body]
    elif isinstance(stmt, ast.Try):
        blocks = [stmt.body, stmt.orelse, stmt.finalbody] \
            + [h.body for h in stmt.handlers]
    skip = {id(s) for b in blocks for s in b}

    def walk(node: ast.AST) -> Iterator[ast.AST]:
        for child in ast.iter_child_nodes(node):
            if id(child) in skip or isinstance(child, _SCOPE_STMTS):
                continue
            if isinstance(child, ast.Lambda):
                continue
            yield child
            yield from walk(child)

    if isinstance(stmt, _SCOPE_STMTS):
        # Only the decorators/defaults run here, not the body.
        for dec in getattr(stmt, "decorator_list", []):
            yield dec
            yield from walk(dec)
        return
    yield from walk(stmt)


def assigned_names(stmt: ast.stmt) -> set[str]:
    """Plain names bound by this statement (tuple targets flattened)."""
    out: set[str] = set()

    def grab(t: ast.AST) -> None:
        if isinstance(t, ast.Name):
            out.add(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                grab(e)
        elif isinstance(t, ast.Starred):
            grab(t.value)

    if isinstance(stmt, ast.Assign):
        for t in stmt.targets:
            grab(t)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        grab(stmt.target)
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        grab(stmt.target)
    for node in statement_exprs(stmt):
        if isinstance(node, ast.NamedExpr):
            grab(node.target)
    return out


def self_attribute(node: ast.AST) -> str | None:
    """'x' when node is ``self.x`` (one level), else None."""
    if isinstance(node, ast.Attribute) \
            and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None
