"""Inline suppressions: ``# pml: allow[PML00N] reason``.

A suppression covers findings of the named rule(s) on its own physical
line, or — when the comment stands alone — on the next non-blank line
(so multi-call statements can carry one justification above them).
Multiple rules: ``# pml: allow[PML001,PML006] reason``.

The reason is MANDATORY: a reasonless allow is itself reported (PML000),
so the suppression inventory stays reviewable — every silenced finding
says why it is safe, in the line that silences it.
"""

from __future__ import annotations

import dataclasses
import io
import re
import tokenize

from photon_ml_tpu.analysis.findings import Finding

_ALLOW_RE = re.compile(
    r"#\s*pml:\s*allow\[(?P<rules>[A-Z]{3}\d{3}(?:\s*,\s*[A-Z]{3}\d{3})*)\]"
    r"\s*(?P<reason>.*)$")


@dataclasses.dataclass
class Suppression:
    line: int  # line the comment sits on (1-based)
    rules: tuple[str, ...]
    reason: str
    standalone: bool  # comment-only line → also covers the next code line
    used: bool = False

    def covers(self, rule: str, line: int, next_code_line: int) -> bool:
        if rule not in self.rules:
            return False
        if line == self.line:
            return True
        return self.standalone and line == next_code_line


def _comment_tokens(source: str):
    """(line, col, text) of every real COMMENT token — tokenizing (not
    line-regexing) keeps allow-syntax examples inside docstrings from
    registering as suppressions."""
    try:
        toks = tokenize.generate_tokens(io.StringIO(source).readline)
        return [(t.start[0], t.start[1], t.string) for t in toks
                if t.type == tokenize.COMMENT]
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return []


def parse_suppressions(path: str, source: str
                       ) -> tuple[list[Suppression], list[Finding]]:
    """(suppressions, meta-findings). Meta-findings are PML000 diagnostics
    for allows with no reason — those never silence anything."""
    sups: list[Suppression] = []
    meta: list[Finding] = []
    for line, col, text in _comment_tokens(source):
        m = _ALLOW_RE.search(text)
        if not m:
            continue
        rules = tuple(r.strip() for r in m.group("rules").split(","))
        reason = m.group("reason").strip()
        if not reason:
            meta.append(Finding(
                rule="PML000", path=path, line=line, col=col,
                message=f"suppression of {','.join(rules)} carries no "
                        f"reason — every allow must say why it is safe",
                snippet=text.strip()))
            continue
        sups.append(Suppression(line=line, rules=rules, reason=reason,
                                standalone=_standalone(source, line)))
    return sups, meta


def _standalone(source: str, line: int) -> bool:
    lines = source.splitlines()
    return 1 <= line <= len(lines) and lines[line - 1].lstrip().startswith("#")


def next_code_lines(lines: list[str]) -> dict[int, int]:
    """line → the next non-blank, non-comment-only line after it (for
    standalone suppression coverage)."""
    out: dict[int, int] = {}
    nxt = 0
    for i in range(len(lines), 0, -1):
        out[i] = nxt
        stripped = lines[i - 1].strip()
        if stripped and not stripped.startswith("#"):
            nxt = i
    return out


def apply_suppressions(findings: list[Finding], sups: list[Suppression],
                       code_after: dict[int, int]) -> list[Finding]:
    """Drop findings covered by a suppression (marking it used)."""
    kept = []
    for f in findings:
        covered = False
        for s in sups:
            if s.covers(f.rule, f.line, code_after.get(s.line, 0)):
                s.used = True
                covered = True
                break
        if not covered:
            kept.append(f)
    return kept
