"""photon-lint: AST static analysis that mechanizes this repo's
hard-won JAX/concurrency bug classes.

Seven rules, each derived from a bug this codebase actually shipped and
debugged (see docs/ANALYSIS.md for the before/after stories):

- PML001  host-device sync in hot paths
- PML002  recompilation hazards at jit boundaries
- PML003  tracer leaks out of traced functions
- PML004  wall-clock durations/deadlines
- PML005  unguarded shared mutable state on thread seams
- PML006  nondeterministic numeric accumulation
- PML007  unbalanced lifecycle events

Entry points: the ``photon-lint`` console script (cli/lint.py), or
``lint_paths()`` here. Pure stdlib — no JAX import, repo-wide in seconds.
"""

from photon_ml_tpu.analysis.baseline import (BaselineEntry, DEFAULT_BASELINE,
                                             entries_from_findings,
                                             load_baseline, save_baseline)
from photon_ml_tpu.analysis.engine import (LintResult, iter_python_files,
                                           lint_file, lint_paths)
from photon_ml_tpu.analysis.findings import Finding, fingerprint_findings
from photon_ml_tpu.analysis.rules import ALL_RULES

__all__ = [
    "ALL_RULES", "BaselineEntry", "DEFAULT_BASELINE", "Finding",
    "LintResult", "entries_from_findings", "fingerprint_findings",
    "iter_python_files", "lint_file", "lint_paths", "load_baseline",
    "save_baseline",
]
