"""photon-lint: AST static analysis that mechanizes this repo's
hard-won JAX/concurrency bug classes.

Per-file rules, each derived from a bug this codebase actually shipped
and debugged (see docs/ANALYSIS.md for the before/after stories):

- PML001  host-device sync in hot paths
- PML002  recompilation hazards at jit boundaries
- PML003  tracer leaks out of traced functions
- PML004  wall-clock durations/deadlines
- PML005  unguarded shared mutable state on thread seams
- PML006  nondeterministic numeric accumulation
- PML007  unbalanced lifecycle events
- PML008  swallowed broad exceptions
- PML009  raw tracer spans outside with/finally
- PML010  raw telemetry writes in loops
- PML011  blocking network calls without timeouts

Whole-program rules over the project graph (analysis/project.py —
symbol table + call graph + cached per-file summaries):

- PML012  cross-module host-device sync chains in loops
- PML013  raw writes breaking the .ok-marker crash-consistency protocol
- PML014  string-registry drift (fault sites, metrics, spans, events)
- PML015  cross-class callbacks writing shared state off-thread
- PML016  resource lifecycle (subprocess/socket/server/pool leaks)
- PML018  lock-order cycles in the global lock graph (photon-lockdep)
- PML019  blocking calls reached while a lock is held

Entry points: the ``photon-lint`` console script (cli/lint.py), or
``lint_paths()`` here. Pure stdlib — no JAX import, repo-wide in
seconds (``.photon-lint-cache.json`` keeps warm runs under ~3 s).
"""

from photon_ml_tpu.analysis.baseline import (BaselineEntry, DEFAULT_BASELINE,
                                             entries_from_findings,
                                             load_baseline, save_baseline)
from photon_ml_tpu.analysis.engine import (LintResult, iter_python_files,
                                           lint_file, lint_paths)
from photon_ml_tpu.analysis.findings import Finding, fingerprint_findings
from photon_ml_tpu.analysis.locks import (lock_graph_json, reconcile)
from photon_ml_tpu.analysis.project import (DEFAULT_CACHE, ProjectCache,
                                            ProjectGraph, build_catalog,
                                            summarize_file)
from photon_ml_tpu.analysis.rules import ALL_RULES, PROJECT_RULES

__all__ = [
    "ALL_RULES", "BaselineEntry", "DEFAULT_BASELINE", "DEFAULT_CACHE",
    "Finding", "LintResult", "PROJECT_RULES", "ProjectCache",
    "ProjectGraph", "build_catalog", "entries_from_findings",
    "fingerprint_findings", "iter_python_files", "lint_file",
    "lint_paths", "load_baseline", "lock_graph_json", "reconcile",
    "save_baseline", "summarize_file",
]
