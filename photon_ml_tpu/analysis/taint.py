"""Device-value taint: which expressions plausibly hold JAX arrays.

Pure-AST heuristic (no imports resolved, no types): an expression is
"device-ish" when it is built from ``jnp.*`` / ``jax.*`` / ``lax.*``
calls, from names assigned such values earlier in the same scope, or from
calls fed a device-ish argument (functions over device values generally
return device values — the propagation that makes ``f, g =
value_and_grad(w)`` device-ish when ``w`` is). Host casts
(``float``/``int``/``np.*``/``.item()``/``jax.device_get``) launder the
taint: their RESULT is host — the cast itself is where PML001 fires.

The scope model is deliberately simple: one taint set per function body
(module top level counts as one body), computed by two forward passes so
loop-carried assignments converge; nested function bodies are analyzed
independently. Over-taint is acceptable — rules pair taint with a second
signal (inside a loop, stored on self, …) before flagging.
"""

from __future__ import annotations

import ast
from typing import Optional

DEVICE_MODULES = {"jnp", "jax", "lax"}
HOST_CASTS = {"float", "int", "bool", "complex", "str", "len", "repr"}
HOST_MODULES = {"np", "numpy", "math", "os", "time", "json", "logging"}
# jax.* attributes that return CALLABLES (transform factories), not arrays.
TRANSFORM_FACTORIES = {"jit", "vmap", "pmap", "grad", "value_and_grad",
                       "checkpoint", "custom_jvp", "custom_vjp",
                       "named_call", "shard_map"}
# Methods/calls whose result lands on the host.
HOST_SINK_METHODS = {"item", "tolist", "device_get"}


def dotted_name(node: ast.AST) -> Optional[str]:
    """'jax.numpy.dot' for nested Attribute/Name chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_func_name(call: ast.Call) -> Optional[str]:
    return dotted_name(call.func)


class TaintScope:
    """Tainted names within one function (or module) body."""

    def __init__(self, body: list[ast.stmt],
                 pre_tainted: Optional[set[str]] = None):
        self.tainted: set[str] = set(pre_tainted or ())
        for _ in range(2):  # two passes ≈ fixpoint for loop-carried taint
            for stmt in body:
                self._visit_stmt(stmt)

    # -- expression classification ---------------------------------------

    def is_device(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Attribute):
            # x.T / x.dtype-ish chains on a device value; bare module
            # attributes (jnp.float32) are not values.
            return self.is_device(node.value)
        if isinstance(node, ast.Subscript):
            return self.is_device(node.value)
        if isinstance(node, (ast.BinOp,)):
            return self.is_device(node.left) or self.is_device(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.is_device(node.operand)
        if isinstance(node, ast.Compare):
            return self.is_device(node.left) or any(
                self.is_device(c) for c in node.comparators)
        if isinstance(node, ast.IfExp):
            return self.is_device(node.body) or self.is_device(node.orelse)
        if isinstance(node, (ast.Tuple, ast.List)):
            return any(self.is_device(e) for e in node.elts)
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            return self.is_device(node.elt)
        if isinstance(node, ast.NamedExpr):
            return self.is_device(node.value)
        if isinstance(node, ast.Call):
            return self._call_is_device(node)
        return False

    def _call_is_device(self, call: ast.Call) -> bool:
        name = call_func_name(call)
        if name is not None:
            root, _, rest = name.partition(".")
            leaf = name.rsplit(".", 1)[-1]
            if leaf in HOST_SINK_METHODS or name in HOST_CASTS:
                return False
            if root in DEVICE_MODULES:
                # jax.jit(f) yields a callable; jnp.dot(...) yields device.
                return leaf not in TRANSFORM_FACTORIES
            if root in HOST_MODULES:
                return False
        # Method call on a device value (x.sum()) or any call fed a
        # device argument: propagate.
        if isinstance(call.func, ast.Attribute) \
                and self.is_device(call.func.value):
            return True
        return any(self.is_device(a) for a in call.args) or any(
            self.is_device(k.value) for k in call.keywords)

    # -- statement walk ----------------------------------------------------

    def _taint_target(self, target: ast.AST) -> None:
        if isinstance(target, ast.Name):
            self.tainted.add(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._taint_target(e)
        elif isinstance(target, ast.Starred):
            self._taint_target(target.value)

    def _visit_stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # nested bodies get their own scope
        if isinstance(stmt, ast.Assign):
            if self.is_device(stmt.value):
                for t in stmt.targets:
                    self._taint_target(t)
        elif isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None and self.is_device(stmt.value):
                self._taint_target(stmt.target)
        elif isinstance(stmt, ast.AugAssign):
            if self.is_device(stmt.value) or self.is_device(stmt.target):
                self._taint_target(stmt.target)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            if self.is_device(stmt.iter):
                self._taint_target(stmt.target)
            for s in stmt.body + stmt.orelse:
                self._visit_stmt(s)
        elif isinstance(stmt, ast.While):
            for s in stmt.body + stmt.orelse:
                self._visit_stmt(s)
        elif isinstance(stmt, ast.If):
            for s in stmt.body + stmt.orelse:
                self._visit_stmt(s)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for s in stmt.body:
                self._visit_stmt(s)
        elif isinstance(stmt, ast.Try):
            for s in (stmt.body + stmt.orelse + stmt.finalbody
                      + [h for hh in stmt.handlers for h in hh.body]):
                self._visit_stmt(s)


def function_bodies(tree: ast.Module):
    """Yield (node, body) for the module and every (async) function in it
    — the per-scope unit rules iterate."""
    yield tree, tree.body
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node, node.body
