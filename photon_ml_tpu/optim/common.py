"""Shared optimizer scaffolding: configs, results, convergence, tracking.

Reference parity: photon-lib ``optimization/Optimizer.scala``,
``OptimizerConfig.scala``, ``OptimizerType.scala``,
``OptimizationStatesTracker.scala`` / ``OptimizerState.scala``.

TPU-first design: optimizers are pure functions ``(objective, w0) → OptResult``
compiled as ``lax.while_loop`` state machines with static shapes. Two
requirements shape everything here (SURVEY.md §7):

1. **vmap-ability** — the same optimizer must run as one big fixed-effect
   solve AND as thousands of per-entity random-effect solves batched under
   ``vmap``. Under vmap, ``while_loop`` keeps stepping until every lane's
   cond is false, and *done lanes keep executing the body*; therefore every
   state update is masked with the per-lane ``converged`` flag so finished
   lanes are frozen rather than perturbed.
2. **fixed-shape history** — per-iteration (value, grad-norm) history is
   recorded into preallocated ``max_iterations``-length buffers (the
   ``OptimizationStatesTracker`` analogue), NaN-padded past convergence.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Callable, Optional

import jax
import jax.numpy as jnp

Array = jax.Array

# objective(w) -> (value, grad). Regularization is folded in by the caller
# (see photon_ml_tpu/optim/regularization.py).
ValueAndGrad = Callable[[Array], tuple[Array, Array]]
# hvp(w, v) -> H·v for TRON.
Hvp = Callable[[Array, Array], Array]


class OptimizerType(enum.Enum):
    LBFGS = "LBFGS"
    OWLQN = "OWLQN"
    TRON = "TRON"
    # Stochastic solvers — streamed path only (optim/stochastic.py):
    # duality-gap-certified dual coordinate ascent and its primal
    # mini-batch fallback. ``optimize()`` rejects them (there is no
    # compiled device-resident variant); the streamed coordinate
    # dispatches them behind the minimize_streaming contract.
    SDCA = "SDCA"
    SGD = "SGD"


@dataclasses.dataclass(frozen=True)
class OptimizerConfig:
    """Reference parity: OptimizerConfig (type, maxIter, tolerance)."""

    optimizer_type: OptimizerType = OptimizerType.LBFGS
    max_iterations: int = 100
    tolerance: float = 1e-7
    # L-BFGS/OWL-QN history length (Breeze default m=10).
    history_length: int = 10
    # Max line-search / inner-CG steps (static bounds for while_loops).
    max_line_search_steps: int = 25
    max_cg_iterations: int = 20
    # Strong-Wolfe constants (Breeze StrongWolfeLineSearch defaults):
    # sufficient decrease c1, curvature c2.
    wolfe_c1: float = 1e-4
    wolfe_c2: float = 0.9


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class OptResult:
    """Final state + per-iteration history (OptimizationStatesTracker)."""

    w: Array
    value: Array
    grad_norm: Array
    iterations: Array  # int32, iterations actually executed
    converged: Array  # bool
    value_history: Array  # (max_iterations + 1,), NaN past the end
    grad_norm_history: Array  # (max_iterations + 1,), NaN past the end


def masked_update(converged: Array, new, old):
    """Freeze a pytree once this lane has converged (vmap safety)."""
    def _sel(n, o):
        c = jnp.reshape(converged, converged.shape + (1,) * (n.ndim - converged.ndim))
        return jnp.where(c, o, n)
    return jax.tree.map(_sel, new, old)


def check_convergence(
    value: Array,
    prev_value: Array,
    grad_norm: Array,
    initial_grad_norm: Array,
    tolerance: float,
) -> Array:
    """Photon/Breeze-style convergence: relative gradient norm OR relative
    objective-change below tolerance.

    Reference parity: Optimizer.scala convergence checks
    (``relativeTolerance`` on both loss delta and gradient norm).
    """
    grad_ok = grad_norm <= tolerance * jnp.maximum(initial_grad_norm, 1.0)
    val_ok = jnp.abs(value - prev_value) <= tolerance * jnp.maximum(
        jnp.abs(prev_value), 1e-12)
    return grad_ok | val_ok
