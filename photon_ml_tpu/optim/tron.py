"""TRON: Trust-Region Newton with conjugate-gradient inner solves.

Reference parity: photon-lib ``optimization/TRON.scala`` — itself a port of
LIBLINEAR's tron.cpp (Lin, Weng & Keerthi 2008): an outer trust-region loop
whose step comes from a Steihaug conjugate-gradient solve of H·s = −g using
Hessian-VECTOR products only (H is never materialized), truncated at the
trust-region boundary.

TPU-first design: both loops are ``lax.while_loop``s compiled into one XLA
program; each CG iteration costs exactly one Hessian-vector product — one
fused matmul pair (+ one psum when distributed), the analogue of the
reference's one ``treeAggregate(HessianVectorAggregator)`` per CG step.
Masked updates make the machine vmappable for per-entity solves, like
photon_ml_tpu/optim/lbfgs.py.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax import lax

from photon_ml_tpu.optim.common import (Hvp, OptResult, OptimizerConfig,
                                        ValueAndGrad, check_convergence,
                                        masked_update)

Array = jax.Array

# LIBLINEAR trust-region constants (tron.cpp).
_ETA0, _ETA1, _ETA2 = 1e-4, 0.25, 0.75
_SIGMA1, _SIGMA2, _SIGMA3 = 0.25, 0.5, 4.0


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class _TronState:
    w: Array
    f: Array
    g: Array
    delta: Array  # trust-region radius
    it: Array
    converged: Array
    failed: Array  # trust region collapsed before convergence
    g0_norm: Array
    value_history: Array
    grad_norm_history: Array


def _cg_steihaug(hvp, w, g, delta, max_cg, tol_cg):
    """Truncated CG: approximately solve H s = −g within ‖s‖ ≤ delta.

    Returns (s, sHs, gs) where sHs = sᵀHs and gs = gᵀs, the pieces needed
    for the model-decrease computation.
    """
    d = g.shape[-1]
    s0 = jnp.zeros_like(g)
    r0 = -g  # residual = -g - H s, s=0
    p0 = r0
    rr0 = jnp.dot(r0, r0)
    cg_tol = tol_cg * jnp.sqrt(rr0)

    def cond(st):
        s, r, p, rr, i, done = st
        return (~done) & (i < max_cg) & (jnp.sqrt(rr) > cg_tol)

    def body(st):
        s, r, p, rr, i, done = st
        hp = hvp(w, p)
        php = jnp.dot(p, hp)
        # Negative curvature or tiny curvature → step to the boundary.
        alpha = rr / jnp.maximum(php, 1e-30)
        s_next = s + alpha * p
        over = (php <= 0.0) | (jnp.linalg.norm(s_next) >= delta)

        # Boundary step: find tau >= 0 with ‖s + tau p‖ = delta.
        ss, sp, pp = jnp.dot(s, s), jnp.dot(s, p), jnp.dot(p, p)
        disc = jnp.sqrt(jnp.maximum(sp * sp + pp * (delta * delta - ss), 0.0))
        tau = (disc - sp) / jnp.maximum(pp, 1e-30)
        s_bound = s + tau * p

        s_new = jnp.where(over, s_bound, s_next)
        r_new = r - jnp.where(over, tau, alpha) * hp
        rr_new = jnp.dot(r_new, r_new)
        beta = rr_new / jnp.maximum(rr, 1e-30)
        p_new = r_new + beta * p
        return (s_new, r_new, p_new, rr_new, i + 1, done | over)

    st = (s0, r0, p0, rr0, jnp.asarray(0, jnp.int32), jnp.asarray(False))
    s, r, p, rr, i, done = lax.while_loop(cond, body, st)
    sHs = jnp.dot(s, -g - r)  # H s = -g - r by the residual invariant
    gs = jnp.dot(g, s)
    return s, sHs, gs


def minimize(
    value_and_grad: ValueAndGrad,
    hvp: Hvp,
    w0: Array,
    config: OptimizerConfig = OptimizerConfig(),
) -> OptResult:
    """Trust-region Newton minimization of a twice-differentiable objective."""
    max_iter = config.max_iterations

    f0, g0 = value_and_grad(w0)
    g0_norm = jnp.linalg.norm(g0)
    vh = jnp.full((max_iter + 1,), jnp.nan, jnp.float32).at[0].set(
        f0.astype(jnp.float32))
    gh = jnp.full((max_iter + 1,), jnp.nan, jnp.float32).at[0].set(
        g0_norm.astype(jnp.float32))

    init = _TronState(
        w=w0, f=f0, g=g0,
        delta=g0_norm,  # LIBLINEAR: initial radius = ‖g0‖
        it=jnp.asarray(0, jnp.int32),
        converged=g0_norm <= config.tolerance,
        failed=jnp.asarray(False),
        g0_norm=g0_norm,
        value_history=vh, grad_norm_history=gh,
    )

    def body(state: _TronState) -> _TronState:
        s, sHs, gs = _cg_steihaug(hvp, state.w, state.g, state.delta,
                                  config.max_cg_iterations, 0.1)
        prered = -(gs + 0.5 * sHs)  # predicted decrease of the quadratic model
        w_new = state.w + s
        f_new, g_new = value_and_grad(w_new)
        actred = state.f - f_new
        snorm = jnp.linalg.norm(s)

        # Radius update (LIBLINEAR tron.cpp rules, simplified alpha=1 form).
        ratio = actred / jnp.maximum(prered, 1e-30)
        delta = state.delta
        delta = jnp.where(
            ratio < _ETA0, _SIGMA1 * jnp.minimum(delta, snorm),
            jnp.where(
                ratio < _ETA1, jnp.maximum(_SIGMA1 * delta, _SIGMA2 * snorm),
                jnp.where(
                    ratio < _ETA2, delta,  # acceptable step: keep radius
                    jnp.maximum(delta, _SIGMA3 * snorm))))

        accept = (actred > _ETA0 * prered) & jnp.isfinite(f_new)
        w_acc = jnp.where(accept, w_new, state.w)
        f_acc = jnp.where(accept, f_new, state.f)
        g_acc = jnp.where(accept, g_new, state.g)

        gnorm = jnp.linalg.norm(g_acc)
        it = state.it + 1
        # Value-based convergence only counts on accepted steps (a rejected
        # step trivially has Δf = 0); gradient-based convergence is valid at
        # the current iterate regardless of acceptance.
        grad_conv = gnorm <= config.tolerance * jnp.maximum(state.g0_norm, 1.0)
        conv = grad_conv | (accept & check_convergence(
            f_acc, state.f, gnorm, state.g0_norm, config.tolerance))
        # A collapsed radius with the gradient still large is a true stall.
        stalled = delta < 1e-12

        vh = state.value_history.at[it].set(f_acc.astype(jnp.float32))
        gh = state.grad_norm_history.at[it].set(gnorm.astype(jnp.float32))

        new_state = _TronState(
            w=w_acc, f=f_acc, g=g_acc, delta=delta, it=it,
            converged=state.converged | conv | stalled,
            failed=state.failed | (stalled & ~conv),
            g0_norm=state.g0_norm,
            value_history=vh, grad_norm_history=gh,
        )
        return masked_update(state.converged, new_state, state)

    def cond(state: _TronState):
        return (~state.converged) & (state.it < max_iter)

    final = lax.while_loop(cond, body, init)
    return OptResult(
        w=final.w,
        value=final.f,
        grad_norm=jnp.linalg.norm(final.g),
        iterations=final.it,
        converged=final.converged & ~final.failed,
        value_history=final.value_history,
        grad_norm_history=final.grad_norm_history,
    )
