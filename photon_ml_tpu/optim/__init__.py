"""Optimizers: L-BFGS, OWL-QN, TRON as compiled state machines.

Reference parity: photon-lib ``optimization/`` — ``Optimizer.scala``,
``OptimizerFactory.scala``, ``LBFGS.scala``, ``OWLQN.scala``, ``TRON.scala``.
"""

from __future__ import annotations

from typing import Optional

import jax

from photon_ml_tpu.optim import lbfgs as _lbfgs
from photon_ml_tpu.optim import tron as _tron
from photon_ml_tpu.optim.common import (Hvp, OptResult, OptimizerConfig,
                                        OptimizerType, ValueAndGrad)
from photon_ml_tpu.optim.regularization import (RegularizationContext,
                                                RegularizationType,
                                                intercept_mask,
                                                l1_weights_vector, with_l2,
                                                with_l2_hvp)

Array = jax.Array

minimize_lbfgs = _lbfgs.minimize
minimize_owlqn = _lbfgs.minimize_owlqn
minimize_tron = _tron.minimize


def optimize(
    value_and_grad: ValueAndGrad,
    w0: Array,
    config: OptimizerConfig,
    *,
    hvp: Optional[Hvp] = None,
    l1_weights: Optional[Array] = None,
) -> OptResult:
    """Dispatch on OptimizerType (reference: OptimizerFactory.scala).

    ``value_and_grad`` must already include any L2 term (use ``with_l2``);
    ``l1_weights`` routes to OWL-QN; TRON additionally needs ``hvp``.
    """
    t = OptimizerType(config.optimizer_type)
    if t == OptimizerType.LBFGS:
        if l1_weights is not None:
            raise ValueError("L1 regularization requires OWLQN, not LBFGS")
        return minimize_lbfgs(value_and_grad, w0, config)
    if t == OptimizerType.OWLQN:
        if l1_weights is None:
            raise ValueError("OWLQN requires l1_weights (else use LBFGS)")
        return minimize_owlqn(value_and_grad, w0, l1_weights, config)
    if t == OptimizerType.TRON:
        if hvp is None:
            raise ValueError("TRON requires a Hessian-vector product (hvp)")
        if l1_weights is not None:
            raise ValueError("TRON does not support L1 (reference parity)")
        return minimize_tron(value_and_grad, hvp, w0, config)
    if t in (OptimizerType.SDCA, OptimizerType.SGD):
        raise ValueError(
            f"{t.value} is a streamed-path stochastic solver (it needs "
            f"the chunk feed for its per-row/per-chunk updates) — use "
            f"the streaming coordinate (GameEstimator(streaming=...) / "
            f"game_train --streaming solver={t.value.lower()}), not "
            f"optimize()")
    raise ValueError(t)  # pragma: no cover


__all__ = [
    "OptResult", "OptimizerConfig", "OptimizerType", "ValueAndGrad", "Hvp",
    "RegularizationContext", "RegularizationType",
    "minimize_lbfgs", "minimize_owlqn", "minimize_tron", "optimize",
    "with_l2", "with_l2_hvp", "l1_weights_vector", "intercept_mask",
]
