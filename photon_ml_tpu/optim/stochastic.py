"""Host-driven stochastic solvers (SDCA + mini-batch SGD) for the
row-streamed objective — the duality gap as a first-class subsystem.

Snap ML and the GPU duality-gap work (PAPERS.md) fit Criteo-scale GLMs
several times faster than batch L-BFGS to a given AUC with stochastic
DUAL coordinate ascent, using the duality gap (optim/gap.py) both as a
principled stopping certificate and as an importance signal for what
stays resident on the accelerator. This module is that solver family
behind the exact :func:`optim.streaming.minimize_streaming` driver
contract: the same ``ChunkedHybrid`` chunk feed, the same
checkpoint/resume snapshot discipline (the dual vector α rides in the
snapshot beside w), the same watchdog arming and fault sites, the same
``opt_iter`` ledger rows — plus a ``gap`` column, the
``photon_opt_duality_gap`` gauge, and a gap-gated stop.

**SDCA** (``solver="sdca"``): one epoch visits every chunk in global
order; within a chunk the rows update SEQUENTIALLY (a ``lax.fori_loop``
inside one jitted per-chunk kernel — dual coordinate ascent is
inherently sequential; Snap ML's asynchronous parallel variant is out of
scope), each row taking the exact single-coordinate dual step
(``gap.sdca_delta``) and applying w ← w + (Δα/λ)·xᵢ so w ≡ w(α) holds
after every row — the invariant the gap identity rests on. The dual
vector α is HOST-resident (device residency would double the stream's
HBM footprint); each chunk's slice rides to the device beside the chunk
and comes home with the per-chunk gap partials. The epoch-end gap is
EXACT (not estimated): conj/α·offset partials accumulate during the
dual pass, the loss side is the epoch-end value pass, and the pieces
assemble per ``gap.assemble_gap`` — with the partial reduction grouped
by ``gap.reduce_gap_partials`` so a 1-device reduction is bit-identical
to the plain chunk-order sum.

**SGD** (``solver="sgd"``, and the fallback for losses without a cheap
conjugate — poisson, smoothed hinge): one epoch takes one
``w ← w − η_t·(C·g_chunk + λ·w)`` step per chunk (C = num_chunks makes
the chunk gradient an unbiased estimate of the full one) with the
classic λ-strong-convexity schedule η_t = 1/(λ(t + t₀)), t₀ = C; the
epoch-end (value, gradient) pass prices convergence and the gap column
carries the primal surrogate ‖∇P‖²/(2λ) (``gap.sgd_gap_surrogate``).

**Gap-driven residency**: ``pin_budget`` chunks stay pinned on device
through ``ops/chunk_sampler.GapChunkSampler`` — after each SDCA epoch
the pin set re-ranks by per-chunk gap contribution (the DuHL pattern),
so the chunks with convergence progress left in them stop paying the
transfer wall. Residency never changes chunk order, so results are
bit-identical for every pin set.

Warm starts: SDCA maintains w ≡ (1/λ)Σαᵢxᵢ and an arbitrary w₀ has no
α representation — a nonzero warm start is IGNORED (logged) and the
ascent starts at (w, α) = 0, unless ``resume_state`` carries a
snapshotted (w, α) pair. SGD warm-starts normally.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu import faults as flt
from photon_ml_tpu import obs
from photon_ml_tpu.obs.ledger import transfer_totals
from photon_ml_tpu.obs.watchdog import ConvergenceWatchdog
from photon_ml_tpu.ops import streaming_sparse as ss
from photon_ml_tpu.ops.chunk_sampler import GapChunkSampler
from photon_ml_tpu.ops.losses import PointwiseLoss
from photon_ml_tpu.optim import gap as gap_mod
from photon_ml_tpu.optim.common import OptResult, OptimizerConfig

Array = jax.Array

STOCHASTIC_SOLVERS = ("sdca", "sgd")

# Per-(loss, storage dtype) jitted SDCA chunk kernels — the same
# one-program-per-stream accounting as the value/gradient kernel caches
# in ops/streaming_sparse.py.
_SDCA_KERNELS: dict = {}


def _sdca_kernel(loss: PointwiseLoss, dtype: str):
    """One jitted per-chunk dual pass: (w_pad, α_chunk, offsets, λ,
    chunk) → (w_pad′, α_chunk′, [conj_sum, α·offset_sum, gap_sum]).

    Rows update sequentially (``fori_loop``); every per-row gather and
    scatter is 1-D over (H,) / (k,) slices, so the chunk-scale layout
    rules of ops/streaming_sparse.py (no (n, k)-shaped index operands)
    are never in play. int8 chunks dequantize per row — codes × scale
    gathers, f32 accumulation, no dense f32 block materialized."""
    key = (loss.name, dtype)
    f = _SDCA_KERNELS.get(key)
    if f is not None:
        ss._count_kernel_hit("stream_sdca_dual", dtype)
        return f
    ss._count_kernel_build("stream_sdca_dual", dtype)
    delta_fn = gap_mod.sdca_delta(loss.name)
    conj_fn = gap_mod.conjugate_term(loss.name)

    @jax.jit
    def f(w_pad: Array, alpha: Array, offsets: Array, lam: Array,
          ch: ss.CanonicalChunk):
        quantized = ch.cold_scale is not None

        def body(i, carry):
            w_pad, alpha, conj_s, aoff_s, gap_s = carry
            cc = ch.cold_cols[i]
            if quantized:
                xh = ch.X_hot[i].astype(jnp.float32) * ch.hot_scale
                cv = ch.cold_vals[i].astype(jnp.float32) * \
                    ch.cold_scale[cc]
            else:
                xh = ch.X_hot[i].astype(jnp.float32)
                cv = ch.cold_vals[i].astype(jnp.float32)
            o = offsets[i]
            y = ch.labels[i]
            wgt = ch.weights[i]
            a = alpha[i]
            # Margin + row norm from the hot row and the cold ELL row
            # (pad/hot-inert cold slots carry value 0 and the sentinel
            # column, so they contribute exactly 0 to both).
            z = o + jnp.dot(xh, w_pad[ch.hot_cols]) + \
                jnp.sum(w_pad[cc] * cv)
            xsq = jnp.dot(xh, xh) + jnp.sum(cv * cv)
            d_a = delta_fn(z, y, wgt, a, xsq, lam)
            a_new = a + d_a
            # w ≡ w(α): the dual step lands on w immediately. Sentinel
            # scatters (hot pad columns, cold pad slots) add exact 0.
            scale = d_a / lam
            w_pad = w_pad.at[ch.hot_cols].add(scale * xh)
            w_pad = w_pad.at[cc].add(scale * cv)
            alpha = alpha.at[i].set(a_new)
            cj = conj_fn(a_new, y, wgt)
            li, _ = loss.loss_and_dz(z, y)
            # Per-row Fenchel–Young term (≥ 0): the DuHL importance
            # signal, summed per chunk. Clamped at 0 against f32 noise.
            gap_i = jnp.where(wgt > 0.0, wgt * li + cj + a_new * z, 0.0)
            return (w_pad, alpha, conj_s + cj, aoff_s + a_new * o,
                    gap_s + jnp.maximum(gap_i, 0.0))

        zero = jnp.zeros((), jnp.float32)
        w_pad, alpha, conj_s, aoff_s, gap_s = jax.lax.fori_loop(
            0, ch.labels.shape[0], body,
            (w_pad, alpha, zero, zero, zero))
        return w_pad, alpha, jnp.stack([conj_s, aoff_s, gap_s])

    _SDCA_KERNELS[key] = f
    return f


# SGD step-norm trust radius: poisson/smoothed-hinge gradients are not
# Lipschitz-bounded (exp(z) grows without bound), so a raw 1/(λ(t+t₀))
# schedule can overshoot into overflow on the very first epoch. Clipping
# the STEP norm to R/t keeps every update bounded (total travel grows
# only like log t — the normalized-gradient-descent stabilization) while
# leaving the schedule untouched once iterates reach the region where
# steps are naturally small. Deterministic in (w, t), so a snapshot
# resume replays it exactly.
_SGD_TRUST_RADIUS = 1.0


@jax.jit
def _sgd_step(w: Array, g_chunk: Array, eta: Array, lam: Array,
              scale: Array, mask: Array, radius: Array) -> Array:
    """One mini-batch step: w − η·(C·g_chunk + λ·(w∘mask)) — the chunk
    gradient scaled by C = num_chunks is an unbiased estimate of the
    full data gradient of the SUM objective — with the step norm clipped
    to ``radius`` (= ``_SGD_TRUST_RADIUS``/t)."""
    step = eta * (scale * g_chunk + lam * (w * mask))
    norm = jnp.linalg.norm(step)
    clip = jnp.minimum(1.0, radius / jnp.maximum(norm, 1e-30))
    return w - clip * step


def snapshot_stochastic(w, alpha, it, fv, gap, f0, gap0, vals, gaps,
                        t_step) -> dict:
    """Host-side snapshot of the full stochastic driver state at an
    epoch boundary — the α vector rides beside w, so a save→load→resume
    round trip replays the remaining epochs BIT-identically to an
    uninterrupted run (chunk order and the within-chunk row order are
    fixed; residency never changes either). Plain numpy, keyed like
    optim/streaming.snapshot_state ("it" included — the checkpoint
    store's span reads it)."""
    return {
        "w": np.asarray(w), "alpha": np.asarray(alpha),
        "it": np.int32(it), "fv": np.float32(fv),
        "gap": np.float32(gap), "f0": np.float32(f0),
        "gap0": np.float32(gap0), "vals": np.asarray(vals),
        "gns": np.asarray(gaps), "t": np.int32(t_step),
    }


def minimize_stochastic(
    value_and_grad: Callable[[Array], tuple[Array, Array]],
    w0: Array,
    config: OptimizerConfig,
    *,
    chunked: ss.ChunkedHybrid,
    loss: PointwiseLoss,
    l2_weight: float,
    solver: str = "sdca",
    offsets: Optional[Array] = None,
    reg_mask: Optional[Array] = None,
    log: Callable[[str], None] = lambda m: None,
    value_only: Optional[Callable[[Array], Array]] = None,
    checkpoint_save: Optional[Callable[[dict], None]] = None,
    resume_state: Optional[dict] = None,
    prefetch_depth: int = 2,
    pin_budget: int = 0,
    num_devices: int = 1,
) -> OptResult:
    """Driver-loop stochastic solve behind the ``minimize_streaming``
    contract: same return type, same checkpoint/resume discipline, same
    telemetry sites.

    ``value_and_grad``/``value_only`` are the L2-WRAPPED streamed
    callables the coordinate already builds (``with_l2`` /
    ``with_l2_value``); ``l2_weight`` must match the λ folded into them
    — SDCA reads it for the dual step and the gap assembly, SGD for the
    step schedule and the gap surrogate. ``offsets`` is the full
    (padded_n,) residual array sliced per chunk for the dual pass (the
    wrapped callables close over their own copy).

    One ``opt_iter`` ledger row per ACCEPTED epoch carries ``gap``
    (finite, monotone-trending for SDCA); the ``photon_opt_duality_gap``
    gauge tracks it live; an armed watchdog gets both the standard
    ``observe`` feed and the gap gate (``observe_gap`` — ``gap <= tol``
    stops, non-finite raises). Convergence is gap-gated:
    ``gap <= config.tolerance · max(|f|, 1)``.

    ``num_devices`` fixes the GROUPING of the per-chunk gap-partial
    reduction (``gap.reduce_gap_partials``) so the certificate a D-device
    run reports is reproducible; the dual pass itself streams on the
    default device (sequential by nature).
    """
    if solver not in STOCHASTIC_SOLVERS:
        raise ValueError(f"unknown stochastic solver {solver!r}; "
                         f"expected one of {STOCHASTIC_SOLVERS}")
    if l2_weight <= 0.0:
        raise ValueError(
            f"stochastic solvers need l2_weight > 0 (the dual step, the "
            f"step schedule, and the gap certificate all rest on strong "
            f"convexity), got {l2_weight}")
    if solver == "sdca":
        if loss.name not in gap_mod.CONJUGATE_LOSSES:
            raise ValueError(
                f"sdca needs a loss with a cheap conjugate (have "
                f"{loss.name!r}, supported "
                f"{sorted(gap_mod.CONJUGATE_LOSSES)}); use solver='sgd'")
        if reg_mask is not None and \
                not bool(np.all(np.asarray(reg_mask) == 1.0)):
            raise ValueError(
                "sdca requires every coordinate regularized (w ≡ "
                "(1/λ)Σαᵢxᵢ has no unregularized analogue); drop the "
                "intercept exclusion or use solver='sgd'")

    d = int(w0.shape[0])
    rows = chunked.chunk_rows
    num_chunks = chunked.num_chunks
    padded_n = num_chunks * rows
    max_it = config.max_iterations
    led = obs.ledger()
    wd_cfg = obs.watchdog_config()
    wd = (ConvergenceWatchdog(wd_cfg) if wd_cfg is not None else None)
    mx = obs.metrics()
    v = (value_only if value_only is not None
         else (lambda w: value_and_grad(w)[0]))
    lam = jnp.asarray(l2_weight, jnp.float32)
    mask = (jnp.ones((d,), jnp.float32) if reg_mask is None
            else jnp.asarray(reg_mask, jnp.float32))
    dtype = ss.chunk_dtype(chunked.chunks[0])
    sampler = GapChunkSampler(chunked, pin_budget)
    t_step = 0  # SGD step counter (cumulative, rides the snapshot)
    t0_sched = num_chunks

    vals = np.full((max_it + 1,), np.nan, np.float32)
    gaps = np.full((max_it + 1,), np.nan, np.float32)
    if resume_state is not None:
        st = resume_state
        if st["w"].shape != (d,) or st["alpha"].shape != (padded_n,):
            raise ValueError(
                f"resume state shape mismatch: saved w {st['w'].shape} "
                f"/ alpha {st['alpha'].shape}, expected ({d},) / "
                f"({padded_n},) — the checkpoint was written under a "
                f"different configuration")
        w = jnp.asarray(st["w"], jnp.float32)
        alpha = np.array(st["alpha"], np.float32)
        fv, gap = float(st["fv"]), float(st["gap"])
        f0, gap0 = float(st["f0"]), float(st["gap0"])
        t_step = int(st["t"])
        start_it = int(st["it"]) + 1
        k = min(st["vals"].shape[0], max_it + 1)
        vals[:k], gaps[:k] = st["vals"][:k], st["gns"][:k]
        log(f"resuming streamed {solver} at epoch {start_it} "
            f"(f={fv:.6g}, gap={gap:.3g})")
    else:
        alpha = np.zeros((padded_n,), np.float32)
        if solver == "sdca":
            if bool(jnp.any(jnp.asarray(w0) != 0.0)):
                log("sdca ignores the warm start (w has no dual "
                    "representation); starting from (w, alpha) = 0")
            w = jnp.zeros((d,), jnp.float32)
            with obs.span("stochastic.initial_pass", cat="optim",
                          solver=solver):
                fv = float(v(w))
            # At (w, α) = (0, 0) the conjugate and α·offset sums vanish
            # (φ*(0) = 0 for both conjugate losses with {0,1}/real
            # labels), so gap₀ = P(0) exactly.
            gap = fv
        else:
            w = jnp.asarray(w0, jnp.float32)
            with obs.span("stochastic.initial_pass", cat="optim",
                          solver=solver):
                f_init, g_init = value_and_grad(w)
            fv = float(f_init)
            gap = gap_mod.sgd_gap_surrogate(
                float(jnp.linalg.norm(g_init)), l2_weight)
        f0, gap0 = fv, gap
        vals[0], gaps[0] = fv, gap
        start_it = 1

    w_pad = jnp.concatenate([w, jnp.zeros((1,), jnp.float32)])
    kernel = (_sdca_kernel(loss, dtype) if solver == "sdca" else None)
    vg_kernel = (ss._chunk_value_grad(loss, dtype) if solver == "sgd"
                 else None)
    scale_c = jnp.asarray(float(num_chunks), jnp.float32)

    converged = False
    it = start_it - 1
    try:
        for it in range(start_it, max_it + 1):
            t_iter = time.perf_counter()
            with obs.span("stochastic.epoch", cat="optim", it=it,
                          solver=solver):
                gn = None
                if solver == "sdca":
                    parts_rows = []
                    for i, ch, streamed in sampler.stream(prefetch_depth):
                        # Chaos seam (docs/ROBUSTNESS.md): the per-chunk
                        # stochastic update — a kill here must resume
                        # from the LAST epoch boundary's (w, α) snapshot
                        # to bit-identical coefficients.
                        flt.fire(flt.sites.OPT_DUAL_UPDATE, index=i)
                        off = ss._offsets_for(chunked, offsets, i, ch)
                        a_dev = jnp.asarray(alpha[i * rows:(i + 1) * rows])
                        w_pad, a_new, parts = kernel(w_pad, a_dev, off,
                                                     lam, ch)
                        # Same enqueue-scratch barrier as every streamed
                        # pass (ops/streaming_sparse.py).
                        jax.block_until_ready(w_pad)
                        # pml: allow[PML001] α is HOST-resident by design (a device-resident (padded_n,) dual would double the stream's HBM footprint); the chunk slice + (3,) partials ride home behind the per-chunk barrier
                        alpha[i * rows:(i + 1) * rows] = np.asarray(a_new)
                        # pml: allow[PML001] same by-design per-chunk copy as the α slice above
                        parts_rows.append(np.asarray(parts))
                        if streamed:
                            ss._delete_chunk(ch)
                    ss._collect_after_pass(chunked)
                    w = w_pad[:d]
                    # pml: allow[PML001] epoch-boundary value read is the BY-DESIGN host decision point (the gap assembly + convergence gate), one scalar per epoch
                    fv = float(v(w))
                    parts_arr = np.stack(parts_rows)
                    conj_sum = gap_mod.reduce_gap_partials(
                        parts_arr[:, 0], num_devices)
                    aoff_sum = gap_mod.reduce_gap_partials(
                        parts_arr[:, 1], num_devices)
                    # pml: allow[PML001] ‖w‖² closes the gap identity on host once per epoch
                    w_sq = float(jnp.dot(w, w))
                    gap = gap_mod.assemble_gap(fv, conj_sum, aoff_sum,
                                               l2_weight, w_sq)
                    sampler.update(parts_arr[:, 2])
                    v_passes, g_passes, dual_passes = 1, 0, 1
                else:
                    for i, ch, streamed in sampler.stream(prefetch_depth):
                        flt.fire(flt.sites.OPT_DUAL_UPDATE, index=i)
                        off = ss._offsets_for(chunked, offsets, i, ch)
                        _, g_chunk = vg_kernel(w, off, ch)
                        t_step += 1
                        eta = jnp.asarray(
                            1.0 / (l2_weight * (t_step + t0_sched)),
                            jnp.float32)
                        radius = jnp.asarray(
                            _SGD_TRUST_RADIUS / t_step, jnp.float32)
                        w = _sgd_step(w, g_chunk, eta, lam, scale_c,
                                      mask, radius)
                        jax.block_until_ready(w)
                        if streamed:
                            ss._delete_chunk(ch)
                    ss._collect_after_pass(chunked)
                    f_ep, g_ep = value_and_grad(w)
                    # pml: allow[PML001] epoch-boundary convergence read, one pair of scalars per epoch
                    fv = float(f_ep)
                    # Host f64 norm: early poisson iterates can carry
                    # per-row exp(z) gradients whose f32 sum-of-squares
                    # overflows to inf even though every element is
                    # finite.
                    # pml: allow[PML001] same epoch-boundary read as fv above
                    gn = float(np.linalg.norm(np.asarray(g_ep, np.float64)))
                    gap = gap_mod.sgd_gap_surrogate(gn, l2_weight)
                    w_pad = jnp.concatenate([w, jnp.zeros((1,),
                                                          jnp.float32)])
                    v_passes, g_passes, dual_passes = 0, 2, 0
                # Watchdog chaos seam (docs/ROBUSTNESS.md): a "nan"
                # fault spec here is the injected form of a numerically
                # sick gap certificate.
                gap = flt.poison_scalar(flt.sites.OPT_GAP_CHECK, gap)
                if mx is not None:
                    mx.gauge("photon_opt_duality_gap").set(gap)
                vals[it], gaps[it] = fv, gap
                seconds = time.perf_counter() - t_iter
                log(f"epoch {it}: f={fv:.6g} gap={gap:.3g} "
                    f"[{solver}]")
                if led is not None:
                    # Append-as-produced, exactly like the L-BFGS rows —
                    # a SIGKILL one epoch later still leaves this point
                    # (and its gap) on the curve.
                    led.record("opt_iter", opt=f"{solver}-stream",
                               iteration=it, value=fv,
                               grad_norm=(gn if gn is not None else gap),
                               gap=gap, value_passes=v_passes,
                               grad_passes=g_passes,
                               dual_passes=dual_passes,
                               seconds=round(seconds, 6),
                               **transfer_totals())
                if checkpoint_save is not None:
                    # Epoch boundary = the resume point; w AND α go in.
                    checkpoint_save(snapshot_stochastic(
                        w, alpha, it, fv, gap, f0, gap0, vals, gaps,
                        t_step))
                if wd is not None:
                    # After the checkpoint write (a "raise" verdict
                    # still leaves a resumable snapshot), the standard
                    # feed first, then the gap gate.
                    if wd.observe(it, fv, gap, seconds) == "stop":
                        log(f"epoch {it}: watchdog early stop")
                        break
                    if wd.observe_gap(it, gap) == "stop":
                        log(f"epoch {it}: duality gap "
                            f"{gap:.3g} <= watchdog tolerance — stopping")
                        break
                elif not np.isfinite(gap):
                    # No watchdog armed: a non-finite certificate still
                    # must not spin the remaining epochs.
                    log(f"epoch {it}: non-finite gap ({gap!r}); "
                        f"stopping")
                    break
                if gap <= config.tolerance * max(abs(fv), 1.0):
                    converged = True
                    break
    finally:
        sampler.release()

    return OptResult(
        w=w,
        value=jnp.asarray(fv, jnp.float32),
        # The gap IS the convergence certificate of the stochastic path;
        # it rides the grad_norm slots of the shared result type.
        grad_norm=jnp.asarray(gap, jnp.float32),
        iterations=jnp.asarray(it, jnp.int32),
        converged=jnp.asarray(converged),
        value_history=jnp.asarray(vals),
        grad_norm_history=jnp.asarray(gaps),
    )
