"""GLM optimization problems: bind loss + data + regularization + optimizer.

Reference parity: photon-api ``optimization/
GeneralizedLinearOptimizationProblem.scala`` /
``SingleNodeOptimizationProblem.scala`` (the per-entity local solve) and the
config bundles in photon-lib ``optimization/game/
GLMOptimizationConfiguration.scala``. The distributed twin lives in
photon_ml_tpu/parallel/objective.py.

Variance computation (reference ``computeVariances``,
``VarianceComputationType``): SIMPLE = 1/diag(H); FULL = diag(H⁻¹).
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Optional

import jax
import jax.numpy as jnp

from photon_ml_tpu.data.batch import LabeledBatch
from photon_ml_tpu.models.coefficients import Coefficients
from photon_ml_tpu.normalization import NormalizationContext
from photon_ml_tpu.ops import aggregators as agg
from photon_ml_tpu.ops.losses import PointwiseLoss
from photon_ml_tpu.optim import (OptimizerConfig, OptimizerType, OptResult,
                                 RegularizationContext, l1_weights_vector,
                                 optimize, with_l2, with_l2_hvp)
from photon_ml_tpu.optim.regularization import intercept_mask

Array = jax.Array


class VarianceComputationType(enum.Enum):
    NONE = "NONE"
    SIMPLE = "SIMPLE"  # 1 / diag(H)
    FULL = "FULL"  # diag(H^-1) — materializes H, small d only


@dataclasses.dataclass(frozen=True)
class GLMOptimizationConfiguration:
    """(optimizer, regularization, variance) bundle for one coordinate.

    Reference parity: GLMOptimizationConfiguration.scala.
    """

    optimizer: OptimizerConfig = OptimizerConfig()
    regularization: RegularizationContext = RegularizationContext()
    variance_computation: VarianceComputationType = VarianceComputationType.NONE
    # Down-sampling rate for this coordinate (1.0 = off); applied by the
    # coordinate, not here (reference: DownSampler).
    down_sampling_rate: float = 1.0


def resolve_optimizer_config(
    opt_cfg: OptimizerConfig, has_l1: bool
) -> OptimizerConfig:
    """L1/elastic-net silently selects OWL-QN (reference behavior)."""
    if has_l1 and OptimizerType(opt_cfg.optimizer_type) == OptimizerType.LBFGS:
        return dataclasses.replace(opt_cfg, optimizer_type=OptimizerType.OWLQN)
    return opt_cfg


def variances_from_diagonal(diag: Array, l2: float, reg_mask: Array) -> Array:
    """SIMPLE variances: elementwise 1/(diag(H) + λ·mask)."""
    return 1.0 / jnp.maximum(diag + l2 * reg_mask, 1e-12)


def variances_from_matrix(H: Array, l2: float, reg_mask: Array) -> Array:
    """FULL variances: diag(H⁻¹) with the L2 term on the diagonal."""
    dim = H.shape[-1]
    eye = jnp.eye(dim, dtype=H.dtype)
    H = H + jnp.diag(l2 * reg_mask) + 1e-9 * eye
    return jnp.diagonal(jnp.linalg.solve(H, eye))


def make_objective(
    loss: PointwiseLoss,
    batch: LabeledBatch,
    norm: NormalizationContext,
    reg: RegularizationContext,
    intercept_index: Optional[int],
    dim: int,
):
    """Build (value_and_grad, hvp, l1_weights) for a local batch."""
    mask = jnp.asarray(intercept_mask(dim, intercept_index))

    def vg(w: Array):
        return agg.value_and_gradient(loss, w, batch, norm)

    def hvp(w: Array, v: Array):
        return agg.hessian_vector(loss, w, v, batch, norm)

    l2 = reg.l2_weight()
    vg = with_l2(vg, l2, mask)
    hvp = with_l2_hvp(hvp, l2, mask)
    l1 = reg.l1_weight()
    l1_weights = (l1_weights_vector(l1, dim, intercept_index)
                  if l1 > 0.0 else None)
    return vg, hvp, l1_weights


def run(
    loss: PointwiseLoss,
    batch: LabeledBatch,
    config: GLMOptimizationConfiguration,
    initial: Optional[Coefficients] = None,
    norm: NormalizationContext = NormalizationContext(),
    intercept_index: Optional[int] = None,
) -> tuple[Coefficients, OptResult]:
    """Solve one GLM on one local batch (SingleNodeOptimizationProblem.run).

    Pure and jit/vmap-compatible given fixed shapes; the vmapped form is the
    random-effect per-entity path.
    """
    dim = batch.dim
    w0 = initial.means if initial is not None else jnp.zeros(
        (dim,), batch.features.dtype)
    vg, hvp, l1w = make_objective(loss, batch, norm, config.regularization,
                                  intercept_index, dim)
    opt_cfg = resolve_optimizer_config(config.optimizer, l1w is not None)
    result = optimize(vg, w0, opt_cfg, hvp=hvp, l1_weights=l1w)
    variances = compute_variances(loss, result.w, batch, norm,
                                  config.variance_computation,
                                  config.regularization, intercept_index)
    return Coefficients(means=result.w, variances=variances), result


def compute_variances(
    loss: PointwiseLoss,
    w: Array,
    batch: LabeledBatch,
    norm: NormalizationContext,
    kind: VarianceComputationType,
    reg: RegularizationContext,
    intercept_index: Optional[int],
) -> Optional[Array]:
    """Coefficient variance estimates from the Hessian at the optimum.

    Reference parity: GeneralizedLinearOptimizationProblem.computeVariances:
    SIMPLE → elementwise 1/diag(H); FULL → diag(H⁻¹). L2 contributes λ to
    regularized diagonal entries.
    """
    kind = VarianceComputationType(kind)
    if kind == VarianceComputationType.NONE:
        return None
    l2 = reg.l2_weight()
    mask = jnp.asarray(intercept_mask(w.shape[-1], intercept_index))
    if kind == VarianceComputationType.SIMPLE:
        return variances_from_diagonal(
            agg.hessian_diagonal(loss, w, batch, norm), l2, mask)
    return variances_from_matrix(
        agg.hessian_matrix(loss, w, batch, norm), l2, mask)
