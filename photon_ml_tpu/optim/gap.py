"""Duality-gap machinery for the stochastic streamed solvers.

The duality gap is the convergence certificate of the stochastic path
(optim/stochastic.py): for the L2-regularized GLM

    P(w) = Σᵢ ωᵢ·φ(zᵢ) + (λ/2)‖w‖²,    zᵢ = xᵢᵀw + oᵢ

(the SUM objective the streamed kernels accumulate — photon's weighted
per-row losses, ``oᵢ`` the coordinate-descent residual offsets), SDCA
maintains a dual vector α with w ≡ w(α) = (1/λ)Σᵢ αᵢxᵢ, and

    gap(w, α) = P(w) − D(α)
              = Σᵢ [ωᵢ·φ(zᵢ) + φ*ᵢ(−αᵢ) + αᵢ·zᵢ]          (Fenchel–Young)

where φ*ᵢ is the convex conjugate of the WEIGHTED per-row loss
(φᵢ = ωᵢ·φ ⇒ φ*ᵢ(u) = ωᵢ·φ*(u/ωᵢ); ωᵢ = 0 pad rows contribute exactly
0). Every bracketed term is ≥ 0, so per-row sums double as the DuHL
importance signal (``ops/chunk_sampler.py``): a chunk's summed gap
contribution says how much dual progress is still available in it.

Because Σᵢ αᵢzᵢ = λ‖w‖² + Σᵢ αᵢoᵢ when w = w(α), the EXACT epoch gap
assembles from quantities the streamed passes already produce:

    gap = v(w) + conj_sum + alpha_off_sum + (λ/2)‖w‖²

with ``v`` the L2-wrapped value pass (P(w) itself), ``conj_sum`` =
Σ φ*ᵢ(−αᵢ) and ``alpha_off_sum`` = Σ αᵢoᵢ accumulated during the dual
pass — each αᵢ is touched only in its own chunk, so per-chunk partials
sum to the global terms exactly, in any grouping. ``gap ≥ P(w) − P(w*)``
upper-bounds suboptimality at every iterate (tests/test_stochastic.py
pins this against closed-form optima).

The primal-only SGD fallback has no α; for a λ-strongly-convex P the
surrogate ‖∇P(w)‖²/(2λ) ≥ P(w) − P(w*) is the same kind of certificate
(:func:`sgd_gap_surrogate`).

Losses with a cheap scalar conjugate: ``logistic`` (labels {0, 1}) and
``squared``. ``poisson``/``smoothed_hinge`` route to SGD.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array

# Losses whose per-row conjugate has a closed form cheap enough to
# evaluate once per row inside the sequential dual update loop. The
# stochastic driver falls back to SGD (with the gap surrogate) for
# anything else.
CONJUGATE_LOSSES = frozenset({"logistic", "squared"})

# Newton safeguard for the logistic dual update: σ(z) clipped into the
# open unit interval so logit/1/(s(1-s)) stay finite.
_SIGMOID_EPS = 1e-6
_NEWTON_ITERS = 8


def _xlogx(x: Array) -> Array:
    """x·log(x) continued by 0 at x = 0 (the entropy endpoint)."""
    safe = jnp.maximum(x, 1e-30)
    return jnp.where(x > 0.0, x * jnp.log(safe), 0.0)


def conjugate_term(loss_name: str):
    """(alpha, label, weight) → φ*ᵢ(−alpha), the weighted per-row
    conjugate term of the gap identity. Weight-0 (pad) rows return
    exactly 0."""
    if loss_name == "logistic":

        def conj(alpha: Array, label: Array, weight: Array) -> Array:
            w_safe = jnp.maximum(weight, 1e-30)
            # φ*(−a) for φ(z) = softplus(z) − y·z is the binary entropy
            # of s = y − a (negated): s·log s + (1−s)·log(1−s); weighted
            # form substitutes s = y − a/ω and multiplies by ω.
            s = jnp.clip(label - alpha / w_safe, 0.0, 1.0)
            return jnp.where(weight > 0.0,
                             weight * (_xlogx(s) + _xlogx(1.0 - s)), 0.0)

        return conj
    if loss_name == "squared":

        def conj(alpha: Array, label: Array, weight: Array) -> Array:
            w_safe = jnp.maximum(weight, 1e-30)
            # φ*(−a) for φ(z) = ½(z − y)²: a²/(2ω) − a·y.
            return jnp.where(weight > 0.0,
                             alpha * alpha / (2.0 * w_safe)
                             - alpha * label, 0.0)

        return conj
    raise ValueError(
        f"loss {loss_name!r} has no cheap conjugate (supported: "
        f"{sorted(CONJUGATE_LOSSES)}); use the SGD fallback")


def sdca_delta(loss_name: str):
    """(z, label, weight, alpha, xsq, lam) → Δα, the exact (squared) or
    Newton-solved (logistic) single-coordinate dual ascent step.

    ``z`` is the CURRENT margin xᵢᵀw + oᵢ, ``xsq`` = ‖xᵢ‖²; the caller
    applies w ← w + (Δα/λ)·xᵢ so the w ≡ w(α) invariant — which the gap
    identity rests on — holds after every row. Weight-0 rows get Δ = 0.
    """
    if loss_name == "squared":

        def delta(z, label, weight, alpha, xsq, lam):
            # Closed form: the new α satisfies α' = ω(y − z′) with
            # z′ = z + Δ·xsq/λ ⇒ Δ = (ω(y − z) − α)/(1 + ω·xsq/λ).
            d = (weight * (label - z) - alpha) / \
                (1.0 + weight * xsq / lam)
            return jnp.where(weight > 0.0, d, 0.0)

        return delta
    if loss_name == "logistic":

        def delta(z, label, weight, alpha, xsq, lam):
            # Optimal α' = ω(y − s) where s = σ(z′) at the post-update
            # margin z′ = z + Δ·xsq/λ. Stationarity in s:
            #   F(s) = logit(s) − z − (ωy − α)·q + s·ω·q = 0, q = xsq/λ
            # F is strictly increasing ⇒ unique root; safeguarded Newton
            # from s₀ = σ(z) converges in a handful of steps.
            q = xsq / lam
            c = (weight * label - alpha) * q
            s0 = jnp.clip(jax.nn.sigmoid(z), _SIGMOID_EPS,
                          1.0 - _SIGMOID_EPS)

            def newton(_, s):
                F = jnp.log(s) - jnp.log1p(-s) - z - c + s * weight * q
                Fp = 1.0 / (s * (1.0 - s)) + weight * q
                return jnp.clip(s - F / Fp, _SIGMOID_EPS,
                                1.0 - _SIGMOID_EPS)

            s = jax.lax.fori_loop(0, _NEWTON_ITERS, newton, s0)
            d = weight * (label - s) - alpha
            return jnp.where(weight > 0.0, d, 0.0)

        return delta
    raise ValueError(
        f"loss {loss_name!r} has no SDCA update (supported: "
        f"{sorted(CONJUGATE_LOSSES)}); use the SGD fallback")


def assemble_gap(value: float, conj_sum: float, alpha_off_sum: float,
                 l2_weight: float, w_sq: float) -> float:
    """The exact epoch gap from its streamed pieces (module docstring):
    ``value`` is the L2-WRAPPED objective P(w) (what the value pass
    returns under ``with_l2_value``), so only ONE extra (λ/2)‖w‖² is
    added here — P carries the other."""
    return float(value) + float(conj_sum) + float(alpha_off_sum) + \
        0.5 * float(l2_weight) * float(w_sq)


def reduce_gap_partials(partials, num_devices: int) -> float:
    """Reduce per-chunk gap partials the way the sharded stream would:
    group chunks into the contiguous per-device ranges of
    ``shard_chunk_ranges``, subtotal per device in chunk order, then sum
    the device subtotals in device order.

    This fixes the accumulation ORDER as a pure function of
    ``(num_chunks, num_devices)`` — at ``num_devices=1`` the grouping is
    the identity, so the reduction is BIT-identical to a plain
    left-to-right sum over chunks (the D=1 parity contract,
    tests/test_stochastic.py)."""
    from photon_ml_tpu.ops.streaming_sparse import shard_chunk_ranges

    parts = np.asarray(partials, np.float32)
    subtotals = []
    for lo, hi in shard_chunk_ranges(parts.shape[0], num_devices):
        sub = np.float32(0.0)
        for i in range(lo, hi):
            sub = np.float32(sub + parts[i])
        subtotals.append(sub)
    total = np.float32(0.0)
    for sub in subtotals:
        total = np.float32(total + sub)
    return float(total)


def sgd_gap_surrogate(grad_norm: float, l2_weight: float) -> float:
    """‖∇P(w)‖²/(2λ): a valid suboptimality upper bound for the
    λ-strongly-convex P — the primal-only stand-in for the duality gap
    on the SGD path (finite whenever the gradient is)."""
    if l2_weight <= 0.0:
        raise ValueError(
            "the SGD gap surrogate needs l2_weight > 0 (strong "
            f"convexity), got {l2_weight}")
    return float(grad_norm) * float(grad_norm) / (2.0 * float(l2_weight))
