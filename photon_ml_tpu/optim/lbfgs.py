"""L-BFGS and OWL-QN as jit/vmap-compatible ``lax.while_loop`` programs.

Reference parity: photon-lib ``optimization/LBFGS.scala`` (wraps
``breeze.optimize.LBFGS``, m=10 history, line search) and ``OWLQN.scala``
(wraps ``breeze.optimize.OWLQN``: L1 via orthant-wise QN with per-coordinate
L1 weights, intercept excluded).

TPU-first design (SURVEY.md §7 step 2): instead of wrapping a host-side
optimization library, the whole optimizer is a single compiled state machine:

- fixed-shape circular (m, d) history buffers + ``lax.fori_loop`` two-loop
  recursion — no Python lists, no dynamic shapes;
- strong-Wolfe line search (Breeze ``StrongWolfeLineSearch`` parity) as a
  bounded bisection-with-expansion inner ``while_loop`` — each trial costs
  one fused objective evaluation = one psum when the objective is
  distributed; OWL-QN uses backtracking Armijo on the projected point
  (orthant projection makes the Wolfe curvature condition ill-defined);
- every state update is masked by the per-lane ``converged`` flag so the
  SAME machine runs vmapped over thousands of padded per-entity problems
  (the random-effect regime, reference ``SingleNodeOptimizationProblem``)
  with lanes freezing as they individually converge;
- OWL-QN is the same machine with pseudo-gradients, orthant projection of
  the direction and the post-step point, and the L1 term added to the
  line-search objective.

OWL-QN follows Andrew & Gao (2007), as Breeze's implementation does.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from photon_ml_tpu.optim.common import (OptResult, OptimizerConfig,
                                        ValueAndGrad, check_convergence,
                                        masked_update)

Array = jax.Array

_EPS = 1e-10


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class _LBFGSState:
    w: Array
    f: Array
    g: Array  # gradient of the SMOOTH part
    s_hist: Array  # (m, d)
    y_hist: Array  # (m, d)
    rho: Array  # (m,)
    head: Array  # int32: slot of newest pair
    count: Array  # int32: number of valid pairs
    it: Array  # int32
    converged: Array  # bool
    failed: Array  # bool: line search stalled
    g0_norm: Array
    value_history: Array
    grad_norm_history: Array


def _two_loop(g, s_hist, y_hist, rho, head, count):
    """Two-loop recursion: returns d ≈ H⁻¹ g (descent dir is −d)."""
    m = s_hist.shape[0]
    alphas0 = jnp.zeros((m,), dtype=g.dtype)

    def bwd(j, carry):
        q, alphas = carry
        idx = (head - j) % m
        valid = j < count
        a = jnp.where(valid, rho[idx] * jnp.dot(s_hist[idx], q), 0.0)
        q = q - a * y_hist[idx]
        return q, alphas.at[idx].set(a)

    q, alphas = lax.fori_loop(0, m, bwd, (g, alphas0))

    sy = jnp.dot(s_hist[head], y_hist[head])
    yy = jnp.dot(y_hist[head], y_hist[head])
    gamma = jnp.where(count > 0, sy / jnp.maximum(yy, _EPS), 1.0)
    r = gamma * q

    def fwd(j, r):
        # oldest → newest
        idx = (head - (count - 1 - j)) % m
        valid = j < count
        b = rho[idx] * jnp.dot(y_hist[idx], r)
        r = r + jnp.where(valid, alphas[idx] - b, 0.0) * s_hist[idx]
        return r

    return lax.fori_loop(0, m, fwd, r)


def _project_orthant(x: Array, orthant: Array) -> Array:
    """Zero coordinates whose sign disagrees with the orthant."""
    return jnp.where(jnp.sign(x) == orthant, x, 0.0)


def _pseudo_gradient(w: Array, g: Array, l1: Array) -> Array:
    """OWL-QN pseudo-gradient of f(w) + Σ l1ⱼ|wⱼ| (Andrew & Gao 2007)."""
    right = g + l1
    left = g - l1
    pg_zero = jnp.where(left > 0.0, left, jnp.where(right < 0.0, right, 0.0))
    return jnp.where(w > 0.0, g + l1, jnp.where(w < 0.0, g - l1, pg_zero))


def minimize(
    value_and_grad: ValueAndGrad,
    w0: Array,
    config: OptimizerConfig = OptimizerConfig(),
    l1_weights: Optional[Array] = None,
) -> OptResult:
    """Minimize f(w) (+ Σ l1ⱼ|wⱼ| when ``l1_weights`` given → OWL-QN).

    ``value_and_grad`` must be the SMOOTH part only; the L1 term is handled
    by pseudo-gradients / orthant projection, never differentiated.
    """
    m = config.history_length
    max_iter = config.max_iterations
    is_owlqn = l1_weights is not None
    dtype = w0.dtype
    d = w0.shape[-1]

    def total_value(f_smooth: Array, w: Array) -> Array:
        if not is_owlqn:
            return f_smooth
        return f_smooth + jnp.sum(l1_weights * jnp.abs(w), axis=-1)

    def search_gradient(w: Array, g: Array) -> Array:
        """The gradient driving direction + convergence (pg for OWL-QN)."""
        if not is_owlqn:
            return g
        return _pseudo_gradient(w, g, l1_weights)

    f0, g0 = value_and_grad(w0)
    ft0 = total_value(f0, w0)
    sg0 = search_gradient(w0, g0)
    g0_norm = jnp.linalg.norm(sg0)

    hist_shape = (m, d)
    vh = jnp.full((max_iter + 1,), jnp.nan, jnp.float32).at[0].set(
        ft0.astype(jnp.float32))
    gh = jnp.full((max_iter + 1,), jnp.nan, jnp.float32).at[0].set(
        g0_norm.astype(jnp.float32))

    init = _LBFGSState(
        w=w0, f=f0, g=g0,
        s_hist=jnp.zeros(hist_shape, dtype), y_hist=jnp.zeros(hist_shape, dtype),
        rho=jnp.zeros((m,), dtype),
        head=jnp.asarray(0, jnp.int32), count=jnp.asarray(0, jnp.int32),
        it=jnp.asarray(0, jnp.int32),
        converged=g0_norm <= config.tolerance,
        failed=jnp.asarray(False),
        g0_norm=g0_norm,
        value_history=vh, grad_norm_history=gh,
    )

    def line_search_owlqn(w, ft, sg, direction):
        """Backtracking Armijo on the TOTAL objective; returns new point.

        OWL-QN only: the trial point is projected onto the orthant defined
        by sign(w) (or sign(−pg) at zeros) before evaluation, which makes
        the Wolfe curvature condition ill-defined — so Armijo it stays
        (Andrew & Gao 2007 use backtracking too).
        """
        orthant = jnp.where(w != 0.0, jnp.sign(w), jnp.sign(-sg))

        def ls_cond(st):
            alpha, steps, done, *_ = st
            return (~done) & (steps < config.max_line_search_steps)

        def ls_body(st):
            alpha, steps, done, best_w, best_f, best_g = st
            cand = _project_orthant(w + alpha * direction, orthant)
            f_new, g_new = value_and_grad(cand)
            ft_new = total_value(f_new, cand)
            # Armijo with the projected displacement (OWL-QN form).
            decrease = jnp.dot(sg, cand - w)
            ok = jnp.isfinite(ft_new) & (ft_new <= ft + config.wolfe_c1 * decrease)
            best_w = jnp.where(ok, cand, best_w)
            best_f = jnp.where(ok, f_new, best_f)
            best_g = jnp.where(ok, g_new, best_g)
            return (alpha * 0.5, steps + 1, ok, best_w, best_f, best_g)

        init_alpha = jnp.asarray(1.0, dtype)
        st = (init_alpha, jnp.asarray(0, jnp.int32), jnp.asarray(False),
              w, jnp.asarray(jnp.inf, dtype), sg)
        _, steps, ok, new_w, new_f, new_g = lax.while_loop(ls_cond, ls_body, st)
        return ok, new_w, new_f, new_g

    def line_search_wolfe(w, ft, sg, direction):
        """Strong-Wolfe line search as a bounded bisection-with-expansion.

        Reference parity: breeze ``StrongWolfeLineSearch`` driven by
        ``optimization/LBFGS.scala``. Instead of Breeze's host-side
        bracket-and-zoom recursion this is one fixed-bound ``while_loop``
        maintaining a bracket [a, b] (b = ∞ until an upper bound is seen):

        - Armijo fails, or slope already ≥ +c2·|φ'(0)| (overshot)  → b = α
        - Armijo holds but slope < c2·φ'(0) (still descending hard) → a = α
        - Armijo holds and |φ'(α)| ≤ −c2·φ'(0)                      → accept

        Next trial: 2α while unbracketed, else the midpoint. One fused
        value+grad per trial (one psum when distributed), vmap-safe: under
        vmap, JAX's while_loop batching select-freezes finished lanes.
        Guarantees sᵀy > 0 for accepted points, so every step yields a
        valid curvature pair. On budget exhaustion falls back to the best
        Armijo-satisfying point seen (the sy > eps gate below discards its
        pair if curvature is bad).
        """
        c1 = config.wolfe_c1
        c2 = config.wolfe_c2
        dg0 = jnp.dot(sg, direction)  # φ'(0) < 0 for descent directions
        inf = jnp.asarray(jnp.inf, dtype)

        def ls_cond(st):
            _, _, _, steps, done, *_ = st
            return (~done) & (steps < config.max_line_search_steps)

        def ls_body(st):
            a, b, alpha, steps, done, has_pt, res_w, res_f, res_g = st
            cand = w + alpha * direction
            f_new, g_new = value_and_grad(cand)
            dg_new = jnp.dot(g_new, direction)
            armijo = jnp.isfinite(f_new) & (f_new <= ft + c1 * alpha * dg0)
            strong = armijo & (jnp.abs(dg_new) <= -c2 * dg0)
            curv_low = dg_new < c2 * dg0
            # Record: a strong point always wins; otherwise keep the best
            # (lowest-f) Armijo point as the exhaustion fallback. res_f
            # starts at f(w), and any Armijo point is below that.
            take = strong | (armijo & (f_new < res_f))
            res_w = jnp.where(take, cand, res_w)
            res_f = jnp.where(take, f_new, res_f)
            res_g = jnp.where(take, g_new, res_g)
            grow = armijo & curv_low & ~strong
            a2 = jnp.where(grow, alpha, a)
            b2 = jnp.where(~strong & ~grow, alpha, b)
            alpha2 = jnp.where(grow & ~jnp.isfinite(b2),
                               2.0 * alpha, 0.5 * (a2 + b2))
            return (a2, b2, alpha2, steps + 1, strong, has_pt | armijo,
                    res_w, res_f, res_g)

        st = (jnp.asarray(0.0, dtype), inf, jnp.asarray(1.0, dtype),
              jnp.asarray(0, jnp.int32), jnp.asarray(False),
              jnp.asarray(False), w, ft, sg)
        (_, _, _, _, done, has_pt,
         new_w, new_f, new_g) = lax.while_loop(ls_cond, ls_body, st)
        return done | has_pt, new_w, new_f, new_g

    line_search = line_search_owlqn if is_owlqn else line_search_wolfe

    def body(state: _LBFGSState) -> _LBFGSState:
        sg = search_gradient(state.w, state.g)
        d_dir = -_two_loop(sg, state.s_hist, state.y_hist, state.rho,
                           state.head, state.count)
        if is_owlqn:
            # Constrain the direction to the descent orthant of −pg.
            d_dir = jnp.where(d_dir * (-sg) > 0.0, d_dir, 0.0)
        # Safeguard: fall back to steepest descent on non-descent directions.
        descent = jnp.dot(sg, d_dir) < 0.0
        d_dir = jnp.where(descent, d_dir, -sg)
        # First iteration: scale like Breeze (step ~ 1/‖g‖ effect) to avoid
        # wild first steps on poorly scaled problems.
        first = state.count == 0
        d_dir = jnp.where(
            first, d_dir / jnp.maximum(jnp.linalg.norm(d_dir), 1.0), d_dir)

        ft = total_value(state.f, state.w)
        ok, new_w, new_f, new_g = line_search(state.w, ft, sg, d_dir)

        s = new_w - state.w
        y = new_g - state.g
        sy = jnp.dot(s, y)
        good_pair = ok & (sy > _EPS)
        new_head = jnp.where(good_pair, (state.head + 1) % m, state.head)
        new_count = jnp.where(good_pair, jnp.minimum(state.count + 1, m),
                              state.count)

        def upd(buf, row):
            return jnp.where(
                good_pair,
                buf.at[new_head].set(row),
                buf)

        s_hist = upd(state.s_hist, s)
        y_hist = upd(state.y_hist, y)
        rho = jnp.where(good_pair,
                        state.rho.at[new_head].set(1.0 / jnp.maximum(sy, _EPS)),
                        state.rho)

        new_sg = search_gradient(new_w, new_g)
        new_gnorm = jnp.linalg.norm(new_sg)
        ft_new = total_value(new_f, new_w)
        it = state.it + 1
        conv = ok & check_convergence(ft_new, ft, new_gnorm, state.g0_norm,
                                      config.tolerance)
        failed = ~ok  # line search exhausted: stop (stalled)

        vh = state.value_history.at[it].set(
            jnp.where(ok, ft_new, ft).astype(jnp.float32))
        gh = state.grad_norm_history.at[it].set(
            jnp.where(ok, new_gnorm,
                      jnp.linalg.norm(sg)).astype(jnp.float32))

        new_state = _LBFGSState(
            w=jnp.where(ok, new_w, state.w),
            f=jnp.where(ok, new_f, state.f),
            g=jnp.where(ok, new_g, state.g),
            s_hist=s_hist, y_hist=y_hist, rho=rho,
            head=new_head, count=new_count,
            it=it,
            converged=state.converged | conv | failed,
            failed=state.failed | failed,
            g0_norm=state.g0_norm,
            value_history=vh, grad_norm_history=gh,
        )
        # vmap safety: freeze lanes that were already converged (history
        # buffers included — body still executes for them).
        return masked_update(state.converged, new_state, state)

    def cond(state: _LBFGSState):
        return (~state.converged) & (state.it < max_iter)

    final = lax.while_loop(cond, body, init)
    sg_final = search_gradient(final.w, final.g)
    return OptResult(
        w=final.w,
        value=total_value(final.f, final.w),
        grad_norm=jnp.linalg.norm(sg_final),
        iterations=final.it,
        converged=final.converged & ~final.failed,
        value_history=final.value_history,
        grad_norm_history=final.grad_norm_history,
    )


def minimize_owlqn(
    value_and_grad: ValueAndGrad,
    w0: Array,
    l1_weights: Array,
    config: OptimizerConfig = OptimizerConfig(),
) -> OptResult:
    """OWL-QN: minimize smooth f(w) + Σⱼ l1ⱼ |wⱼ|.

    Reference parity: photon-lib ``optimization/OWLQN.scala``.
    """
    return minimize(value_and_grad, w0, config, l1_weights=l1_weights)
