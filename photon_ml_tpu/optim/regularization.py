"""Regularization contexts and objective wrappers.

Reference parity: photon-lib ``optimization/RegularizationContext.scala`` /
``RegularizationType.scala`` — NONE, L1, L2, ELASTIC_NET with mixing weight
alpha: l1 = alpha*lambda, l2 = (1-alpha)*lambda. L2 is folded into the smooth
objective's value/gradient/Hessian; L1 is handled by OWL-QN's pseudo-gradient
(never differentiated).

The ``reg_mask`` vector excludes coordinates from regularization — the
reference excludes the intercept (OWLQN.scala: L1 weight 0 for intercept).
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array


class RegularizationType(enum.Enum):
    NONE = "NONE"
    L1 = "L1"
    L2 = "L2"
    ELASTIC_NET = "ELASTIC_NET"


@dataclasses.dataclass(frozen=True)
class RegularizationContext:
    reg_type: RegularizationType = RegularizationType.NONE
    reg_weight: float = 0.0
    # Elastic-net mixing: l1 = alpha * weight, l2 = (1 - alpha) * weight.
    elastic_net_alpha: float = 0.5

    def l1_weight(self) -> float:
        t = RegularizationType(self.reg_type)
        if t == RegularizationType.L1:
            return self.reg_weight
        if t == RegularizationType.ELASTIC_NET:
            return self.elastic_net_alpha * self.reg_weight
        return 0.0

    def l2_weight(self) -> float:
        t = RegularizationType(self.reg_type)
        if t == RegularizationType.L2:
            return self.reg_weight
        if t == RegularizationType.ELASTIC_NET:
            return (1.0 - self.elastic_net_alpha) * self.reg_weight
        return 0.0


def intercept_mask(dim: int, intercept_index: Optional[int]) -> np.ndarray:
    """1.0 for regularized coordinates, 0.0 for the intercept."""
    mask = np.ones((dim,), np.float32)
    if intercept_index is not None:
        mask[intercept_index] = 0.0
    return mask


def with_l2(
    value_and_grad: Callable[[Array], tuple[Array, Array]],
    l2_weight: float,
    reg_mask: Optional[Array] = None,
) -> Callable[[Array], tuple[Array, Array]]:
    """Fold 0.5·λ‖w∘mask‖² into a smooth objective."""
    if l2_weight == 0.0:
        return value_and_grad

    def wrapped(w: Array) -> tuple[Array, Array]:
        f, g = value_and_grad(w)
        wm = w if reg_mask is None else w * reg_mask
        f = f + 0.5 * l2_weight * jnp.sum(wm * wm, axis=-1)
        g = g + l2_weight * wm
        return f, g

    return wrapped


def with_l2_value(
    value_fn: Callable[[Array], Array],
    l2_weight: float,
    reg_mask: Optional[Array] = None,
) -> Callable[[Array], Array]:
    """Value-only companion of :func:`with_l2` — for streamed line-search
    probes where the gradient pass is deferred to acceptance."""
    if l2_weight == 0.0:
        return value_fn

    def wrapped(w: Array) -> Array:
        wm = w if reg_mask is None else w * reg_mask
        return value_fn(w) + 0.5 * l2_weight * jnp.sum(wm * wm, axis=-1)

    return wrapped


def with_l2_hvp(
    hvp: Callable[[Array, Array], Array],
    l2_weight: float,
    reg_mask: Optional[Array] = None,
) -> Callable[[Array, Array], Array]:
    if l2_weight == 0.0:
        return hvp

    def wrapped(w: Array, v: Array) -> Array:
        hv = hvp(w, v)
        vm = v if reg_mask is None else v * reg_mask
        return hv + l2_weight * vm

    return wrapped


def l1_weights_vector(
    l1_weight: float, dim: int, intercept_index: Optional[int],
    dtype=jnp.float32,
) -> Array:
    """Per-coordinate L1 weights for OWL-QN (intercept excluded)."""
    return jnp.asarray(l1_weight * intercept_mask(dim, intercept_index),
                       dtype=dtype)
