"""Host-driven L-BFGS for row-streamed objectives.

Reference parity: photon-api's distributed fits are DRIVER-loop
optimization — Breeze L-BFGS iterates on the Spark driver, and every
value/gradient is one cluster pass (``DistributedGLMLossFunction`` →
``treeAggregate``). The compiled optimizer in ``optim/lbfgs.py`` is the
right shape when the data is device-resident (the whole solve is one XLA
program, vmappable for per-entity lanes), but a row-STREAMED objective
(``ops/streaming_sparse.py``) is a Python loop over chunk dispatches and
cannot be traced into a ``lax.while_loop``. This module is the
driver-loop counterpart: the two-loop recursion and vector math stay on
device (jitted helpers over (d,)-vectors — history for d=1M, m=10 is
40 MB), the iteration control runs in Python, and each objective
evaluation streams the chunks once.

Line search is backtracking Armijo (not strong Wolfe): each probe costs a
FULL pass over the data, and Armijo accepts in 1–2 probes from the
well-scaled L-BFGS direction where the bracket/bisect Wolfe machine
budgets for ~10. Curvature pairs that fail s·y > 0 are skipped (standard
damping), preserving a positive-definite inverse-Hessian model; parity
with the compiled strong-Wolfe L-BFGS is pinned by test on shared small
problems (tests/test_streaming.py).

L1/OWL-QN (``l1_weights``): the same driver loop runs Andrew & Gao's
orthant-wise scheme, mirroring the compiled ``minimize_owlqn``
(optim/lbfgs.py) — the PSEUDO-gradient drives the two-loop direction and
the convergence norm, every probe is projected onto the orthant of the
current point (sign(w), or sign(−pg) at zeros), Armijo tests the TOTAL
objective with the projected displacement ``pg·(cand − w)``, and
curvature pairs come from the RAW smooth gradients. The streamed
``value_and_grad``/``value_only`` stay the smooth part only; the L1 term
is added host-side at the probe barrier (the value is already synced
there) and is never differentiated.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu import faults as flt
from photon_ml_tpu import obs
from photon_ml_tpu.obs.ledger import transfer_totals
from photon_ml_tpu.obs.watchdog import ConvergenceWatchdog
from photon_ml_tpu.optim.common import OptResult, OptimizerConfig
from photon_ml_tpu.optim.lbfgs import _project_orthant, _pseudo_gradient

Array = jax.Array


@jax.jit
def _two_loop(grad: Array, s_stack: Array, y_stack: Array,
              rho: Array, m: Array) -> Array:
    """Standard L-BFGS two-loop recursion over a fixed-size (M, d)
    history ring; entries past ``m`` (the live count) are masked out.
    Newest pair is at index m-1."""
    M = s_stack.shape[0]

    def bwd(i, carry):
        q, alpha = carry
        j = m - 1 - i  # newest → oldest; j < 0 once i >= m (dead lanes)
        live = j >= 0
        jc = jnp.maximum(j, 0)
        a = jnp.where(live, rho[jc] * jnp.dot(s_stack[jc], q), 0.0)
        q = q - a * y_stack[jc]  # a == 0 on dead lanes
        return q, jnp.where(live, alpha.at[jc].set(a), alpha)

    q, alpha = jax.lax.fori_loop(
        0, M, bwd, (grad, jnp.zeros((M,), jnp.float32)))
    # Initial Hessian scaling γ = s·y / y·y from the newest pair.
    newest = jnp.maximum(m - 1, 0)
    sy = jnp.dot(s_stack[newest], y_stack[newest])
    yy = jnp.dot(y_stack[newest], y_stack[newest])
    gamma = jnp.where((m > 0) & (yy > 0), sy / jnp.maximum(yy, 1e-30), 1.0)
    r = gamma * q

    def fwd(j, r):
        # Oldest → newest. Ring slots ≥ m hold zeros (s/y/rho/alpha), so
        # dead lanes contribute exactly 0 with no masking needed.
        beta = rho[j] * jnp.dot(y_stack[j], r)
        return r + (alpha[j] - beta) * s_stack[j]

    return -jax.lax.fori_loop(0, M, fwd, r)


@jax.jit
def _shift_in(stack: Array, v: Array, m: Array) -> Array:
    """Append ``v`` at ring position m (or shift left when full)."""
    M = stack.shape[0]
    full = m >= M
    shifted = jnp.where(full, jnp.roll(stack, -1, axis=0), stack)
    idx = jnp.where(full, M - 1, m)
    return shifted.at[idx].set(v)


def snapshot_state(w, g, s_stack, y_stack, rho, m_host, it, fv, gn_prev,
                   f0, gn0, vals, gns) -> dict:
    """Host-side snapshot of the FULL driver-loop state at an iteration
    boundary — everything the loop reads before its next streamed pass.
    Plain numpy (f32 exact), so a save→load→resume round trip replays
    the remaining iterations BIT-identically to an uninterrupted run
    (the objective itself is deterministic: fixed chunk order per
    device, fixed merge order)."""
    return {
        "w": np.asarray(w), "g": np.asarray(g),
        "s_stack": np.asarray(s_stack), "y_stack": np.asarray(y_stack),
        "rho": np.asarray(rho), "m": np.int32(m_host),
        "it": np.int32(it), "fv": np.float32(fv),
        "gn_prev": np.float32(gn_prev), "f0": np.float32(f0),
        "gn0": np.float32(gn0), "vals": np.asarray(vals),
        "gns": np.asarray(gns),
    }


def minimize_streaming(
    value_and_grad: Callable[[Array], tuple[Array, Array]],
    w0: Array,
    config: OptimizerConfig,
    log: Callable[[str], None] = lambda m: None,
    value_only: Optional[Callable[[Array], Array]] = None,
    checkpoint_save: Optional[Callable[[dict], None]] = None,
    resume_state: Optional[dict] = None,
    l1_weights: Optional[Array] = None,
    on_accept: Optional[Callable[[int, Array, float, float], None]] = None,
) -> OptResult:
    """Driver-loop L-BFGS: minimize a host-driven (value, grad) callable.

    ``value_and_grad`` is called once per iteration plus once per
    line-search probe; everything it returns stays on device until the
    final host read of the convergence scalars (one small sync per
    iteration — the stream itself is the dominant cost by orders of
    magnitude).

    ``value_only``, when given, is a cheaper streamed pass computing just
    the objective value; Armijo probes then use it — only the VALUE gates
    acceptance — and the gradient pass runs once per iteration, on the
    accepted point (ADVICE r5: without this, every backtracking probe
    paid the full gradient stream only to discard it). Probe cost per
    iteration drops from ``k·cost(vg)`` to ``k·cost(v) + cost(vg)``; on
    the hybrid-sparse chunk kernels the gradient half (hot rmatvec +
    per-slot cold scatter-adds) dominates compute, so cost(v) ≪
    cost(vg) and the win grows with every backtrack.

    ``checkpoint_save``, when given, is called at the end of every
    accepted iteration with a :func:`snapshot_state` dict; passing a
    saved snapshot back as ``resume_state`` restarts the loop at the
    NEXT iteration with bit-identical state (the crash-resume seam of
    the streamed fixed-effect coordinate — game/checkpoint.py's
    StreamingStateStore persists the snapshots). A resumed call skips
    the initial value/gradient pass entirely: the snapshot carries it.

    ``l1_weights``, when given, switches the loop to OWL-QN (module
    docstring) — ``value_and_grad``/``value_only`` must stay the SMOOTH
    part only; the L1 term is never differentiated.

    ``on_accept``, when given, runs once per ACCEPTED iteration with
    ``(it, w, value, grad_norm)``, after the ledger row and the
    checkpoint write — the fabric's cross-rank digest exchange hooks
    here (fabric/stream.py), so a ``RankDivergence`` raised from the
    hook still leaves a resumable snapshot and a flushed curve point
    behind, exactly like a watchdog verdict.

    Telemetry (docs/OBSERVABILITY.md "The run ledger"): when a run
    ledger is active (``obs.ledger()``), every accepted iteration
    records an ``opt_iter`` row LIVE — value, gradient norm, step,
    probe/pass counts, per-iteration wall seconds, cumulative transfer
    counters. When a watchdog config is installed
    (``obs.watchdog_config()``), the same per-iteration stream feeds a
    :class:`ConvergenceWatchdog` — NaN/stall/divergence/slow-iteration
    become a loud event plus a defined error or early stop. Both are
    off by default at one None check here.
    """
    d = int(w0.shape[0])
    M = config.history_length
    max_it = config.max_iterations
    led = obs.ledger()
    wd_cfg = obs.watchdog_config()
    wd = (ConvergenceWatchdog(wd_cfg) if wd_cfg is not None else None)
    l1 = (None if l1_weights is None
          else jnp.asarray(l1_weights, jnp.float32))
    opt_name = "lbfgs-stream" if l1 is None else "owlqn-stream"

    def _sgrad(w, g):
        """Gradient driving direction + convergence (pg under L1)."""
        return g if l1 is None else _pseudo_gradient(w, g, l1)

    def _l1_term(w) -> float:
        if l1 is None:
            return 0.0
        return float(jnp.sum(l1 * jnp.abs(w)))

    v_passes = g_passes = 0  # streamed passes, cumulative this call
    if resume_state is not None:
        st = resume_state
        if st["s_stack"].shape != (M, d) or st["w"].shape != (d,):
            raise ValueError(
                f"resume state shape mismatch: saved history "
                f"{st['s_stack'].shape} / w {st['w'].shape}, expected "
                f"({M}, {d}) / ({d},) — the checkpoint was written under "
                f"a different optimizer configuration")
        w = jnp.asarray(st["w"], jnp.float32)
        g = jnp.asarray(st["g"], jnp.float32)
        s_stack = jnp.asarray(st["s_stack"], jnp.float32)
        y_stack = jnp.asarray(st["y_stack"], jnp.float32)
        rho = jnp.asarray(st["rho"], jnp.float32)
        m_host = int(st["m"])
        m = jnp.asarray(m_host, jnp.int32)
        f0, gn0 = float(st["f0"]), float(st["gn0"])
        fv, gn_prev = float(st["fv"]), float(st["gn_prev"])
        start_it = int(st["it"]) + 1
        vals = np.full((max_it + 1,), np.nan, np.float32)
        gns = np.full((max_it + 1,), np.nan, np.float32)
        k = min(st["vals"].shape[0], max_it + 1)
        vals[:k], gns[:k] = st["vals"][:k], st["gns"][:k]
        sg = _sgrad(w, g)  # snapshot carries the RAW gradient
        log(f"resuming streamed L-BFGS at iteration {start_it} "
            f"(f={fv:.6g})")
    else:
        w = jnp.asarray(w0, jnp.float32)
        with obs.span("lbfgs.initial_pass", cat="optim"):
            f, g = value_and_grad(w)
        g_passes += 1
        sg = _sgrad(w, g)
        f0 = float(f) + _l1_term(w)
        gn0 = float(jnp.linalg.norm(sg))
        s_stack = jnp.zeros((M, d), jnp.float32)
        y_stack = jnp.zeros((M, d), jnp.float32)
        rho = jnp.zeros((M,), jnp.float32)
        m = jnp.zeros((), jnp.int32)
        m_host = 0  # host mirror of m — step-size branch must not sync
        vals = np.full((max_it + 1,), np.nan, np.float32)
        gns = np.full((max_it + 1,), np.nan, np.float32)
        vals[0], gns[0] = f0, gn0
        fv, gn_prev = f0, gn0
        start_it = 1
    converged = False
    it = start_it - 1
    for it in range(start_it, max_it + 1):
        t_iter = time.perf_counter()
        v0_passes, g0_passes = v_passes, g_passes
        # One span per driver-loop iteration (docs/OBSERVABILITY.md):
        # streamed passes, probes, and the checkpoint write all nest
        # under it, so the trace waterfall reads as the optimizer ran.
        with obs.span("lbfgs.iteration", cat="optim", it=it):
            direction = _two_loop(sg, s_stack, y_stack, rho, m)
            # pml: allow[PML001] direction-validity guard is a host branch by design; one scalar read per iteration vs a full data pass
            dg = float(jnp.dot(direction, sg))
            if not np.isfinite(dg) or dg >= 0.0:
                # pml: allow[PML001] steepest-descent fallback needs the host scalar for the same Armijo branch; rare path
                direction, dg = -sg, -float(jnp.dot(sg, sg))
            # First iteration: steepest descent scaled to unit step
            # length (Breeze's determineStepSize init); later γ-scaling
            # makes 1.0 the natural trial step.
            step = 1.0 if m_host > 0 else min(1.0,
                                              1.0 / max(gn_prev, 1e-12))
            # OWL-QN probes live in the orthant of the CURRENT point
            # (sign(w); sign(−pg) at zeros) — fixed across backtracks.
            orthant = (None if l1 is None else
                       jnp.where(w != 0.0, jnp.sign(w), jnp.sign(-sg)))
            accepted = False
            for probe in range(config.max_line_search_steps):
                w_try = w + step * direction
                if orthant is not None:
                    w_try = _project_orthant(w_try, orthant)
                with obs.span("lbfgs.probe", cat="optim", it=it,
                              probe=probe, step=step):
                    if value_only is None:
                        f_try, g_try = value_and_grad(w_try)
                        g_passes += 1
                        # pml: allow[PML001] Armijo probe is a BY-DESIGN barrier: the host decides accept/backtrack on this value (ISSUE 3)
                        f_try_h = float(f_try)
                    else:
                        v_passes += 1
                        # pml: allow[PML001] Armijo probe barrier, value-only pass (same by-design host decision as above)
                        f_try_h = float(value_only(w_try))
                f_try_h += _l1_term(w_try)  # total objective under L1
                # Watchdog chaos seam (docs/ROBUSTNESS.md): a "nan"
                # fault spec here is the injected form of a numerically
                # sick objective.
                f_try_h = flt.poison_scalar(flt.sites.STREAM_OBJECTIVE, f_try_h)
                if l1 is None:
                    decrease = step * dg
                else:
                    # Armijo with the projected displacement (the
                    # orthant projection breaks the step·dg identity).
                    # pml: allow[PML001] same by-design probe barrier — one scalar per probe
                    decrease = float(jnp.dot(sg, w_try - w))
                if np.isfinite(f_try_h) and \
                        f_try_h <= fv + config.wolfe_c1 * decrease:
                    accepted = True
                    break
                step *= 0.5
            if not accepted:
                if wd is not None:
                    # A line search that died on NON-FINITE probes is
                    # the NaN failure shape — loud, defined (a finite
                    # failed search stays the optimizer's own stop).
                    wd.on_line_search_failure(f_try_h, it)
                log(f"iter {it}: line search failed (f={fv:.6g}); "
                    f"stopping")
                break
            if value_only is not None:
                # Gradient pass only on acceptance (the curvature pair
                # and the next direction need it; rejected probes never
                # did).
                _, g_try = value_and_grad(w_try)
                g_passes += 1
            s = w_try - w
            y = g_try - g  # RAW smooth gradients (OWL-QN included)
            # pml: allow[PML001] curvature-damping skip is a host branch; one scalar per accepted step
            sy = float(jnp.dot(s, y))
            if sy > 1e-10:
                s_stack = _shift_in(s_stack, s, m)
                y_stack = _shift_in(y_stack, y, m)
                rho = _shift_in(rho[:, None], jnp.full((1,), 1.0 / sy,
                                                       jnp.float32),
                                m)[:, 0]
                m = jnp.minimum(m + 1, M)
                m_host = min(m_host + 1, M)
            w, g = w_try, g_try
            sg = _sgrad(w, g)
            f_prev, fv = fv, f_try_h
            # pml: allow[PML001] convergence test runs on host once per iteration; the streamed pass dominates by orders of magnitude
            gn = float(jnp.linalg.norm(sg))
            vals[it], gns[it] = fv, gn
            log(f"iter {it}: f={fv:.6g} |g|={gn:.3g} step={step:.3g}")
            if led is not None:
                # Append-as-produced: a SIGKILL one iteration later
                # still leaves this point on the curve (the ledger's
                # whole reason to exist).
                led.record("opt_iter", opt=opt_name, iteration=it,
                           value=fv, grad_norm=gn, step=step,
                           probes=probe + 1,
                           value_passes=v_passes - v0_passes,
                           grad_passes=g_passes - g0_passes,
                           seconds=round(time.perf_counter() - t_iter, 6),
                           **transfer_totals())
            if checkpoint_save is not None:
                # Iteration boundary = the resume point: everything the
                # next iteration reads goes into the snapshot (gn_prev is
                # the gn just computed — the value the next iteration
                # would see).
                checkpoint_save(snapshot_state(
                    w, g, s_stack, y_stack, rho, m_host, it, fv, gn, f0,
                    gn0, vals, gns))
            if on_accept is not None:
                # After the checkpoint write (same rationale as the
                # watchdog below): a divergence raised here leaves a
                # resumable snapshot + a flushed ledger row behind.
                on_accept(it, w, fv, gn)
            if wd is not None:
                # After the checkpoint write: a "raise" verdict still
                # leaves a resumable snapshot + a flushed ledger row.
                if wd.observe(it, fv, gn,
                              time.perf_counter() - t_iter) == "stop":
                    log(f"iter {it}: watchdog early stop")
                    break
            if gn <= config.tolerance * max(gn0, 1.0) or \
                    abs(fv - f_prev) <= config.tolerance * max(abs(f_prev),
                                                               1e-12):
                converged = True
                break
            gn_prev = gn

    return OptResult(
        w=w,
        value=jnp.asarray(fv, jnp.float32),
        grad_norm=jnp.asarray(gns[it] if not np.isnan(gns[it]) else gn_prev,
                              jnp.float32),
        iterations=jnp.asarray(it, jnp.int32),
        converged=jnp.asarray(converged),
        value_history=jnp.asarray(vals),
        grad_norm_history=jnp.asarray(gns),
    )
