"""Logging + timing utilities.

Reference parity: photon-lib ``util/PhotonLogger.scala`` (log4j logger whose
output is also persisted next to the job output) and ``util/Timer.scala``
(wall-clock scopes).
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import time
from typing import Optional


def setup_logging(
    level: int = logging.INFO,
    log_file: Optional[str] = None,
) -> logging.Logger:
    """Configure the framework logger; optionally tee to a file beside the
    job output (PhotonLogger behavior)."""
    logger = logging.getLogger("photon_ml_tpu")
    logger.setLevel(level)
    if not logger.handlers:
        h = logging.StreamHandler()
        h.setFormatter(logging.Formatter(
            "%(asctime)s %(levelname)s %(name)s: %(message)s"))
        logger.addHandler(h)
    if log_file:
        os.makedirs(os.path.dirname(log_file) or ".", exist_ok=True)
        fh = logging.FileHandler(log_file)
        fh.setFormatter(logging.Formatter(
            "%(asctime)s %(levelname)s %(name)s: %(message)s"))
        logger.addHandler(fh)
    return logger


class Timer:
    """Wall-clock scope timer (reference: util/Timer.scala)."""

    def __init__(self):
        self.durations: dict[str, float] = {}

    @contextlib.contextmanager
    def scope(self, name: str):
        t0 = time.monotonic()
        try:
            yield
        finally:
            self.durations[name] = self.durations.get(name, 0.0) + (
                time.monotonic() - t0)


class MetricsWriter:
    """Structured per-step metrics to a JSONL file (the rebuild's
    OptimizationStatesTracker/EvaluationResults observability sink)."""

    def __init__(self, path: str):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f = open(path, "a")

    def write(self, record: dict) -> None:
        self._f.write(json.dumps(record) + "\n")
        self._f.flush()

    def close(self) -> None:
        self._f.close()


@contextlib.contextmanager
def profile_trace(trace_dir: Optional[str]):
    """XLA/TPU profiler scope (SURVEY.md §5 tracing row): when ``trace_dir``
    is set, everything inside the scope is captured with ``jax.profiler``
    (HLO timelines, per-op device time, memory) viewable in
    TensorBoard/Perfetto; a no-op when None. This replaces the reference's
    Spark-UI stage timeline as the "where did the time go" tool."""
    if not trace_dir:
        yield
        return
    import jax

    os.makedirs(trace_dir, exist_ok=True)
    with jax.profiler.trace(trace_dir):
        yield


def annotate(name: str):
    """Named sub-scope inside a profiler trace (TraceAnnotation)."""
    import jax

    return jax.profiler.TraceAnnotation(name)
