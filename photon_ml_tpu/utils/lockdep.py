"""The runtime half of photon-lockdep: an opt-in instrumented lock
layer that observes the REAL acquisition DAG while tests run.

The static graph (analysis/locks.py) proves what the resolver can see;
this module catches what it can't — an order inversion that happened on
a benign interleaving (thread 1 took A→B, thread 2 took B→A, nobody
deadlocked *this* run), or a blocking call made while a package lock
was held through a code path the call-graph resolver missed. The two
halves meet in ``photon-lint --locks --reconcile .photon-lockdep.json``:
runtime edges missing from the static graph are resolver gaps to fix;
static edges never exercised are test-coverage debt to report.

Discipline is photon-fault's: ONE env/flag check arms it
(``PHOTON_LOCKDEP=1``, or ``instrument(force=True)``), and when it is
off this module changes NOTHING — ``threading.Lock`` stays the builtin,
no wrapper, no per-acquire bookkeeping, zero overhead (tests assert
that). Armed, ``instrument()`` monkeypatches
``threading.Lock/RLock/Condition`` so that constructions **from inside
the package** return tracked wrappers; any other construction (stdlib
queues, executors, third-party code) still gets the real thing.

Tracked-lock node ids match the static graph exactly —
``{module}.{Class}.{attr}`` for ``self.attr = threading.Lock()``
assignments, ``{module}.{NAME}`` for module-level constants — derived
from the constructing frame (``__name__``, ``type(self).__name__``, and
the assignment target on the source line), which is what makes the
reconciliation diff line up without a mapping table.

Recorded, per process, dumped merged to ``.photon-lockdep.json``
(``PHOTON_LOCKDEP_OUT`` overrides) at exit:

- **edges**: (held → acquired) pairs with thread + site witness;
- **inversions**: an edge whose reverse was already observed — both
  witnesses kept; also bumps ``photon_lockdep_inversions_total`` when
  the obs registry is live (run_tier1's lockdep leg fails on any);
- **blocking**: ``time.sleep`` / ``urlopen`` / ``Future.result`` /
  ``Popen.wait`` entered while a tracked lock was held (PML019's
  runtime shadow; reported, not failing — the static rule owns the
  verdict and its allows).
"""

from __future__ import annotations

import atexit
import json
import linecache
import os
import re
import sys
import threading
from typing import Optional

DEFAULT_OUT = ".photon-lockdep.json"
_PKG = "photon_ml_tpu"

_SELF_ASSIGN_RE = re.compile(r"self\.(\w+)\s*=")
_NAME_ASSIGN_RE = re.compile(r"^\s*(\w+)\s*=")


class _State:
    def __init__(self):
        self.armed = False
        self.guard = _REAL["Lock"]()  # real lock: the tracker itself
        self.tls = threading.local()
        self.nodes: dict = {}        # node id -> "Lock"/"RLock"/"Condition"
        self.edges: dict = {}        # (src, dst) -> {count, witness}
        self.inversions: list = []
        self.blocking: list = []
        self.dump_registered = False


# The real constructors, captured at import so instrument()/deactivate()
# round-trips even if called twice.
_REAL = {
    "Lock": threading.Lock,
    "RLock": threading.RLock,
    "Condition": threading.Condition,
}
_STATE = _State()
_PATCHED_BLOCKING: dict = {}


# -------------------------------------------------------------- bookkeeping


def _held() -> list:
    held = getattr(_STATE.tls, "held", None)
    if held is None:
        held = _STATE.tls.held = []
    return held


def _caller_site() -> str:
    f = sys._getframe(2)
    while f is not None:
        mod = f.f_globals.get("__name__", "")
        if mod.startswith(__name__) or mod == "threading":
            f = f.f_back
            continue
        break
    if f is None:
        return "?"
    try:
        path = os.path.relpath(f.f_code.co_filename)
    except ValueError:
        path = f.f_code.co_filename
    return f"{path.replace(os.sep, '/')}:{f.f_lineno}"


def _bump_inversion_counter(n: int) -> None:
    # Lazy and OUTSIDE _STATE.guard: importing obs constructs a
    # package-level lock (obs/__init__._LOCK), which re-enters
    # _register -> guard and would deadlock if we still held it.
    try:
        from photon_ml_tpu import obs
        mx = obs.metrics()
        if mx is not None:
            mx.counter("photon_lockdep_inversions_total").inc(n)
    # pml: allow[PML008] best-effort metric bump from inside the lock
    # tracker: the inversion is already recorded; an obs failure here
    # must never wedge or recurse into the instrumented path
    except Exception:
        pass


def _note_acquire(node: str) -> None:
    if not _STATE.armed:   # a leftover wrapper after deactivate()
        return
    held = _held()
    if node in held:            # re-entrant (RLock): no new ordering fact
        held.append(node)
        return
    site = _caller_site()
    thread = threading.current_thread().name
    inversions = 0
    with _STATE.guard:
        for h in dict.fromkeys(held):
            if h == node:
                continue
            edge = (h, node)
            entry = _STATE.edges.get(edge)
            if entry is None:
                witness = {"thread": thread, "site": site}
                _STATE.edges[edge] = {"count": 1, "witness": witness}
                rev = _STATE.edges.get((node, h))
                if rev is not None:
                    _STATE.inversions.append({
                        "edge": f"{edge[0]} -> {edge[1]}",
                        "prior": f"{node} -> {h}",
                        "witness": {"thread": thread, "site": site},
                        "prior_witness": rev["witness"],
                    })
                    inversions += 1
            else:
                entry["count"] += 1
    held.append(node)
    if inversions:
        _bump_inversion_counter(inversions)


def _note_release(node: str) -> None:
    held = _held()
    for i in range(len(held) - 1, -1, -1):
        if held[i] == node:
            del held[i]
            return


def note_blocking(kind: str, bounded: bool) -> None:
    """A blocking primitive is about to run on this thread; record it
    when any tracked lock is held (the runtime shadow of PML019)."""
    if not _STATE.armed:
        return
    held = _held()
    if not held:
        return
    site = _caller_site()
    with _STATE.guard:
        _STATE.blocking.append({
            "kind": kind, "bounded": bool(bounded), "site": site,
            "locks": sorted(dict.fromkeys(held)),
            "thread": threading.current_thread().name,
        })


# ------------------------------------------------------------- the wrappers


class _TrackedLock:
    """A named, order-tracked wrapper over a real Lock. Condition can
    wrap one: it binds our acquire/release (we define none of the
    ``_release_save`` fast-path attrs), so waits keep tracking."""

    _reentrant = False

    def __init__(self, inner, node: str):
        self._inner = inner
        self._node = node

    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = self._inner.acquire(blocking, timeout)
        if got:
            _note_acquire(self._node)
        return got

    def release(self):
        _note_release(self._node)
        self._inner.release()

    def locked(self):
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"<lockdep {type(self).__name__} {self._node}>"


class _TrackedRLock(_TrackedLock):
    """RLock wrapper carrying Condition's fast-path protocol, so
    ``Condition(tracked_rlock).wait()`` releases/reacquires through the
    tracker instead of around it."""

    _reentrant = True

    def _release_save(self):
        _note_release(self._node)
        return self._inner._release_save()

    def _acquire_restore(self, state):
        self._inner._acquire_restore(state)
        _note_acquire(self._node)

    def _is_owned(self):
        return self._inner._is_owned()


def _node_from_frame(frame) -> Optional[str]:
    """The static-graph node id for a lock constructed at ``frame``, or
    None when the construction is outside the package (the caller then
    hands back a REAL lock — zero tracking tax on foreign code)."""
    if frame is None:
        return None
    mod = frame.f_globals.get("__name__", "")
    if not (mod == _PKG or mod.startswith(_PKG + ".")):
        return None
    line = linecache.getline(frame.f_code.co_filename, frame.f_lineno)
    m = _SELF_ASSIGN_RE.search(line)
    if m is not None:
        slf = frame.f_locals.get("self")
        if slf is not None:
            return f"{mod}.{type(slf).__name__}.{m.group(1)}"
        return None
    m = _NAME_ASSIGN_RE.match(line)
    if m is not None:
        return f"{mod}.{m.group(1)}"
    return None


def _register(node: str, type_leaf: str) -> None:
    with _STATE.guard:
        _STATE.nodes[node] = type_leaf


def _lock_factory():
    node = _node_from_frame(sys._getframe(1))
    real = _REAL["Lock"]()
    if node is None or not _STATE.armed:
        return real
    _register(node, "Lock")
    return _TrackedLock(real, node)


def _rlock_factory():
    node = _node_from_frame(sys._getframe(1))
    real = _REAL["RLock"]()
    if node is None or not _STATE.armed:
        return real
    _register(node, "RLock")
    return _TrackedRLock(real, node)


def _condition_factory(lock=None):
    if lock is not None:
        # Caller-supplied lock: if it came from a patched constructor
        # it is already tracked under its own name.
        return _REAL["Condition"](lock)
    node = _node_from_frame(sys._getframe(1))
    if node is None or not _STATE.armed:
        return _REAL["Condition"]()
    _register(node, "Condition")
    return _REAL["Condition"](_TrackedRLock(_REAL["RLock"](), node))


# ------------------------------------------------------- blocking patches


def _patch_blocking() -> None:
    import time as _time
    import urllib.request as _request
    from concurrent.futures import Future as _Future
    from subprocess import Popen as _Popen

    if _PATCHED_BLOCKING:
        return

    real_sleep = _time.sleep
    real_urlopen = _request.urlopen
    real_result = _Future.result
    real_wait = _Popen.wait

    def sleep(seconds):
        note_blocking("sleep", True)
        return real_sleep(seconds)

    def urlopen(*a, **kw):
        note_blocking("net", "timeout" in kw or len(a) > 2)
        return real_urlopen(*a, **kw)

    def result(self, timeout=None):
        note_blocking("result", timeout is not None)
        return real_result(self, timeout)

    def wait(self, timeout=None):
        note_blocking("wait", timeout is not None)
        return real_wait(self, timeout)

    _PATCHED_BLOCKING.update({
        (_time, "sleep"): real_sleep,
        (_request, "urlopen"): real_urlopen,
        (_Future, "result"): real_result,
        (_Popen, "wait"): real_wait,
    })
    _time.sleep = sleep
    _request.urlopen = urlopen
    _Future.result = result
    _Popen.wait = wait


def _unpatch_blocking() -> None:
    for (obj, name), orig in _PATCHED_BLOCKING.items():
        setattr(obj, name, orig)
    _PATCHED_BLOCKING.clear()


# --------------------------------------------------------------- lifecycle


def armed() -> bool:
    return _STATE.armed


def instrument(force: bool = False) -> bool:
    """Arm the validator. Patches the lock constructors and the
    blocking primitives; registers the exit dump. One env/flag check —
    ``PHOTON_LOCKDEP=1`` or ``force=True`` — or this is a no-op
    returning False with nothing touched."""
    if not force and os.environ.get("PHOTON_LOCKDEP") != "1":
        return False
    if _STATE.armed:
        return True
    _STATE.armed = True
    threading.Lock = _lock_factory
    threading.RLock = _rlock_factory
    threading.Condition = _condition_factory
    _patch_blocking()
    if not _STATE.dump_registered:
        _STATE.dump_registered = True
        atexit.register(_dump_at_exit)
    return True


def maybe_instrument() -> bool:
    """The conftest hook: arm iff ``PHOTON_LOCKDEP=1``."""
    return instrument(force=False)


def deactivate() -> None:
    """Disarm: restore the real constructors and blocking primitives.
    Locks already constructed keep their wrappers (harmless — they
    still delegate to real locks) but record nothing new."""
    if not _STATE.armed:
        return
    _STATE.armed = False
    threading.Lock = _REAL["Lock"]
    threading.RLock = _REAL["RLock"]
    threading.Condition = _REAL["Condition"]
    _unpatch_blocking()


def reset() -> None:
    """Drop every recorded fact (test isolation)."""
    with _STATE.guard:
        _STATE.nodes.clear()
        _STATE.edges.clear()
        _STATE.inversions.clear()
        _STATE.blocking.clear()


# ------------------------------------------------------------------ output


def snapshot() -> dict:
    """The current observation doc (the ``.photon-lockdep.json``
    schema; ``photon-lint --reconcile`` consumes it)."""
    with _STATE.guard:
        return {
            "version": 1,
            "nodes": [{"id": n, "type": _STATE.nodes[n]}
                      for n in sorted(_STATE.nodes)],
            "edges": [{"src": s, "dst": d,
                       "count": _STATE.edges[(s, d)]["count"],
                       "witness": _STATE.edges[(s, d)]["witness"]}
                      for s, d in sorted(_STATE.edges)],
            "inversions": list(_STATE.inversions),
            "blocking": list(_STATE.blocking),
        }


def _merge(into: dict, doc: dict) -> dict:
    nodes = {n["id"]: n["type"] for n in into.get("nodes", [])}
    nodes.update({n["id"]: n["type"] for n in doc.get("nodes", [])})
    edges: dict = {(e["src"], e["dst"]): e
                   for e in into.get("edges", [])}
    for e in doc.get("edges", []):
        key = (e["src"], e["dst"])
        if key in edges:
            edges[key]["count"] += e["count"]
        else:
            edges[key] = e
    return {
        "version": 1,
        "nodes": [{"id": n, "type": nodes[n]} for n in sorted(nodes)],
        "edges": [edges[k] for k in sorted(edges)],
        "inversions": (into.get("inversions", [])
                       + doc.get("inversions", [])),
        "blocking": (into.get("blocking", [])
                     + doc.get("blocking", [])),
    }


def dump(path: Optional[str] = None) -> dict:
    """Write the merged observation doc (existing file + this process)
    and return it."""
    path = path or os.environ.get("PHOTON_LOCKDEP_OUT", DEFAULT_OUT)
    doc = snapshot()
    try:
        with open(path) as fh:
            doc = _merge(json.load(fh), doc)
    except (OSError, ValueError):
        pass
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as fh:
        json.dump(doc, fh, indent=2, sort_keys=True)
    os.replace(tmp, path)
    return doc


def _dump_at_exit() -> None:
    try:
        if _STATE.nodes or _STATE.edges or _STATE.inversions \
                or _STATE.blocking:
            dump()
    # pml: allow[PML008] atexit hook: raising here would mask the
    # process's real exit status; a lost dump only costs one
    # reconciliation data point
    except Exception:
        pass
