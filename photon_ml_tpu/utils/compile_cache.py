"""Persistent XLA compilation cache.

The reference has no analog (JVM bytecode is its "compiled artifact"); on
TPU the expensive artifact is the XLA executable — tens of seconds per
program over a remote-compile tunnel. JAX's persistent compilation cache
serializes executables keyed by HLO hash, so every process after the first
(re-runs of a driver, the benchmark, CI shards) loads them in milliseconds.

Call :func:`enable_compilation_cache` before the first ``jit`` execution.
Opt out with PHOTON_TPU_NO_COMPILE_CACHE=1; override the location with
PHOTON_TPU_COMPILE_CACHE_DIR.
"""

from __future__ import annotations

import os

_DEFAULT_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), ".jax_cache")


def enable_compilation_cache(path: str | None = None) -> str | None:
    """Point JAX's persistent compilation cache at ``path`` (default: a
    ``.jax_cache`` directory beside the package, overridable via
    PHOTON_TPU_COMPILE_CACHE_DIR). Returns the directory, or None when
    disabled via PHOTON_TPU_NO_COMPILE_CACHE=1."""
    if os.environ.get("PHOTON_TPU_NO_COMPILE_CACHE") == "1":
        return None
    import jax

    configured = jax.config.jax_compilation_cache_dir
    if configured:
        # Respect an existing configuration (e.g. the test harness pins a
        # separate CPU cache dir before driver entry points run).
        return configured
    path = path or os.environ.get("PHOTON_TPU_COMPILE_CACHE_DIR",
                                  _DEFAULT_DIR)
    try:
        os.makedirs(path, exist_ok=True)
    except OSError:
        # The cache is an optional optimization; an unwritable location
        # (read-only install dir, locked-down container) must not stop
        # training.
        return None
    jax.config.update("jax_compilation_cache_dir", path)
    # Cache everything: even sub-second compiles add up across the many
    # per-bucket-shape programs a GAME fit builds.
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    return path
