"""Training lifecycle events: emitter + listener registry.

Reference parity: photon-lib ``event/`` (PhotonMLEvent hierarchy +
EventEmitter trait consumed by the drivers for audit logging and external
progress reporting). TPU-native shape: plain dataclass events dispatched
synchronously from the coordinate-descent loop and the estimator — there
is no executor fan-in to marshal, so a listener is just a callable.

Listeners must be cheap and non-failing; a raising listener is logged and
detached rather than killing training (the reference swallows listener
errors the same way).
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Callable, Optional

logger = logging.getLogger("photon_ml_tpu.events")


@dataclasses.dataclass(frozen=True)
class Event:
    """Base class for all training events (PhotonMLEvent parity)."""


@dataclasses.dataclass(frozen=True)
class TrainingStart(Event):
    task: str
    update_sequence: tuple
    iterations: int


@dataclasses.dataclass(frozen=True)
class CoordinateUpdate(Event):
    """One (iteration, coordinate) block update finished
    (PhotonOptimizationLogEvent parity)."""

    iteration: int
    coordinate: str
    train_seconds: float
    validation: Optional[dict] = None


@dataclasses.dataclass(frozen=True)
class TrainingFinish(Event):
    task: str
    total_updates: int


@dataclasses.dataclass(frozen=True)
class StagingStart(Event):
    """One random-effect staging pipeline starting: ``num_shards`` staged
    bucket groups over ``workers`` pool workers (``mode`` "thread" or
    "process"); ``cached_shards`` of them will come from the staging cache
    without restaging."""

    label: str  # "<re_type>:<shard_id>"
    num_shards: int
    workers: int
    mode: str
    cached_shards: int


@dataclasses.dataclass(frozen=True)
class StagingShard(Event):
    """One staged bucket group became available to the fit stream.
    ``source`` is "staged" (projected now) or "cache" (memory-mapped from
    the staging cache); ``seconds`` is the projection+gather time for
    staged shards (0.0 for cache hits)."""

    label: str
    index: int
    bucket: int
    entities: int
    seconds: float
    source: str


@dataclasses.dataclass(frozen=True)
class StagingRetry(Event):
    """One staged shard's task failed and is being retried (bounded,
    jittered backoff — docs/ROBUSTNESS.md). ``attempt`` is 1-based."""

    label: str
    index: int
    attempt: int
    error: str


@dataclasses.dataclass(frozen=True)
class StagingStraggler(Event):
    """One staged shard exceeded the straggler deadline and was
    re-staged serially; the late pool result is discarded (content is
    scheduling-independent, so either producer's bytes are THE bytes)."""

    label: str
    index: int
    waited_seconds: float


@dataclasses.dataclass(frozen=True)
class StreamStageStart(Event):
    """One streamed fixed-effect chunk-staging pass starting: the
    coordinate's SparseShard canonicalizes into ``num_chunks``
    hot-dense/cold-ELL chunks over ``workers`` staging threads
    (docs/STREAMING.md)."""

    shard_id: str
    num_rows: int
    chunk_rows: int
    num_chunks: int
    workers: int


@dataclasses.dataclass(frozen=True)
class StreamStageFinish(Event):
    """The chunk-staging pass ended (finally-guarded pair with
    StreamStageStart). ``num_chunks`` is 0 when staging raised before
    the layout was built."""

    shard_id: str
    num_chunks: int
    seconds: float


@dataclasses.dataclass(frozen=True)
class IngestStart(Event):
    """One Avro ingestion pipeline starting: ``num_chunks`` block-aligned
    decode tasks over ``num_files`` container files, fanned over
    ``workers`` pool workers (``mode`` "thread" or "process");
    ``cached_chunks`` of them load from the columnar ingest cache
    without touching Avro bytes (photon_ml_tpu/ingest)."""

    num_files: int
    num_chunks: int
    workers: int
    mode: str
    cached_chunks: int


@dataclasses.dataclass(frozen=True)
class IngestBlock(Event):
    """One decoded chunk (a sync-aligned run of Avro blocks) became
    available to the columnar fold. ``source`` is "decoded" (native
    block decode ran now) or "cache" (memory-mapped from the ingest
    cache); ``seconds`` is the decode time (0.0 for cache hits)."""

    index: int
    records: int
    seconds: float
    source: str


@dataclasses.dataclass(frozen=True)
class IngestFinish(Event):
    """Every chunk of one ingestion pipeline was consumed by the fold
    (or the pipeline was abandoned after ``num_chunks`` consumed chunks
    on error — the Start/Finish pair is finally-guarded)."""

    num_files: int
    num_chunks: int
    records: int
    cached_chunks: int
    wall_seconds: float


@dataclasses.dataclass(frozen=True)
class IngestFallback(Event):
    """Avro ingestion degraded to the pure-Python codec (~20x slower
    than the native block decoder per BENCH_r05) instead of the
    parallel native path; ``reason`` says why (no toolchain,
    unsupported schema, ...)."""

    reason: str


@dataclasses.dataclass(frozen=True)
class KernelFallback(Event):
    """A registered fused kernel (ops/kernels) degraded to its XLA
    fallback closure instead of the Pallas program the flag asked for —
    the kernel-registry analog of IngestFallback's loud-degradation
    discipline. ``kernel`` is the registry name, ``backend`` the backend
    the resolve actually landed on ("xla"), ``reason`` why (no TPU,
    injected kernel.launch fault, ...). The obs bridge turns this into
    ``photon_kernel_fallbacks_total{kernel=...}`` + a timeline instant;
    a silent fallback would let a flagged perf win quietly evaporate."""

    kernel: str
    backend: str
    reason: str


@dataclasses.dataclass(frozen=True)
class CheckpointRecovered(Event):
    """A corrupted checkpoint artifact failed its CRC and the manager
    fell back to the previous committed generation (game/checkpoint.py).
    ``done_steps`` is the step count of the RECOVERED state."""

    directory: str
    done_steps: int
    reason: str


@dataclasses.dataclass(frozen=True)
class BootRecovered(Event):
    """A generation store's CURRENT generation could not be trusted
    (blob CRC mismatch, torn/unparseable marker) and the boot path fell
    back one committed generation (boot/generations.py). Loud by
    contract: a replica that silently booted an older model would serve
    stale rows with no operator signal — the obs bridge turns this into
    a timeline instant + ``photon_boot_recoveries_total``."""

    directory: str
    from_version: int  # the generation that failed verification
    to_version: int  # the generation actually booted
    reason: str


@dataclasses.dataclass(frozen=True)
class WatchdogAlert(Event):
    """A convergence watchdog fired (obs/watchdog.py): ``kind`` names
    the detector (nan/stall/divergence/slow_iter), ``action`` what
    happened (warn/stop/raise). The obs bridge turns these into timeline
    instants + ``photon_watchdog_alerts_total{kind=...}``."""

    kind: str
    action: str
    detail: str
    coordinate: Optional[str] = None


@dataclasses.dataclass(frozen=True)
class StagingFinish(Event):
    """Every shard of one staging pipeline is produced (NOT necessarily
    consumed — consumption is the fit stream's side of the handoff)."""

    label: str
    num_shards: int
    cached_shards: int
    wall_seconds: float


@dataclasses.dataclass(frozen=True)
class ScoringStart(Event):
    """A scoring lifecycle begins — one offline driver run (``source=
    "game_score"``) or one online service coming up (``source="serving"``,
    ``num_rows`` None: the stream is unbounded)."""

    source: str
    num_rows: Optional[int] = None


@dataclasses.dataclass(frozen=True)
class ScoringBatch(Event):
    """One device scoring batch finished: ``rows`` real rows scored inside
    a ``padded_rows``-shaped program (shape-bucketing pads; padded_rows ==
    rows on the unbatched offline path)."""

    source: str
    rows: int
    padded_rows: int
    seconds: float


@dataclasses.dataclass(frozen=True)
class ScoringFinish(Event):
    source: str
    num_rows: int
    wall_seconds: float


@dataclasses.dataclass(frozen=True)
class ReplicaDied(Event):
    """A fleet scoring replica was declared dead (process exit or
    heartbeat-deadline expiry) — the `CheckpointRecovered` of the
    serving fleet's failure ladder (docs/SERVING.md "Scaling out")."""

    replica_id: int
    reason: str


@dataclasses.dataclass(frozen=True)
class ShardRehomed(Event):
    """A dead replica's routing shards were re-assigned to survivors
    (serving/router.py ShardMap). ``seconds`` is detection → the new
    owners confirmed healthy — the window `fleet_rehome_seconds`
    gates against the configured deadline."""

    replica_id: int
    shards: tuple[int, ...]
    new_owners: tuple[int, ...]
    seconds: float


@dataclasses.dataclass(frozen=True)
class ReplicaRecovered(Event):
    """A restarted replica answered /healthz and its home shards moved
    back; the fleet leaves the degraded state when every replica is
    healthy again."""

    replica_id: int
    shards_restored: tuple[int, ...]


@dataclasses.dataclass(frozen=True)
class ShardSplit(Event):
    """A hot shard split into two consistent-hash children
    (serving/router.py ShardMap.split + serving/elastic.py;
    docs/SERVING.md "Elastic fleet"). ``heat_fraction`` is the share of
    the window's total heat the parent carried when the controller
    ruled — the triggering evidence, also written to the ``elastic``
    ledger row."""

    shard: int
    children: tuple[int, int]
    heat_fraction: float
    map_version: int


@dataclasses.dataclass(frozen=True)
class ReplicaScaled(Event):
    """The elastic controller changed the fleet's replica count:
    ``direction`` "up" (spawned + warmed + admitted to the map) or
    "down" (drained → migrated empty → retired). ``reason`` names the
    triggering signal (error-budget burn, queue depth, heat
    imbalance, idle)."""

    direction: str
    replica_id: int
    num_replicas: int
    reason: str


@dataclasses.dataclass(frozen=True)
class FleetDegraded(Event):
    """The overload ladder changed state (docs/SERVING.md "Elastic
    fleet" brownout semantics): ``mode`` "brownout" = per-shard
    admission tightened on ``hot_shards`` (their 503s name the shard),
    "recovered" = the ladder released."""

    mode: str
    hot_shards: tuple[int, ...]
    reason: str


@dataclasses.dataclass(frozen=True)
class DeltaPublished(Event):
    """A versioned model delta finished the canary ladder and is live on
    EVERY replica (serving/publish.py + fleet.py; docs/SERVING.md
    "Continuous publication"). ``entities`` is the total dirty-row count
    across coordinates."""

    version: int
    coordinates: tuple[str, ...]
    entities: int
    canary_replica: int
    swap_seconds: float


@dataclasses.dataclass(frozen=True)
class CanaryVerdict(Event):
    """The canary judge ruled on one delta after its bake window:
    ``accepted`` False carries the rejection ``reason`` (the delta never
    reaches a non-canary replica; a RollbackExecuted follows when the
    canary had already applied it)."""

    version: int
    replica_id: int
    accepted: bool
    reason: str
    burn_rate: float


@dataclasses.dataclass(frozen=True)
class RollbackExecuted(Event):
    """A delta was backed out (canary rejection or a failed fleet-wide
    swap): every replica that applied ``version`` restored the previous
    rows. ``replicas`` lists who rolled back."""

    version: int
    reason: str
    replicas: tuple[int, ...]


class EventEmitter:
    """Synchronous listener registry (EventEmitter trait parity)."""

    def __init__(self):
        self._listeners: list[Callable[[Event], None]] = []

    def register(self, listener: Callable[[Event], None]) -> None:
        self._listeners.append(listener)

    def unregister(self, listener: Callable[[Event], None]) -> None:
        self._listeners.remove(listener)

    def emit(self, event: Event) -> None:
        for listener in list(self._listeners):
            try:
                listener(event)
            except Exception:
                logger.exception(
                    "event listener %r failed on %r — detaching it",
                    listener, event)
                if listener in self._listeners:  # may have self-unregistered
                    self._listeners.remove(listener)


# Process-wide default emitter: drivers and libraries emit here unless
# handed an explicit one (the reference's driver-scoped emitter analog).
default_emitter = EventEmitter()
