"""Shared worker-pool plumbing for the host-side data pipelines.

Both producer/consumer pipelines — random-effect staging
(game/staging.py) and block-parallel Avro ingestion (ingest/) — fan CPU
work over the same two pool shapes: a thread pool (the default; the
dominant kernels release the GIL — numpy sort/segment passes for
staging, the ctypes native-decode calls for ingestion) and a
spawn-context process pool for workloads where GIL-holding Python work
dominates. This module is the one implementation of that choice.

Spawn, not fork: the parent holds live XLA runtime threads, and forking
them is undefined; spawn re-imports cleanly. Per-worker context (big
read-only arrays, the active fault plan) ships once per worker through
the pool initializer instead of once per task; process-pool workers are
fresh interpreters, so the driver's fault plan rides the ctx and
injected worker crashes/kills happen in the worker process, exactly
where a real one would (photon_ml_tpu/faults).
"""

from __future__ import annotations

import concurrent.futures as cf
import contextvars

from photon_ml_tpu import faults as flt
from photon_ml_tpu import obs

# Per-process context installed by the pool initializer (empty in the
# driver process and in thread-mode workers, which share the driver's).
_WORKER_CTX: dict = {}


def worker_ctx() -> dict:
    """The per-process worker context (see ``init_worker``)."""
    return _WORKER_CTX


def init_worker(ctx: dict) -> None:
    """Process-pool initializer: install the shipped context and arm the
    driver's fault plan (and, when the driver is tracing, a spilling
    worker tracer) inside the fresh worker interpreter."""
    _WORKER_CTX.update(ctx)
    plan = ctx.get("fault_plan")
    if plan is not None:
        flt.install(plan, worker=True)
    trace_ctx = ctx.get("obs_trace")
    if trace_ctx is not None:
        obs.adopt_worker_context(trace_ctx)


class _PropagatingThreadPool(cf.ThreadPoolExecutor):
    """Thread pool whose tasks run under a COPY of the submitter's
    contextvars — worker-side spans (staging shards, ingest chunks,
    stream staging) parent under the driver span that submitted them
    instead of floating at the trace root."""

    def submit(self, fn, /, *args, **kwargs):
        ctx = contextvars.copy_context()
        return super().submit(ctx.run, fn, *args, **kwargs)


def make_pool(mode: str, workers: int, ctx: dict,
              thread_name_prefix: str = "pml-worker"):
    """A thread or spawn-process executor with ``ctx`` installed in every
    process-mode worker (thread-mode workers see the driver's state
    directly and need no initializer). Both shapes propagate the active
    trace context: threads via contextvars, processes via the shipped
    ctx + the tracer's spill file (docs/OBSERVABILITY.md)."""
    if mode == "process":
        import multiprocessing as mp

        trace_ctx = obs.worker_context()
        if trace_ctx is not None:
            ctx = {**ctx, "obs_trace": trace_ctx}
        return cf.ProcessPoolExecutor(
            max_workers=workers, mp_context=mp.get_context("spawn"),
            initializer=init_worker, initargs=(ctx,))
    return _PropagatingThreadPool(max_workers=workers,
                                  thread_name_prefix=thread_name_prefix)
