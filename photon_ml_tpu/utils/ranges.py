"""Numeric ranges for hyperparameter search.

Reference parity: photon-lib ``util/DoubleRange.scala`` — an inclusive
[start, end] interval with linear/log transforms used to describe
hyperparameter search spaces.
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass(frozen=True)
class DoubleRange:
    """Inclusive [start, end] interval (reference: DoubleRange)."""

    start: float
    end: float

    def __post_init__(self):
        if self.start > self.end:
            raise ValueError(
                f"invalid range: start {self.start} > end {self.end}")

    def transform(self, fn) -> "DoubleRange":
        return DoubleRange(fn(self.start), fn(self.end))

    @property
    def length(self) -> float:
        return self.end - self.start

    def contains(self, x: float) -> bool:
        return self.start <= x <= self.end

    def clip(self, x):
        return np.clip(x, self.start, self.end)

    def denormalize(self, u):
        """Map u in [0,1] onto this range linearly."""
        return self.start + u * self.length

    def normalize(self, x):
        """Inverse of :meth:`denormalize` (constant ranges map to 0)."""
        if self.length == 0:
            return np.zeros_like(np.asarray(x, dtype=np.float64))
        return (x - self.start) / self.length
