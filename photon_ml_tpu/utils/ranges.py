"""Numeric ranges for hyperparameter search.

Reference parity: photon-lib ``util/DoubleRange.scala`` — an inclusive
[start, end] interval with linear/log transforms used to describe
hyperparameter search spaces.
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass(frozen=True)
class DoubleRange:
    """Inclusive [start, end] interval (reference: DoubleRange)."""

    start: float
    end: float

    def __post_init__(self):
        if self.start > self.end:
            raise ValueError(
                f"invalid range: start {self.start} > end {self.end}")

    def transform(self, fn) -> "DoubleRange":
        return DoubleRange(fn(self.start), fn(self.end))

    @property
    def length(self) -> float:
        return self.end - self.start

    def contains(self, x: float) -> bool:
        return self.start <= x <= self.end

    def clip(self, x):
        return np.clip(x, self.start, self.end)

    def denormalize(self, u):
        """Map u in [0,1] onto this range linearly."""
        return self.start + u * self.length

    def normalize(self, x):
        """Inverse of :meth:`denormalize` (constant ranges map to 0)."""
        if self.length == 0:
            return np.zeros_like(np.asarray(x, dtype=np.float64))
        return (x - self.start) / self.length


@dataclasses.dataclass(frozen=True)
class DateRange:
    """Inclusive [start, end] calendar-day interval.

    Reference parity: photon-client ``util/DateRange.scala`` +
    ``util/IOUtils.getInputPathsWithinDateRange`` — training inputs are laid
    out as daily partitions (``.../daily/2016/01/15/``) and a job selects
    the directories inside a date range. ``parse`` accepts the reference's
    ``yyyyMMdd-yyyyMMdd`` form and ISO ``yyyy-mm-dd:yyyy-mm-dd``.
    """

    start: "datetime.date"
    end: "datetime.date"

    def __post_init__(self):
        if self.start > self.end:
            raise ValueError(
                f"invalid date range: start {self.start} > end {self.end}")

    @staticmethod
    def parse(spec: str) -> "DateRange":
        import datetime as dt

        for sep in ("-", ":"):
            if sep in spec:
                a, _, b = spec.partition(sep)
                if sep == "-" and (len(a) != 8 or not a.isdigit()):
                    continue  # ISO dashes inside the dates themselves
                return DateRange(_parse_date(a), _parse_date(b))
        raise ValueError(f"cannot parse date range {spec!r} "
                         f"(want yyyyMMdd-yyyyMMdd or ISO a:b)")

    def days(self):
        import datetime as dt

        d = self.start
        while d <= self.end:
            yield d
            d += dt.timedelta(days=1)

    def contains(self, day) -> bool:
        return self.start <= day <= self.end


def _parse_date(s: str):
    import datetime as dt

    s = s.strip()
    if len(s) == 8 and s.isdigit():
        return dt.date(int(s[:4]), int(s[4:6]), int(s[6:8]))
    return dt.date.fromisoformat(s)


def input_paths_within_date_range(root: str, date_range: DateRange,
                                  errors_on_missing: bool = False):
    """Daily-partitioned input discovery (IOUtils parity): returns the
    existing ``<root>/yyyy/mm/dd`` directories inside the range, in date
    order. With ``errors_on_missing`` an absent day raises instead of
    being skipped (the reference's strict mode)."""
    import os

    out = []
    for day in date_range.days():
        p = os.path.join(root, f"{day.year:04d}", f"{day.month:02d}",
                         f"{day.day:02d}")
        if os.path.isdir(p):
            out.append(p)
        elif errors_on_missing:
            raise FileNotFoundError(f"no input partition for {day}: {p}")
    return out
