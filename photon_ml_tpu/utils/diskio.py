"""Atomic file writes + content checksums for on-disk caches.

The crash/corruption discipline every persistent artifact in this repo
follows (staging cache, ingest cache, checkpoints — docs/ROBUSTNESS.md):
writes go through a temp file + ``os.replace`` so a reader never sees a
half-written file, and commit markers carry each artifact's CRC32 so
silent corruption (bit rot, a torn page, an injected fault) degrades to
a per-artifact miss instead of silently wrong bytes.
"""

from __future__ import annotations

import os
import tempfile
import zlib


def file_crc32(path: str) -> int:
    """CRC32 of a file's bytes (chunked; the integrity check of cache
    shards/chunks and checkpoint artifacts)."""
    crc = 0
    with open(path, "rb") as f:
        while chunk := f.read(1 << 20):
            crc = zlib.crc32(chunk, crc)
    return crc & 0xFFFFFFFF


def atomic_write(path: str, write_fn) -> None:
    """Write via a temp file + os.replace (atomic on one filesystem)."""
    d = os.path.dirname(path)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".tmp.")
    try:
        with os.fdopen(fd, "wb") as f:
            write_fn(f)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
