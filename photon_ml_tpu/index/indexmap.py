"""Feature index maps: (name, term) <-> column index.

Reference parity: photon-lib ``index/IndexMap.scala`` /
``DefaultIndexMap.scala`` / ``PalDBIndexMap.scala`` and the loaders in
photon-client ``index/``. The reference stores huge maps in PalDB (read-only
off-heap key-value store); the native analogue here is
``photon_ml_tpu.index.native_store`` (C++ mmap'd open-addressing table) with
:class:`NativeIndexMap` as its loader-facing wrapper.
"""

from __future__ import annotations

import json
import os
from typing import Iterable, Iterator, Optional

INTERCEPT_KEY = "(INTERCEPT)"  # Constants.INTERCEPT_KEY parity
_SEP = "\x01"  # name/term separator, matches reference's delimiter idea


def feature_key(name: str, term: str = "") -> str:
    """Canonical string key for a (name, term) feature."""
    return name if not term else f"{name}{_SEP}{term}"


def split_key(key: str) -> tuple[str, str]:
    name, _, term = key.partition(_SEP)
    return name, term


class IndexMap:
    """Read API shared by all index maps (IndexMap.scala parity)."""

    def get_index(self, key: str) -> int:
        """Column index for a feature key, or -1 if absent."""
        raise NotImplementedError

    def get_feature_name(self, index: int) -> Optional[str]:
        raise NotImplementedError

    def __len__(self) -> int:
        raise NotImplementedError

    def __contains__(self, key: str) -> bool:
        return self.get_index(key) >= 0


class DefaultIndexMap(IndexMap):
    """In-memory dict-backed index map (DefaultIndexMap.scala parity)."""

    def __init__(self, key_to_index: dict[str, int]):
        self._fwd = dict(key_to_index)
        self._rev = {i: k for k, i in self._fwd.items()}

    @classmethod
    def from_keys(cls, keys: Iterable[str],
                  add_intercept: bool = False) -> "DefaultIndexMap":
        uniq = sorted(set(keys))
        if add_intercept and INTERCEPT_KEY not in uniq:
            uniq.append(INTERCEPT_KEY)
        return cls({k: i for i, k in enumerate(uniq)})

    def get_index(self, key: str) -> int:
        return self._fwd.get(key, -1)

    def get_feature_name(self, index: int) -> Optional[str]:
        return self._rev.get(index)

    def __len__(self) -> int:
        return len(self._fwd)

    def __iter__(self) -> Iterator[tuple[str, int]]:
        return iter(self._fwd.items())

    def save(self, path: str) -> None:
        """JSON sidecar persistence for small/medium maps."""
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with open(path, "w") as fh:
            json.dump(self._fwd, fh)

    @classmethod
    def load(cls, path: str) -> "DefaultIndexMap":
        with open(path) as fh:
            return cls(json.load(fh))


def load_index_map(path: str) -> IndexMap:
    """Open an index map by file type: ``.json`` dict or ``.pidx`` native
    store (PalDBIndexMapLoader parity — one loader call works for both)."""
    if path.endswith(".pidx"):
        from photon_ml_tpu.index.native_store import NativeIndexMap
        return NativeIndexMap(path)
    return DefaultIndexMap.load(path)
