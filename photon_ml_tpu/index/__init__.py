"""Feature index maps (reference: photon-lib/client ``index/``)."""

from photon_ml_tpu.index.indexmap import (DefaultIndexMap, INTERCEPT_KEY,
                                          IndexMap, feature_key,
                                          load_index_map, split_key)

__all__ = [
    "DefaultIndexMap",
    "INTERCEPT_KEY",
    "IndexMap",
    "feature_key",
    "load_index_map",
    "split_key",
]
