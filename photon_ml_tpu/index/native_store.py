"""Native mmap'd read-only index store (PalDB analogue).

Reference parity: photon-lib ``index/PalDBIndexMap.scala`` + photon-client
``index/PalDBIndexMapLoader.scala`` — a read-only key-value store holding
feature maps too large for in-process dicts, built offline by the feature
indexing driver and opened (cheaply, shared) by every worker.

Here: ``build_store`` writes the ``.pidx`` format from Python;
:class:`NativeIndexMap` serves lookups through the C++ mmap reader
(``photon_ml_tpu/native/pidx.cc``) via ctypes, falling back to a pure-Python
mmap reader when no C++ toolchain is available. Both readers share the same
on-disk format, documented in pidx.cc.
"""

from __future__ import annotations

import ctypes
import logging
import mmap
import os
import struct
from typing import Iterable, Optional

from photon_ml_tpu.index.indexmap import IndexMap

_MAGIC = b"PIDXv01\x00"
_HEADER = struct.Struct("<8sQQQQQQ")  # magic n slots table ridx blob blobsz
_SLOT = struct.Struct("<QQII")  # hash key_off key_len index_plus1
_RIDX = struct.Struct("<QII")  # key_off key_len pad

_FNV_OFFSET = 1469598103934665603
_FNV_PRIME = 1099511628211
_MASK64 = (1 << 64) - 1


def _fnv1a(data: bytes) -> int:
    h = _FNV_OFFSET
    for b in data:
        h = ((h ^ b) * _FNV_PRIME) & _MASK64
    return h


def build_store(keys: Iterable[str], path: str,
                load_factor: float = 0.7) -> None:
    """Write a ``.pidx`` store mapping each key to its position in ``keys``.

    Keys must be unique; their iteration order defines the column indices
    (mirrors the indexing driver's partition-range assignment).
    """
    encoded = [k.encode("utf-8") for k in keys]
    n = len(encoded)
    if len(set(encoded)) != n:
        raise ValueError("duplicate keys in index store")
    slots = 1
    while slots < max(1, int(n / load_factor)):
        slots *= 2

    blob = bytearray()
    ridx = bytearray()
    offsets = []
    for kb in encoded:
        offsets.append(len(blob))
        ridx += _RIDX.pack(len(blob), len(kb), 0)
        blob += kb

    table = bytearray(_SLOT.size * slots)
    occupied = [False] * slots
    for idx, kb in enumerate(encoded):
        h = _fnv1a(kb)
        i = h & (slots - 1)
        while occupied[i]:
            i = (i + 1) & (slots - 1)
        occupied[i] = True
        _SLOT.pack_into(table, i * _SLOT.size, h, offsets[idx], len(kb),
                        idx + 1)

    table_off = _HEADER.size
    ridx_off = table_off + len(table)
    blob_off = ridx_off + len(ridx)
    header = _HEADER.pack(_MAGIC, n, slots, table_off, ridx_off, blob_off,
                          len(blob))
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "wb") as fh:
        fh.write(header)
        fh.write(table)
        fh.write(ridx)
        fh.write(blob)
    os.replace(tmp, path)


class _CppReader:
    """ctypes wrapper over the mmap'd C++ reader."""

    def __init__(self, path: str):
        from photon_ml_tpu.native import build_library

        lib = ctypes.CDLL(build_library("pidx"))
        lib.pidx_open.restype = ctypes.c_void_p
        lib.pidx_open.argtypes = [ctypes.c_char_p]
        lib.pidx_close.argtypes = [ctypes.c_void_p]
        lib.pidx_size.restype = ctypes.c_int64
        lib.pidx_size.argtypes = [ctypes.c_void_p]
        lib.pidx_get.restype = ctypes.c_int64
        lib.pidx_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                 ctypes.c_uint64]
        lib.pidx_name.restype = ctypes.c_int64
        lib.pidx_name.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                  ctypes.c_char_p, ctypes.c_uint64]
        handle = lib.pidx_open(path.encode())
        if not handle:
            raise OSError(f"pidx_open failed for {path}")
        self._lib = lib
        self._handle = handle
        self.size = int(lib.pidx_size(handle))

    def get(self, key: bytes) -> int:
        return int(self._lib.pidx_get(self._handle, key, len(key)))

    def name(self, index: int) -> Optional[bytes]:
        buf = ctypes.create_string_buffer(256)
        got = self._lib.pidx_name(self._handle, index, buf, 256)
        if got < 0:
            return None
        if got <= 256:
            return buf.raw[:got]
        big = ctypes.create_string_buffer(got)
        self._lib.pidx_name(self._handle, index, big, got)
        return big.raw[:got]

    def close(self) -> None:
        if self._handle:
            self._lib.pidx_close(self._handle)
            self._handle = None


class _PyReader:
    """Pure-Python mmap reader of the same format (toolchain-free hosts)."""

    def __init__(self, path: str):
        self._fh = open(path, "rb")
        self._mm = mmap.mmap(self._fh.fileno(), 0, access=mmap.ACCESS_READ)
        (magic, self.size, self._slots, self._table_off, self._ridx_off,
         self._blob_off, _) = _HEADER.unpack_from(self._mm, 0)
        if magic != _MAGIC:
            raise ValueError(f"{path}: bad magic")

    def get(self, key: bytes) -> int:
        if self._slots == 0:
            return -1
        h = _fnv1a(key)
        i = h & (self._slots - 1)
        while True:
            sh, off, klen, idx1 = _SLOT.unpack_from(
                self._mm, self._table_off + i * _SLOT.size)
            if idx1 == 0:
                return -1
            if sh == h and klen == len(key):
                start = self._blob_off + off
                if self._mm[start:start + klen] == key:
                    return idx1 - 1
            i = (i + 1) & (self._slots - 1)

    def name(self, index: int) -> Optional[bytes]:
        if not 0 <= index < self.size:
            return None
        off, klen, _ = _RIDX.unpack_from(
            self._mm, self._ridx_off + index * _RIDX.size)
        start = self._blob_off + off
        return self._mm[start:start + klen]

    def close(self) -> None:
        self._mm.close()
        self._fh.close()


class NativeIndexMap(IndexMap):
    """IndexMap served from a ``.pidx`` store (PalDBIndexMap parity)."""

    def __init__(self, path: str, force_python: bool = False):
        self.path = path
        if force_python:
            self._reader = _PyReader(path)
        else:
            try:
                self._reader = _CppReader(path)
            except Exception:  # no g++ / load failure → same format, Python
                logging.getLogger("photon_ml_tpu.index").debug(
                    "native .pidx reader unavailable for %s — using the "
                    "Python reader", path, exc_info=True)
                self._reader = _PyReader(path)

    def get_index(self, key: str) -> int:
        return self._reader.get(key.encode("utf-8"))

    def get_feature_name(self, index: int) -> Optional[str]:
        raw = self._reader.name(index)
        return None if raw is None else raw.decode("utf-8")

    def __len__(self) -> int:
        return self._reader.size

    def close(self) -> None:
        self._reader.close()
