"""photon_ml_tpu — a TPU-native framework with the capabilities of Photon-ML.

A from-scratch JAX/XLA rebuild of the capability surface of
LinkedIn's Photon-ML (reference: ``photon-lib``, ``photon-api``,
``photon-client`` Scala modules): fixed-effect GLMs (logistic, linear,
Poisson, smoothed-hinge SVM) with L1/L2/elastic-net regularization trained
by L-BFGS / OWL-QN / TRON, and GAME (Generalized Additive Mixed Effects)
models fit by block coordinate descent — re-designed TPU-first:

- gradient/Hessian reductions are XLA collectives (``psum`` over a device
  mesh) instead of Spark ``RDD.treeAggregate``
  (reference: photon-api ``function/glm/DistributedGLMLossFunction.scala``);
- optimizers are jit-compiled ``lax.while_loop`` state machines over pytrees
  instead of Breeze wrappers (reference: photon-lib ``optimization/``);
- per-entity random-effect solves are ``vmap``-batched and sharded over the
  mesh instead of an ``RDD[(REId, LocalDataset)].mapValues`` loop
  (reference: photon-api ``algorithm/RandomEffectCoordinate.scala``).

Layer map (mirrors SURVEY.md §1, re-architected):

- ``ops/``        pointwise losses + fused batch aggregations (the hot loops)
- ``models/``     Coefficients pytree, GLM model classes, GAME models
- ``optim/``      L-BFGS, OWL-QN, TRON, regularization, state tracking
- ``parallel/``   mesh conventions + distributed objectives (the "comm backend")
- ``data/``       LIBSVM/Avro ingestion, GameData columnar batches, bucketing
- ``ingest/``     block-parallel Avro decode pipeline + columnar mmap cache
- ``evaluation/`` AUC/RMSE/Poisson/precision@k + grouped (per-entity) metrics
- ``game/``       coordinates + coordinate descent + scoring
- ``api/``        GameEstimator / GameTransformer front doors
- ``cli/``        training / scoring / feature-indexing drivers
"""

__version__ = "0.1.0"

__all__ = ["TaskType", "__version__"]


def __getattr__(name):
    # Lazy: importing the bare package must not pull in JAX — the
    # photon-lint gate (analysis/ + cli/lint.py) is pure stdlib and runs
    # where no accelerator stack exists. ``from photon_ml_tpu import
    # TaskType`` still works through this hook.
    if name == "TaskType":
        from photon_ml_tpu.types import TaskType
        return TaskType
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
