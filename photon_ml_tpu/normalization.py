"""Feature normalization applied in-kernel — data is never rewritten.

Reference parity: photon-lib ``normalization/NormalizationContext.scala`` and
``NormalizationType.scala`` (NONE, SCALE_WITH_STANDARD_DEVIATION,
SCALE_WITH_MAX_MAGNITUDE, STANDARDIZATION). The reference's key trick is
preserved: the raw data is untouched; scale factors and shifts are folded
into margin/gradient computation, the model is trained in the transformed
space, and coefficients are mapped back to the original space on output.

TPU-first design: normalization is two broadcasted vectors folded into the
fused margin kernel:

    margin(x) = (w ∘ f)·x − (w ∘ f)·s  ( + offset )

so the transformed-space margin w·((x − s) ∘ f) costs one elementwise
multiply that XLA fuses into the matmul. The gradient pullback is the same
algebra transposed:  ∇_w = f ∘ (Xᵀ r) − (Σ r)(f ∘ s).
"""

from __future__ import annotations

import dataclasses
import enum
import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array


class NormalizationType(enum.Enum):
    NONE = "NONE"
    SCALE_WITH_STANDARD_DEVIATION = "SCALE_WITH_STANDARD_DEVIATION"
    SCALE_WITH_MAX_MAGNITUDE = "SCALE_WITH_MAX_MAGNITUDE"
    STANDARDIZATION = "STANDARDIZATION"


@functools.partial(jax.tree_util.register_dataclass,
                   data_fields=("factors", "shifts"),
                   meta_fields=("intercept_index",))
@dataclasses.dataclass(frozen=True)
class NormalizationContext:
    """Per-feature scale ``factors`` and ``shifts`` (both optional).

    Transformed feature: x' = (x − shifts) ∘ factors. The intercept column
    (if any) must have factor 1 and shift 0 — enforced by the builders, and
    its position recorded in ``intercept_index`` (static metadata) so the
    shift mass can be folded back exactly on model export.
    """

    factors: Optional[Array] = None
    shifts: Optional[Array] = None
    intercept_index: Optional[int] = None

    @property
    def is_identity(self) -> bool:
        return self.factors is None and self.shifts is None

    # -- margin-space helpers (used by objectives) ---------------------------

    def effective_coefficients(self, means: Array) -> tuple[Array, Array]:
        """Return (w_eff, margin_shift) with margin = X @ w_eff + margin_shift.

        ``w_eff = w ∘ f`` and ``margin_shift = −(w ∘ f)·s`` so that
        ``X @ w_eff + margin_shift == ((X − s) ∘ f) @ w`` without rewriting X.
        """
        w_eff = means if self.factors is None else means * self.factors
        if self.shifts is None:
            shift = jnp.zeros(means.shape[:-1], dtype=means.dtype)
        else:
            shift = -jnp.sum(w_eff * self.shifts, axis=-1)
        return w_eff, shift

    def pullback_gradient(self, xtr: Array, r_sum: Array) -> Array:
        """Map a raw-space gradient accumulation to transformed space.

        Given ``xtr = Xᵀ r`` (raw features) and ``r_sum = Σ r``, the gradient
        w.r.t. transformed-space coefficients is ``f ∘ xtr − r_sum (f ∘ s)``.
        """
        g = xtr if self.factors is None else xtr * self.factors
        if self.shifts is not None:
            s_eff = self.shifts if self.factors is None else self.shifts * self.factors
            g = g - jnp.expand_dims(r_sum, -1) * s_eff
        return g

    # -- model-space transforms (reference: modelToTransformedSpace etc.) ----

    def model_to_original_space(self, means: Array) -> Array:
        """Coefficients trained on x' → coefficients applying to raw x.

        w_orig = w ∘ f with the total shift −(w ∘ f)·s folded into the
        intercept. Requires an intercept if shifts are present; the builders
        guarantee the intercept column has f=1, s=0.
        """
        w = means if self.factors is None else means * self.factors
        if self.shifts is not None:
            if self.intercept_index is None:
                raise ValueError("shifts present but intercept_index unknown")
            # Shift mass goes to the intercept column (factor 1, shift 0).
            adjust = -jnp.sum(w * self.shifts, axis=-1)
            w = w.at[..., self.intercept_index].add(adjust)
        return w

    def variances_to_original_space(self, variances: Array) -> Array:
        """Diagonal-approximation variance transform matching
        ``model_to_original_space``: w_orig = w ∘ f with shift mass folded
        into the intercept, so Var(w_orig_j) = f_j² Var(w_j) and
        Var(w0_orig) = Var(w0) + Σ_j (f_j s_j)² Var(w_j) (treating
        coefficients as independent — the same approximation SIMPLE variance
        mode already makes; the intercept's own shift is 0 so it is not
        double-counted)."""
        v = variances if self.factors is None \
            else variances * self.factors * self.factors
        if self.shifts is not None:
            if self.intercept_index is None:
                raise ValueError("shifts present but intercept_index unknown")
            f = 1.0 if self.factors is None else self.factors
            shift_mass = jnp.sum((f * self.shifts) ** 2 * variances, axis=-1)
            v = v.at[..., self.intercept_index].add(shift_mass)
        return v

    def model_to_transformed_space(self, means: Array) -> Array:
        """Inverse of ``model_to_original_space`` (for warm starts)."""
        if self.shifts is not None:
            if self.intercept_index is None:
                raise ValueError("shifts present but intercept_index unknown")
            shift_mass = jnp.sum(means * self.shifts, axis=-1)
            means = means.at[..., self.intercept_index].add(shift_mass)
        if self.factors is not None:
            means = means / self.factors
        return means


def build_normalization(
    norm_type: NormalizationType,
    *,
    means: Optional[np.ndarray] = None,
    variances: Optional[np.ndarray] = None,
    max_magnitudes: Optional[np.ndarray] = None,
    intercept_index: Optional[int] = None,
    dtype=jnp.float32,
) -> NormalizationContext:
    """Build a NormalizationContext from summary statistics.

    Reference parity: ``NormalizationContext.apply(normalizationType,
    summary, interceptIdOpt)``. Features with zero variance / zero max
    magnitude get factor 1 (reference behavior: avoid division by zero).
    """
    norm_type = NormalizationType(norm_type)
    if norm_type == NormalizationType.NONE:
        return NormalizationContext()

    def _safe_inv(x: np.ndarray) -> np.ndarray:
        x = np.asarray(x, dtype=np.float64)
        return np.where(x > 0.0, 1.0 / np.maximum(x, 1e-300), 1.0)

    factors: Optional[np.ndarray]
    shifts: Optional[np.ndarray] = None
    if norm_type == NormalizationType.SCALE_WITH_STANDARD_DEVIATION:
        if variances is None:
            raise ValueError("SCALE_WITH_STANDARD_DEVIATION requires variances")
        factors = _safe_inv(np.sqrt(np.asarray(variances)))
    elif norm_type == NormalizationType.SCALE_WITH_MAX_MAGNITUDE:
        if max_magnitudes is None:
            raise ValueError("SCALE_WITH_MAX_MAGNITUDE requires max_magnitudes")
        factors = _safe_inv(np.abs(np.asarray(max_magnitudes)))
    elif norm_type == NormalizationType.STANDARDIZATION:
        if variances is None or means is None:
            raise ValueError("STANDARDIZATION requires means and variances")
        if intercept_index is None:
            raise ValueError(
                "STANDARDIZATION requires an intercept column (reference "
                "requires addIntercept=true when shifts are used)")
        factors = _safe_inv(np.sqrt(np.asarray(variances)))
        shifts = np.asarray(means, dtype=np.float64).copy()
    else:  # pragma: no cover
        raise ValueError(norm_type)

    if intercept_index is not None:
        factors[intercept_index] = 1.0
        if shifts is not None:
            shifts[intercept_index] = 0.0

    return NormalizationContext(
        factors=jnp.asarray(factors, dtype=dtype),
        shifts=None if shifts is None else jnp.asarray(shifts, dtype=dtype),
        intercept_index=intercept_index,
    )
