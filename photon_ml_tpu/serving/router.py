"""Entity-affinity routing: shard → replica assignment + HTTP forwarding.

The single-process ``HashShardedStore`` shards the random-effect host
store by ``entity_id % num_shards`` and its docstring promised the
multi-host reading: "shard s would live on host s". The fleet instates
that layout one level up: the SAME modulo now picks a **routing shard**,
and a shard→replica table assigns each shard to the replica that serves
it. Every request for an entity therefore lands on one replica — its
device LRU stays hot on exactly its shard's entities (the Snap ML
hierarchical-sharding observation: partition per-entity state, keep each
worker's local cache hot).

Replicas hold the FULL host store (host DRAM is the cheap tier; device
HBM is the scarce one the affinity exists for), so any replica *can*
serve any entity bit-identically — affinity is a performance contract,
ownership is a routing-table entry. That is what makes recovery cheap:
when a replica dies, its shards **re-home** to survivors by table swap,
the survivors serve them from their own host stores (cold device cache,
same scores), and when the replica returns its shards come home.

Failure handling per forward (docs/ROBUSTNESS.md failure ladder):

- **bounded retry with deterministic backoff** on connection errors and
  timeouts — re-resolving the owner each attempt, so a retry lands on
  the NEW owner once the supervisor re-homed a dead replica's shards;
- **hedged second-send**: a primary slower than ``hedge_after_s`` gets a
  duplicate sent to the next healthy replica; first response wins, the
  loser is discarded under a winner lock (exactly-one response — safe
  because scoring is pure), ``hedge_wins_total`` counts upsets;
- a replica's 503 (its own admission control) is FINAL — retrying an
  overloaded replica amplifies the overload; the fleet translates it to
  a fleet 503 carrying the replica id and fleet depth.

Every blocking HTTP call carries an explicit timeout (PML011).
"""

from __future__ import annotations

import collections
import itertools
import json
import logging
import threading
import time
import urllib.error
import urllib.request
import zlib
from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor, wait
from typing import Callable, Optional, Sequence

from photon_ml_tpu import faults as flt

logger = logging.getLogger("photon_ml_tpu.serving.fleet")


class ReplicaUnavailable(RuntimeError):
    """The forward retry budget is exhausted and no replica answered;
    the request was possibly scored but never acknowledged — callers
    get a defined 503, never a silent wrong answer."""

    def __init__(self, message: str, replica_id: Optional[int] = None):
        super().__init__(message)
        self.replica_id = replica_id


class ReplicaShed(RuntimeError):
    """A replica's own admission control shed the forwarded batch
    (HTTP 503). Final: shedding is the replica telling us to back off.
    Carries the replica id and its reported queue depth for the fleet
    503 body."""

    def __init__(self, message: str, replica_id: int,
                 queue_depth: Optional[int] = None):
        super().__init__(message)
        self.replica_id = replica_id
        self.queue_depth = queue_depth


class ReplicaHTTPError(RuntimeError):
    """A replica answered with a non-retryable error status (400/500
    class): the forward reached a live replica and FAILED there, so
    retrying elsewhere cannot help (same model, same code)."""

    def __init__(self, message: str, replica_id: int, status: int):
        super().__init__(message)
        self.replica_id = replica_id
        self.status = status


def route_key(value) -> int:
    """A request's entity id → stable non-negative routing key.

    Integer ids (the NPZ vocabulary-row contract) route by value so the
    router's modulo matches the host store's shard modulo exactly; raw
    string keys (the Avro entity-vocabs contract) hash via crc32 —
    stable across processes and runs (Python's ``hash`` is salted per
    process and would scatter a user's requests across replicas on
    every restart, defeating affinity).
    """
    if isinstance(value, bool) or value is None:
        return 0
    if isinstance(value, int):
        return abs(int(value))
    return zlib.crc32(str(value).encode())


class ShardMap:
    """The VERSIONED shard → replica assignment table (thread-safe).

    ``home(shard) = shard % num_replicas`` is the balanced layout;
    ``mark_down`` re-homes a dead replica's shards to the surviving
    replicas round-robin (deterministic — a drill replays identically),
    and ``restore`` sends a recovered replica's home shards back. The
    table is tiny and swapped under one lock: re-homing is O(shards),
    never O(entities) — the host stores already hold every row.

    photon-elastic extended the table from the static ``key %
    num_shards`` layout to a consistent-hash-style split trie: a hot
    shard ``s`` (residue ``s`` under modulus ``m``, base ``m =
    num_shards``) SPLITS into children ``s`` and ``s + m`` under modulus
    ``2m`` — entities of every OTHER shard keep their residue, so a
    split never remaps a cold entity (the consistent-hash property the
    ISSUE requires). Shard ids stay plain ints: a leaf's residue is
    globally unique because its integer encodes the base shard (low
    bits) plus its split path (high bits). ``shard_of_key`` descends
    the trie; with no splits it is exactly ``key % num_shards``.

    Every mutation (split, migrate, re-home, restore, add/remove
    replica, drain) bumps ``version`` under the one lock — readers see
    the OLD table or the NEW one, never a torn mix, which is what makes
    a kill mid-split recoverable by construction (docs/ROBUSTNESS.md).
    """

    def __init__(self, num_shards: int, num_replicas: int):
        if num_shards < num_replicas:
            raise ValueError(
                f"num_shards ({num_shards}) must be >= num_replicas "
                f"({num_replicas}) or some replicas would own nothing")
        self.num_shards = int(num_shards)
        self.num_replicas = int(num_replicas)
        self._lock = threading.Lock()
        # Leaves: residue → owning replica / residue → modulus; interior
        # (split) nodes are (residue, modulus) pairs the routing loop
        # descends through.
        self._owner = {s: s % num_replicas for s in range(num_shards)}
        self._modulus = {s: int(num_shards) for s in range(num_shards)}
        self._interior: set[tuple[int, int]] = set()
        self._up = set(range(num_replicas))
        self._draining: set[int] = set()
        self.version = 1

    def home(self, shard: int) -> int:
        return shard % self.num_replicas

    def owner(self, shard: int) -> int:
        with self._lock:
            return self._owner[shard]

    def up(self) -> list[int]:
        with self._lock:
            return sorted(self._up)

    def live(self) -> list[int]:
        """Healthy AND accepting new traffic (up minus draining) — the
        set hedges and entity-less round-robin route through."""
        with self._lock:
            return sorted(self._up - self._draining)

    def is_up(self, replica_id: int) -> bool:
        with self._lock:
            return replica_id in self._up

    def is_live(self, replica_id: int) -> bool:
        with self._lock:
            return (replica_id in self._up
                    and replica_id not in self._draining)

    def shards(self) -> list[int]:
        """Every LEAF shard (sorted); grows as hot shards split."""
        with self._lock:
            return sorted(self._owner)

    def shards_of(self, replica_id: int) -> list[int]:
        with self._lock:
            return sorted(s for s, r in self._owner.items()
                          if r == replica_id)

    def shard_of_key(self, key: int) -> int:
        """Route a non-negative key to its LEAF shard: ``key %
        num_shards``, descending split children until a leaf."""
        with self._lock:
            m = self.num_shards
            r = key % m
            while (r, m) in self._interior:
                m *= 2
                r = key % m
            return r

    def modulus_of(self, shard: int) -> int:
        with self._lock:
            return self._modulus[shard]

    def split(self, shard: int) -> tuple[int, int]:
        """Split leaf ``shard`` into two children under the doubled
        modulus; both children inherit the parent's owner (migration is
        a separate, also-atomic step). Returns ``(child_a, child_b)``.
        One version bump: routing sees the pre-split or post-split
        table, never a half-split one."""
        with self._lock:
            if shard not in self._owner:
                raise KeyError(f"shard {shard} is not a leaf")
            m = self._modulus[shard]
            owner = self._owner[shard]
            a, b = shard, shard + m
            self._interior.add((shard, m))
            self._owner[a] = owner
            self._owner[b] = owner
            self._modulus[a] = 2 * m
            self._modulus[b] = 2 * m
            self.version += 1
            return a, b

    def migrate(self, shard: int, new_owner: int) -> int:
        """Re-assign leaf ``shard`` to ``new_owner`` (one table write,
        one version bump). Returns the previous owner. Every replica
        holds the full host store, so this is the whole migration —
        the re-home discipline's table-swap leg, reused."""
        with self._lock:
            if shard not in self._owner:
                raise KeyError(f"shard {shard} is not a leaf")
            if new_owner not in self._up:
                raise ReplicaUnavailable(
                    f"migration target replica {new_owner} is not up",
                    replica_id=new_owner)
            old = self._owner[shard]
            self._owner[shard] = int(new_owner)
            self.version += 1
            return old

    def add_replica(self) -> int:
        """Admit one new replica id (the next integer) to the map —
        ownerless until migrations move shards onto it."""
        with self._lock:
            rid = self.num_replicas
            self.num_replicas += 1
            self._up.add(rid)
            self.version += 1
            return rid

    def remove_replica(self, replica_id: int) -> None:
        """Retire a DRAINED replica from the map (it must own nothing —
        scale-down migrates its shards away first; the guard is what
        makes 'never retire the last owner of any shard' structural)."""
        with self._lock:
            owned = [s for s, r in self._owner.items()
                     if r == replica_id]
            if owned:
                raise ValueError(
                    f"replica {replica_id} still owns shard(s) {owned} "
                    f"— migrate them away before retiring")
            self._up.discard(replica_id)
            self._draining.discard(replica_id)
            self.version += 1

    def set_draining(self, replica_id: int, draining: bool) -> None:
        with self._lock:
            if draining:
                self._draining.add(replica_id)
            else:
                self._draining.discard(replica_id)
            self.version += 1

    def mark_down(self, replica_id: int) -> dict[int, int]:
        """Re-home ``replica_id``'s shards to survivors; returns
        {shard: new_owner}. Raises when no survivor remains (a fleet of
        zero replicas cannot degrade gracefully — it is down)."""
        with self._lock:
            self._up.discard(replica_id)
            survivors = sorted(self._up - self._draining) \
                or sorted(self._up)
            if not survivors:
                raise ReplicaUnavailable(
                    "no surviving replica to re-home to",
                    replica_id=replica_id)
            moved = {}
            ring = itertools.cycle(survivors)
            for s in sorted(self._owner):
                if self._owner[s] == replica_id:
                    new = next(ring)
                    self._owner[s] = new
                    moved[s] = new
            self.version += 1
            return moved

    def restore(self, replica_id: int) -> list[int]:
        """Mark ``replica_id`` healthy again and return its HOME shards
        to it; returns the shards that moved back."""
        with self._lock:
            self._up.add(replica_id)
            self._draining.discard(replica_id)
            back = []
            for s in sorted(self._owner):
                if (self.home(s) == replica_id
                        and self._owner[s] != replica_id):
                    self._owner[s] = replica_id
                    back.append(s)
            self.version += 1
            return back

    def next_up(self, after: int) -> int:
        """The next healthy, non-draining replica on the ring after
        ``after`` (deterministic, never ``after`` itself unless it is
        the only survivor)."""
        with self._lock:
            pool = (self._up - self._draining) or self._up
            if not pool:
                raise ReplicaUnavailable("no replica is up")
            for delta in range(1, self.num_replicas + 1):
                cand = (after + delta) % self.num_replicas
                if cand in pool:
                    return cand
            return after  # pragma: no cover — unreachable (set nonempty)

    def snapshot(self) -> dict:
        """The whole assignment, for ledger evidence and /healthz."""
        with self._lock:
            return {
                "version": self.version,
                "num_shards": self.num_shards,
                "num_replicas": self.num_replicas,
                "owners": dict(self._owner),
                "moduli": dict(self._modulus),
                "up": sorted(self._up),
                "draining": sorted(self._draining),
            }


class FleetRouter:
    """Routes scoring requests to shard-owning replicas over HTTP.

    ``endpoint_fn(replica_id) -> (host, port)`` resolves live endpoints
    (the supervisor's — a restarted replica has a new port).
    ``route_re_type`` picks which entity id carries the affinity when a
    request names several (default: lexicographically first key, so
    routing is deterministic under dict-order changes).
    """

    def __init__(
        self,
        shard_map: ShardMap,
        endpoint_fn: Callable[[int], tuple[str, int]],
        route_re_type: Optional[str] = None,
        request_timeout_s: float = 30.0,
        retries: int = 3,
        retry_backoff_s: float = 0.1,
        hedge_after_s: Optional[float] = None,
        metrics=None,
        health_fn: Optional[Callable[[int], bool]] = None,
    ):
        self.shard_map = shard_map
        self._endpoint = endpoint_fn
        self.route_re_type = route_re_type
        self.request_timeout_s = float(request_timeout_s)
        self.retries = int(retries)
        self.retry_backoff_s = float(retry_backoff_s)
        self.hedge_after_s = (None if hedge_after_s is None
                              else float(hedge_after_s))
        self.metrics = metrics
        # Liveness beyond the shard map's own view: the supervisor
        # declares a replica dead/restarting BEFORE the map re-homes
        # (the map swap runs on the monitor thread, after detection) —
        # a hedge aimed into that gap burns the hedge budget on a
        # corpse. None = trust the map alone.
        self._health = health_fn
        self._rr = itertools.count()  # entity-less requests round-robin
        # Recent successful send walls (submit → response), the signal
        # the elastic controller auto-tunes hedge_after_s from
        # (serving/elastic.py): p99 of THESE is what "the primary is
        # slow" should mean, not a static guess.
        self._send_lock = threading.Lock()
        self._send_walls: collections.deque = collections.deque(
            maxlen=512)
        # Forward pool: grouped per-replica sends of one /score body run
        # concurrently; hedges ride the same pool.
        # TWO pools, strictly layered: group threads (one per per-replica
        # sub-batch of a body) block on send futures, and send threads
        # never block on anything pool-managed — a single shared pool
        # here can fill up with group threads all WAITING on send tasks
        # that have no worker left to run on (nested-submit deadlock).
        self._group_pool = ThreadPoolExecutor(
            max_workers=max(16, 4 * shard_map.num_replicas),
            thread_name_prefix="photon-fleet-group")
        self._send_pool = ThreadPoolExecutor(
            max_workers=max(32, 8 * shard_map.num_replicas),
            thread_name_prefix="photon-fleet-send")

    # -- routing -------------------------------------------------------------

    def shard_for(self, request_obj: dict) -> Optional[int]:
        """The routing shard of one /score request object (None =
        entity-less: any replica serves it identically)."""
        ents = request_obj.get("entity_ids") or {}
        if not ents:
            return None
        if self.route_re_type is not None:
            if self.route_re_type in ents:
                key = ents[self.route_re_type]
            else:
                return None
        else:
            key = ents[min(ents)]
        return self.shard_map.shard_of_key(route_key(key))

    def replica_for(self, request_obj: dict) -> int:
        shard = self.shard_for(request_obj)
        if shard is None:
            live = self.shard_map.live()
            if not live:
                raise ReplicaUnavailable("no replica is up")
            return live[next(self._rr) % len(live)]
        return self.shard_map.owner(shard)

    # -- hedging ------------------------------------------------------------

    def _is_live(self, replica_id: int) -> bool:
        if not self.shard_map.is_live(replica_id):
            return False
        return self._health is None or self._health(replica_id)

    def hedge_target(self, after: int) -> Optional[int]:
        """The next LIVE replica on the ring after ``after`` — up in
        the map, not draining, and healthy per the supervisor's view
        when one is wired. None = no useful hedge target exists (a
        hedge to a known-dead or draining replica only burns budget —
        the satellite fix of ISSUE 15)."""
        for delta in range(1, self.shard_map.num_replicas + 1):
            cand = (after + delta) % self.shard_map.num_replicas
            if cand == after:
                continue
            if self._is_live(cand):
                return cand
        return None

    def observed_send_p99(self) -> Optional[float]:
        """p99 of the recent successful send walls (seconds); None
        until enough samples exist to make a tail meaningful."""
        with self._send_lock:
            if len(self._send_walls) < 20:
                return None
            walls = sorted(self._send_walls)
        return walls[min(len(walls) - 1, int(0.99 * len(walls)))]

    # -- forwarding ----------------------------------------------------------

    def _post_score(self, replica_id: int, body: bytes) -> dict:
        """One POST /score to one replica. Raises ReplicaShed on its
        503, ReplicaHTTPError on other HTTP errors, OSError-family on
        connection trouble (the retryable class)."""
        # Injection seam for the network edge: `delay` = slow link (what
        # hedging exists for), `partition` = dropped traffic to this
        # replica (drop-by-site: indices=[replica_id]).
        flt.fire(flt.sites.FLEET_ROUTE, index=replica_id)
        host, port = self._endpoint(replica_id)
        req = urllib.request.Request(
            f"http://{host}:{port}/score", data=body,
            headers={"Content-Type": "application/json"})
        t0 = time.monotonic()
        try:
            with urllib.request.urlopen(
                    req, timeout=self.request_timeout_s) as resp:
                out = json.loads(resp.read())
            with self._send_lock:
                self._send_walls.append(time.monotonic() - t0)
            return out
        except urllib.error.HTTPError as e:
            try:
                payload = json.loads(e.read())
            except ValueError:
                payload = {}
            if e.code == 503:
                raise ReplicaShed(
                    payload.get("error", "replica shed the batch"),
                    replica_id=replica_id,
                    queue_depth=payload.get("queue_depth")) from e
            raise ReplicaHTTPError(
                payload.get("error", f"replica HTTP {e.code}"),
                replica_id=replica_id, status=e.code) from e

    def _forward_group(self, replica_id: int, body: bytes,
                       hedged: bool) -> dict:
        """Forward one per-replica sub-batch, hedging when the primary
        is slow. Returns the replica's JSON response; the losing send of
        a hedge is discarded (its pool thread finishes harmlessly —
        scoring is pure, so the duplicate work is latency insurance, not
        a correctness hazard)."""
        primary = self._send_pool.submit(self._post_score, replica_id,
                                         body)
        if not hedged or self.hedge_after_s is None:
            return primary.result(timeout=self.request_timeout_s + 1)
        done, _ = wait([primary], timeout=self.hedge_after_s)
        if done:
            return primary.result()
        # Primary is slow: duplicate to the next LIVE replica (up in
        # the map, not draining, healthy per the supervisor — a hedge
        # to a known-dead replica would burn the budget for nothing).
        # Both futures race; the first SUCCESSFUL response wins (a fast
        # failure must not beat a slow success).
        hedge_to = self.hedge_target(replica_id)
        if hedge_to is None:
            return primary.result(timeout=self.request_timeout_s + 1)
        if self.metrics is not None:
            self.metrics.record_hedge()
        logger.info("hedging slow replica %d → %d", replica_id, hedge_to)
        secondary = self._send_pool.submit(self._post_score, hedge_to,
                                           body)
        pending = {primary: replica_id, secondary: hedge_to}
        deadline = time.monotonic() + self.request_timeout_s + 1
        first_exc = None
        while pending:
            done, _ = wait(list(pending),
                           timeout=max(deadline - time.monotonic(), 0.01),
                           return_when=FIRST_COMPLETED)
            if not done:
                break
            for fut in done:
                rid = pending.pop(fut)
                exc = fut.exception()
                if exc is None:
                    if fut is secondary and self.metrics is not None:
                        self.metrics.record_hedge_win()
                    return fut.result()
                first_exc = first_exc or exc
        raise first_exc or ReplicaUnavailable(
            "hedged sends both timed out", replica_id=replica_id)

    def score(self, request_objs: Sequence[dict],
              want_trace: bool = False) -> dict:
        """Route and score one /score body's requests across the fleet.

        Returns ``{"scores": [...], "attribution": [...] | None}`` in
        the INPUT order. Connection-class failures retry with
        deterministic backoff, re-grouping each round so retries follow
        re-homed shards; shed and HTTP-error outcomes are final and
        raise (``ReplicaShed`` / ``ReplicaHTTPError`` /
        ``ReplicaUnavailable``).
        """
        n = len(request_objs)
        scores: list[Optional[float]] = [None] * n
        attributions: list[Optional[dict]] = [None] * n
        want_attr = False
        remaining = list(range(n))
        last_exc: Optional[BaseException] = None
        for attempt in range(self.retries + 1):
            if not remaining:
                break
            if attempt:
                # Deterministic backoff; the supervisor's re-home runs
                # concurrently, so by the retry the owner table usually
                # already points at a survivor.
                time.sleep(self.retry_backoff_s * attempt)
                if self.metrics is not None:
                    self.metrics.record_retry(len(remaining))
            groups: dict[int, list[int]] = {}
            for i in remaining:
                groups.setdefault(
                    self.replica_for(request_objs[i]), []).append(i)
            futures = {}
            for rid, idxs in groups.items():
                body = json.dumps(
                    {"requests": [request_objs[i] for i in idxs],
                     "trace": want_trace}).encode()
                futures[self._group_pool.submit(
                    self._forward_group, rid, body,
                    hedged=(attempt == 0))] = (rid, idxs)
            still_failed: list[int] = []
            for fut, (rid, idxs) in futures.items():
                try:
                    payload = fut.result(
                        timeout=2 * self.request_timeout_s + 2)
                except (ReplicaShed, ReplicaHTTPError):
                    raise  # final: defined fleet error, no retry
                except (OSError, TimeoutError, RuntimeError) as exc:
                    # Connection-class: the replica died or the edge
                    # dropped (InjectedPartition lands here). Fail these
                    # indices over to the next round's owner.
                    last_exc = exc
                    if self.metrics is not None:
                        self.metrics.record_forward_error()
                    logger.warning(
                        "forward to replica %d failed (%s: %s) — "
                        "%d request(s) will retry", rid,
                        type(exc).__name__, exc, len(idxs))
                    still_failed.extend(idxs)
                    continue
                got = payload.get("scores", [])
                if len(got) != len(idxs):
                    raise ReplicaHTTPError(
                        f"replica {rid} returned {len(got)} scores for "
                        f"{len(idxs)} requests", replica_id=rid,
                        status=500)
                attr = payload.get("attribution")
                for k, i in enumerate(idxs):
                    scores[i] = float(got[k])
                    if attr is not None and attr[k] is not None:
                        attributions[i] = attr[k]
                        want_attr = True
            remaining = still_failed
        if remaining:
            raise ReplicaUnavailable(
                f"{len(remaining)} request(s) unserved after "
                f"{self.retries + 1} attempts: "
                f"{type(last_exc).__name__ if last_exc else 'unknown'}: "
                f"{last_exc}")
        return {"scores": scores,
                "attribution": attributions if want_attr else None}

    def close(self) -> None:
        self._group_pool.shutdown(wait=False)
        self._send_pool.shutdown(wait=False)
