"""Resident GAME model store: the online-serving memory hierarchy.

Reference parity: none — the reference's GameScoringDriver reloads the whole
model per batch job. The serving design instead follows the Snap ML /
GPU-stochastic-learning observation (PAPERS.md): keep model state resident
next to the accelerator and stream requests through it. Three tiers:

- **Fixed effects** are tiny (one (d,) vector per coordinate) and hot on
  every request → device-resident for the service lifetime, placed once at
  load ("broadcast" is just replication, as everywhere in this rebuild).
- **Random effects** are the big tier (an (E, d)-shaped table per
  coordinate, E up to millions) → a hash-sharded HOST store in the model's
  own representation (dense rows, subspace cols+means, or latent factors —
  never densified wholesale), plus an **LRU device cache** of densified
  rows for the hot entities actually being scored. Zipf-skewed traffic
  (the realistic per-user activity distribution — same skew the training
  bucketing exploits) makes a small cache absorb most rows.
- **Unseen entities** (ids outside the table, unknown vocabulary keys,
  requests that omit the id) resolve to a permanent all-zero fallback row:
  scores degrade gracefully to fixed-effect-only, exactly the offline
  ``game_score`` semantics for unseen entities.

The cache table has C+1 rows; row C is the zero fallback row and is never
written (cache-fill scatters pad with zero rows into slot C, which keeps it
zero by construction — no masks in the scoring gather).
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu import faults as flt

from photon_ml_tpu.game.factored import FactoredRandomEffectModel
from photon_ml_tpu.game.models import (FixedEffectModel, GameModel,
                                       RandomEffectModel,
                                       SubspaceRandomEffectModel,
                                       dense_rows_from_subspace)

logger = logging.getLogger("photon_ml_tpu.serving")


def _next_pow2(n: int) -> int:
    return 1 << max(0, (int(n) - 1)).bit_length()


class HashShardedStore:
    """Host-resident per-entity coefficients, hash-sharded by entity id.

    Sharding is ``id % num_shards`` (ids are already the dense vocabulary
    rows, so the modulo IS the hash); because every row 0..E-1 exists
    (untrained entities hold zero rows), an entity's position within its
    shard is ``id // num_shards`` — O(1) lookup, no per-shard index. The
    shard structure matches the multi-host layout this store is the
    single-process degenerate case of: shard s would live on host s and
    ``fetch`` would become a host s RPC, with everything above unchanged.

    Payloads stay in the model's own representation per shard; densification
    happens per fetched row batch via the model-type helpers in
    game/models.py / game/factored.py.
    """

    def __init__(self, model, num_shards: int = 8):
        from photon_ml_tpu.boot.mapfmt import is_mapped_array

        self.num_shards = int(num_shards)
        self.num_entities = int(model.num_entities)
        if isinstance(model, SubspaceRandomEffectModel):
            self.dim = int(model.num_features)
        else:
            self.dim = int(model.dim)
        # Only the dense representation accepts published row deltas
        # (swap_rows); the flag is the serving store's capability probe.
        self.mutable = isinstance(model, RandomEffectModel)
        # mmap-backed models (boot/mapfmt.py) take the ZERO-COPY path:
        # the eager `table[partition]` below would fault every page in
        # and copy the whole (E, d) tier at boot — exactly the parse
        # cost sub-second restart exists to kill. Direct mode keeps the
        # mapped tables whole (fetch gathers just the requested rows off
        # the page cache) and absorbs published row swaps into a sparse
        # host OVERLAY instead of copying a table to write 50 rows.
        self.mapped = self._init_direct(model, is_mapped_array)
        if self.mapped:
            return
        ids = np.arange(self.num_entities, dtype=np.int64)
        part = [ids[ids % self.num_shards == s]
                for s in range(self.num_shards)]
        if isinstance(model, RandomEffectModel):
            means = np.asarray(model.means, np.float32)
            self._shards = [(means[p],) for p in part]
            self._densify = lambda payload, pos: payload[0][pos]
        elif isinstance(model, SubspaceRandomEffectModel):
            cols = np.asarray(model.cols)
            means = np.asarray(model.means, np.float32)
            nf = int(model.num_features)
            self._shards = [(cols[p], means[p]) for p in part]
            self._densify = lambda payload, pos: dense_rows_from_subspace(
                payload[0][pos], payload[1][pos], nf)
        elif isinstance(model, FactoredRandomEffectModel):
            factors = np.asarray(model.factors, np.float32)
            proj_t = np.asarray(model.projection, np.float32).T
            self._shards = [(factors[p],) for p in part]
            self._densify = lambda payload, pos: payload[0][pos] @ proj_t
        else:
            raise TypeError(f"unsupported random-effect model type "
                            f"{type(model).__name__}")

    def _init_direct(self, model, is_mapped_array) -> bool:
        """Arrange the zero-copy representation when the model's tables
        are mmap-backed; returns False (→ eager sharding) otherwise."""
        if isinstance(model, RandomEffectModel):
            table = np.asarray(model.means)
            if not is_mapped_array(table):
                return False
            table = table.astype(np.float32, copy=False)
            self._direct = (table,)
            self._densify_direct = lambda payload, ids: payload[0][ids]
        elif isinstance(model, SubspaceRandomEffectModel):
            cols = np.asarray(model.cols)
            means = np.asarray(model.means)
            if not (is_mapped_array(cols) and is_mapped_array(means)):
                return False
            nf = int(model.num_features)
            self._direct = (cols, means.astype(np.float32, copy=False))
            self._densify_direct = \
                lambda payload, ids: dense_rows_from_subspace(
                    payload[0][ids], payload[1][ids], nf)
        elif isinstance(model, FactoredRandomEffectModel):
            factors = np.asarray(model.factors)
            if not is_mapped_array(factors):
                return False
            proj_t = np.asarray(model.projection, np.float32).T
            self._direct = (factors.astype(np.float32, copy=False),)
            self._densify_direct = \
                lambda payload, ids: payload[0][ids] @ proj_t
        else:
            return False
        # Published row swaps land here: entity id → replacement row.
        self._overlay: dict[int, np.ndarray] = {}
        return True

    def fetch(self, ids: np.ndarray) -> np.ndarray:
        """Dense (len(ids), dim) rows for in-table ids (the cache-fill
        path). Grouped by shard; result rows follow the input order."""
        ids = np.asarray(ids, np.int64)
        if self.mapped:
            # One fancy-index gather straight off the mapped pages —
            # copies exactly the requested rows, nothing else.
            out = np.asarray(self._densify_direct(self._direct, ids),
                             np.float32)
            if self._overlay:
                for i, e in enumerate(ids):
                    row = self._overlay.get(int(e))
                    if row is not None:
                        out[i] = row
            return out
        out = np.zeros((ids.shape[0], self.dim), np.float32)
        sid = ids % self.num_shards
        for s in np.unique(sid):
            m = sid == s
            out[m] = self._densify(self._shards[s],
                                   ids[m] // self.num_shards)
        return out

    def swap_rows(self, ids: np.ndarray, rows: np.ndarray) -> None:
        """Replace the host rows of ``ids`` in place (the publication
        hot-swap seam — serving/publish.py row deltas land HERE).

        Dense stores only: subspace/factored shards keep coefficients in
        a representation a dense row cannot be written back into (the
        refit path produces dense rows), so those coordinates refuse
        loudly instead of silently mis-writing. Mapped stores absorb the
        swap into the overlay — the read-only generation artifact on
        disk is never written (rollback = dropping overlay rows, and a
        re-booted replica reads the artifact's committed bytes)."""
        if not self.mutable:
            raise ValueError(
                "host store holds a non-dense random-effect "
                "representation — row hot-swap serves dense "
                "RandomEffectModel coordinates only")
        ids = np.asarray(ids, np.int64)
        rows = np.asarray(rows, np.float32)
        if self.mapped:
            for e, row in zip(ids, rows):
                self._overlay[int(e)] = np.array(row, np.float32)
            return
        sid = ids % self.num_shards
        for s in np.unique(sid):
            m = sid == s
            table = self._shards[int(s)][0]
            if not table.flags.writeable:  # e.g. a read-only source
                table = table.copy()
                self._shards[int(s)] = (table,)
            table[ids[m] // self.num_shards] = rows[m]

    def host_bytes(self) -> int:
        """Host address-space bytes of the coefficient tier (mapped
        stores report the mapped extent — RESIDENT bytes are whatever
        the page cache chose to keep, which is the point)."""
        if self.mapped:
            return sum(int(a.nbytes) for a in self._direct)
        return sum(int(a.nbytes) for payload in self._shards
                   for a in payload)


# The single-process HashShardedStore cannot really fail, but its fetch
# is the seam that becomes a host RPC in the multi-host layout — so the
# retry contract lives HERE, where the distributed failure will surface.
_FETCH_RETRIES = 2
_FETCH_BACKOFF_S = 0.01


def _fetch_with_retry(store: "HashShardedStore", ids: np.ndarray,
                      on_retry: Optional[Callable[[int], None]] = None
                      ) -> np.ndarray:
    """Bounded retry around the host-store fetch (transient I/O fault
    class; ``serving.fetch`` is the injection site)."""
    attempt = 0
    while True:
        try:
            flt.fire(flt.sites.SERVING_FETCH)
            return store.fetch(ids)
        except OSError as e:
            attempt += 1
            if attempt > _FETCH_RETRIES:
                raise
            logger.warning("host-store fetch attempt %d failed (%s: %s) "
                           "— retrying", attempt, type(e).__name__, e)
            if on_retry is not None:
                on_retry(1)
            time.sleep(_FETCH_BACKOFF_S * attempt)


class REServingState:
    """One random-effect coordinate's host store + LRU device cache.

    ``cache_dtype="int8"`` stores the device table as symmetric per-ROW
    int8 (the streamed chunk format's quantization scheme reused on the
    serving side — ``ops/streaming_sparse.quantize_rows_int8``): rows
    quantize once at fill time, the scoring gather dequantizes on device
    (one per-row f32 scale multiply AFTER the einsum — exact algebra),
    and the table costs ~dim bytes per row instead of 4·dim, so the same
    HBM budget caches ~4× the entities (docs/SERVING.md "Quantized
    device cache"). The LRU bookkeeping — fill, eviction, pinning,
    publication invalidation — is dtype-blind: ``apply_rows`` pops the
    same slots and the next resolve re-quantizes from the swapped host
    rows, so a quantized hot-swap serves the same bits as a quantized
    cold restart."""

    def __init__(self, cid: str, model, cache_entities: int,
                 store_shards: int, cache_dtype: str = "float32"):
        if cache_dtype not in ("float32", "int8"):
            raise ValueError(
                f"unsupported cache_dtype {cache_dtype!r}; expected "
                "float32 or int8")
        self.cid = cid
        self.re_type = model.re_type
        self.shard_id = model.shard_id
        self.cache_dtype = cache_dtype
        self.store = HashShardedStore(model, num_shards=store_shards)
        self.num_entities = self.store.num_entities
        self.dim = self.store.dim
        # Cache size never exceeds the entity table (plus the fallback row).
        self.capacity = max(1, min(int(cache_entities), self.num_entities))
        self.fallback_slot = self.capacity
        self._lru: collections.OrderedDict[int, int] = \
            collections.OrderedDict()  # entity id → slot, oldest first
        self._free = list(range(self.capacity))
        # cache.at[slots].set(rows): one scatter per fill, insert count
        # padded to power-of-two buckets so steady state never recompiles.
        # Padding rows are zeros aimed at the fallback slot — which is what
        # keeps that row zero forever (int8 mode scatters the scale vector
        # in the same program; the fallback scale stays 0, so the fallback
        # row dequantizes to exactly zero).
        if cache_dtype == "int8":
            self.cache = jnp.zeros((self.capacity + 1, self.dim),
                                   jnp.int8)
            self.cache_scale = jnp.zeros((self.capacity + 1,),
                                         jnp.float32)
            self._insert = jax.jit(
                lambda cache, scale, slots, rows, row_scale: (
                    cache.at[slots].set(rows),
                    scale.at[slots].set(row_scale)))
        else:
            self.cache = jnp.zeros((self.capacity + 1, self.dim),
                                   jnp.float32)
            self.cache_scale = None
            self._insert = jax.jit(
                lambda cache, slots, rows: cache.at[slots].set(rows))

    def device_bytes(self) -> int:
        """Device-resident bytes of this coordinate's cache (table +
        scale vector under int8) — the capacity-at-fixed-HBM accounting
        bench_serving.py sweeps."""
        rows = self.capacity + 1
        if self.cache_scale is not None:
            return rows * self.dim + rows * 4
        return rows * self.dim * 4

    def resolve(self, ids: np.ndarray,
                on_retry: Optional[Callable[[int], None]] = None
                ) -> tuple[np.ndarray, dict]:
        """Entity ids → device-cache slots, filling the cache for misses.

        Returns (slots int32 (n,), counters dict). Ids outside [0, E) map
        to the fallback slot. The batch's own entities are PINNED for the
        duration of the resolve — eviction only reclaims slots no row of
        this batch reads, so one flush can never overwrite a slot it is
        about to gather (the caller guarantees a batch's unique entities
        fit: capacity >= max_batch). NOT thread-safe on its own — the
        service serializes resolve+score (the device is serial anyway).
        """
        ids = np.asarray(ids, np.int64)
        slots = np.full(ids.shape[0], self.fallback_slot, np.int32)
        stats = {"hits": 0, "misses": 0, "unseen": 0, "evictions": 0}
        pinned = {int(e) for e in ids if 0 <= int(e) < self.num_entities}
        if len(pinned) > self.capacity:
            raise ValueError(
                f"batch references {len(pinned)} distinct entities of "
                f"coordinate {self.cid!r} but the device cache holds "
                f"{self.capacity} — raise cache_entities or lower "
                f"max_batch")
        miss_ids: list[int] = []
        miss_rows: list[int] = []
        for i, e in enumerate(ids):
            e = int(e)
            if e < 0 or e >= self.num_entities:
                stats["unseen"] += 1
                continue
            slot = self._lru.get(e)
            if slot is not None:
                self._lru.move_to_end(e)
                slots[i] = slot
                stats["hits"] += 1
            else:
                stats["misses"] += 1
                miss_ids.append(e)
                miss_rows.append(i)
        if miss_ids:
            # Assign slots to the unique missed entities (a batch may
            # repeat an entity), evicting the oldest UNPINNED entries.
            unique: dict[int, int] = {}
            for e in miss_ids:
                if e in unique:
                    continue
                if self._free:
                    slot = self._free.pop()
                else:
                    victim = next(v for v in self._lru if v not in pinned)
                    slot = self._lru.pop(victim)
                    stats["evictions"] += 1
                unique[e] = slot
                self._lru[e] = slot
            fetch_ids = np.fromiter(unique, np.int64, len(unique))
            rows = _fetch_with_retry(self.store, fetch_ids,
                                     on_retry=on_retry)
            k = _next_pow2(len(unique))
            ins_slots = np.full(k, self.fallback_slot, np.int32)
            ins_slots[: len(unique)] = list(unique.values())
            if self.cache_scale is not None:
                # Quantize at fill time (per-row symmetric int8 — the
                # chunk format's scheme); padding rows keep code 0 and
                # scale 0 aimed at the fallback slot.
                from photon_ml_tpu.ops.streaming_sparse import \
                    quantize_rows_int8

                q, row_scale = quantize_rows_int8(rows)
                ins_rows = np.zeros((k, self.dim), np.int8)
                ins_scale = np.zeros((k,), np.float32)
                ins_rows[: len(unique)] = q
                ins_scale[: len(unique)] = row_scale
                self.cache, self.cache_scale = self._insert(
                    self.cache, self.cache_scale, jnp.asarray(ins_slots),
                    jnp.asarray(ins_rows), jnp.asarray(ins_scale))
            else:
                ins_rows = np.zeros((k, self.dim), np.float32)
                ins_rows[: len(unique)] = rows
                self.cache = self._insert(self.cache,
                                          jnp.asarray(ins_slots),
                                          jnp.asarray(ins_rows))
            for i in miss_rows:
                slots[i] = unique[int(ids[i])]
        return slots, stats

    def cached_entities(self) -> list[int]:
        return list(self._lru)

    def apply_rows(self, ids: np.ndarray, rows: np.ndarray) -> int:
        """Hot-swap published rows into this coordinate: write the host
        shards, then invalidate ONLY the affected device-LRU slots (the
        next resolve of those entities re-fills from the new host rows;
        every other cached entity stays hot). Returns the number of
        device slots invalidated. Caller holds the store lock — swaps
        happen BETWEEN flushes, never under one."""
        self.store.swap_rows(ids, rows)
        invalidated = 0
        for e in np.asarray(ids, np.int64):
            slot = self._lru.pop(int(e), None)
            if slot is not None:
                self._free.append(slot)
                invalidated += 1
        return invalidated


class ResidentModelStore:
    """A loaded GameModel arranged for low-latency online scoring."""

    def __init__(
        self,
        model: GameModel,
        cache_entities: int = 4096,
        store_shards: int = 8,
        entity_vocabs: Optional[dict[str, dict]] = None,
        metrics_retry: Optional[Callable[[int], None]] = None,
        cache_dtype: str = "float32",
        initial_version: int = 0,
    ):
        self.task = model.task
        self.entity_vocabs = entity_vocabs or {}
        self._metrics_retry = metrics_retry
        self.cache_dtype = cache_dtype
        self.fixed: list[tuple[str, str, jax.Array]] = []
        self.random: list[REServingState] = []
        self.shard_dims: dict[str, int] = {}
        self._lock = threading.Lock()
        # Publication state (serving/publish.py): the version this
        # store serves and the undo rows of every applied delta, newest
        # last — rollback restores them in reverse. A store booted from
        # a COMPACTED generation (boot/generations.py) starts at the
        # folded model_version, so the chain-order check accepts only
        # deltas genuinely newer than its tables.
        self.version = int(initial_version)
        self._undo: list[tuple[int, dict]] = []
        for cid, m in model.models.items():
            if isinstance(m, FixedEffectModel):
                w = jax.device_put(jnp.asarray(m.coefficients.means,
                                               jnp.float32))
                self.fixed.append((cid, m.shard_id, w))
                self._claim_dim(m.shard_id, int(m.dim))
            else:
                st = REServingState(cid, m, cache_entities, store_shards,
                                    cache_dtype=cache_dtype)
                self.random.append(st)
                self._claim_dim(m.shard_id, st.dim)
        host = sum(st.store.host_bytes() for st in self.random)
        device = sum(int(np.prod(w.shape)) * 4 for _, _, w in self.fixed) \
            + self.device_cache_bytes()
        logger.info(
            "model store resident: %d fixed + %d random coordinates, "
            "%.1f MB host store, %.1f MB device (coefficients + %s "
            "caches)", len(self.fixed), len(self.random), host / 2**20,
            device / 2**20, cache_dtype)

    def device_cache_bytes(self) -> int:
        """Device bytes of the random-effect LRU caches (tables + scale
        vectors under int8) — the quantized-capacity accounting."""
        return sum(st.device_bytes() for st in self.random)

    def _claim_dim(self, shard_id: str, dim: int) -> None:
        prev = self.shard_dims.setdefault(shard_id, dim)
        if prev != dim:
            raise ValueError(
                f"feature shard {shard_id!r} used at two dimensions "
                f"({prev} and {dim}) — model metadata is inconsistent")

    def entity_row_id(self, re_type: str, key) -> int:
        """A request's raw entity id → vocabulary row (−1 = unseen).

        Integers index the entity table directly (the NPZ-model contract);
        anything else goes through the serving vocabularies (the
        entity-vocabs.json written by Avro-format training).
        """
        if key is None:
            return -1
        if isinstance(key, (int, np.integer)) \
                and not isinstance(key, bool):
            return int(key)
        vocab = self.entity_vocabs.get(re_type)
        if vocab is None:
            return -1
        return int(vocab.get(str(key), -1))

    def resolve_slots(self, ids_by_cid: dict[str, np.ndarray],
                      metrics=None) -> dict[str, np.ndarray]:
        """Per-coordinate entity ids → cache slots (filling caches)."""
        out = {}
        with self._lock:
            for st in self.random:
                slots, stats = st.resolve(ids_by_cid[st.cid],
                                          on_retry=self._metrics_retry)
                if metrics is not None:
                    metrics.record_cache(st.cid, **stats)
                out[st.cid] = slots
        return out

    def caches(self) -> dict[str, jax.Array]:
        return {st.cid: st.cache for st in self.random}

    def cache_scales(self) -> dict[str, Optional[jax.Array]]:
        """Per-coordinate dequant scale vectors (None for f32 caches —
        an empty pytree leaf, so the scorer's signature is dtype-
        stable)."""
        return {st.cid: st.cache_scale for st in self.random}

    # -- continuous publication (serving/publish.py) -------------------------

    def delta_dims(self) -> dict[str, tuple[int, int]]:
        """Coordinate → (num_entities, dim) for delta validation."""
        return {st.cid: (st.num_entities, st.dim) for st in self.random}

    def apply_delta(self, delta) -> dict:
        """Install one committed :class:`~photon_ml_tpu.serving.publish.
        ModelDelta` into the live store: validate, swap host rows,
        invalidate affected device-LRU slots — all under the store lock,
        so in-flight flushes complete against the OLD version and every
        later flush sees the NEW one (no mixed-version batch can exist).

        The delta chain is enforced (``delta.parent == self.version``):
        a replica that missed a version cannot silently apply on top of
        the wrong base — it must catch up in order (the fleet replays
        committed deltas to restarted replicas). Undo rows are captured
        before the swap so :meth:`rollback_to` is exact.
        """
        from photon_ml_tpu.serving.publish import (BadDelta,
                                                   validate_delta)

        with self._lock:
            validate_delta(delta, self.delta_dims())
            if delta.parent != self.version:
                raise BadDelta(
                    f"delta v{delta.version} was cut against version "
                    f"{delta.parent} but this store serves "
                    f"{self.version} — apply the chain in order")
            by_cid = {st.cid: st for st in self.random}
            undo: dict[str, tuple[np.ndarray, np.ndarray]] = {}
            invalidated = 0
            for cid, (ids, rows) in delta.rows.items():
                st = by_cid[cid]
                undo[cid] = (ids, st.store.fetch(ids))
                invalidated += st.apply_rows(ids, rows)
            self._undo.append((delta.version, undo))
            self.version = delta.version
        logger.info(
            "delta v%d applied: %d row(s) across %s, %d device slot(s) "
            "invalidated", delta.version, delta.num_rows,
            delta.coordinates, invalidated)
        return {"version": self.version, "rows": delta.num_rows,
                "invalidated_slots": invalidated}

    def rollback_to(self, version: int) -> dict:
        """Back out every applied delta newer than ``version`` (newest
        first, restoring the captured undo rows). Exact inverse of the
        applied chain — after it, served bits equal a store that never
        saw the rolled-back deltas."""
        with self._lock:
            if version > self.version:
                raise ValueError(
                    f"cannot roll back FORWARD (serving {self.version}, "
                    f"asked for {version})")
            by_cid = {st.cid: st for st in self.random}
            restored = 0
            while self.version > version:
                if not self._undo:
                    raise ValueError(
                        f"no undo rows recorded past version "
                        f"{self.version} — cannot reach {version}")
                v, undo = self._undo.pop()
                for cid, (ids, old_rows) in undo.items():
                    by_cid[cid].apply_rows(ids, old_rows)
                    restored += int(ids.shape[0])
                self.version = v - 1
        logger.info("rolled back to v%d (%d row(s) restored)",
                    self.version, restored)
        return {"version": self.version, "rows_restored": restored}
