"""photon-fleet: replicated serving with entity-affinity routing.

The single-process ``ScoringService`` (serving/service.py) is the
degenerate case ROADMAP item 3 promised to outgrow: one process, one
device cannot serve "millions of users". ``ServingFleet`` instates the
multi-host layout the host store was designed around:

    clients ──▶ fleet front door (this module)
                  │  admission control (503: replica id + fleet depth)
                  ▼
              FleetRouter (router.py): entity → shard → owning replica,
                  bounded retry, hedged second-sends
                  │
        ┌─────────┼─────────┐
        ▼         ▼         ▼
    replica 0  replica 1  replica N-1     ← ReplicaSupervisor
    (full ScoringService subprocesses:      (supervisor.py): probes,
     fixed effects replicated, host          heartbeat deadlines,
     store complete, device LRU hot          death → re-home →
     on OWN shards only)                     bounded restart

Failure half (the robustness core — docs/SERVING.md failure ladder):
replica death fails in-flight forwards fast (connection errors, the
``BatcherDied`` discipline one level up), the dead replica's shards
re-home to survivors within ``rehome_deadline_s`` (table swap + health
confirmation; survivors serve them from their own host stores with the
SAME scores), the supervisor restarts the replica, and its shards come
home. Every step is observable: ``ReplicaDied`` / ``ShardRehomed`` /
``ReplicaRecovered`` events, ``photon_fleet_*`` metrics, a ``degraded``
flag on ``/healthz`` while any shard is away from home, and a
fleet-level ``SLOTracker`` burning error budget on shed/unserved
requests.

Parity contract (the PR 1 discipline): every routed request's score is
bit-identical to the single-process ``ScoringService`` on the same
model — replicas RUN that service, and re-homing only changes which one
answers. ``tests/test_fleet.py`` proves it through SIGKILL chaos.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Sequence

from photon_ml_tpu import faults as flt
from photon_ml_tpu.serving.metrics import SLOTracker
from photon_ml_tpu.serving.publish import (CanaryRejected, ModelDelta,
                                           PublishError, read_delta)
from photon_ml_tpu.serving.router import (FleetRouter, ReplicaHTTPError,
                                          ReplicaShed, ReplicaUnavailable,
                                          ShardMap, route_key)
from photon_ml_tpu.serving.supervisor import (RETIRED, UP,
                                              ReplicaSupervisor)
from photon_ml_tpu.utils.events import (CanaryVerdict, DeltaPublished,
                                        FleetDegraded, ReplicaDied,
                                        ReplicaRecovered,
                                        RollbackExecuted, ShardRehomed,
                                        default_emitter)

logger = logging.getLogger("photon_ml_tpu.serving.fleet")


class FleetMetrics:
    """The fleet scoreboard: ``photon_fleet_*`` exposition +
    fleet-level SLO window. Thread-safe (router pool threads, the
    supervisor monitor, and HTTP handler threads all record)."""

    def __init__(self, num_replicas: int, slo_window_s: float = 60.0,
                 slo_availability: float = 0.999,
                 slo_latency_ms: Optional[float] = None):
        self._lock = threading.Lock()
        self.num_replicas = num_replicas
        self.requests_total = 0
        self.requests_by_replica = {i: 0 for i in range(num_replicas)}
        self.shed_total = 0  # fleet admission + replica-shed translations
        self.error_total = 0  # non-retryable replica HTTP errors
        self.unserved_total = 0  # retry budget exhausted (ReplicaUnavailable)
        self.forward_retries_total = 0
        self.forward_errors_total = 0
        self.hedges_total = 0
        self.hedge_wins_total = 0
        self.rehomes_total = 0
        self.rehome_seconds_last = 0.0
        self.rehome_seconds_max = 0.0
        self.rehome_deadline_misses_total = 0
        self.replica_deaths_total = 0
        self.replica_restarts_total = 0
        # Elastic control loop (serving/elastic.py).
        self.splits_total = 0
        self.migrations_total = 0
        self.scale_ups_total = 0
        self.scale_downs_total = 0
        self.brownout_sheds_total = 0
        # Continuous publication (serving/publish.py canary ladder).
        self.published_version = 0
        self.publishes_total = 0
        self.canary_rejects_total = 0
        self.publish_rollbacks_total = 0
        self.publish_swap_seconds_last = 0.0
        self.publish_swap_seconds_max = 0.0
        self.slo = SLOTracker(window_s=slo_window_s,
                              availability_objective=slo_availability,
                              latency_objective_ms=slo_latency_ms)

    # Router callbacks (FleetRouter.metrics protocol).
    def record_retry(self, n: int = 1) -> None:
        with self._lock:
            self.forward_retries_total += n

    def record_forward_error(self, n: int = 1) -> None:
        with self._lock:
            self.forward_errors_total += n

    def record_hedge(self) -> None:
        with self._lock:
            self.hedges_total += 1

    def record_hedge_win(self) -> None:
        with self._lock:
            self.hedge_wins_total += 1

    # Fleet-side records.
    def record_routed(self, replica_counts: dict[int, int]) -> None:
        with self._lock:
            for rid, n in replica_counts.items():
                self.requests_by_replica[rid] = \
                    self.requests_by_replica.get(rid, 0) + n
                self.requests_total += n

    def record_ok(self, latency_s: float, n: int = 1) -> None:
        for _ in range(n):
            self.slo.record_ok(latency_s)

    def record_shed(self, n: int = 1) -> None:
        with self._lock:
            self.shed_total += n
        self.slo.record_bad("shed", n)

    def record_error(self, n: int = 1) -> None:
        with self._lock:
            self.error_total += n
        self.slo.record_bad("error", n)

    def record_unserved(self, n: int = 1) -> None:
        with self._lock:
            self.unserved_total += n
        self.slo.record_bad("error", n)

    def record_death(self) -> None:
        with self._lock:
            self.replica_deaths_total += 1

    def record_restart(self) -> None:
        with self._lock:
            self.replica_restarts_total += 1

    def record_publish(self, version: int, swap_seconds: float) -> None:
        with self._lock:
            self.published_version = int(version)
            self.publishes_total += 1
            self.publish_swap_seconds_last = swap_seconds
            self.publish_swap_seconds_max = max(
                self.publish_swap_seconds_max, swap_seconds)

    def record_canary_reject(self) -> None:
        with self._lock:
            self.canary_rejects_total += 1

    def record_publish_rollback(self, n: int = 1) -> None:
        with self._lock:
            self.publish_rollbacks_total += n

    def record_split(self) -> None:
        with self._lock:
            self.splits_total += 1

    def record_migration(self) -> None:
        with self._lock:
            self.migrations_total += 1

    def record_scale(self, direction: str) -> None:
        with self._lock:
            if direction == "up":
                self.scale_ups_total += 1
            else:
                self.scale_downs_total += 1

    def record_brownout_shed(self, n: int = 1) -> None:
        with self._lock:
            self.brownout_sheds_total += n
            self.shed_total += n
        self.slo.record_bad("shed", n)

    def record_rehome(self, seconds: float, deadline_s: float) -> None:
        with self._lock:
            self.rehomes_total += 1
            self.rehome_seconds_last = seconds
            self.rehome_seconds_max = max(self.rehome_seconds_max,
                                          seconds)
            if seconds > deadline_s:
                self.rehome_deadline_misses_total += 1

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "requests_total": self.requests_total,
                "requests_by_replica": dict(self.requests_by_replica),
                "shed_total": self.shed_total,
                "error_total": self.error_total,
                "unserved_total": self.unserved_total,
                "forward_retries_total": self.forward_retries_total,
                "forward_errors_total": self.forward_errors_total,
                "hedges_total": self.hedges_total,
                "hedge_wins_total": self.hedge_wins_total,
                "rehomes_total": self.rehomes_total,
                "rehome_seconds_last": self.rehome_seconds_last,
                "rehome_seconds_max": self.rehome_seconds_max,
                "rehome_deadline_misses_total":
                    self.rehome_deadline_misses_total,
                "replica_deaths_total": self.replica_deaths_total,
                "replica_restarts_total": self.replica_restarts_total,
                "splits_total": self.splits_total,
                "migrations_total": self.migrations_total,
                "scale_ups_total": self.scale_ups_total,
                "scale_downs_total": self.scale_downs_total,
                "brownout_sheds_total": self.brownout_sheds_total,
                "published_version": self.published_version,
                "publishes_total": self.publishes_total,
                "canary_rejects_total": self.canary_rejects_total,
                "publish_rollbacks_total": self.publish_rollbacks_total,
                "publish_swap_seconds_last":
                    self.publish_swap_seconds_last,
                "publish_swap_seconds_max":
                    self.publish_swap_seconds_max,
            }

    def render_text(self, states: dict[int, str], degraded: bool,
                    boot_seconds: Optional[dict[int, float]] = None,
                    shard_heat: Optional[dict[int, dict]] = None,
                    map_version: Optional[int] = None,
                    hedge_after_s: Optional[float] = None,
                    num_live: Optional[int] = None,
                    ) -> str:
        """Prometheus-style ``photon_fleet_*`` lines (the metric
        catalog rows in docs/OBSERVABILITY.md)."""
        s = self.snapshot()
        lines = [
            f"photon_fleet_replicas "
            f"{num_live if num_live is not None else self.num_replicas}",
            f"photon_fleet_degraded {1 if degraded else 0}",
            f"photon_fleet_requests_total {s['requests_total']}",
            f"photon_fleet_shed_total {s['shed_total']}",
            f"photon_fleet_errors_total {s['error_total']}",
            f"photon_fleet_unserved_total {s['unserved_total']}",
            f"photon_fleet_forward_retries_total "
            f"{s['forward_retries_total']}",
            f"photon_fleet_forward_errors_total "
            f"{s['forward_errors_total']}",
            f"photon_fleet_hedges_total {s['hedges_total']}",
            f"photon_fleet_hedge_wins_total {s['hedge_wins_total']}",
            f"photon_fleet_rehomes_total {s['rehomes_total']}",
            f"photon_fleet_rehome_seconds{{window=\"last\"}} "
            f"{s['rehome_seconds_last']:.6f}",
            f"photon_fleet_rehome_seconds{{window=\"max\"}} "
            f"{s['rehome_seconds_max']:.6f}",
            f"photon_fleet_rehome_deadline_misses_total "
            f"{s['rehome_deadline_misses_total']}",
            f"photon_fleet_replica_deaths_total "
            f"{s['replica_deaths_total']}",
            f"photon_fleet_replica_restarts_total "
            f"{s['replica_restarts_total']}",
            f"photon_fleet_splits_total {s['splits_total']}",
            f"photon_fleet_migrations_total {s['migrations_total']}",
            f"photon_fleet_scale_ups_total {s['scale_ups_total']}",
            f"photon_fleet_scale_downs_total {s['scale_downs_total']}",
            f"photon_fleet_brownout_sheds_total "
            f"{s['brownout_sheds_total']}",
            f"photon_publish_model_version {s['published_version']}",
            f"photon_publish_deltas_total {s['publishes_total']}",
            f"photon_publish_canary_rejects_total "
            f"{s['canary_rejects_total']}",
            f"photon_publish_rollbacks_total "
            f"{s['publish_rollbacks_total']}",
            f"photon_publish_swap_seconds{{window=\"last\"}} "
            f"{s['publish_swap_seconds_last']:.6f}",
            f"photon_publish_swap_seconds{{window=\"max\"}} "
            f"{s['publish_swap_seconds_max']:.6f}",
        ]
        for rid in sorted(states):
            lines.append(
                f"photon_fleet_replica_up{{replica=\"{rid}\"}} "
                f"{1 if states[rid] == UP else 0}")
            lines.append(
                f"photon_fleet_requests_routed_total"
                f"{{replica=\"{rid}\"}} "
                f"{s['requests_by_replica'].get(rid, 0)}")
            if boot_seconds is not None and rid in boot_seconds:
                # spawn → first healthy probe of the LAST (re)start —
                # the fleet-side view of photon_boot_seconds.
                lines.append(
                    f"photon_fleet_replica_boot_seconds"
                    f"{{replica=\"{rid}\"}} {boot_seconds[rid]:.6f}")
        if map_version is not None:
            lines.append(f"photon_fleet_map_version {map_version}")
        if hedge_after_s is not None:
            lines.append(f"photon_fleet_hedge_after_seconds "
                         f"{hedge_after_s:.6f}")
        if shard_heat:
            for shard in sorted(shard_heat):
                lines.append(
                    f"photon_fleet_shard_heat{{shard=\"{shard}\"}} "
                    f"{shard_heat[shard]['heat']:.4f}")
        slo = self.slo.snapshot()
        lines.append(f"photon_fleet_slo_requests_in_window "
                     f"{slo['requests_in_window']}")
        lines.append(f"photon_fleet_slo_bad_in_window "
                     f"{slo['bad_in_window']}")
        lines.append(f"photon_fleet_slo_availability "
                     f"{slo['availability']:.6f}")
        lines.append(f"photon_fleet_slo_budget_burn_rate "
                     f"{slo['budget_burn_rate']:.6f}")
        for q in ("p50", "p95", "p99"):
            lines.append(f"photon_fleet_slo_latency_ms"
                         f"{{quantile=\"{q}\"}} {slo[q + '_ms']:.4f}")
        return "\n".join(lines) + "\n"


class ServingFleet:
    """N supervised scoring replicas behind one entity-affinity router.

    ``replica_args`` is the ``photon_ml_tpu.cli.serve`` argv tail every
    replica shares (model flags, batching knobs); the fleet appends the
    per-replica plumbing (``--port 0 --ready-file … --replica-id …`` and
    the fault plan, when drilling). Replicas inherit this process's
    environment, so ``JAX_PLATFORMS=cpu`` tests stay on CPU.
    """

    def __init__(
        self,
        replica_args: Sequence[str],
        num_replicas: int,
        workdir: str,
        num_shards: Optional[int] = None,
        route_re_type: Optional[str] = None,
        request_timeout_s: float = 30.0,
        retries: int = 3,
        retry_backoff_s: float = 0.1,
        hedge_after_s: Optional[float] = None,
        probe_interval_s: float = 0.25,
        probe_timeout_s: float = 1.0,
        heartbeat_deadline_s: float = 2.0,
        rehome_deadline_s: float = 5.0,
        start_timeout_s: float = 120.0,
        max_restarts: int = 3,
        backoff_reset_s: float = 60.0,
        max_inflight: Optional[int] = None,
        fault_plan_file: Optional[str] = None,
        slo_window_s: float = 60.0,
        slo_availability: float = 0.999,
        slo_latency_ms: Optional[float] = None,
        publish_dir: Optional[str] = None,
        publish_bake_s: float = 0.5,
        publish_burn_threshold: float = 1.0,
        elastic=None,
        emitter=default_emitter,
        transport=None,
        delta_base_url: Optional[str] = None,
    ):
        self.replica_args = list(replica_args)
        self.num_replicas = int(num_replicas)
        self.num_shards = int(num_shards if num_shards is not None
                              else max(8, 2 * self.num_replicas))
        self.workdir = workdir
        self.rehome_deadline_s = float(rehome_deadline_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self.fault_plan_file = fault_plan_file
        self.emitter = emitter
        # Fleet admission control: beyond this many in-flight /score
        # bodies the front door sheds (the replicas' own queues are the
        # deeper backstop; this bound keeps the router pool sane).
        self.max_inflight = (int(max_inflight) if max_inflight is not None
                             else 16 * self.num_replicas)
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        self.metrics = FleetMetrics(self.num_replicas,
                                    slo_window_s=slo_window_s,
                                    slo_availability=slo_availability,
                                    slo_latency_ms=slo_latency_ms)
        self.shard_map = ShardMap(self.num_shards, self.num_replicas)
        self.supervisor = ReplicaSupervisor(
            self._replica_argv, self.num_replicas, workdir,
            probe_interval_s=probe_interval_s,
            probe_timeout_s=probe_timeout_s,
            heartbeat_deadline_s=heartbeat_deadline_s,
            start_timeout_s=start_timeout_s,
            max_restarts=max_restarts,
            backoff_reset_s=backoff_reset_s,
            on_death=self._on_death,
            on_recovered=self._on_recovered,
            transport=transport)
        self.router = FleetRouter(
            self.shard_map, self.supervisor.endpoint,
            route_re_type=route_re_type,
            request_timeout_s=request_timeout_s,
            retries=retries, retry_backoff_s=retry_backoff_s,
            hedge_after_s=hedge_after_s, metrics=self.metrics,
            health_fn=self._replica_healthy)
        self._degraded = False
        self._rehoming = False
        self._closed = False
        # Elastic control loop (serving/elastic.py; docs/SERVING.md
        # "Elastic fleet"): heat model always on (cheap sliding window
        # — /metrics readers want the gauge even with the loop off),
        # the controller only when an ElasticConfig is handed in.
        from photon_ml_tpu.serving.elastic import (ElasticConfig,
                                                   ElasticController)
        from photon_ml_tpu.serving.metrics import ShardHeat

        self.elastic_config = elastic
        self.heat = ShardHeat(
            window_s=(elastic.heat_window_s
                      if isinstance(elastic, ElasticConfig)
                      else 30.0))
        self.elastic = (ElasticController(self, elastic)
                        if elastic is not None else None)
        # Brownout state: written only by the controller thread via
        # set_brownout, read by HTTP handler threads; dict swap is
        # atomic under the GIL and staleness of one tick is by design.
        self._brownout: dict[int, str] = {}
        self._elastic_ledger = None
        # Continuous publication state (serving/publish.py ladder):
        # committed deltas newest-last (restarted replicas replay them),
        # one publish at a time, and the publish ledger (lazy — the row
        # sink `photon-obs tail --publish` reads).
        self.publish_dir = publish_dir
        self.publish_bake_s = float(publish_bake_s)
        self.publish_burn_threshold = float(publish_burn_threshold)
        # Publish-over-the-wire (docs/SERVING.md "Multi-host fleet"):
        # when set, replicas are told to PULL delta artifacts from this
        # base URL (a DeltaArtifactServer over the publish dir) instead
        # of resolving a shared-filesystem path — remote replicas have
        # no such filesystem. CRC verification stays with the artifact.
        self.delta_base_url = (delta_base_url.rstrip("/")
                               if delta_base_url else None)
        self._published: list[tuple[int, str]] = []
        # Two locks, strictly ordered _ladder_lock -> _publish_lock
        # (photon-lint --locks proves the graph stays acyclic):
        # _ladder_lock serializes whole publish ladders and IS held
        # across the canary HTTP + bake sleep by design (see the
        # allow[PML019] notes in publish_delta) — only publish_delta
        # takes it, so the monitor thread never convoys on a bake.
        # _publish_lock guards the committed chain and the lazy ledger
        # handles with short holds only; the monitor thread's recovery
        # replay and /healthz readers take just this one.
        self._ladder_lock = threading.Lock()
        self._publish_lock = threading.Lock()
        self._publish_ledger = None

    # -- replica plumbing ----------------------------------------------------

    def _replica_argv(self, replica_id: int, ready_file: str) -> list[str]:
        argv = [sys.executable, "-m", "photon_ml_tpu.cli.serve",
                *self.replica_args,
                "--host", "127.0.0.1", "--port", "0",
                "--ready-file", ready_file,
                "--replica-id", str(replica_id)]
        if self.fault_plan_file:
            argv += ["--fault-plan", self.fault_plan_file]
        return argv

    # -- failure half --------------------------------------------------------

    def _on_death(self, replica_id: int) -> None:
        """Supervisor monitor-thread callback: the rehome window starts
        HERE (detection) and closes when every moved shard's new owner
        confirmed healthy."""
        t0 = time.monotonic()
        self.metrics.record_death()
        # pml: allow[PML015] single-writer publish: only the monitor thread flips these bools; /healthz readers tolerate staleness by design
        self._degraded = True
        self._rehoming = True  # pml: allow[PML015] same single-writer monitor-thread publish as above
        self.emitter.emit(ReplicaDied(replica_id=replica_id,
                                      reason="declared dead by probe"))
        try:
            moved = self.shard_map.mark_down(replica_id)
        except ReplicaUnavailable:
            logger.error("replica %d died and no survivor remains — "
                         "the fleet is down until a restart succeeds",
                         replica_id)
            self._rehoming = False  # pml: allow[PML015] single-writer monitor-thread publish; readers poll
            return
        # Confirm each new owner actually serves before declaring the
        # re-home done — a table swap to another corpse is not recovery.
        from photon_ml_tpu.serving.supervisor import _probe_healthz
        for rid in sorted(set(moved.values())):
            host, port = self.supervisor.endpoint(rid)
            try:
                _probe_healthz(f"http://{host}:{port}",
                               self.probe_timeout_s)
            except (OSError, ValueError) as e:
                logger.warning("re-home target %d not yet healthy "
                               "(%s) — the monitor will handle it", rid, e)
        seconds = time.monotonic() - t0
        self._rehoming = False  # pml: allow[PML015] single-writer monitor-thread publish; readers poll
        self.metrics.record_rehome(seconds, self.rehome_deadline_s)
        self.emitter.emit(ShardRehomed(
            replica_id=replica_id, shards=tuple(sorted(moved)),
            new_owners=tuple(moved[s] for s in sorted(moved)),
            seconds=seconds))
        level = (logger.error if seconds > self.rehome_deadline_s
                 else logger.info)
        level("re-homed %d shard(s) of dead replica %d in %.3fs "
              "(deadline %.3fs)", len(moved), replica_id, seconds,
              self.rehome_deadline_s)

    def _on_recovered(self, replica_id: int) -> None:
        back = self.shard_map.restore(replica_id)
        self.metrics.record_restart()
        self.emitter.emit(ReplicaRecovered(
            replica_id=replica_id, shards_restored=tuple(back)))
        # A restarted replica loaded the BASE model — replay the
        # committed delta chain before declaring it healthy, or it would
        # serve stale rows for every published entity.
        self._reapply_published(replica_id)
        states = self.supervisor.states()
        if all(st in (UP, RETIRED) for st in states.values()):
            self._degraded = False  # pml: allow[PML015] single-writer monitor-thread publish; healthz re-derives from supervisor states anyway
        logger.info("replica %d recovered; %d shard(s) back home; "
                    "fleet %s", replica_id, len(back),
                    "healthy" if not self._degraded else "still degraded")

    # -- elastic fleet (serving/elastic.py; docs/SERVING.md "Elastic
    #    fleet") ---------------------------------------------------------------

    def _replica_healthy(self, replica_id: int) -> bool:
        """The router's liveness oracle beyond the shard map: the
        supervisor's state machine knows a replica is down/restarting
        BEFORE the map re-homes it — hedges must not aim into that
        gap (ISSUE 15 satellite fix)."""
        try:
            return self.supervisor.replicas[replica_id].state == UP
        except IndexError:
            return False

    def set_brownout(self, hot_shards, reason: str) -> None:
        """Engage (or with an empty list, release) per-shard admission
        tightening — the first rung of the overload ladder: requests
        routed to a browned-out shard shed with a 503 NAMING the shard,
        while every other shard keeps serving; the fleet-wide
        ``max_inflight`` bound stays the second rung."""
        new = {int(s): reason for s in hot_shards}
        was = self._brownout
        # Single-writer publish: only the controller thread swaps this
        # dict; handler reads tolerate one-tick staleness by design.
        self._brownout = new
        if new and not was:
            self.emitter.emit(FleetDegraded(
                mode="brownout", hot_shards=tuple(sorted(new)),
                reason=reason))
            self._elastic_record(action="brownout",
                                 hot_shards=sorted(new), reason=reason)
            logger.warning("BROWNOUT: per-shard admission tightened "
                           "for shard(s) %s (%s)", sorted(new), reason)
        elif was and not new:
            self.emitter.emit(FleetDegraded(
                mode="recovered", hot_shards=(), reason=reason))
            self._elastic_record(action="brownout_clear",
                                 reason=reason)
            logger.info("brownout released (%s)", reason)

    def brownout_shard_of(self, request_objs: Sequence[dict]
                          ) -> Optional[tuple[int, str]]:
        """The first browned-out shard a body routes to, or None."""
        hot = self._brownout
        if not hot:
            return None
        for obj in request_objs:
            shard = self.router.shard_for(obj)
            if shard in hot:
                return shard, hot[shard]
        return None

    def add_replica(self) -> int:
        """The scale-up leg: spawn + warm a new supervised replica,
        admit it to the shard map only after it answered /healthz, and
        replay the committed delta chain so it serves the same model
        version as the rest of the fleet."""
        rid = self.supervisor.add_replica()
        admitted = self.shard_map.add_replica()
        if admitted != rid:  # pragma: no cover — ids advance together
            logger.error("replica id drift: supervisor %d vs map %d",
                         rid, admitted)
        self.num_replicas = len(self.shard_map.live())
        self._reapply_published(rid)
        return rid

    def _elastic_record(self, **fields) -> None:
        """One ``elastic`` ledger row (append-as-produced, per-row CRC
        — the obs/ledger.py discipline; ``photon-obs tail --elastic``
        renders the decision tape). Lazy like the publish ledger; rows
        land in ``<workdir>/elastic/ledger``."""
        with self._publish_lock:
            if self._elastic_ledger is None:
                from photon_ml_tpu.obs.ledger import RunLedger

                self._elastic_ledger = RunLedger.resume(
                    os.path.join(self.workdir, "elastic", "ledger"),
                    config={"kind": "elastic",
                            "num_replicas": self.num_replicas,
                            "num_shards": self.num_shards})
            self._elastic_ledger.record(
                "elastic", map_snapshot_version=self.shard_map.version,
                **fields)

    # -- continuous publication (serving/publish.py; docs/SERVING.md
    #    "Continuous publication") --------------------------------------------

    @property
    def published_version(self) -> int:
        with self._publish_lock:
            return self._published[-1][0] if self._published else 0

    def _replica_url(self, replica_id: int) -> str:
        host, port = self.supervisor.endpoint(replica_id)
        return f"http://{host}:{port}"

    def _replica_post(self, replica_id: int, path: str,
                      payload: dict, timeout_s: float = 30.0) -> dict:
        body = json.dumps(payload).encode()
        req = urllib.request.Request(
            self._replica_url(replica_id) + path, data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            return json.loads(resp.read())

    def _replica_get_json(self, replica_id: int, path: str,
                          timeout_s: float = 10.0) -> dict:
        with urllib.request.urlopen(self._replica_url(replica_id) + path,
                                    timeout=timeout_s) as resp:
            return json.loads(resp.read())

    def _publish_record(self, **fields) -> None:
        """One ``publish`` ledger row (append-as-produced, per-row CRC —
        the obs/ledger.py discipline; ``photon-obs tail --publish``
        renders these)."""
        if self.publish_dir is None:
            return
        with self._publish_lock:
            if self._publish_ledger is None:
                from photon_ml_tpu.obs.ledger import RunLedger

                self._publish_ledger = RunLedger.resume(
                    os.path.join(self.publish_dir, "ledger"),
                    config={"kind": "publish",
                            "num_replicas": self.num_replicas})
            self._publish_ledger.record("publish", **fields)

    def _kill_replica(self, replica_id: int) -> None:
        """Last rung of the rollback ladder: a replica that cannot be
        rolled back is in an UNKNOWN model state — SIGKILL it so the
        supervisor restarts it from the base model and
        ``_reapply_published`` replays only the COMMITTED chain
        (consistency restored by construction)."""
        logger.error(
            "replica %d could not roll back — killing it; the "
            "supervised restart replays the committed delta chain",
            replica_id)
        self.supervisor.kill_replica(replica_id)

    def _delta_payload(self, delta_dir: str) -> dict:
        """The ``/admin/delta`` body: a shared-filesystem path, or —
        with ``delta_base_url`` set — the URL the replica PULLS the
        artifacts from (serving/publish.fetch_delta re-verifies the
        CRC fence on its side of the wire)."""
        if self.delta_base_url is not None:
            return {"url":
                    f"{self.delta_base_url}/"
                    f"{os.path.basename(delta_dir.rstrip(os.sep))}"}
        return {"path": delta_dir}

    def _reapply_published(self, replica_id: int) -> None:
        with self._publish_lock:
            chain = list(self._published)
        if not chain:
            return
        # A replica that mmap-booted a COMPACTED generation
        # (boot/generations.py) already holds some prefix of the chain
        # folded into its tables — /healthz says how much; replaying a
        # folded delta would fail the parent check and strand the rest.
        base = 0
        try:
            base = int(self._replica_get_json(
                replica_id, "/healthz").get("model_version", 0) or 0)
        except (OSError, ValueError):
            pass  # unknown base: replay everything (the classic boot)
        for version, path in chain:
            if version <= base:
                continue
            try:
                self._replica_post(replica_id, "/admin/delta",
                                   self._delta_payload(path))
                self._publish_record(phase="reapply", version=version,
                                     replica=replica_id)
            except (OSError, ValueError) as e:
                logger.error(
                    "recovered replica %d failed to re-apply committed "
                    "delta v%d (%s: %s) — it serves STALE rows until "
                    "the next restart", replica_id, version,
                    type(e).__name__, e)
                return

    def _rollback(self, replica_ids: Sequence[int], delta: ModelDelta,
                  reason: str) -> None:
        """Back ``delta`` out of every replica that applied it. A
        replica whose rollback fails is killed (see ``_kill_replica``) —
        the ladder never leaves a replica in an unknown state."""
        rolled = []
        for rid in replica_ids:
            try:
                flt.fire(flt.sites.PUBLISH_ROLLBACK, index=rid)
                self._replica_post(rid, "/admin/rollback",
                                   {"to_version": delta.parent})
                rolled.append(rid)
            except Exception as e:
                logger.error("rollback of delta v%d on replica %d "
                             "failed (%s: %s)", delta.version, rid,
                             type(e).__name__, e)
                self._kill_replica(rid)
        self.metrics.record_publish_rollback(len(replica_ids))
        self.emitter.emit(RollbackExecuted(
            version=delta.version, reason=reason,
            replicas=tuple(rolled)))
        self._publish_record(phase="rollback", version=delta.version,
                             reason=reason, replicas=list(rolled))

    def _judge_canary(self, canary: int, delta: ModelDelta,
                      bake_s: float, burn_threshold: float,
                      probe_objs: Optional[list] = None,
                      probe_max_abs: Optional[float] = None
                      ) -> tuple[bool, str, float]:
        """The canary judge: bake, then rule on (1) probe scores —
        finite, and inside ``probe_max_abs`` when given (the quality
        delta), (2) the canary's error-budget burn and flush errors
        over the window (the SLO half). Returns (accepted, reason,
        burn_rate)."""
        before = self._replica_get_json(canary, "/slo")
        if probe_objs:
            try:
                resp = self._replica_post(
                    canary, "/score", {"requests": probe_objs})
                scores = [float(s) for s in resp.get("scores", [])]
            except (OSError, ValueError) as e:
                return False, f"canary probe failed ({e})", 0.0
            if any(s != s or s in (float("inf"), float("-inf"))
                   for s in scores):
                return False, "canary probe produced non-finite scores", \
                    0.0
            if probe_max_abs is not None and any(
                    abs(s) > probe_max_abs for s in scores):
                worst = max(abs(s) for s in scores)
                return (False,
                        f"canary probe scores out of band "
                        f"(|score| {worst:.4g} > {probe_max_abs:.4g})",
                        0.0)
        time.sleep(bake_s)
        after = self._replica_get_json(canary, "/slo")
        burn = float(after.get("budget_burn_rate", 0.0))
        flush_delta = (after["lifetime"]["flush_errors_total"]
                       - before["lifetime"]["flush_errors_total"])
        if flush_delta > 0:
            return (False, f"{flush_delta} flush error(s) on the canary "
                           f"during the bake window", burn)
        if burn > burn_threshold:
            return (False, f"canary error-budget burn {burn:.3f} over "
                           f"threshold {burn_threshold:.3f}", burn)
        return True, "ok", burn

    def publish_delta(self, delta_dir: str,
                      bake_s: Optional[float] = None,
                      burn_threshold: Optional[float] = None,
                      probe_objs: Optional[list] = None,
                      probe_max_abs: Optional[float] = None) -> dict:
        """The publication ladder: canary-apply → bake/judge → roll
        fleet-wide or auto-roll-back. Raises the defined taxonomy —
        ``DeltaCorrupt``/``BadDelta`` (nothing applied anywhere),
        ``CanaryRejected`` (canary rolled back, no other replica ever
        saw the delta), ``PublishError`` (a fleet-wide swap leg failed;
        every applied replica rolled back). On success the delta joins
        the committed chain restarted replicas replay."""
        bake_s = self.publish_bake_s if bake_s is None else float(bake_s)
        burn_threshold = (self.publish_burn_threshold
                          if burn_threshold is None
                          else float(burn_threshold))
        # Replicas resolve the path from THEIR cwd (the workdir) — hand
        # them an absolute one.
        delta_dir = os.path.abspath(delta_dir)
        with self._ladder_lock:
            delta = read_delta(delta_dir)  # DeltaCorrupt stops it here
            with self._publish_lock:
                current = (self._published[-1][0]
                           if self._published else 0)
            if delta.parent != current:
                raise PublishError(
                    f"delta v{delta.version} was cut against version "
                    f"{delta.parent} but the fleet serves {current} — "
                    f"publish the chain in order")
            up = self.supervisor.up_replicas()
            if not up:
                raise PublishError("no healthy replica to canary on")
            canary = up[0]
            self._publish_record(phase="canary_apply",
                                 version=delta.version, replica=canary)
            t0 = time.monotonic()
            try:
                # pml: allow[PML019] ladder lock held across fault hook + canary HTTP by design: one publish at a time IS the contract, and nothing on the request path ever takes _ladder_lock
                flt.fire(flt.sites.PUBLISH_CANARY_APPLY, index=canary)
                # pml: allow[PML019] ladder lock held across canary/fleet HTTP + bake by design; every leg carries a finite timeout and only publish_delta takes this lock
                self._replica_post(canary, "/admin/delta",
                                   self._delta_payload(delta_dir))
            except urllib.error.HTTPError as e:
                # The replica REFUSED (validation, chain break): nothing
                # applied, nothing to roll back.
                detail = e.read().decode(errors="replace")
                self.metrics.record_canary_reject()
                self.emitter.emit(CanaryVerdict(
                    version=delta.version, replica_id=canary,
                    accepted=False, reason=detail, burn_rate=0.0))
                self._publish_record(phase="canary_verdict",
                                     version=delta.version,
                                     replica=canary, accepted=False,
                                     reason=detail)
                raise CanaryRejected(delta.version,
                                     f"replica refused the delta: "
                                     f"{detail}")
            except Exception as e:
                # Ambiguous failure (timeout, injected fault): the
                # canary MAY have applied — roll it back (idempotent
                # when it had not).
                self.metrics.record_canary_reject()
                self._rollback([canary], delta,
                               f"canary apply failed: {e}")
                raise CanaryRejected(delta.version,
                                     f"canary apply failed: {e}")
            apply_s = time.monotonic() - t0
            accepted, reason, burn = self._judge_canary(
                canary, delta, bake_s, burn_threshold,
                probe_objs=probe_objs, probe_max_abs=probe_max_abs)
            self.emitter.emit(CanaryVerdict(
                version=delta.version, replica_id=canary,
                accepted=accepted, reason=reason, burn_rate=burn))
            self._publish_record(phase="canary_verdict",
                                 version=delta.version, replica=canary,
                                 accepted=accepted, reason=reason,
                                 burn_rate=burn)
            if not accepted:
                self.metrics.record_canary_reject()
                self._rollback([canary], delta, reason)
                raise CanaryRejected(delta.version, reason)
            # Verdict: roll fleet-wide. A failed leg rolls EVERYTHING
            # back (the failed replica included — its state is unknown).
            t1 = time.monotonic()
            applied = [canary]
            for rid in up[1:]:
                try:
                    flt.fire(flt.sites.PUBLISH_SWAP, index=rid)
                    self._replica_post(rid, "/admin/delta",
                                       self._delta_payload(delta_dir))
                    applied.append(rid)
                    self._publish_record(phase="swap",
                                         version=delta.version,
                                         replica=rid)
                except Exception as e:
                    reason = (f"fleet-wide swap failed on replica "
                              f"{rid}: {type(e).__name__}: {e}")
                    logger.error("%s — rolling every applied replica "
                                 "back", reason)
                    self._rollback(applied + [rid], delta, reason)
                    raise PublishError(reason)
            swap_seconds = apply_s + (time.monotonic() - t1)
            with self._publish_lock:
                self._published.append((delta.version, delta_dir))
            self.metrics.record_publish(delta.version, swap_seconds)
            self.emitter.emit(DeltaPublished(
                version=delta.version, coordinates=delta.coordinates,
                entities=delta.num_rows, canary_replica=canary,
                swap_seconds=swap_seconds))
            self._publish_record(phase="published",
                                 version=delta.version,
                                 entities=delta.num_rows,
                                 replicas=applied,
                                 swap_seconds=round(swap_seconds, 6),
                                 burn_rate=burn)
            logger.info("delta v%d live on %d replica(s) "
                        "(canary %d, swap %.3fs)", delta.version,
                        len(applied), canary, swap_seconds)
            return {"version": delta.version, "canary_replica": canary,
                    "replicas": applied, "entities": delta.num_rows,
                    "swap_seconds": swap_seconds, "burn_rate": burn}

    # -- serving -------------------------------------------------------------

    def start(self) -> None:
        os.makedirs(self.workdir, exist_ok=True)
        self.supervisor.start()
        if self.elastic is not None:
            self.elastic.start()

    def score(self, request_objs: Sequence[dict],
              want_trace: bool = False) -> dict:
        """Route one /score body through the fleet; returns the merged
        response payload. Raises the router's defined errors — the HTTP
        front end maps them to status codes; programmatic callers get
        the same exception taxonomy."""
        counts: dict[int, int] = {}
        shards: list[Optional[int]] = []
        for obj in request_objs:
            shard = self.router.shard_for(obj)
            shards.append(shard)
            if shard is not None:
                # Heat model feed: the request count + distinct-entity
                # cardinality half of the shard's window.
                ents = obj.get("entity_ids") or {}
                key = ents[min(ents)] if ents else None
                self.heat.record(shard, entity=key)
                rid = self.shard_map.owner(shard)
            else:
                rid = self.router.replica_for(obj)
            counts[rid] = counts.get(rid, 0) + 1
        self.metrics.record_routed(counts)
        t0 = time.monotonic()
        out = self.router.score(request_objs, want_trace=want_trace)
        dt = time.monotonic() - t0
        self.metrics.record_ok(dt, n=len(request_objs))
        # The service-seconds half: a shard whose requests run long is
        # hotter at equal QPS (queue contribution, approximated by the
        # body wall split evenly over its requests).
        per = dt / max(len(request_objs), 1)
        for shard in shards:
            if shard is not None:
                self.heat.record_seconds(shard, per)
        return out

    def admission_acquire(self) -> bool:
        with self._inflight_lock:
            if self._inflight >= self.max_inflight:
                return False
            self._inflight += 1
            return True

    def admission_release(self) -> None:
        with self._inflight_lock:
            self._inflight = max(0, self._inflight - 1)

    @property
    def inflight(self) -> int:
        with self._inflight_lock:
            return self._inflight

    def healthz(self) -> dict:
        states = self.supervisor.states()
        # RETIRED is a deliberate scale-down outcome, not degradation.
        degraded = self._degraded or any(st not in (UP, RETIRED)
                                         for st in states.values())
        leaves = self.shard_map.shards()
        return {
            "status": "degraded" if degraded else "ok",
            "degraded": degraded,
            "rehoming": self._rehoming,
            "fleet_depth": len(self.shard_map.live()),
            "replicas": {str(k): v for k, v in states.items()},
            "num_shards": len(leaves),
            "map_version": self.shard_map.version,
            "hot_shards": sorted(self._brownout),
            "shards_away_from_home": sum(
                1 for s in leaves
                if self.shard_map.owner(s) != self.shard_map.home(s)),
            "published_version": self.published_version,
        }

    def metrics_text(self) -> str:
        return self.metrics.render_text(
            self.supervisor.states(), self.healthz()["degraded"],
            boot_seconds={h.replica_id: h.boot_seconds
                          for h in self.supervisor.replicas
                          if h.boot_seconds > 0.0},
            shard_heat=self.heat.snapshot(
                resolver=lambda key: self.shard_map.shard_of_key(
                    route_key(key))),
            map_version=self.shard_map.version,
            hedge_after_s=self.router.hedge_after_s or 0.0,
            num_live=len(self.shard_map.live()))

    def slo_snapshot(self) -> dict:
        out = self.metrics.slo.snapshot()
        out["lifetime"] = self.metrics.snapshot()
        return out

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self.elastic is not None:
            self.elastic.stop()
        self.router.close()
        self.supervisor.stop()
        if self._publish_ledger is not None:
            self._publish_ledger.close()
        if self._elastic_ledger is not None:
            self._elastic_ledger.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# -- fleet HTTP front door ---------------------------------------------------

class _FleetHandler(BaseHTTPRequestHandler):
    """POST /score, GET /metrics, GET /slo, GET /healthz — the same
    surface as one replica, so clients cannot tell the fleet from a
    single ``photon-game-serve`` (except via the richer /healthz)."""

    fleet: ServingFleet = None  # bound by make_fleet_http_server

    def _json(self, code: int, payload: dict) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        if self.path == "/metrics":
            body = self.fleet.metrics_text().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif self.path == "/slo":
            self._json(200, self.fleet.slo_snapshot())
        elif self.path == "/healthz":
            hz = self.fleet.healthz()
            # Degraded is still SERVING (shards re-homed) — 200 with the
            # flag, not a 5xx that would page as an outage.
            self._json(200, hz)
        else:
            self._json(404, {"error": f"unknown path {self.path}"})

    def _do_publish(self) -> None:
        """``POST /publish``: drive the canary ladder from the front
        door (``photon-game-publish --fleet-url`` lands here). The
        response carries the verdict; rejections are DEFINED statuses —
        409 canary-rejected (rolled back), 422 untrustworthy/unservable
        delta (never applied), 503 swap failure (rolled back)."""
        fleet = self.fleet
        try:
            length = int(self.headers.get("Content-Length", 0))
            payload = json.loads(self.rfile.read(length) or b"{}")
            delta_dir = str(payload["path"])
            probe = payload.get("probe") or {}
        except (ValueError, TypeError, KeyError) as exc:
            self._json(400, {"error": f"malformed publish request: "
                                      f"{exc}"})
            return
        from photon_ml_tpu.serving.publish import (BadDelta,
                                                   DeltaCorrupt)

        try:
            out = fleet.publish_delta(
                delta_dir,
                bake_s=payload.get("bake_s"),
                burn_threshold=payload.get("burn_threshold"),
                probe_objs=probe.get("requests"),
                probe_max_abs=probe.get("max_abs_score"))
        except CanaryRejected as exc:
            self._json(409, {"error": str(exc), "version": exc.version,
                             "reason": exc.reason, "rolled_back": True})
            return
        except (DeltaCorrupt, BadDelta) as exc:
            self._json(422, {"error": str(exc), "applied": False})
            return
        except PublishError as exc:
            self._json(503, {"error": str(exc), "rolled_back": True})
            return
        self._json(200, out)

    def do_POST(self):
        fleet = self.fleet
        if self.path == "/publish":
            self._do_publish()
            return
        if self.path != "/score":
            self._json(404, {"error": f"unknown path {self.path}"})
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
            payload = json.loads(self.rfile.read(length) or b"{}")
            if not isinstance(payload, dict):
                raise ValueError("request body must be a JSON object")
            reqs = payload.get("requests", [])
            if not isinstance(reqs, list) or not reqs:
                raise ValueError("no requests")
            want_trace = bool(payload.get("trace", False))
        except (ValueError, TypeError, AttributeError, KeyError) as exc:
            self._json(400, {"error": f"malformed request: {exc}"})
            return
        # Overload ladder rung 1 — per-shard brownout: admission
        # tightens for the HOT shard before anything fleet-wide, and
        # the 503 NAMES it (docs/SERVING.md "Elastic fleet").
        hot = fleet.brownout_shard_of(reqs)
        if hot is not None:
            shard, reason = hot
            fleet.metrics.record_brownout_shed(len(reqs))
            self._json(503, {
                "error": f"brownout: shard {shard} is overloaded "
                         f"({reason})",
                "hot_shard": shard,
                "replica_id": None,
                "fleet_depth": fleet.num_replicas,
                "degraded": True,
            })
            return
        if not fleet.admission_acquire():
            # Fleet-level admission: the 503 names the FLEET (no single
            # replica shed) and carries the depth context the ISSUE's
            # degradation contract requires.
            fleet.metrics.record_shed(len(reqs))
            self._json(503, {
                "error": "fleet admission control: too many in-flight "
                         "score bodies",
                "replica_id": None,
                "fleet_depth": fleet.num_replicas,
                "inflight": fleet.inflight,
                "max_inflight": fleet.max_inflight,
            })
            return
        try:
            out = fleet.score(reqs, want_trace=want_trace)
        except ReplicaShed as exc:
            fleet.metrics.record_shed(len(reqs))
            self._json(503, {
                "error": str(exc),
                "replica_id": exc.replica_id,
                "fleet_depth": fleet.num_replicas,
                "queue_depth": exc.queue_depth,
                "degraded": fleet.healthz()["degraded"],
            })
            return
        except ReplicaUnavailable as exc:
            fleet.metrics.record_unserved(len(reqs))
            self._json(503, {
                "error": str(exc),
                "replica_id": exc.replica_id,
                "fleet_depth": fleet.num_replicas,
                "degraded": True,
            })
            return
        except ReplicaHTTPError as exc:
            fleet.metrics.record_error(len(reqs))
            self._json(exc.status if exc.status >= 400 else 500, {
                "error": str(exc),
                "replica_id": exc.replica_id,
                "fleet_depth": fleet.num_replicas,
            })
            return
        finally:
            fleet.admission_release()
        body = {"scores": out["scores"],
                "uids": [r.get("uid") for r in reqs]}
        if want_trace and out.get("attribution") is not None:
            body["attribution"] = out["attribution"]
        self._json(200, body)

    def log_message(self, fmt, *args):  # access logs off stderr
        logger.debug("fleet http: " + fmt, *args)


def make_fleet_http_server(fleet: ServingFleet, host: str = "127.0.0.1",
                           port: int = 8080) -> ThreadingHTTPServer:
    """Bind the fleet front door (call ``serve_forever`` to serve);
    ``port=0`` picks a free port — it is ``server.server_address[1]``."""
    handler = type("BoundFleetHandler", (_FleetHandler,),
                   {"fleet": fleet})
    return ThreadingHTTPServer((host, port), handler)
